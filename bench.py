"""Headline benchmark: GPT-2 training throughput, tokens/sec/chip.

This is the north-star metric from BASELINE.json ("Ray Train GPT-2
tokens/sec/chip").  The reference publishes no TPU numbers
(BASELINE.md: published = {}), so vs_baseline normalizes against the
reference's NCCL/GPU-era equivalent: ~51k tokens/sec/chip for GPT-2-small
with torch DDP on an A100-class device (6*N*tok/s at ~40% MFU of 312
TFLOPs bf16).  A v5e chip (197 TFLOPs bf16) at the same MFU would be
~0.63 of that; vs_baseline > 0.63 therefore means better MFU than the
reference stack.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

GPU_BASELINE_TOKENS_PER_SEC = 51000.0


def main():
    import jax

    try:
        jax.devices()
    except RuntimeError:
        # Env names a backend whose plugin isn't registered (e.g. a
        # stripped PYTHONPATH): let jax pick whatever is available.
        jax.config.update("jax_platforms", "")
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh

    on_tpu = jax.default_backend() == "tpu"
    n_dev = len(jax.devices())
    if on_tpu:
        cfg = gpt2.GPT2Config(max_seq_len=1024)  # GPT-2 small, 124M, bf16
        B, T, steps = 16, 1024, 10
    else:  # CI fallback: tiny model so the line still prints quickly
        cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
        B, T, steps = 4, 64, 3

    mesh = create_mesh({"dp": n_dev}, jax.devices())
    opt = gpt2.make_adamw(lr=3e-4)
    params, opt_state, specs = gpt2.make_sharded_train_state(cfg, mesh, opt)
    step = gpt2.make_sharded_train_step(cfg, mesh, opt)

    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (B, T + 1), dtype=np.int32)
    tokens, targets = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])

    # Warmup / compile.  Sync via device_get: block_until_ready is not a
    # reliable barrier on tunneled backends.
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    float(jax.device_get(loss))

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    # The final loss depends on the whole step chain, so fetching it
    # synchronizes every timed step.
    float(jax.device_get(loss))
    dt = time.perf_counter() - t0

    tokens_per_sec = B * T * steps / dt
    per_chip = tokens_per_sec / n_dev
    print(
        json.dumps(
            {
                "metric": "gpt2_small_train_tokens_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(per_chip / GPU_BASELINE_TOKENS_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
