"""Headline benchmark: GPT-2 training throughput, tokens/sec/chip.

This is the north-star metric from BASELINE.json ("Ray Train GPT-2
tokens/sec/chip").  The reference publishes no TPU numbers
(BASELINE.md: published = {}), so vs_baseline normalizes against the
reference's NCCL/GPU-era equivalent: ~51k tokens/sec/chip for GPT-2-small
with torch DDP on an A100-class device (6*N*tok/s at ~40% MFU of 312
TFLOPs bf16).  A v5e chip (197 TFLOPs bf16) at the same MFU would be
~0.63 of that; vs_baseline > 0.63 therefore means better MFU than the
reference stack.

Deadline architecture (round-5 redesign; a wedged TPU tunnel must never
again produce an empty record):

  * A global wall-clock deadline (BENCH_DEADLINE_S, default 1500 s)
    bounds the WHOLE script; every stage gets a hard budget carved out
    of what remains, so the stage budgets can never sum past the driver's
    own timeout the way the round-4 ladder did (1200+900+900+900 s).
  * Stage 0 is a ~60 s chip PROBE in its own subprocess (tiny jitted
    matmul).  A wedged tunnel hangs jax backend init rather than raising,
    so the probe is the only place we pay that risk — with a small budget
    and a SIGTERM-first kill so we never SIGKILL a process mid-TPU-op
    (which is what wedges the tunnel for hours in the first place).
  * The result JSON line is emitted INCREMENTALLY: as soon as the
    in-framework number exists, a complete, parseable record is printed
    and flushed; later stages (raw comparison, PPO) re-print an enriched
    record.  The LAST line is the most complete one, but any line is a
    valid result — so even if the driver kills us, the tail parses.
  * The PPO bench (north-star #2) runs only if the probe passed and
    enough budget remains.
  * BENCH_FAKE_WEDGE=1 simulates a wedged tunnel (backend init that
    never returns) so the fallback ladder is testable hermetically —
    see tests/test_bench_deadline.py.

Two throughput measurements, each in its own subprocess so exactly one
process owns the chip at a time:
  raw       — the jitted train step driven directly (no framework).
  framework — the SAME step inside JaxTrainer.fit() (1-worker group on
              the chip), proving the runtime adds <~3% overhead
              (VERDICT r2 ask #3; reference: train/base_trainer.py fit).

`value` is the in-framework number (the honest "what a user gets"
figure).  See PERF_ANALYSIS.md for the shape-limited roofline study.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

GPU_BASELINE_TOKENS_PER_SEC = 51000.0

_START = time.monotonic()
_DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "1500"))


def _remaining() -> float:
    return _DEADLINE_S - (time.monotonic() - _START)


# Simulated wedged tunnel: backend init that never returns.  Injected into
# every non-CPU subprocess when BENCH_FAKE_WEDGE=1 so the deadline ladder
# is testable without real TPU hardware (VERDICT r4 ask #1).
_FAKE_WEDGE_PRELUDE = """
import os as _os, time as _time
if _os.environ.get("JAX_PLATFORMS") != "cpu":
    _time.sleep(10**6)
"""

_PROBE_SNIPPET = """
import json, time
t0 = time.time()
import jax, jax.numpy as jnp
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = jax.jit(lambda a: a @ a)(x)
jax.block_until_ready(y)
print("BENCH_RESULT " + json.dumps(
    {"backend": jax.default_backend(), "secs": round(time.time() - t0, 1)}))
"""

# Shared measurement body: build the sharded GPT-2 train state, warm up,
# time `steps` steps.  Defines tok_s_chip + on_tpu.  Used verbatim by both
# the raw and the in-framework runs so the overhead comparison compares
# exactly the same work.
_MEASURE_BODY = """
import time
import jax
try:
    jax.devices()
except RuntimeError:
    jax.config.update("jax_platforms", "")
import jax.numpy as jnp
import numpy as np
from ray_tpu.models import gpt2
from ray_tpu.parallel import create_mesh

on_tpu = jax.default_backend() == "tpu"
platform = jax.default_backend()
n_dev = len(jax.devices())
if on_tpu:
    cfg = gpt2.GPT2Config(max_seq_len=1024, remat=False)  # fits HBM at 124M/B16/T1024
    B, T, steps = 16, 1024, 30
else:
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    B, T, steps = 4, 64, 3

mesh = create_mesh({"dp": n_dev}, jax.devices())
opt = gpt2.make_adamw(lr=3e-4)
params, opt_state, specs = gpt2.make_sharded_train_state(cfg, mesh, opt)
step = gpt2.make_sharded_train_step(cfg, mesh, opt)
rng = np.random.default_rng(0)
toks = rng.integers(0, cfg.vocab_size, (B, T + 1), dtype=np.int32)
tokens, targets = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
for _ in range(3):
    params, opt_state, loss = step(params, opt_state, tokens, targets)
float(jax.device_get(loss))  # sync: block_until_ready is unreliable on tunneled backends
t0 = time.perf_counter()
for _ in range(steps):
    params, opt_state, loss = step(params, opt_state, tokens, targets)
float(jax.device_get(loss))
dt = time.perf_counter() - t0
tok_s_chip = B * T * steps / dt / n_dev
"""

_RAW_SNIPPET = f"""
import json
{_MEASURE_BODY}
print("BENCH_RESULT " + json.dumps({{"tok_s_chip": tok_s_chip, "on_tpu": on_tpu, "platform": platform}}))
"""

_FRAMEWORK_SNIPPET = f"""
import json
import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, ScalingConfig

_BODY = {_MEASURE_BODY!r}

def train_loop(config):
    ns = {{}}
    exec(_BODY, ns)
    train.report({{"tok_s_chip": ns["tok_s_chip"], "on_tpu": ns["on_tpu"], "platform": ns["platform"]}})

ray_tpu.init(num_cpus=4)
result = JaxTrainer(
    train_loop, scaling_config=ScalingConfig(num_workers=1)
).fit()
print("BENCH_RESULT " + json.dumps({{
    "tok_s_chip": result.metrics["tok_s_chip"], "on_tpu": result.metrics["on_tpu"],
    "platform": result.metrics.get("platform", "unknown"),
}}))
ray_tpu.shutdown()
"""


def _run(snippet: str, *, timeout: float, force_cpu: bool = False) -> dict:
    """Run a measurement snippet in a subprocess with a hard budget.

    On timeout the child gets SIGTERM + a 15 s grace before SIGKILL:
    SIGKILLing a process mid-TPU-operation is what wedges the tunnel
    for hours (round-4 postmortem), so it is strictly the last resort.
    """
    env = dict(os.environ)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    if env.get("JAX_PLATFORMS") == "cpu":
        # a wedged accelerator tunnel HANGS jax init rather than raising —
        # even when the platform is pinned to cpu the tunnel plugin's
        # registration can hang — so CPU runs drop it before any import
        env.pop("PALLAS_AXON_POOL_IPS", None)
    elif env.get("BENCH_FAKE_WEDGE"):
        snippet = _FAKE_WEDGE_PRELUDE + snippet
    out, err, timed_out = _communicate(
        [sys.executable, "-c", snippet], env=env, timeout=timeout)
    if timed_out:
        raise RuntimeError(
            f"stage exceeded its {max(timeout, 1.0):.0f}s budget:\n"
            f"{out[-1000:]}\n{err[-1000:]}"
        )
    for line in out.splitlines():
        if line.startswith("BENCH_RESULT "):
            return json.loads(line[len("BENCH_RESULT "):])
    raise RuntimeError(
        f"bench subprocess produced no result:\n{out[-2000:]}\n{err[-2000:]}"
    )


def _communicate(argv: list, *, env: dict, timeout: float):
    """Popen + communicate with SIGTERM-first, SIGKILL-last-resort kill.

    Every chip-owning subprocess must go through this: SIGKILL mid-TPU-op
    is what wedges the tunnel for hours.
    """
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env,
    )
    try:
        out, err = proc.communicate(timeout=max(timeout, 1.0))
        return out, err, False
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            out, err = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        return out, err, True


def _emit(record: dict) -> None:
    """Print the current-best COMPLETE result record and flush.

    Called after every stage; each line is independently parseable so a
    kill at any point leaves a valid result in the output tail.  The
    last line printed is the most complete one.
    """
    print(json.dumps(record), flush=True)


_PROBE_ENV_KEYS = (
    "JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS", "TPU_SKIP_MDS_QUERY",
    "TPU_ACCELERATOR_TYPE", "TPU_WORKER_ID", "TPU_CHIPS_PER_HOST_BOUNDS",
)


def _probe_chip() -> tuple[dict | None, dict]:
    """Tiny-matmul chip probe with a budgeted retry loop.

    The round-5 postmortem lost the flagship TPU number twice to an
    unretried one-shot probe: a transient tunnel error at second 0 sent
    the whole bench to CPU.  Now fast failures retry under decorrelated
    backoff (ray_tpu._private.retry — the same policy the runtime uses)
    inside a budget of ~half the remaining deadline; a wedged tunnel
    (hang, not error) still burns the budget at most once.

    Returns (probe_record_or_None, provenance).  The provenance dict is
    attached to every emitted bench JSON so a fallback record says WHY:
    "no accelerator env" reads very differently from "tunnel wedged
    after 3 attempts".
    """
    from ray_tpu._private import retry

    cap = float(os.environ.get("BENCH_PROBE_BUDGET_S", "90"))
    budget = max(min(cap, _remaining() / 2), 1.0)
    prov: dict = {
        "probe_attempts": 0,
        "probe_budget_s": round(budget, 1),
        "probe_env": {k: os.environ[k] for k in _PROBE_ENV_KEYS if k in os.environ},
    }
    tpu_env = bool(
        prov["probe_env"].get("PALLAS_AXON_POOL_IPS")
        or any(k.startswith("TPU_") for k in prov["probe_env"])
    ) and prov["probe_env"].get("JAX_PLATFORMS") != "cpu"
    bo = retry.BENCH_PROBE.start(deadline_s=budget)
    last_err = ""
    while True:
        prov["probe_attempts"] += 1
        per_try = max(bo.remaining() or budget, 1.0)
        try:
            rec = _run(_PROBE_SNIPPET, timeout=per_try)
            prov["probe_backend"] = rec.get("backend")
            if rec.get("backend") != "tpu":
                prov["fallback_reason"] = (
                    f"probe_backend_{rec.get('backend')}" if tpu_env else "no_tpu_env"
                )
            return rec, prov
        except (RuntimeError, ValueError) as e:
            last_err = str(e)
        delay = bo.next_delay()
        if delay is None:
            break
        time.sleep(delay)
    prov["probe_error_tail"] = last_err[-500:]
    if not tpu_env:
        prov["fallback_reason"] = "no_tpu_env"
    elif "exceeded its" in last_err:
        prov["fallback_reason"] = "tunnel_wedged_probe_timeout"
    else:
        prov["fallback_reason"] = "probe_error"
    return None, prov


def _run_ppo_bench(timeout: float) -> dict:
    """North-star metric #2 (RLlib PPO env-steps/s) via bench_rllib.py in
    its own subprocess (one chip owner at a time); absent on failure so a
    wedged RL bench can't take down the headline number."""
    try:
        out, _err, timed_out = _communicate(
            [sys.executable, "bench_rllib.py"], env=dict(os.environ),
            timeout=timeout)
        if timed_out:
            return {}
        for line in out.splitlines():
            if line.startswith("{"):
                rec = json.loads(line)
                return {
                    "ppo_cartpole_env_steps_per_sec": rec["cartpole"]["env_steps_per_sec"],
                    "ppo_pong_scale_env_steps_per_sec": rec["pong_scale"]["env_steps_per_sec"],
                }
    except Exception:
        pass
    return {}


def _measure(force_cpu: bool, prov: dict | None = None) -> tuple[dict, dict | None]:
    """Framework run first (it IS the headline number), raw second.

    Returns (framework, raw_or_None); emits an interim record as soon as
    the framework number exists — carrying the probe provenance, so even
    a record the driver kills mid-enrichment says why it fell back.
    """
    fw_budget = min(600.0, _remaining() - 240.0) if not force_cpu else min(
        300.0, _remaining() - 90.0)
    fw = _run(_FRAMEWORK_SNIPPET, timeout=fw_budget, force_cpu=force_cpu)
    _emit(_record(fw, None, prov or {}))
    raw = None
    if _remaining() > 90.0:
        try:
            raw = _run(_RAW_SNIPPET, timeout=min(420.0, _remaining() - 60.0),
                       force_cpu=force_cpu)
        except (RuntimeError, ValueError):
            raw = None
    return fw, raw


def _record(fw: dict, raw: dict | None, extra: dict) -> dict:
    per_chip = fw["tok_s_chip"]
    rec = {
        "metric": "gpt2_small_train_tokens_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(per_chip / GPU_BASELINE_TOKENS_PER_SEC, 4),
        # platform provenance first-class in the record header:
        # bench_gate refuses cross-platform comparisons keyed on on_tpu
        # (the r04/r05 "CPU number read as TPU regression" class)
        "on_tpu": fw["on_tpu"],
        "platform": fw.get("platform", "unknown"),
    }
    if raw is not None and raw.get("tok_s_chip"):
        rec["raw_tokens_per_sec_per_chip"] = round(raw["tok_s_chip"], 1)
        rec["framework_overhead_pct"] = round(
            100 * (1.0 - per_chip / raw["tok_s_chip"]), 2)
    rec.update(extra)
    return rec


def main():
    probe, prov = _probe_chip()
    # a present-but-fail-fast tunnel can leave jax on CPU: that is not a
    # chip, and must not be granted TPU-sized budgets or the PPO stage
    chip_ok = probe is not None and probe.get("backend") == "tpu"
    try:
        try:
            fw, raw = _measure(force_cpu=not chip_ok, prov=prov)
        except (RuntimeError, ValueError):
            if not chip_ok:
                raise  # CPU fallback itself failed: nothing honest to report
            # chip probe passed but the big run wedged: fall back to CPU
            chip_ok = False
            prov["fallback_reason"] = "measure_wedged_after_probe_ok"
            fw, raw = _measure(force_cpu=True, prov=prov)
    except (RuntimeError, ValueError) as exc:
        # even total failure must leave a parseable line in the tail
        _emit({
            "metric": "gpt2_small_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,
            "on_tpu": False,
            "platform": "unknown",
            "error": str(exc),
            **prov,
        })
        raise
    extra: dict = dict(prov)
    if probe:
        extra["chip_probe_secs"] = probe["secs"]
    if chip_ok and not os.environ.get("BENCH_SKIP_PPO") and _remaining() > 420.0:
        extra.update(_run_ppo_bench(timeout=_remaining() - 60.0))
    _emit(_record(fw, raw, extra))


if __name__ == "__main__":
    main()
