"""Headline benchmark: GPT-2 training throughput, tokens/sec/chip.

This is the north-star metric from BASELINE.json ("Ray Train GPT-2
tokens/sec/chip").  The reference publishes no TPU numbers
(BASELINE.md: published = {}), so vs_baseline normalizes against the
reference's NCCL/GPU-era equivalent: ~51k tokens/sec/chip for GPT-2-small
with torch DDP on an A100-class device (6*N*tok/s at ~40% MFU of 312
TFLOPs bf16).  A v5e chip (197 TFLOPs bf16) at the same MFU would be
~0.63 of that; vs_baseline > 0.63 therefore means better MFU than the
reference stack.

Two measurements, each in its own subprocess so exactly one process owns
the chip at a time:
  raw       — the jitted train step driven directly (no framework).
  framework — the SAME step inside JaxTrainer.fit() (1-worker group on
              the chip), proving the runtime adds <~3% overhead
              (VERDICT r2 ask #3; reference: train/base_trainer.py fit).

Prints exactly one JSON line; `value` is the in-framework number (the
honest "what a user gets" figure), with the raw number and overhead
attached.  See PERF_ANALYSIS.md for the shape-limited roofline study.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

GPU_BASELINE_TOKENS_PER_SEC = 51000.0

# Shared measurement body: build the sharded GPT-2 train state, warm up,
# time `steps` steps.  Defines tok_s_chip + on_tpu.  Used verbatim by both
# the raw and the in-framework runs so the overhead comparison compares
# exactly the same work.
_MEASURE_BODY = """
import time
import jax
try:
    jax.devices()
except RuntimeError:
    jax.config.update("jax_platforms", "")
import jax.numpy as jnp
import numpy as np
from ray_tpu.models import gpt2
from ray_tpu.parallel import create_mesh

on_tpu = jax.default_backend() == "tpu"
n_dev = len(jax.devices())
if on_tpu:
    cfg = gpt2.GPT2Config(max_seq_len=1024, remat=False)  # fits HBM at 124M/B16/T1024
    B, T, steps = 16, 1024, 30
else:
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    B, T, steps = 4, 64, 3

mesh = create_mesh({"dp": n_dev}, jax.devices())
opt = gpt2.make_adamw(lr=3e-4)
params, opt_state, specs = gpt2.make_sharded_train_state(cfg, mesh, opt)
step = gpt2.make_sharded_train_step(cfg, mesh, opt)
rng = np.random.default_rng(0)
toks = rng.integers(0, cfg.vocab_size, (B, T + 1), dtype=np.int32)
tokens, targets = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
for _ in range(3):
    params, opt_state, loss = step(params, opt_state, tokens, targets)
float(jax.device_get(loss))  # sync: block_until_ready is unreliable on tunneled backends
t0 = time.perf_counter()
for _ in range(steps):
    params, opt_state, loss = step(params, opt_state, tokens, targets)
float(jax.device_get(loss))
dt = time.perf_counter() - t0
tok_s_chip = B * T * steps / dt / n_dev
"""

_RAW_SNIPPET = f"""
import json
{_MEASURE_BODY}
print("BENCH_RESULT " + json.dumps({{"tok_s_chip": tok_s_chip, "on_tpu": on_tpu}}))
"""

_FRAMEWORK_SNIPPET = f"""
import json
import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxTrainer, ScalingConfig

_BODY = {_MEASURE_BODY!r}

def train_loop(config):
    ns = {{}}
    exec(_BODY, ns)
    train.report({{"tok_s_chip": ns["tok_s_chip"], "on_tpu": ns["on_tpu"]}})

ray_tpu.init(num_cpus=4)
result = JaxTrainer(
    train_loop, scaling_config=ScalingConfig(num_workers=1)
).fit()
print("BENCH_RESULT " + json.dumps({{
    "tok_s_chip": result.metrics["tok_s_chip"], "on_tpu": result.metrics["on_tpu"],
}}))
ray_tpu.shutdown()
"""


def _run(snippet: str, force_cpu: bool = False, timeout: int = 1200) -> dict:
    env = dict(os.environ)
    if force_cpu:
        # a wedged accelerator tunnel HANGS jax init rather than raising;
        # the CPU fallback must drop the tunnel plugin before any import
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", snippet],
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        timeout=timeout,
        env=env,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_RESULT "):
            return json.loads(line[len("BENCH_RESULT "):])
    raise RuntimeError(
        f"bench subprocess produced no result (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )


def _run_ppo_bench() -> dict:
    """North-star metric #2 (RLlib PPO env-steps/s) via bench_rllib.py in
    its own subprocess (one chip owner at a time); absent on failure so a
    wedged RL bench can't take down the headline number."""
    try:
        proc = subprocess.run(
            [sys.executable, "bench_rllib.py"],
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=900,
        )
        for line in proc.stdout.splitlines():
            if line.startswith("{"):
                out = json.loads(line)
                return {
                    "ppo_cartpole_env_steps_per_sec": out["cartpole"]["env_steps_per_sec"],
                    "ppo_pong_scale_env_steps_per_sec": out["pong_scale"]["env_steps_per_sec"],
                }
    except Exception:
        pass
    return {}


def main():
    try:
        fw = _run(_FRAMEWORK_SNIPPET)
        raw = _run(_RAW_SNIPPET)
    except (subprocess.TimeoutExpired, RuntimeError):
        # chip unreachable (tunnel wedged): still emit the one JSON line,
        # honestly marked on_tpu=false, from a CPU run of the same step
        fw = _run(_FRAMEWORK_SNIPPET, force_cpu=True, timeout=900)
        raw = _run(_RAW_SNIPPET, force_cpu=True, timeout=900)
        fw["on_tpu"] = raw["on_tpu"] = False
    overhead = 1.0 - fw["tok_s_chip"] / raw["tok_s_chip"] if raw["tok_s_chip"] else 0.0
    per_chip = fw["tok_s_chip"]
    print(
        json.dumps(
            {
                "metric": "gpt2_small_train_tokens_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(per_chip / GPU_BASELINE_TOKENS_PER_SEC, 4),
                "raw_tokens_per_sec_per_chip": round(raw["tok_s_chip"], 1),
                "framework_overhead_pct": round(100 * overhead, 2),
                "on_tpu": fw["on_tpu"],
                **_run_ppo_bench(),
            }
        )
    )


if __name__ == "__main__":
    main()
