"""Core-runtime microbenchmarks (reference: python/ray/_private/ray_perf.py:93).

Measures the framework's task/actor/object hot paths against the reference's
published numbers (BASELINE.md, release/perf_metrics/microbenchmark.json):

    tasks/s single client sync        —
    tasks/s single client async       7,133
    tasks/s multi client async        21,860
    actor calls/s 1:1 sync            —
    actor calls/s 1:1 async           8,671
    actor calls/s n:n async           26,065
    put GB/s single client            16.4
    wait on 1k refs                   —

Run: python bench_micro.py [--out BENCH_micro.json]
Prints one JSON line per metric and writes the aggregate to --out.

Hardware caveats: the reference's numbers come from its release infra
(64-core machines).  On a 1-visible-core CI box the multi-process benches
(multi-client, n:n actors) are context-switch-bound and can't approach the
baseline; single-client async tasks and 1:1 actor calls are the comparable
numbers.  put GB/s is bounded by this box's shm memcpy bandwidth
(~1.2-1.6 GB/s measured raw), not by the framework.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

import ray_tpu


@ray_tpu.remote
def nullary():
    return b"ok"


@ray_tpu.remote
class Sink:
    def ping(self):
        return b"ok"


@ray_tpu.remote
class Client:
    """In-cluster driver for multi-client benchmarks."""

    def run_tasks_async(self, n: int) -> float:
        start = time.perf_counter()
        refs = [nullary.remote() for _ in range(n)]
        ray_tpu.get(refs)
        return time.perf_counter() - start

    def setup_sink(self) -> None:
        self.sink = Sink.remote()
        ray_tpu.get(self.sink.ping.remote())

    def run_actor_async(self, n: int) -> float:
        start = time.perf_counter()
        refs = [self.sink.ping.remote() for _ in range(n)]
        ray_tpu.get(refs)
        return time.perf_counter() - start

    def teardown_sink(self) -> None:
        ray_tpu.kill(self.sink)


def timeit(fn, warmup=1, repeat=3):
    for _ in range(warmup):
        fn()
    best = None
    for _ in range(repeat):
        t = fn()
        best = t if best is None else min(best, t)
    return best


def bench_tasks_sync(n=300) -> float:
    def run():
        start = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(nullary.remote())
        return time.perf_counter() - start

    return n / timeit(run)


def bench_tasks_async(n=2000) -> float:
    def run():
        start = time.perf_counter()
        refs = [nullary.remote() for _ in range(n)]
        ray_tpu.get(refs)
        return time.perf_counter() - start

    return n / timeit(run)


def bench_tasks_multi_client(n_clients=4, n=1000) -> float:
    clients = [Client.remote() for _ in range(n_clients)]
    # steady-state warmup: a burst comparable to the measured one, so
    # worker spawns + lease grants happen BEFORE the timed window (a
    # 10-task warmup leaves the 4x1000 burst spawning workers mid-
    # measurement — the dominant variance source on the 1-core box)
    ray_tpu.get([c.run_tasks_async.remote(200) for c in clients])
    best = None
    for _ in range(2):
        start = time.perf_counter()
        ray_tpu.get([c.run_tasks_async.remote(n) for c in clients])
        elapsed = time.perf_counter() - start
        best = elapsed if best is None else min(best, elapsed)
    for c in clients:
        ray_tpu.kill(c)
    return n_clients * n / best


def bench_actor_sync(n=300) -> float:
    a = Sink.remote()
    ray_tpu.get(a.ping.remote())

    def run():
        start = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(a.ping.remote())
        return time.perf_counter() - start

    out = n / timeit(run)
    ray_tpu.kill(a)
    return out


def bench_actor_async(n=2000) -> float:
    a = Sink.remote()
    ray_tpu.get(a.ping.remote())

    def run():
        start = time.perf_counter()
        refs = [a.ping.remote() for _ in range(n)]
        ray_tpu.get(refs)
        return time.perf_counter() - start

    out = n / timeit(run)
    ray_tpu.kill(a)
    return out


def bench_actor_nn(n_pairs=4, n=1000) -> float:
    """n client actors each driving their own sink actor.  Actors are
    created OUTSIDE the timed region, like the reference's ray_perf
    (actors_async multi: the pairs exist before the measured calls)."""
    clients = [Client.remote() for _ in range(n_pairs)]
    ray_tpu.get([c.setup_sink.remote() for c in clients])
    ray_tpu.get([c.run_actor_async.remote(10) for c in clients])  # warm
    start = time.perf_counter()
    ray_tpu.get([c.run_actor_async.remote(n) for c in clients])
    elapsed = time.perf_counter() - start
    ray_tpu.get([c.teardown_sink.remote() for c in clients])
    for c in clients:
        ray_tpu.kill(c)
    return n_pairs * n / elapsed


def bench_put_gbps(size_mb=256, repeat=3) -> float:
    arr = np.random.default_rng(0).integers(0, 255, size_mb << 20, dtype=np.uint8)
    best = None
    for i in range(repeat + 1):
        start = time.perf_counter()
        ref = ray_tpu.put(arr)
        t = time.perf_counter() - start
        del ref
        # ref release is async (refcount message to the raylet): give it
        # time to land or later puts measure eviction/spill, not memcpy
        time.sleep(0.2)
        if i == 0:
            continue  # warmup: first put populates arena pages
        best = t if best is None else min(best, t)
    return (size_mb / 1024) / best


def bench_put_small(n=1000) -> float:
    def run():
        start = time.perf_counter()
        refs = [ray_tpu.put(i) for i in range(n)]
        del refs
        return time.perf_counter() - start

    return n / timeit(run)


def bench_get_small(n=1000) -> float:
    refs = [ray_tpu.put(i) for i in range(n)]

    def run():
        start = time.perf_counter()
        ray_tpu.get(refs)
        return time.perf_counter() - start

    return n / timeit(run)


@ray_tpu.remote
class EchoActor:
    def echo(self, x):
        return x


def _compile_echo(max_inflight=64, **actor_opts):
    from ray_tpu.dag import InputNode

    cls = EchoActor.options(**actor_opts) if actor_opts else EchoActor
    with InputNode() as inp:
        dag = cls.bind().echo.bind(inp)
    compiled = dag.experimental_compile(max_inflight=max_inflight)
    ray_tpu.get(compiled.execute(0))  # warm: loops resident, channels open
    return compiled


_compiled_lat: list = []  # p50/p99 share one capture with the sync rate


def bench_compiled_actor_sync(n=2000) -> float:
    """Compiled-DAG sync round-trip rate (the like-for-like comparator
    of actor_calls_per_s_1_1_sync: same 1:1 echo, zero-copy dataplane
    instead of the per-call RPC stack).  Also captures per-call latency
    for the p50/p99 entries."""
    compiled = _compile_echo()

    def run():
        _compiled_lat.clear()
        start = time.perf_counter()
        for i in range(n):
            t1 = time.perf_counter()
            ray_tpu.get(compiled.execute(i))
            _compiled_lat.append(time.perf_counter() - t1)
        return time.perf_counter() - start

    out = n / timeit(run)
    compiled.teardown()
    _compiled_lat.sort()
    return out


def bench_compiled_roundtrip_p50_ms() -> float:
    """p50 of the sync capture above (ordering: runs after it)."""
    return _compiled_lat[len(_compiled_lat) // 2] * 1e3 if _compiled_lat else -1.0


def bench_compiled_roundtrip_p99_ms() -> float:
    return (
        _compiled_lat[int(len(_compiled_lat) * 0.99)] * 1e3 if _compiled_lat else -1.0
    )


def bench_compiled_actor_pipelined(n=4000, depth=32) -> float:
    """Compiled executions submitted depth-deep before each get: the
    multi-slot ring carries many in-flight messages per edge, so driver
    serialization overlaps actor compute."""
    compiled = _compile_echo(max_inflight=depth * 2)

    def run():
        start = time.perf_counter()
        refs = []
        for i in range(n):
            refs.append(compiled.execute(i))
            if len(refs) >= depth:
                ray_tpu.get(refs.pop(0))
        for r in refs:
            ray_tpu.get(r)
        return time.perf_counter() - start

    out = n / timeit(run)
    compiled.teardown()
    return out


def bench_execute_many(n=4096, k=64) -> float:
    """Batched submissions: K executions per channel write per edge
    (execute_many), drained batch-by-batch.  The like-for-like single
    comparator is bench_compiled_actor_pipelined at the same depth —
    the `vs_single` stamp below measures exactly the per-message wire
    overhead the batching amortizes (trajectory-fragment / weight-
    broadcast shaped traffic)."""
    compiled = _compile_echo(max_inflight=k * 2)

    def run():
        start = time.perf_counter()
        prev = None
        for base in range(0, n, k):
            refs = compiled.execute_many(list(range(base, base + k)))
            if prev is not None:
                for r in prev:
                    ray_tpu.get(r)
            prev = refs
        for r in prev:
            ray_tpu.get(r)
        return time.perf_counter() - start

    out = n / timeit(run)
    compiled.teardown()
    return out


def bench_compiled_single_depth_k(n=4096, k=64) -> float:
    """The single-execute comparator for bench_execute_many: identical
    pipeline depth and get cadence, one channel write per execution."""
    compiled = _compile_echo(max_inflight=k * 2)

    def run():
        start = time.perf_counter()
        prev = None
        for base in range(0, n, k):
            refs = [compiled.execute(i) for i in range(base, base + k)]
            if prev is not None:
                for r in prev:
                    ray_tpu.get(r)
            prev = refs
        for r in prev:
            ray_tpu.get(r)
        return time.perf_counter() - start

    out = n / timeit(run)
    compiled.teardown()
    return out


def bench_compiled_socket_roundtrip(n=1000) -> dict:
    """Cross-host (separate-raylet) compiled edge: the same echo DAG
    with the actor pinned to a second node, so every hop rides a
    persistent socket channel.  Runs on its OWN 2-node cluster AFTER the
    main single-node benches (returns {calls/s, p50_ms})."""
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2, resources={"edge": 2})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        compiled = _compile_echo(resources={"edge": 0.1})
        assert any(
            d["kind"] == "socket" for d in compiled._descs.values()
        ), "socket edge not selected"
        lat = []
        start = time.perf_counter()
        for i in range(n):
            t1 = time.perf_counter()
            ray_tpu.get(compiled.execute(i))
            lat.append(time.perf_counter() - t1)
        elapsed = time.perf_counter() - start
        compiled.teardown()
        lat.sort()
        return {
            "compiled_socket_calls_per_s": n / elapsed,
            "compiled_socket_roundtrip_p50_ms": lat[len(lat) // 2] * 1e3,
        }
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def _rtt_echo_child(req_path: str, rep_path: str, total: int) -> None:
    """Echo peer of the ring RTT bench: read a value off the request
    ring, write it back on the reply ring.  When the channel layer has
    the trace-propagation API, traced frames are echoed UNDER the
    frame's context so the reply leg is traced too (the full traced
    round trip); untraced frames take the plain path."""
    from ray_tpu.experimental.channel import Channel

    req, rep = Channel(req_path), Channel(rep_path)
    traced_api = hasattr(req, "read_value_traced")
    if traced_api:
        from ray_tpu.util import tracing
    try:
        for _ in range(total):
            if traced_api:
                tag, v, tctx = req.read_value_traced(timeout=60.0)
                if tctx is not None:
                    tok = tracing.set_frame_context(tctx)
                    try:
                        rep.write_value(v, tag)
                    finally:
                        tracing.reset_context(tok)
                else:
                    rep.write_value(v, tag)
            else:
                tag, v = req.read_value(timeout=60.0)
                rep.write_value(v, tag)
    except Exception:
        import traceback

        traceback.print_exc()  # bench infra failure: name it, don't hide it
    finally:
        req.close()
        rep.close()


def _ring_rtt_us(traced: bool, n: int = 5000, warm: int = 200) -> float:
    """Two-process ring-channel round trip in microseconds (the serve /
    DAG dataplane hop shape).  ``traced`` runs the driver side under an
    active trace context, so frames carry the trace trailer and every
    hop records channel spans; untraced is the hot-path guard the
    bench gate holds within noise of HEAD."""
    import multiprocessing
    import os
    import shutil
    import tempfile

    from ray_tpu.experimental.channel import Channel

    runs = 4  # timeit: 1 warmup run + 3 timed repeats
    td = tempfile.mkdtemp(prefix="bench_chan_rtt_")
    try:
        req_path = os.path.join(td, "req")
        rep_path = os.path.join(td, "rep")
        Channel.create_file(req_path, 1 << 20)
        Channel.create_file(rep_path, 1 << 20)
        proc = multiprocessing.Process(
            target=_rtt_echo_child,
            args=(req_path, rep_path, runs * (warm + n)),
            daemon=True,
        )
        proc.start()
        req, rep = Channel(req_path), Channel(rep_path)

        def ping_pong(k: int) -> None:
            for i in range(k):
                req.write_value(i, 0, timeout=60.0)
                rep.read_value(timeout=60.0)

        def run() -> float:
            if traced:
                from ray_tpu.util import tracing

                with tracing.start_span("bench_channel_rtt"):
                    ping_pong(warm)
                    start = time.perf_counter()
                    ping_pong(n)
                    return time.perf_counter() - start
            ping_pong(warm)
            start = time.perf_counter()
            ping_pong(n)
            return time.perf_counter() - start

        best = timeit(run)
        req.close()
        rep.close()
        proc.join(timeout=10)
        if proc.is_alive():
            proc.terminate()
        return best / n * 1e6
    finally:
        shutil.rmtree(td, ignore_errors=True)


def bench_channel_rtt_untraced() -> float:
    return _ring_rtt_us(False)


def bench_channel_rtt_traced() -> float:
    return _ring_rtt_us(True)


def _make_ckpt_src(td: str, n_files: int = 8, file_kb: int = 256) -> str:
    import os

    src = os.path.join(td, "src")
    os.makedirs(src, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n_files):
        with open(os.path.join(src, f"shard_{i}.bin"), "wb") as f:
            f.write(rng.integers(0, 255, file_kb << 10, dtype=np.uint8).tobytes())
    return src


def bench_checkpoint_stall_sync_ms(repeat=5) -> float:
    """Caller-visible stall of one SYNCHRONOUS checkpoint report: the
    full snapshot-commit (per-file tmp+fsync+rename + CRC32 + manifest
    os.replace) of a 2 MiB / 8-shard checkpoint, median of ``repeat``."""
    import os
    import shutil
    import tempfile

    from ray_tpu.train import checkpoint_plane as cp

    td = tempfile.mkdtemp(prefix="bench_ckpt_sync_")
    try:
        src = _make_ckpt_src(td)
        times = []
        for i in range(repeat + 1):
            dest = os.path.join(td, f"checkpoint_{i:06d}")
            t0 = time.perf_counter()
            cp.persist_dir(src, dest, mode="sync")
            t = (time.perf_counter() - t0) * 1e3
            if i:  # first is warmup (page cache, dir creation)
                times.append(t)
        times.sort()
        return times[len(times) // 2]
    finally:
        shutil.rmtree(td, ignore_errors=True)


def bench_checkpoint_stall_async_ms(repeat=5) -> float:
    """Caller-visible stall of one ASYNC checkpoint report in the
    steady state the async writer targets (compute time covers the
    write): submit() hands the same snapshot-commit to the background
    writer and returns after enqueue; the previous write drains during
    the between-reports compute window (modeled by wait() OUTSIDE the
    timed region).  The acceptance gap vs the sync number is the train-
    step stall the async writer buys back."""
    import os
    import shutil
    import tempfile

    from ray_tpu.train import checkpoint_plane as cp

    td = tempfile.mkdtemp(prefix="bench_ckpt_async_")
    writer = cp.AsyncCheckpointWriter(name="bench-ckpt-writer")
    try:
        src = _make_ckpt_src(td)
        times = []
        for i in range(repeat + 1):
            dest = os.path.join(td, f"checkpoint_{i:06d}")
            t0 = time.perf_counter()
            writer.submit(lambda d=dest: cp.persist_dir(src, d, mode="async"))
            t = (time.perf_counter() - t0) * 1e3
            writer.wait()  # the "compute" window: drain outside the stall
            if i:
                times.append(t)
        times.sort()
        return times[len(times) // 2]
    finally:
        writer.close()
        shutil.rmtree(td, ignore_errors=True)


def bench_wait_1k() -> float:
    refs = [nullary.remote() for _ in range(1000)]
    ray_tpu.get(refs)  # all complete

    def run():
        start = time.perf_counter()
        ray_tpu.wait(refs, num_returns=1000, timeout=10)
        return time.perf_counter() - start

    return 1.0 / timeit(run)


BENCHES = [
    # (name, fn, unit, baseline or None)
    ("tasks_per_s_single_client_sync", bench_tasks_sync, "tasks/s", None),
    ("tasks_per_s_single_client_async", bench_tasks_async, "tasks/s", 7133.0),
    ("tasks_per_s_multi_client_async", bench_tasks_multi_client, "tasks/s", 21860.0),
    ("actor_calls_per_s_1_1_sync", bench_actor_sync, "calls/s", None),
    ("actor_calls_per_s_1_1_async", bench_actor_async, "calls/s", 8671.0),
    ("actor_calls_per_s_n_n_async", bench_actor_nn, "calls/s", 26065.0),
    ("put_gb_per_s_single_client", bench_put_gbps, "GB/s", 16.4),
    ("put_small_per_s", bench_put_small, "puts/s", None),
    ("get_small_per_s", bench_get_small, "gets/s", None),
    ("wait_1k_refs_per_s", bench_wait_1k, "waits/s", None),
    # Compiled-DAG fast path (zero-copy dataplane; ROADMAP item 1's
    # >=10x-vs-uncompiled target is stamped as vs_uncompiled below).
    ("compiled_actor_calls_per_s_1_1_sync", bench_compiled_actor_sync, "calls/s", None),
    ("compiled_local_roundtrip_p50_ms", bench_compiled_roundtrip_p50_ms, "ms", None),
    ("compiled_local_roundtrip_p99_ms", bench_compiled_roundtrip_p99_ms, "ms", None),
    ("compiled_actor_calls_per_s_pipelined", bench_compiled_actor_pipelined, "calls/s", None),
    # execute_many (ROADMAP item 1 remainder): K executions per channel
    # write; vs_single stamped against the depth-matched single path.
    ("compiled_calls_per_s_single_depth64", bench_compiled_single_depth_k, "calls/s", None),
    ("compiled_calls_per_s_execute_many_k64", bench_execute_many, "calls/s", None),
    # Dataplane tracing overhead guard (ISSUE 17): ring round trip with
    # and without an active trace context.  The untraced number is the
    # hot-path invariant (bench_gate holds it within noise of HEAD); the
    # traced number prices the trailer + channel spans.
    ("channel_rtt_us_untraced", bench_channel_rtt_untraced, "us", None),
    ("channel_rtt_us_traced", bench_channel_rtt_traced, "us", None),
    # Durable checkpoint plane (ISSUE 16): the train-step stall of one
    # checkpoint report, sync vs the bounded async writer (the async
    # number must sit measurably below the sync one — the stall the
    # background writer buys back; docs/failure_semantics.md).
    ("checkpoint_stall_ms_sync", bench_checkpoint_stall_sync_ms, "ms", None),
    ("checkpoint_stall_ms_async", bench_checkpoint_stall_async_ms, "ms", None),
]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="BENCH_micro.json")
    parser.add_argument("--only", default=None, help="substring filter on bench name")
    args = parser.parse_args()

    ray_tpu.init(num_cpus=8)
    import time as _time

    _time.sleep(5)  # let the arena prefault thread drain before timing
    results = {}
    for name, fn, unit, baseline in BENCHES:
        if args.only and args.only not in name:
            continue
        # capture-time load state (VERDICT r4 weak #2: every published
        # number must carry the conditions it was measured under)
        with open("/proc/loadavg") as f:
            load1m = float(f.read().split()[0])
        value = fn()
        from bench_common import provenance

        rec = {
            "metric": name,
            "value": round(value, 2),
            "unit": unit,
            # platform provenance FIRST-CLASS in every record: bench_gate
            # refuses cross-platform comparisons keyed on this
            **provenance(),
            "loadavg_1m_at_capture": load1m,
        }
        if baseline:
            rec["vs_baseline"] = round(value / baseline, 4)
        results[name] = rec
        print(json.dumps(rec), flush=True)
    ray_tpu.shutdown()

    # like-for-like speedup of the compiled dataplane vs the per-call
    # RPC stack, measured in THIS run on THIS box (acceptance: >=10x)
    sync = results.get("actor_calls_per_s_1_1_sync")
    for compiled_name in (
        "compiled_actor_calls_per_s_1_1_sync",
        "compiled_actor_calls_per_s_pipelined",
    ):
        comp = results.get(compiled_name)
        if comp and sync and sync["value"]:
            comp["vs_uncompiled"] = round(comp["value"] / sync["value"], 2)
            print(json.dumps(comp), flush=True)

    # execute_many vs the depth-matched single-execute path, this run
    single = results.get("compiled_calls_per_s_single_depth64")
    many = results.get("compiled_calls_per_s_execute_many_k64")
    if single and many and single["value"]:
        many["vs_single"] = round(many["value"] / single["value"], 2)
        print(json.dumps(many), flush=True)

    # cross-host socket edge: its own 2-node cluster, after the main one
    if not args.only or "socket" in args.only:
        from bench_common import provenance

        with open("/proc/loadavg") as f:
            load1m = float(f.read().split()[0])
        for name, value in bench_compiled_socket_roundtrip().items():
            rec = {
                "metric": name,
                "value": round(value, 3),
                "unit": "ms" if name.endswith("_ms") else "calls/s",
                **provenance(),
                "loadavg_1m_at_capture": load1m,
            }
            results[name] = rec
            print(json.dumps(rec), flush=True)

    # merge-preserve keys this run didn't produce (stress_* entries come
    # from tests/test_stress.py runs)
    try:
        with open(args.out) as f:
            prev = json.load(f)
    except Exception:
        prev = {}
    prev.update(results)
    with open(args.out, "w") as f:
        json.dump(prev, f, indent=2)


if __name__ == "__main__":
    main()
