"""Sharded-training bench: tokens/s for the GSPMD batch x model layout
vs the pure data-parallel layout, plus the MPMD 2-stage pipeline, on
whatever devices the box has (8 virtual CPU devices on the CI box; a
real TPU slice when present — provenance() stamps which, so bench_gate
can never score a CPU capture against a TPU one).

On CPU the sharded number is a CORRECTNESS-scale capture (tiny model,
collectives over host memory) — the interesting trajectory is
like-for-like across commits, which is exactly what the embedded
``bench_gate.py --compare`` run scores: each capture writes a flat
metric dict (``gate_capture``), and when a previous BENCH_sharded.json
exists its capture is compared against the fresh one at the gate's
threshold, with the verdict recorded in the new record.

    JAX_PLATFORMS=cpu python bench_sharded.py        # writes BENCH_sharded.json
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

BATCH = 16
SEQ = 129  # 128 tokens + 1 shift
STEPS = 8
WARMUP = 2
BEST_OF = 2


def _model_cfg():
    import jax.numpy as jnp

    from ray_tpu.models import gpt2

    return gpt2.GPT2Config(
        vocab_size=512, n_layer=4, n_head=4, d_model=128, max_seq_len=SEQ,
        dtype=jnp.bfloat16, remat=False,
    )


def _tokens_per_s(step_fn, params, opt_state, data) -> tuple:
    import jax

    losses = []
    for i in range(WARMUP):
        params, opt_state, loss = step_fn(
            params, opt_state, data[i][:, :-1], data[i][:, 1:]
        )
    jax.block_until_ready(loss)
    t0 = time.monotonic()
    for i in range(STEPS):
        params, opt_state, loss = step_fn(
            params, opt_state, data[i][:, :-1], data[i][:, 1:]
        )
        losses.append(loss)
    jax.block_until_ready(losses[-1])
    wall = time.monotonic() - t0
    return BATCH * (SEQ - 1) * STEPS / wall, wall


def bench_mpmd() -> dict:
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu
    from ray_tpu.models import gpt2
    from ray_tpu.train.sharding import (
        PipelineConfig,
        PipelinePlane,
        gpt2_pipeline_programs,
    )

    cfg = _model_cfg()
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": jnp.float32})
    data = np.random.default_rng(0).integers(
        0, 512, (WARMUP + STEPS, BATCH, SEQ)
    ).astype(np.int32)

    def data_fn(step):
        toks = data[step % len(data)]
        return toks[:, :-1], toks[:, 1:]

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
    plane = PipelinePlane(
        prog,
        PipelineConfig(
            stages=2, microbatches=4, step_timeout_s=300.0,
            ring_capacity=64 * 1024 * 1024,
        ),
    )
    try:
        plane.start()
        for i in range(WARMUP):
            plane.train_step(*data_fn(i))
        t0 = time.monotonic()
        for i in range(WARMUP, WARMUP + STEPS):
            plane.train_step(*data_fn(i))
        wall = time.monotonic() - t0
        stats = plane.stage_stats()
    finally:
        plane.stop()
        ray_tpu.shutdown()
    return {
        "stages": 2,
        "microbatches": 4,
        "tokens_per_s": round(BATCH * (SEQ - 1) * STEPS / wall, 1),
        "bubble_fraction_per_stage": [
            round(s["bubble_fraction"], 3) for s in stats
        ],
    }


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench_common import provenance

    import ray_tpu.train.sharding as sharding

    dp = _bench_with_config(
        sharding.ShardingConfig(
            mesh=("batch",), mesh_shape={"batch": -1},
            partition_rules=[(r".*", ())],
        )
    )
    try:
        tp = _bench_with_config(
            sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 2})
        )
    except Exception as e:  # noqa: BLE001 — record the failure, not crash
        tp = {"error": f"{type(e).__name__}: {e}"}
    pp = bench_mpmd()

    prov = provenance()
    gate_capture = {
        "tokens_per_s_dp": {"value": dp["tokens_per_s"], **prov},
        "tokens_per_s_sharded": {
            "value": tp.get("tokens_per_s", -1.0), **prov
        },
        "tokens_per_s_pipeline": {"value": pp["tokens_per_s"], **prov},
    }
    record = {
        "metric": "sharded_tokens_per_s",
        "unit": "tokens/s",
        **provenance(),
        "loadavg_1m_at_capture": round(os.getloadavg()[0], 2),
        "data_parallel": dp,
        "gspmd_batch_x_model": tp,
        "mpmd_pipeline": pp,
        "sharded_vs_dp": (
            round(tp["tokens_per_s"] / dp["tokens_per_s"], 3)
            if tp.get("tokens_per_s")
            else None
        ),
        "gate_capture": gate_capture,
    }

    # Like-for-like trajectory: score this capture against the previous
    # checked-in one with the bench gate's own comparator.
    here = os.path.dirname(os.path.abspath(__file__))
    prev_path = os.path.join(here, "BENCH_sharded.json")
    if os.path.exists(prev_path):
        try:
            with open(prev_path) as f:
                prev = json.load(f)
            sys.path.insert(0, os.path.join(here, "scripts"))
            import bench_gate

            if prev.get("platform") == record.get("platform") and prev.get(
                "gate_capture"
            ):
                result = bench_gate.compare_metric_dicts(
                    prev["gate_capture"], gate_capture,
                    bench_gate.DEFAULT_THRESHOLD,
                )
                record["gate_compare_vs_previous"] = {
                    "regressions": result.get("regressions", []),
                    "skips": len(result.get("skips", [])),
                    "ok": len(result.get("ok", [])),
                }
            else:
                record["gate_compare_vs_previous"] = "skipped: platform mismatch"
        except Exception as e:  # noqa: BLE001 — the gate is advisory here
            record["gate_compare_vs_previous"] = f"error: {e}"

    out = json.dumps(record, indent=2)
    print(out)
    with open(prev_path, "w") as f:
        f.write(out + "\n")
    return 0


def _bench_with_config(cfg) -> dict:
    import numpy as np

    import ray_tpu.train.sharding as sharding
    from ray_tpu.models import gpt2

    mcfg = _model_cfg()
    plan = sharding.build_plan(cfg)
    opt = gpt2.make_adamw(1e-3)

    def init(rng):
        import jax.numpy as jnp

        return gpt2.GPT2(mcfg).init(
            rng, jnp.zeros((2, 16), dtype=jnp.int32)
        )["params"]

    data = np.random.default_rng(0).integers(
        0, 512, (WARMUP + STEPS, BATCH, SEQ)
    ).astype(np.int32)
    best = 0.0
    runs = []
    for _ in range(BEST_OF):
        params, opt_state = plan.shard_init(init, opt)
        step = plan.jit_train_step(
            gpt2.make_train_step(mcfg, opt), params, opt_state
        )
        tps, _wall = _tokens_per_s(step, params, opt_state, data)
        runs.append(round(tps, 1))
        best = max(best, tps)
    return {
        "mesh": dict(plan.mesh.shape),
        "tokens_per_s": round(best, 1),
        "runs": runs,
    }


if __name__ == "__main__":
    sys.exit(main())
