"""LLM serving smoke (wired into scripts/verify.sh).

Deploys the tiny GPT-2 config behind serve.run, streams N concurrent
requests (mixed lengths, one explicit mid-stream cancel), and asserts:

- every non-cancelled stream completes with exactly its max_tokens
  tokens and a final done event;
- the KV block pool balances to ZERO afterwards (alloc == free — the
  leak gate);
- the engine actually ran continuous batching (step count well below
  what serial execution would need).

Exit 0 on success; any assertion exits nonzero (verify.sh fails).
"""

import os
import sys
import time

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve import llm

N_STREAMS = 24
MAX_TOKENS = [4 + (i % 12) for i in range(N_STREAMS)]


def main() -> int:
    ray_tpu.init(num_cpus=4)
    try:
        app = llm.build_app(
            llm.LLMConfig(model="tiny", max_batch_size=8, num_blocks=128,
                          block_size=8, name="llm_smoke")
        )
        handle = serve.run(app, name="llm_smoke_app")

        t0 = time.time()
        streams = []
        for i in range(N_STREAMS):
            gen = handle.options(stream=True).generate.remote(
                {"prompt": [1, 2, 3, i], "max_tokens": MAX_TOKENS[i]}
            )
            streams.append({"i": i, "it": iter(gen), "tokens": [], "done": None})

        # one explicit cancel mid-stream: the canceled request must still
        # free its blocks (the leak assertion below covers it)
        cancel_gen = handle.options(stream=True).generate.remote(
            {"prompt": [7, 7], "max_tokens": 120}
        )
        cancel_it = iter(cancel_gen)
        first = next(cancel_it)
        handle.cancel.remote(first["request_id"]).result(timeout=30)
        list(cancel_it)

        open_streams = list(streams)
        deadline = time.time() + 120
        while open_streams and time.time() < deadline:
            for s in list(open_streams):
                try:
                    ev = next(s["it"])
                except StopIteration:
                    open_streams.remove(s)
                    continue
                if "token" in ev:
                    s["tokens"].append(ev["token"])
                if ev.get("done"):
                    s["done"] = ev
        assert not open_streams, f"{len(open_streams)} streams never finished"
        wall = time.time() - t0
        for s in streams:
            assert s["done"] is not None, f"stream {s['i']} had no done event"
            want = MAX_TOKENS[s["i"]]
            assert len(s["tokens"]) == want, (
                f"stream {s['i']}: {len(s['tokens'])} tokens != {want}"
            )

        # KV accounting must balance to zero (completion + cancel paths)
        deadline = time.time() + 20
        while time.time() < deadline:
            st = handle.stats.remote().result(timeout=30)
            if st["kv_blocks_in_use"] == 0 and st["waiting"] == 0:
                break
            time.sleep(0.3)
        assert st["kv_blocks_in_use"] == 0, f"KV LEAK: {st['kv_leak_report']}"
        rep = st["kv_leak_report"]
        assert rep["total_allocs"] == rep["total_frees"] == N_STREAMS + 1, rep

        # continuous batching really batched: serial execution would need
        # ~sum(max_tokens) decode steps; lanes cut that by ~batch width
        total_tokens = sum(MAX_TOKENS)
        assert st["steps"] < total_tokens, (
            f"engine took {st['steps']} steps for {total_tokens} tokens — "
            "lanes never ran concurrently"
        )
        print(
            f"serve_llm_smoke OK: {N_STREAMS} streams + 1 cancel, "
            f"{total_tokens} tokens in {wall:.1f}s "
            f"({total_tokens / wall:.0f} tok/s), {st['steps']} engine steps, "
            "kv blocks balanced to 0"
        )
        return 0
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
