#!/usr/bin/env python
"""Drain smoke: boot a 2-worker-node local cluster with a live actor and
a sole-copy object on one node, drain that node through the GCS, and
assert the proactive recovery plane works end to end —

  * the actor migrates to a live node (restart-elsewhere at drain time),
  * the sole-copy object is re-replicated so its ref survives the kill,
  * util.state and the dashboard /api/nodes both show the
    ALIVE -> DRAINING -> DEAD transition.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/drain_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from urllib import request as urlrequest

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _wait_for(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


def main() -> int:
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import state

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    nodes = [cluster.add_node(num_cpus=2) for _ in range(2)]
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    try:
        worker = ray_tpu._private.worker.get_global_worker()
        url = worker.session_info.get("dashboard_url")

        @ray_tpu.remote(num_cpus=2, max_restarts=1)
        class Keeper:
            def make(self):
                # sole-copy object in THIS node's store
                return ray_tpu.put(np.arange(200_000))

            def home(self):
                return ray_tpu.get_runtime_context().get_node_id()

        keeper = Keeper.remote()
        home = ray_tpu.get(keeper.home.remote(), timeout=60)
        data_ref = ray_tpu.get(keeper.make.remote(), timeout=60)

        # Drain the node hosting the actor (and the object's only copy).
        reply = worker.gcs_client.call(
            "drain_node",
            {"node_id": bytes.fromhex(home), "reason": "PREEMPTION", "deadline_s": 20},
        )
        assert reply and reply.get("accepted"), reply

        def node_state(source):
            return {n["node_id"]: n for n in source}.get(home, {})

        # state API and dashboard both observe DRAINING.
        _wait_for(
            lambda: node_state(state.list_nodes()).get("state") == "DRAINING",
            15, "util.state DRAINING",
        )
        if url:
            with urlrequest.urlopen(url + "/api/nodes", timeout=10) as r:
                api_nodes = json.loads(r.read())
            assert node_state(api_nodes).get("state") == "DRAINING", api_nodes
            assert node_state(api_nodes).get("drain_reason") == "PREEMPTION"

        # Actor migrates off the draining node and answers again.
        def migrated():
            acts = state.list_actors([("state", "=", "ALIVE")])
            return any(
                a["class_name"].endswith("Keeper") and a["node_id"] != home
                for a in acts
            )

        _wait_for(migrated, 30, "actor migration off the draining node")
        new_home = ray_tpu.get(keeper.home.remote(), timeout=60)
        assert new_home != home, "actor still on the draining node"

        # Migration completes (objects replicated) before the kill.
        _wait_for(
            lambda: node_state(state.list_nodes()).get("drain_complete"),
            30, "drain_complete",
        )

        # Kill the node at its "deadline"; DRAINING -> DEAD.
        victim = next(
            h for h in nodes
            if node_state(state.list_nodes()).get("raylet_address") == h.raylet_address
        )
        cluster.remove_node(victim)
        _wait_for(
            lambda: node_state(state.list_nodes()).get("state") == "DEAD",
            30, "DEAD after kill",
        )

        # The pre-replicated object survives with no lineage repair.
        arr = ray_tpu.get(data_ref, timeout=60)
        assert int(arr.sum()) == 19999900000

        print(
            f"drain smoke: OK (actor {home[:8]} -> {new_home[:8]}, "
            "object survived the node kill, DRAINING->DEAD visible in "
            "state API and /api/nodes)"
        )
        return 0
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


if __name__ == "__main__":
    sys.exit(main())
