"""Podracer RLlib smoke (verify.sh): 2 streaming env runners + a local
learner over REAL channels, fixed seed, reward parity vs the
synchronous path on CartPole.

Asserts, end to end:
  1. the streaming plane engages (fragments flow over ring channels,
     weight generations advance, zero runner deaths);
  2. the synchronous PPO baseline learns CartPole within the budget;
  3. the async streaming path (in-jit GAE, staleness-bounded weight
     lag) reaches reward parity with it;
  4. the IMPALA-style fully-async config clears the same learning bar
     (the ISSUE 12 acceptance criterion).

Skippable via RAY_TPU_SKIP_RLLIB_SMOKE=1 (wired in scripts/verify.sh).
"""

from __future__ import annotations

import os
import sys

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


TARGET = 100.0  # CartPole: random play sits near ~22
PARITY = 0.6  # async best must reach this fraction of the sync best


def _train_until(algo, bar: float, max_iters: int) -> float:
    best = 0.0
    for _ in range(max_iters):
        out = algo.train()
        r = out.get("episode_return_mean")
        if r:
            best = max(best, r)
        if best >= bar:
            break
    return best


def main() -> int:
    import ray_tpu
    from ray_tpu.rllib import IMPALAConfig, PPOConfig

    ray_tpu.init(num_cpus=4)

    def ppo_cfg():
        return (
            PPOConfig()
            .environment("CartPole-v1")
            .env_runners(
                num_env_runners=2,
                num_envs_per_env_runner=4,
                rollout_fragment_length=64,
            )
            .training(
                lr=3e-4,
                train_batch_size=1024,
                minibatch_size=128,
                num_epochs=6,
                entropy_coeff=0.01,
            )
            .debugging(seed=7)
        )

    # ① synchronous baseline (inline runner — the pre-podracer path)
    sync = ppo_cfg().env_runners(num_env_runners=0).build()
    sync_best = _train_until(sync, TARGET, 30)
    sync.cleanup()
    assert sync_best > 60, f"sync PPO failed to learn: best={sync_best}"

    # ② the same config on the podracer streaming plane
    algo = ppo_cfg().podracer().build()
    pod_best = _train_until(algo, TARGET, 30)
    plane, drv = algo.env_runner_group, algo._podracer
    frags = plane.fragments_received
    gens = drv.generation
    deaths = plane.runner_deaths
    kinds = {rs.traj.kind for rs in plane.streams if rs.alive}
    algo.cleanup()
    assert frags > 10, f"no streaming: {frags} fragments"
    assert gens > 5, f"weight generations never advanced: {gens}"
    assert deaths == 0, f"{deaths} runner deaths during smoke"
    assert kinds == {"ring"}, f"expected ring transport, got {kinds}"
    assert pod_best >= PARITY * sync_best, (
        f"streaming PPO not at parity: sync={sync_best:.1f} "
        f"podracer={pod_best:.1f}"
    )

    # ③ the fully-async IMPALA-style config clears the same bar
    impala = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4)
        .podracer()
        .training(lr=5e-4, entropy_coeff=0.01, rollout_fragment_length=64)
        .debugging(seed=7)
        .build()
    )
    impala_bar = 0.5 * sync_best  # off-policy V-trace ramps slower than PPO
    impala_best = _train_until(impala, impala_bar, 120)
    impala.cleanup()
    assert impala_best >= impala_bar, (
        f"IMPALA-async not at parity: sync={sync_best:.1f} "
        f"impala={impala_best:.1f}"
    )

    ray_tpu.shutdown()
    print(
        "RLLIB ASYNC SMOKE PASS "
        f"sync_best={sync_best:.1f} podracer_best={pod_best:.1f} "
        f"impala_best={impala_best:.1f} fragments={frags} generations={gens}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
