#!/usr/bin/env python
"""Elastic training smoke: boot a 2-worker-node local cluster, run an
elastic JaxTrainer (num_workers=2, min_workers=1), preempt one rank's
node mid-run through the GCS drain plane, and assert the elastic plane
works end to end —

  * the group shrinks to 1 (>= min_workers): only the affected rank is
    torn down, the survivor keeps its actor,
  * training resumes from the drain checkpoint and completes with the
    deterministic final loss (parity with an uninterrupted run),
  * nothing is charged to FailureConfig.max_failures (budget is ZERO),
  * the resize is visible: train_resize_events_total in the local
    metrics registry and a train.resize span in the span log.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/elastic_smoke.py
"""

from __future__ import annotations

import os
import sys
import threading
import time

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TOTAL_STEPS = 16


def _loop(config):
    import ray_tpu
    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    ctx = train.get_context()
    resume = train.get_checkpoint()
    start = resume.to_pytree()["step"] if resume is not None else 0
    node_id = ray_tpu.get_runtime_context().get_node_id()
    for step in range(start + 1, config["total_steps"] + 1):
        time.sleep(0.2)
        ckpt = None
        if ctx.get_world_rank() == 0 or ctx.drain_requested():
            ckpt = Checkpoint.from_pytree({"step": step})
        path = os.path.join(config["progress_dir"], f"rank_{ctx.get_world_rank()}")
        with open(path, "w") as f:
            f.write(f"{node_id} {step} {ctx.get_world_size()} {ctx.get_generation()}")
        train.report(
            {
                "step": step,
                "loss": 1.0 / step,
                "world_size": ctx.get_world_size(),
                "generation": ctx.get_generation(),
            },
            checkpoint=ckpt,
        )


def main() -> int:
    import tempfile

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    for _ in range(2):
        cluster.add_node(num_cpus=2)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    workdir = tempfile.mkdtemp(prefix="elastic_smoke_")
    progress_dir = os.path.join(workdir, "progress")
    os.makedirs(progress_dir, exist_ok=True)
    try:
        from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
        from ray_tpu.train.jax import JaxConfig, JaxTrainer

        worker = ray_tpu._private.worker.get_global_worker()
        stop = threading.Event()
        drained = []

        def drainer():
            # Preempt rank 1's node once it passes step 4.
            while not stop.is_set():
                path = os.path.join(progress_dir, "rank_1")
                try:
                    with open(path) as f:
                        node_id, step, _w, _g = f.read().split()
                except (OSError, ValueError):
                    time.sleep(0.1)
                    continue
                if int(step) >= 4:
                    worker.gcs_client.call(
                        "drain_node",
                        {
                            "node_id": bytes.fromhex(node_id),
                            "reason": "PREEMPTION",
                            "deadline_s": 60,
                        },
                    )
                    drained.append(node_id)
                    return
                time.sleep(0.1)

        t = threading.Thread(target=drainer, daemon=True)
        t.start()
        trainer = JaxTrainer(
            _loop,
            train_loop_config={
                "total_steps": TOTAL_STEPS,
                "progress_dir": progress_dir,
            },
            jax_config=JaxConfig(distributed=False),
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1, resources_per_worker={"CPU": 2}
            ),
            run_config=RunConfig(
                name="elastic_smoke",
                storage_path=workdir,
                failure_config=FailureConfig(max_failures=0),
            ),
        )
        result = trainer.fit()
        stop.set()
        t.join(timeout=5)

        assert drained, "drill never preempted a node"
        assert result.metrics["step"] == TOTAL_STEPS, result.metrics
        assert result.metrics["loss"] == 1.0 / TOTAL_STEPS, result.metrics
        assert result.metrics["world_size"] == 1, result.metrics
        assert result.metrics["generation"] >= 1, result.metrics

        from ray_tpu.util import metrics as metrics_mod
        from ray_tpu.util import tracing

        shrinks = sum(
            rec.get("value", 0.0)
            for (name, tags), rec in metrics_mod._registry.items()
            if name == "train_resize_events_total"
            and ("direction", "shrink") in tuple(tags)
        )
        assert shrinks >= 1, "train_resize_events_total{shrink} never incremented"
        # PR 4 follow-up: the shrink must have PUBLISHED a grow intent to
        # the autoscaler feed (and the finished run must have cleared it).
        hint_actions = {
            dict(tags).get("action")
            for (name, tags), rec in metrics_mod._registry.items()
            if name == "train_grow_hints_total" and rec.get("value", 0.0) > 0
        }
        assert "publish" in hint_actions, (
            "shrunken trainer never published a grow hint"
        )
        hints_after = worker.gcs_client.call("get_load_metrics")["grow_hints"]
        assert hints_after == [], f"grow hint not cleared at shutdown: {hints_after}"
        span_names = [s.get("name") for s in tracing._finished_spans]
        assert "train.resize" in span_names, "no train.resize span recorded"

        # ...and end-to-end: the resize span reaches the cluster timeline
        # (span flusher -> GCS span table -> state.timeline merge).
        import json

        from ray_tpu.util import state

        tracing.flush()
        trace = json.loads(state.timeline())
        events = trace["traceEvents"] if isinstance(trace, dict) else trace
        resize_events = [
            e for e in events if e.get("name") == "train.resize"
        ]
        assert resize_events, "train.resize span missing from state.timeline()"
        args = resize_events[0].get("args", {})
        assert args.get("direction") == "shrink", args

        print(
            f"elastic smoke: OK (preempted node {drained[0][:8]}, group "
            f"2 -> {result.metrics['world_size']} at generation "
            f"{result.metrics['generation']}, finished step "
            f"{result.metrics['step']} with loss parity, zero failure "
            "charges, resize event + span recorded)"
        )
        return 0
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


if __name__ == "__main__":
    sys.exit(main())
