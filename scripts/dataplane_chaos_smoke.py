#!/usr/bin/env python
"""Dataplane chaos smoke (wired into scripts/verify.sh).

End-to-end proof that the self-healing dataplane heals: a compiled DAG
with a cross-raylet socket edge AND a serve deployment doing calls +
token streams run under a seeded ``chan:*`` chaos spec — a mid-frame
torn write and an abrupt socket drop on every socket writer, plus a
chaos close of the serve request ring — and EVERY result must still be
exact:

- the socket faults heal by epoch reattach + seq replay (writer
  re-dials with the pairing token, unacked frames replayed, duplicates
  dropped by seq — nothing lost, duplicated, or reordered),
- the serve ring close falls back to the RPC path for that call and the
  dataplane lazily re-attaches for the next one,
- teardown + serve shutdown reclaim every shm ring dir (zero leaked
  tmpfs), and the injected schedule is seeded and replayable.

Typed-error surfaces (corrupt frames, dead peers) are drilled in tier-1
(tests/test_dataplane_chaos.py); this smoke pins the zero-loss paths.
"""

import glob
import os
import sys

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAOS_SPEC = (
    "chan:socket:*:torn_write:at=3,"
    "chan:socket:*:close:at=8,"
    "chan:*ray_tpu_serve_*/req:close:at=6"
)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # Seeded chaos BEFORE any cluster process spawns: every worker
    # inherits the same replayable schedule (per-process ordinals).
    os.environ["RAY_TPU_testing_chaos_spec"] = CHAOS_SPEC
    os.environ["RAY_TPU_testing_chaos_seed"] = "14"
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private.chaos import CHAOS
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode
    from ray_tpu.experimental.channel import ring_base_dir

    CHAOS.reset()
    rings_before = set(glob.glob(os.path.join(ring_base_dir(), "ray_tpu_*")))

    c = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 4, "resources": {"head": 4}},
    )
    c.add_node(num_cpus=2, resources={"edge": 2})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        # -- compiled DAG over a socket edge, healed mid-stream --------
        @ray_tpu.remote(resources={"edge": 0.1})
        class Far:
            def step(self, x):
                return x * 3 + 7

        with InputNode() as inp:
            dag = Far.bind().step.bind(inp)
        compiled = dag.experimental_compile(max_inflight=4)
        assert compiled._channels_on, "graph fell back to the task path"
        kinds = {d["kind"] for d in compiled._descs.values()}
        assert "socket" in kinds, f"no socket edge selected: {kinds}"
        for i in range(60):
            out = ray_tpu.get(compiled.execute(i), timeout=30)
            assert out == i * 3 + 7, (i, out)
        # the faults really fired and really healed: at least one
        # driver-side endpoint lived through an epoch bump
        epochs = [compiled._driver_in[0][0].epoch, compiled._driver_out[0].epoch]
        assert max(epochs) >= 2, f"chaos never hit a socket edge: {epochs}"
        compiled.teardown()

        # -- serve calls + token streams over the channel plane --------
        # pinned to the head node: router and replica co-located, so the
        # serve channels are shm rings and the ring-close rule applies
        @serve.deployment(name="SmokeDep", ray_actor_options={"resources": {"head": 0.1}})
        class SmokeDep:
            def __call__(self, payload):
                return {"echo": payload}

            def tokens(self, n):
                for i in range(n):
                    yield {"tok": i}

        h = serve.run(SmokeDep.bind(), name="chaos_smoke")
        from ray_tpu.serve._private.dataplane import ChannelClient
        from ray_tpu.serve._private.router import _routers

        assert h.remote(0).result(timeout=30) == {"echo": 0}
        router = _routers[h.deployment_name]
        assert any(
            isinstance(v, ChannelClient) for v in router._dataplanes.values()
        ), "serve dataplane never attached — smoke is vacuous"
        # the chaos close lands mid-sequence; its call falls back to the
        # RPC path with the exact result, the next re-attaches lazily
        for i in range(1, 12):
            assert h.remote(i).result(timeout=30) == {"echo": i}, i
        for _ in range(3):
            toks = list(h.options(stream=True).tokens.remote(8))
            assert toks == [{"tok": i} for i in range(8)], toks
        serve.shutdown()

        fired = sum(1 for e in CHAOS.schedule if ":fire" in e or "fire" in e)
        assert fired > 0, "driver-side chaos schedule is empty — nothing drilled"

        # -- zero leaked shm -------------------------------------------
        rings_after = set(glob.glob(os.path.join(ring_base_dir(), "ray_tpu_*")))
        leaked = rings_after - rings_before
        assert not leaked, f"leaked shm ring dirs: {sorted(leaked)}"
        print(
            f"dataplane_chaos_smoke ok: 60 DAG executions + 12 serve calls + "
            f"3 token streams exact under seeded chaos "
            f"({fired} driver-side injections, epochs {epochs}), zero leaked shm"
        )
        return 0
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        os.environ.pop("RAY_TPU_testing_chaos_spec", None)
        os.environ.pop("RAY_TPU_testing_chaos_seed", None)


if __name__ == "__main__":
    sys.exit(main())
