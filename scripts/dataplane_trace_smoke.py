#!/usr/bin/env python
"""Dataplane trace smoke: on a two-raylet cluster, drive one traced
serve call over the channel dataplane and one traced compiled-DAG
execution across a socket edge, then assert both come back as SINGLE
connected traces — every span's parent resolves inside its trace
(orphan-span count 0) and each trace spans at least two processes.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/dataplane_trace_smoke.py
"""

from __future__ import annotations

import os
import sys
import time

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _orphans(group):
    ids = {s["span_id"] for s in group}
    return [
        s for s in group
        if s.get("parent_span_id") and s["parent_span_id"] not in ids
    ]


def _wait_connected(trace_id, want_names, deadline_s=45.0):
    """Spans ship on the ~1 s flusher cadence from every process: poll
    until the trace has all of ``want_names`` and zero orphans."""
    from ray_tpu.util import state

    group, names = [], set()
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        group = [s for s in state.spans() if s.get("trace_id") == trace_id]
        names = {s.get("name") for s in group}
        if want_names <= names and not _orphans(group):
            return group
        time.sleep(0.5)
    raise AssertionError(
        f"trace {trace_id}: wanted {sorted(want_names)}, have {sorted(names)}, "
        f"orphans {[(s['name'], s['parent_span_id']) for s in _orphans(group)]}"
    )


def main() -> int:
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode
    from ray_tpu.util import tracing

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=2, resources={"edge": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        # --- serve call over the channel dataplane -------------------
        @serve.deployment(name="TraceSmokeDep")
        class TraceSmokeDep:
            def __call__(self, x):
                return x * 10

        h = serve.run(TraceSmokeDep.bind(), name="trace_smoke_app")
        assert h.remote(1).result(timeout=60) == 10  # attach + warm
        with tracing.start_span("smoke.serve") as serve_root:
            assert h.remote(7).result(timeout=60) == 70

        serve_group = _wait_connected(
            serve_root.trace_id,
            {"smoke.serve", "serve.router", "channel.write", "channel.read"},
        )
        serve_pids = {s.get("pid") for s in serve_group}
        if len(serve_pids) < 2:
            print(f"dataplane trace smoke: FAIL (serve trace pids={serve_pids})")
            return 1

        # --- compiled-DAG execution across a socket edge -------------
        @ray_tpu.remote(resources={"edge": 0.1})
        class Far:
            def step(self, x):
                return x + 1000

        far = Far.bind()
        with InputNode() as inp:
            dag = far.step.bind(inp)
        compiled = dag.experimental_compile(max_inflight=4)
        try:
            assert "socket" in {d["kind"] for d in compiled._descs.values()}
            assert ray_tpu.get(compiled.execute(0), timeout=60) == 1000  # warm
            with tracing.start_span("smoke.dag") as dag_root:
                assert ray_tpu.get(compiled.execute(5), timeout=60) == 1005

            dag_group = _wait_connected(
                dag_root.trace_id,
                {"smoke.dag", "channel.write", "channel.read", "dag.op"},
            )
            dag_pids = {s.get("pid") for s in dag_group}
            if len(dag_pids) < 2:
                print(f"dataplane trace smoke: FAIL (dag trace pids={dag_pids})")
                return 1
            kinds = {
                (s.get("attributes") or {}).get("kind")
                for s in dag_group if s.get("name", "").startswith("channel.")
            }
            if "socket" not in kinds:
                print(f"dataplane trace smoke: FAIL (no socket hop traced: {kinds})")
                return 1
        finally:
            compiled.teardown()

        orphan_count = len(_orphans(serve_group)) + len(_orphans(dag_group))
        if orphan_count:
            print(f"dataplane trace smoke: FAIL (orphan spans: {orphan_count})")
            return 1
        print(
            "dataplane trace smoke: OK "
            f"(serve trace {len(serve_group)} spans/{len(serve_pids)} pids, "
            f"dag trace {len(dag_group)} spans/{len(dag_pids)} pids, 0 orphans)"
        )
        return 0
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
