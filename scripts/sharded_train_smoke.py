#!/usr/bin/env python
"""Sharded training smoke: both halves of the train/sharding plane on
CPU devices (8 virtual devices via the XLA host-platform override) —

  GSPMD half:
  * a batch x model (4x2) mesh trains tiny GPT-2 with LOSS PARITY vs
    the pure data-parallel layout (same seed/data),
  * a per-shard checkpoint saved on the model=2 mesh restores onto a
    model=4 mesh bit-exact (the elastic resize path);

  MPMD half:
  * a 2-stage pipeline (stage actors over real shm-ring channels, 1F1B,
    fan-out weight broadcast) matches the single-process loss to
    fixed-seed parity over 3 steps,
  * per-stage busy/bubble stats are recorded.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/sharded_train_smoke.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def _gspmd_half() -> str:
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu.train.sharding as sharding
    from ray_tpu.models import gpt2

    jax.config.update("jax_platforms", "cpu")
    cfg = gpt2.GPT2Config(
        vocab_size=256, n_layer=2, n_head=2, d_model=64, max_seq_len=64,
        dtype=jnp.float32, remat=False,
    )

    def init(rng):
        return gpt2.GPT2(cfg).init(
            rng, jnp.zeros((2, 16), dtype=jnp.int32)
        )["params"]

    data = np.random.default_rng(0).integers(
        0, 256, (3, 8, 17)
    ).astype(np.int32)

    def run(plan):
        opt = gpt2.make_adamw(1e-3)
        params, opt_state = plan.shard_init(init, opt)
        step = plan.jit_train_step(
            gpt2.make_train_step(cfg, opt), params, opt_state
        )
        losses = []
        for toks in data:
            params, opt_state, loss = step(
                params, opt_state, toks[:, :-1], toks[:, 1:]
            )
            losses.append(float(loss))
        return params, opt_state, losses

    plan_tp = sharding.build_plan(
        sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 2})
    )
    assert dict(plan_tp.mesh.shape) == {"batch": 4, "model": 2}
    params, opt_state, losses_tp = run(plan_tp)
    plan_dp = sharding.build_plan(
        sharding.ShardingConfig(
            mesh=("batch",), mesh_shape={"batch": 8},
            partition_rules=[(r".*", ())],
        )
    )
    _, _, losses_dp = run(plan_dp)
    err = max(abs(a - b) for a, b in zip(losses_tp, losses_dp))
    assert err < 1e-4, (losses_tp, losses_dp)

    # per-shard checkpoint -> restore onto a RESIZED mesh, bit-exact
    ckpt_dir = tempfile.mkdtemp(prefix="sharded_smoke_ckpt_")
    plan_tp.save_checkpoint({"params": params}, ckpt_dir)
    plan_wide = sharding.build_plan(
        sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 4})
    )
    like, _ = plan_wide.shard_init(init, gpt2.make_adamw(1e-3))
    restored = plan_wide.load_checkpoint(ckpt_dir, {"params": like})
    for a, b in zip(
        jax.tree_util.tree_leaves(restored["params"]),
        jax.tree_util.tree_leaves(params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    return (
        f"gspmd 4x2 parity err {err:.2e}, reshard 2->4 exact, "
        f"final loss {losses_tp[-1]:.4f}"
    )


def _mpmd_half() -> str:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu
    from ray_tpu.models import gpt2
    from ray_tpu.train.sharding import (
        PipelineConfig,
        PipelinePlane,
        gpt2_pipeline_programs,
    )

    cfg = gpt2.GPT2Config(
        vocab_size=128, n_layer=2, n_head=2, d_model=32, max_seq_len=32,
        dtype=jnp.float32, remat=False,
    )
    data = np.random.default_rng(1).integers(
        0, 128, (3, 4, 17)
    ).astype(np.int32)

    def data_fn(step):
        toks = data[step]
        return toks[:, :-1], toks[:, 1:]

    prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
    plane = PipelinePlane(
        prog, PipelineConfig(stages=2, microbatches=2, step_timeout_s=120.0)
    )
    try:
        losses = plane.run(data_fn, 3)
        stats = plane.stage_stats()
    finally:
        plane.stop()

    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    opt = gpt2.make_adamw(1e-3)
    opt_state = opt.init(params)
    step_fn = jax.jit(gpt2.make_train_step(cfg, opt))
    ref = []
    for s in range(3):
        toks, tgts = data_fn(s)
        params, opt_state, loss = step_fn(
            params, opt_state, jnp.asarray(toks), jnp.asarray(tgts)
        )
        ref.append(float(loss))
    err = max(abs(a - b) for a, b in zip(losses, ref))
    assert err < 2e-5, (losses, ref)
    assert all(s["steps"] == 3 and s["busy_s"] > 0 for s in stats), stats
    bubbles = [round(s["bubble_fraction"], 3) for s in stats]
    return f"mpmd 2-stage parity err {err:.2e}, bubbles {bubbles}"


def main() -> int:
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        gspmd_msg = _gspmd_half()
        mpmd_msg = _mpmd_half()
        print(f"sharded train smoke: OK ({gspmd_msg}; {mpmd_msg})")
        return 0
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
