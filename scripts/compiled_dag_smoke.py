#!/usr/bin/env python
"""Compiled-DAG dataplane smoke (wired into scripts/verify.sh).

End-to-end over a 2-raylet local cluster: compile a 3-actor fan-out
graph where one branch lives on the second raylet (so one edge rides a
persistent socket channel and the rest ride shm rings), then assert

- exact results across 200 executions (both branches, fan-in order),
- the socket transport was really selected for the remote branch,
- local round-trip p50 under 1 ms on a multicore box (the acceptance
  bound; relaxed to 10 ms on 1-2 core CI where the ring degrades to
  sched_yield handoffs — ROADMAP environment note),
- teardown unblocks every resident loop and reclaims tmpfs.
"""

import os
import sys
import time

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode, MultiOutputNode

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=2, resources={"edge": 2})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        @ray_tpu.remote
        class Pre:
            def step(self, x):
                return x + 1

        @ray_tpu.remote
        class LocalBranch:
            def double(self, x):
                return x * 2

        @ray_tpu.remote(resources={"edge": 0.1})
        class RemoteBranch:
            def square(self, x):
                return x * x

        pre = Pre.bind()
        with InputNode() as inp:
            mid = pre.step.bind(inp)
            dag = MultiOutputNode(
                [LocalBranch.bind().double.bind(mid),
                 RemoteBranch.bind().square.bind(mid)]
            )
        compiled = dag.experimental_compile(max_inflight=16)
        assert compiled._channels_on, "graph fell back to the task path"
        kinds = {d["kind"] for d in compiled._descs.values()}
        assert "socket" in kinds, f"no socket edge selected: {kinds}"
        assert "ring" in kinds, f"no ring edge selected: {kinds}"

        ray_tpu.get(compiled.execute(0))  # warm: loops resident
        lat = []
        for i in range(200):
            t0 = time.perf_counter()
            out = ray_tpu.get(compiled.execute(i))
            lat.append(time.perf_counter() - t0)
            assert out == [(i + 1) * 2, (i + 1) ** 2], (i, out)
        lat.sort()
        p50 = lat[len(lat) // 2]
        bound = 0.001 if (os.cpu_count() or 1) > 2 else 0.010
        # NOTE: the fan-out p50 includes the socket branch round-trip;
        # this is the graph-level bound, not the ring-only one.
        assert p50 < bound * 5, f"fan-out round-trip p50 {p50 * 1e3:.2f} ms"

        # ring-only p50 must be sub-ms on multicore (acceptance bound)
        with InputNode() as inp:
            ldag = LocalBranch.bind().double.bind(inp)
        lcompiled = ldag.experimental_compile()
        ray_tpu.get(lcompiled.execute(0))
        llat = []
        for i in range(200):
            t0 = time.perf_counter()
            assert ray_tpu.get(lcompiled.execute(i)) == i * 2
            llat.append(time.perf_counter() - t0)
        llat.sort()
        lp50 = llat[len(llat) // 2]
        assert lp50 < bound, f"local round-trip p50 {lp50 * 1e3:.3f} ms >= {bound * 1e3} ms"

        stats = compiled.stats()
        assert stats["executions"] == 201 and stats["inflight"] == 0
        chan_dir = compiled._chan_dir
        compiled.teardown()
        lcompiled.teardown()
        assert not os.path.exists(chan_dir), "tmpfs ring dir leaked"
        print(
            f"compiled_dag_smoke ok: fan-out p50 {p50 * 1e3:.2f} ms, "
            f"local p50 {lp50 * 1e3:.3f} ms, socket+ring edges exact over 200 runs"
        )
        return 0
    finally:
        ray_tpu.shutdown()
        c.shutdown()


if __name__ == "__main__":
    sys.exit(main())
