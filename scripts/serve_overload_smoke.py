"""Serving overload smoke (wired into scripts/verify.sh).

Two tenants over real HTTP through the proxy: a hostile tenant floods
one-shot completions at many times its token-rate quota while a victim
tenant runs interactive token streams.  Asserts the overload armor
end-to-end (docs/serving.md "Overload resilience"):

- the hostile tenant is throttled with 429 + Retry-After at the proxy
  and EVERY quota shed is attributed to it — the victim is never shed;
- the victim's streams all complete and its TTFT stays bounded while
  the flood runs (tenant isolation, not shared-fate queueing);
- the KV block pool balances to ZERO afterwards (flood + streams +
  refunds leak nothing).

Exit 0 on success; any assertion exits nonzero (verify.sh fails).
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve import llm

PORT = 18131
VICTIM_STREAMS = 8
VICTIM_TTFT_BOUND_S = 30.0  # generous for the 1-core CI box


def _post(path, payload, headers=None, timeout=60.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{PORT}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def main() -> int:
    ray_tpu.init(num_cpus=4)
    try:
        cfg = llm.LLMConfig(
            model="tiny", max_batch_size=4, num_blocks=128, block_size=8,
            name="llm_overload", temperature=0.0, preempt_wait_s=0.1,
            tenant_weights={"hostile": 1.0, "victim": 1.0},
            tenant_quotas={
                "hostile": {"rate": 20, "burst": 40},
                "victim": {"rate": 1e6, "burst": 1e6},
            },
        )
        handle = serve.run(llm.build_app(cfg), name="llm_overload_app",
                           http_port=PORT)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{PORT}/-/routes", timeout=5
                ) as r:
                    if "/llm_overload" in json.loads(r.read()):
                        break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.3)

        stop = threading.Event()
        hostile = {"sent": 0, "ok": 0, "throttled": 0, "other": 0}

        def hostile_flood():
            while not stop.is_set():
                hostile["sent"] += 1
                status, _ = _post(
                    "/llm_overload",
                    {"prompt": "h" * 16, "max_tokens": 16},
                    headers={"x-serve-tenant": "hostile",
                             "x-serve-slo": "batch"},
                    timeout=30,
                )
                if status == 200:
                    hostile["ok"] += 1
                elif status == 429:
                    hostile["throttled"] += 1
                else:
                    hostile["other"] += 1

        floods = [threading.Thread(target=hostile_flood, daemon=True)
                  for _ in range(3)]
        for t in floods:
            t.start()

        ttfts = []
        try:
            for i in range(VICTIM_STREAMS):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{PORT}/llm_overload",
                    data=json.dumps(
                        {"prompt": [1, 2, i], "max_tokens": 8}
                    ).encode(),
                    headers={"Content-Type": "application/json",
                             "x-serve-stream": "1",
                             "x-serve-tenant": "victim",
                             "x-serve-slo": "interactive"},
                )
                t0 = time.time()
                with urllib.request.urlopen(req, timeout=60) as resp:
                    first = resp.readline()
                    ttfts.append(time.time() - t0)
                    assert first, f"victim stream {i}: empty response"
                    events = [json.loads(l) for l in
                              (first + resp.read()).decode().splitlines() if l]
                assert events[-1].get("done"), (
                    f"victim stream {i} never finished: {events[-1]}"
                )
                assert events[-1]["num_tokens"] == 8, events[-1]
        finally:
            stop.set()
            for t in floods:
                t.join(timeout=30)

        worst = max(ttfts)
        assert worst < VICTIM_TTFT_BOUND_S, (
            f"victim TTFT blew out under the hostile flood: {ttfts}"
        )
        assert hostile["throttled"] >= 5, (
            f"hostile flood was never throttled: {hostile}"
        )
        assert hostile["other"] == 0, f"non-200/429 under flood: {hostile}"

        # shed attribution: quota sheds land on the hostile tenant ONLY
        with urllib.request.urlopen(
            f"http://127.0.0.1:{PORT}/-/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        per_tenant = stats.get("shed_tenant", {}).get("llm_overload", {})
        assert per_tenant.get("hostile", 0) >= hostile["throttled"], (
            hostile, stats,
        )
        assert "victim" not in per_tenant, f"victim was quota-shed: {stats}"

        # KV accounting balances to zero after the storm
        deadline = time.time() + 30
        st = None
        while time.time() < deadline:
            st = handle.stats.remote().result(timeout=30)
            if st["kv_blocks_in_use"] == 0 and st["waiting"] == 0:
                break
            time.sleep(0.3)
        assert st["kv_blocks_in_use"] == 0, f"KV LEAK: {st['kv_leak_report']}"
        rep = st["kv_leak_report"]
        assert rep["total_allocs"] == rep["total_frees"], rep

        print(
            f"serve_overload_smoke OK: {VICTIM_STREAMS} victim streams "
            f"(worst TTFT {worst:.2f}s < {VICTIM_TTFT_BOUND_S:.0f}s) vs "
            f"hostile flood of {hostile['sent']} "
            f"({hostile['ok']} ok, {hostile['throttled']} throttled 429), "
            f"sheds attributed to hostile only, kv blocks balanced to 0"
        )
        return 0
    finally:
        # teardown noise (a flood straggler racing actor-channel close)
        # must never fail the gate — every assertion already ran
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.shutdown()
        except Exception:  # noqa: BLE001
            pass


if __name__ == "__main__":
    sys.exit(main())
