#!/usr/bin/env python
"""Profiling smoke: boot a local cluster, put an actor under load,
attach the on-demand sampling profiler end to end — attach -> sample ->
dump -> merged flamegraph non-empty, with the actor's workload visible
in the collapsed stacks and both export formats well-formed.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/profiling_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import time

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        class Burner:
            def burn_profiling_smoke(self, seconds):
                deadline = time.monotonic() + seconds
                acc = 0
                while time.monotonic() < deadline:
                    acc += sum(i * i for i in range(500))
                return acc

        actor = Burner.remote()
        # Keep the actor busy through the whole capture window.
        ref = actor.burn_profiling_smoke.remote(6.0)

        result = state.profile(actor, duration_s=2.0)
        if result.errors:
            print(f"profiling smoke: FAIL (errors: {result.errors})")
            return 1
        if result.total_samples == 0:
            print("profiling smoke: FAIL (no samples captured)")
            return 1

        collapsed = result.collapsed()
        if "burn_profiling_smoke" not in collapsed:
            print("profiling smoke: FAIL (workload frame missing from flamegraph)")
            print(collapsed[:2000])
            return 1
        if not collapsed.startswith("actor:"):
            print("profiling smoke: FAIL (merged stacks not keyed by actor label)")
            return 1

        ss = result.speedscope()
        json.dumps(ss)  # must serialize
        if not ss["profiles"] or not ss["profiles"][0]["samples"]:
            print("profiling smoke: FAIL (speedscope export empty)")
            return 1

        attribution = result.attribution("burn_profiling_smoke")
        ray_tpu.get(ref, timeout=30)

        print(
            f"profiling smoke: OK ({result.total_samples} samples, "
            f"{attribution:.0%} attributed to the workload, "
            f"{len(collapsed.splitlines())} folded stacks)"
        )
        return 0
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
