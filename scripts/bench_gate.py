#!/usr/bin/env python
"""bench_gate: the perf-trajectory regression gate.

The BENCH_r04/r05 confusion class motivates this: captures taken
off-TPU (``on_tpu: false``) were read as an 8x regression against r03's
TPU number.  The gate loads the checked-in ``BENCH_r*.json`` lineage
and:

- **refuses cross-platform comparisons** — consecutive captures of the
  same metric whose ``on_tpu`` provenance differs (or is missing) are
  SKIPPED with a loud note, never scored;
- **flags >15% regressions** on like-for-like captures (same metric,
  same platform, both with explicit provenance);
- exits nonzero on regressions unless ``--warn-only`` (the verify.sh
  mode: the trajectory is reported every run, but only a human promotes
  a warning to a block — perf capture boxes vary).

Also supports ``--compare OLD.json NEW.json`` for metric-dict captures
(BENCH_micro/BENCH_serve style: ``{metric: {value, ...}}``) so two runs
of the same bench can be gated directly.

Run standalone:  python scripts/bench_gate.py [--repo DIR] [--warn-only]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_THRESHOLD = 0.15

# Metrics where larger is worse (latencies); everything else in the
# lineage is a throughput (larger is better).  Rate metrics
# (`*_per_s`, `*_per_sec`) are throughputs even though they end in a
# seconds-ish suffix — they must not match the latency patterns.
_RATE = re.compile(r"per_s(ec)?$")
_LOWER_IS_BETTER = re.compile(
    r"(latency|seconds|_s$|_ms$|_us\b|rtt|p50|p95|p99|ttft|shed|leak|error"
    r"|fail|drop|evict|timeout|blocks_after)"
)


def _higher_is_better(metric: str) -> bool:
    metric = metric or ""
    if _RATE.search(metric):
        return True
    return not _LOWER_IS_BETTER.search(metric)


def load_lineage(repo: str) -> List[Dict[str, Any]]:
    """Ordered capture records from BENCH_r*.json: one entry per round
    with {round, metric, value, on_tpu}; unparseable rounds (rc != 0,
    empty tail) surface as {parsed: None} entries so the report names
    them instead of silently shortening the lineage."""
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r[0-9]*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            out.append({"round": path, "parsed": None, "note": f"unreadable: {e}"})
            continue
        parsed = rec.get("parsed")
        entry: Dict[str, Any] = {
            "round": rec.get("n", os.path.basename(path)),
            "file": os.path.basename(path),
            "parsed": parsed,
        }
        if parsed:
            entry["metric"] = parsed.get("metric")
            entry["value"] = parsed.get("value")
            entry["on_tpu"] = parsed.get("on_tpu")  # None = missing provenance
            entry["platform"] = parsed.get("platform")
        out.append(entry)
    return out


def _provenance(rec: Dict[str, Any]) -> Tuple[Optional[bool], Optional[str]]:
    """(on_tpu, platform) provenance of a capture, deriving one from
    the other where only one is stamped.  (None, None) = no provenance
    at all."""
    platform = rec.get("platform")
    platform = str(platform) if platform else None
    on_tpu = rec.get("on_tpu")
    if on_tpu is None and platform is not None:
        on_tpu = platform == "tpu"
    return on_tpu, platform


def _prov_label(rec: Dict[str, Any]) -> str:
    on_tpu, platform = _provenance(rec)
    if platform:
        return platform
    return "tpu" if on_tpu else "non-tpu(unknown backend)"


def _comparable(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    """Like-for-like: on_tpu must match, and when BOTH captures also
    stamp a platform name those must match too (a gpu capture is not
    comparable to a cpu one even though both are on_tpu=False).  A
    legacy on_tpu-only record stays comparable to a platform-stamped
    one of the same on_tpu value — the coarse evidence doesn't
    contradict the fine."""
    a_tpu, a_plat = _provenance(a)
    b_tpu, b_plat = _provenance(b)
    if a_tpu is None or b_tpu is None or a_tpu != b_tpu:
        return False
    if a_plat and b_plat and a_plat != b_plat:
        return False
    return True


def check_lineage(
    lineage: List[Dict[str, Any]], threshold: float = DEFAULT_THRESHOLD
) -> Dict[str, List[Dict[str, Any]]]:
    """Compare each capture against the latest EARLIER like-for-like
    capture of the same metric.  Returns {regressions, skips, ok}."""
    regressions: List[Dict[str, Any]] = []
    skips: List[Dict[str, Any]] = []
    ok: List[Dict[str, Any]] = []
    # Per-metric history of provenance-stamped captures: each capture
    # compares against the MOST RECENT earlier one it is comparable
    # with (a TPU capture after a CPU blip still scores against the
    # last TPU point, not the blip).
    history: Dict[str, List[Dict[str, Any]]] = {}
    for cap in lineage:
        if not cap.get("parsed"):
            skips.append(
                {
                    "round": cap.get("round"),
                    "reason": cap.get("note", "no parsed record (bench failed/timed out)"),
                }
            )
            continue
        metric, value = cap.get("metric"), cap.get("value")
        if metric is None or value is None:
            skips.append({"round": cap.get("round"), "reason": "record missing metric/value"})
            continue
        # Infra failures emit a parseable record (error key, value 0)
        # so the lineage stays honest — but they are not perf points
        # and must never be scored as a like-for-like regression.
        if cap.get("parsed", {}).get("error") or value <= 0:
            skips.append(
                {
                    "round": cap.get("round"),
                    "metric": metric,
                    "reason": (
                        "BENCH FAILED (error record / non-positive value) — "
                        "an infra failure, not a perf point"
                    ),
                }
            )
            continue
        if _provenance(cap)[0] is None:
            skips.append(
                {
                    "round": cap.get("round"),
                    "metric": metric,
                    "reason": (
                        "NO PLATFORM PROVENANCE (on_tpu/platform missing) — capture "
                        "cannot be compared; re-run with a provenance-stamped bench"
                    ),
                }
            )
            continue
        earlier = history.setdefault(metric, [])
        prev = next((p for p in reversed(earlier) if _comparable(p, cap)), None)
        if prev is not None:
            comparison = _score(metric, prev, cap, threshold)
            (regressions if comparison["regressed"] else ok).append(comparison)
        elif earlier:
            # Loud cross-platform note (the r04/r05 class): lineage
            # exists for this metric but none of it is like-for-like.
            other = earlier[-1]
            skips.append(
                {
                    "round": cap.get("round"),
                    "metric": metric,
                    "reason": (
                        f"CROSS-PLATFORM: this capture is {_prov_label(cap)} but "
                        f"the previous lineage point (round {other.get('round')}) "
                        f"is {_prov_label(other)} — NOT comparable; a "
                        f"'{value} vs {other.get('value')}' read would be a "
                        "platform artifact, not a perf change"
                    ),
                }
            )
        earlier.append(cap)
    return {"regressions": regressions, "skips": skips, "ok": ok}


def _score(metric: str, prev: Dict[str, Any], cap: Dict[str, Any], threshold: float):
    pv, cv = float(prev["value"]), float(cap["value"])
    if _higher_is_better(metric):
        delta = (cv - pv) / pv if pv else 0.0
        regressed = pv > 0 and cv < pv * (1.0 - threshold)
    else:
        delta = (pv - cv) / pv if pv else 0.0
        regressed = pv > 0 and cv > pv * (1.0 + threshold)
    return {
        "metric": metric,
        "from_round": prev.get("round"),
        "to_round": cap.get("round"),
        "from_value": pv,
        "to_value": cv,
        "on_tpu": cap.get("on_tpu"),
        "delta_pct": round(delta * 100.0, 2),
        "regressed": regressed,
    }


# ----------------------------------------------------------------------
# metric-dict comparison (BENCH_micro / BENCH_serve style captures)
# ----------------------------------------------------------------------

# Workload-shape provenance: captures stamping different values for one
# of these keys measured different workloads (a 4096-stream drill vs a
# 1024-stream one) — the comparison is skipped loudly, same discipline
# as the cross-platform refusal.  Unlike on_tpu there is nothing to
# derive a MISSING stamp from, and a one-sided stamp appears exactly
# when the bench script changed between the captures — the moment the
# workload may have been resized — so one-sided is also not comparable.
_WORKLOAD_KEYS = ("streams", "requests", "requested", "concurrency",
                  "batch_width")


def _workload_mismatch(
    old_rec: Dict[str, Any], new_rec: Dict[str, Any]
) -> Optional[str]:
    for key in _WORKLOAD_KEYS:
        ov, nv = old_rec.get(key), new_rec.get(key)
        if ov is None and nv is None:
            continue
        if ov != nv:
            side = "old" if ov is None else "new"
            if ov is None or nv is None:
                return (f"{key} stamped on one capture only (missing on "
                        f"{side}) — shape unknown across a bench change")
            return f"{key} {ov} -> {nv}"
    return None


def compare_metric_dicts(
    old: Dict[str, Any], new: Dict[str, Any], threshold: float = DEFAULT_THRESHOLD
) -> Dict[str, List[Dict[str, Any]]]:
    regressions: List[Dict[str, Any]] = []
    skips: List[Dict[str, Any]] = []
    ok: List[Dict[str, Any]] = []
    for metric, new_rec in sorted(new.items()):
        if not isinstance(new_rec, dict) or "value" not in new_rec:
            continue
        old_rec = old.get(metric)
        if not isinstance(old_rec, dict) or "value" not in old_rec:
            skips.append({"metric": metric, "reason": "no prior capture"})
            continue
        # Error records and negative values are infra failures, never
        # perf points.  Zero is NOT failure here: metric-dict captures
        # include legitimately-zero gauges (kv_blocks_after=0 is the
        # healthy value) — they score, with _score's pv=0 guard making
        # a zero baseline unratioable rather than a bogus regression.
        if any(
            r.get("error") or not isinstance(r.get("value"), (int, float))
            or r["value"] < 0
            for r in (old_rec, new_rec)
        ):
            skips.append(
                {
                    "metric": metric,
                    "reason": (
                        "BENCH FAILED (error record / negative value) — "
                        "an infra failure, not a perf point"
                    ),
                }
            )
            continue
        o_tpu, n_tpu = _provenance(old_rec)[0], _provenance(new_rec)[0]
        if o_tpu is None or n_tpu is None:
            skips.append(
                {
                    "metric": metric,
                    "reason": (
                        "NO PLATFORM PROVENANCE (on_tpu/platform missing on "
                        f"{'old' if o_tpu is None else 'new'} capture) — "
                        "cannot be compared"
                    ),
                }
            )
            continue
        if not _comparable(old_rec, new_rec):
            skips.append(
                {
                    "metric": metric,
                    "reason": (
                        f"CROSS-PLATFORM: {_prov_label(old_rec)} -> "
                        f"{_prov_label(new_rec)} — not comparable"
                    ),
                }
            )
            continue
        mismatch = _workload_mismatch(old_rec, new_rec)
        if mismatch:
            skips.append(
                {
                    "metric": metric,
                    "reason": (
                        f"WORKLOAD CHANGED ({mismatch}): the runs measured "
                        "different workloads — a value delta here is a "
                        "resize artifact, not a perf change"
                    ),
                }
            )
            continue
        prev = {"value": old_rec["value"], "round": "old"}
        cap = {"value": new_rec["value"], "round": "new", "on_tpu": new_rec.get("on_tpu")}
        comparison = _score(metric, prev, cap, threshold)
        (regressions if comparison["regressed"] else ok).append(comparison)
    return {"regressions": regressions, "skips": skips, "ok": ok}


def _report(result: Dict[str, List[Dict[str, Any]]], warn_only: bool) -> int:
    for s in result["skips"]:
        print(f"bench_gate SKIP  [{s.get('metric', s.get('round', '?'))}] {s['reason']}")
    for c in result["ok"]:
        print(
            f"bench_gate ok    {c['metric']}: {c['from_value']} -> {c['to_value']} "
            f"({c['delta_pct']:+.1f}%, on_tpu={c['on_tpu']})"
        )
    for c in result["regressions"]:
        print(
            f"bench_gate REGRESSION {c['metric']}: {c['from_value']} -> "
            f"{c['to_value']} ({c['delta_pct']:+.1f}%, on_tpu={c['on_tpu']}, "
            f"rounds {c['from_round']} -> {c['to_round']})"
        )
    n_reg = len(result["regressions"])
    if n_reg:
        verdict = "WARN" if warn_only else "FAIL"
        print(f"bench_gate {verdict}: {n_reg} like-for-like regression(s) > threshold")
        return 0 if warn_only else 1
    print(
        f"bench_gate PASS: {len(result['ok'])} like-for-like comparison(s), "
        f"{len(result['skips'])} skip(s)"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    ap.add_argument("--warn-only", action="store_true",
                    help="report regressions but exit 0 (verify.sh mode)")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="compare two metric-dict capture files instead of the lineage")
    args = ap.parse_args(argv)
    if args.compare:
        with open(args.compare[0]) as f:
            old = json.load(f)
        with open(args.compare[1]) as f:
            new = json.load(f)
        result = compare_metric_dicts(old, new, args.threshold)
    else:
        lineage = load_lineage(args.repo)
        if not lineage:
            print("bench_gate PASS: no BENCH_r*.json lineage found")
            return 0
        result = check_lineage(lineage, args.threshold)
    return _report(result, args.warn_only)


if __name__ == "__main__":
    sys.exit(main())
