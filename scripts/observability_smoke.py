#!/usr/bin/env python
"""Observability smoke: boot a local cluster, run 10 traced tasks, and
assert the flight recorder works end to end — /metrics parses in
Prometheus exposition format (with rpc_latency_seconds per method) and
/api/timeline returns at least one cross-process trace.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/observability_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from urllib import request as urlrequest

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import ray_tpu
    from ray_tpu.util import state, tracing

    ctx = ray_tpu.init(num_cpus=2)
    try:
        url = ctx.dashboard_url
        if not url:
            print("observability smoke: FAIL (no dashboard url)")
            return 1

        @ray_tpu.remote
        def traced(x):
            return x + 1

        with tracing.start_span("smoke-root"):
            out = ray_tpu.get([traced.remote(i) for i in range(10)], timeout=60)
        assert out == list(range(1, 11))

        # spans flush on a ~1s cadence from each worker; poll the merge
        deadline = time.monotonic() + 25
        cross = []
        while time.monotonic() < deadline:
            cross = [t for t in state.traces() if len(t["pids"]) >= 2]
            if cross:
                break
            time.sleep(0.5)
        if not cross:
            print("observability smoke: FAIL (no cross-process trace in GCS)")
            return 1

        from ray_tpu.util import metrics as metrics_mod

        metrics_mod.flush()  # ship the driver's own records immediately
        names = []
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            with urlrequest.urlopen(url + "/metrics", timeout=10) as r:
                text = r.read().decode()
            type_lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE ")]
            names = [ln.split()[2] for ln in type_lines]
            if "rpc_latency_seconds" in names:
                break
            time.sleep(0.5)
        if len(names) != len(set(names)):
            print("observability smoke: FAIL (duplicate # TYPE lines)")
            return 1
        if "rpc_latency_seconds" not in names:
            print("observability smoke: FAIL (rpc_latency_seconds missing from /metrics)")
            return 1

        with urlrequest.urlopen(url + "/api/timeline", timeout=10) as r:
            timeline = json.loads(r.read())
        span_pids = {
            e["pid"] for e in timeline if e.get("cat") == "span"
        }
        if len(span_pids) < 2:
            print(f"observability smoke: FAIL (/api/timeline span pids={span_pids})")
            return 1

        print(
            f"observability smoke: OK ({len(cross)} cross-process trace(s), "
            f"{len(names)} metric families, {len(span_pids)} span pids in timeline)"
        )
        return 0
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
