#!/usr/bin/env python
"""Partition smoke: the two end-to-end membership drills from the
partition-tolerance plane, against real spawned clusters with link-level
chaos (``net:<src>-><dst>`` rules).

Drill A — asymmetric partition + incarnation fencing:
  node2's frames TO the GCS are blackholed (``net:node2->gcs:cut``)
  while every other direction keeps flowing.  Asserts:
    * the driver->node2 data path still answers while the control link
      is down (an RPC-plane partition is not a dataplane partition),
    * the GCS declares the silent node DEAD despite the still-open TCP
      conn (dead_conn_open_factor),
    * when the link heals, the zombie raylet's stale write is rejected
      with a typed, counted NodeFencedError and the raylet re-registers
      as a NEW incarnation of the SAME node id.

Drill B — gray failure (slow, never dead):
  node2's frames to the GCS are delayed 2.5 s one-way
  (``net:node2->gcs:slow``).  Asserts the suspicion ladder reads
  sustained slowness as SUSPECT -> QUARANTINED — never as a false DEAD —
  and readmits the node (ALIVE, one flap spent) after the link heals and
  health holds through the hysteresis window.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/partition_smoke.py
"""

from __future__ import annotations

import os
import sys
import time

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _wait_for(pred, timeout: float, what: str, poll: float = 0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(poll)
    raise AssertionError(f"timed out waiting for {what}")


def _set_env(env: dict):
    saved = {}
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    return saved


def _restore_env(saved: dict):
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old


def _spawn(env: dict):
    """Head + one worker node whose processes carry net identity
    'node2' (chaos_net_name is frozen into children at spawn)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    saved = _set_env(env)
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    tag = _set_env({"RAY_TPU_chaos_net_name": "node2"})
    try:
        cluster.add_node(num_cpus=1, resources={"side": 1})
    finally:
        _restore_env(tag)
    cluster.wait_for_nodes()
    ray_tpu.init(address=cluster.address)
    return cluster, saved


def _side_node(info: dict) -> dict:
    return next(n for n in info["nodes"].values() if not n.get("is_head"))


def drill_a_asymmetric_partition() -> None:
    import ray_tpu
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import state

    cluster, saved = _spawn(
        {
            # Fast death detection: 2 s heartbeat threshold; with the
            # conn held open by the asymmetric cut, death needs
            # dead_conn_open_factor (2x) => ~4 s of silence.
            "RAY_TPU_health_check_timeout_ms": "2000",
            "RAY_TPU_health_check_period_ms": "300",
            # Cut arms 8 s after node2's raylet starts (registration and
            # the probe actor must land first) and heals 18 s later.
            "RAY_TPU_testing_chaos_spec": "net:node2->gcs:cut:start=8:for=18",
            "RAY_TPU_testing_chaos_seed": "7",
        }
    )
    try:
        w = get_global_worker()

        @ray_tpu.remote(resources={"side": 0.5})
        class Probe:
            def ping(self):
                return "pong"

        probe = Probe.remote()
        assert ray_tpu.get(probe.ping.remote(), timeout=30) == "pong"

        info = w.gcs_client.call("get_cluster_info")
        side = _side_node(info)
        side_hex = bytes(side["node_id"]).hex()
        inc0 = side["incarnation"]
        assert inc0 > 0, side

        def side_view():
            return _side_node(w.gcs_client.call("get_cluster_info"))

        # The control link goes dark: suspicion climbs from the
        # heartbeat gap while the node is still listed alive.
        _wait_for(
            lambda: side_view()["suspicion"] >= 0.5
            and side_view()["state"] != "DEAD",
            40,
            "suspicion to climb under the cut",
        )
        # ... and the DATA path still answers: the partition is an
        # RPC-plane (node2->gcs) cut, not a dataplane cut.
        assert ray_tpu.get(probe.ping.remote(), timeout=10) == "pong"
        print("drill A: dataplane answered while the control link was cut")

        # Sustained silence past dead_conn_open_factor x timeout kills
        # the node even though its TCP conn never closed.
        _wait_for(lambda: side_view()["state"] == "DEAD", 40, "DEAD under cut")
        print("drill A: asymmetric silence declared DEAD (conn still open)")

        # Heal: the zombie's next report is fenced (typed + counted) and
        # the raylet re-registers the SAME node id as a NEW incarnation.
        def rejoined():
            n = side_view()
            return (
                bytes(n["node_id"]).hex() == side_hex
                and n["state"] == "ALIVE"
                and n["incarnation"] > inc0
            )

        _wait_for(rejoined, 60, "fenced raylet to rejoin as a new incarnation")
        inc1 = side_view()["incarnation"]
        print(
            f"drill A: node {side_hex[:8]} rejoined, incarnation "
            f"{inc0} -> {inc1}"
        )

        def fence_counted():
            return any(
                r["name"] == "node_fence_rejections_total"
                and r.get("value", 0) >= 1
                for r in state.metrics()
            )

        _wait_for(fence_counted, 30, "node_fence_rejections_total >= 1")
        print("drill A: stale write rejection visible in metrics")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        _restore_env(saved)


def drill_b_gray_failure() -> None:
    import ray_tpu
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import state

    cluster, saved = _spawn(
        {
            # Default 10 s death threshold: the delayed heartbeats keep
            # arriving well inside it — DEAD would be a ladder bug.
            "RAY_TPU_health_check_timeout_ms": "10000",
            "RAY_TPU_health_check_period_ms": "300",
            "RAY_TPU_quarantine_after_s": "3",
            "RAY_TPU_quarantine_drain_deadline_s": "5",
            "RAY_TPU_unquarantine_hysteresis_s": "4",
            # 2.5 s one-way delay on node2->gcs: above suspect_rtt_ms
            # (2 s), far below the death threshold.
            "RAY_TPU_testing_chaos_spec": (
                "net:node2->gcs:slow:ms=2500:start=6:for=18"
            ),
            "RAY_TPU_testing_chaos_seed": "7",
        }
    )
    try:
        w = get_global_worker()

        def side_view():
            return _side_node(w.gcs_client.call("get_cluster_info"))

        seen = set()

        def watch(target_states):
            def pred():
                n = side_view()
                seen.add(n["state"])
                assert n["state"] != "DEAD", (
                    f"gray failure escalated to false DEAD (seen {seen})"
                )
                return n["state"] in target_states

            return pred

        _wait_for(watch({"SUSPECT"}), 45, "sustained slowness -> SUSPECT")
        print("drill B: slow link read as SUSPECT (soft cordon)")
        _wait_for(
            watch({"QUARANTINED"}), 45, "sustained suspicion -> QUARANTINED"
        )
        print("drill B: sustained gray failure parked in QUARANTINED")

        # Heal: health holds through the hysteresis window, the node is
        # readmitted with exactly one flap spent.
        _wait_for(watch({"ALIVE"}), 60, "readmission after the link heals")
        n = side_view()
        assert n["flap_count"] == 1, n
        assert "DEAD" not in seen, seen
        print(
            f"drill B: readmitted ALIVE (flap {n['flap_count']}, "
            f"states seen: {sorted(seen)})"
        )

        def suspicion_exported():
            return any(
                r["name"] == "node_suspicion_score" for r in state.metrics()
            )

        _wait_for(suspicion_exported, 30, "node_suspicion_score gauge")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
        _restore_env(saved)


def main() -> int:
    t0 = time.monotonic()
    drill_a_asymmetric_partition()
    drill_b_gray_failure()
    print(
        f"partition smoke: OK (asymmetric-partition fencing + gray-failure "
        f"quarantine, {time.monotonic() - t0:.1f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
