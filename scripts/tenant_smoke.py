#!/usr/bin/env python
"""Tenant smoke: boot a local cluster, register two tenants with
unequal CPU quotas, drive sustained task demand from both via
subprocess drivers, and assert the multi-tenant job plane works end to
end —

  * quotas are enforced: each tenant's steady-state usage converges on
    its quota (the cluster is sized so quotas saturate it) and never
    exceeds it persistently,
  * fair shares converge: the two tenants' average usage matches the
    registered quota split within tolerance,
  * the tenant registry round-trips through /api/tenants.

Run by scripts/verify.sh after tier-1; standalone:
    JAX_PLATFORMS=cpu python scripts/tenant_smoke.py
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap
import time

# sys.path[0] is scripts/; the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DRIVER = textwrap.dedent(
    """
    import sys, time
    import ray_tpu

    addr, tenant, secs = sys.argv[1], sys.argv[2], float(sys.argv[3])
    ray_tpu.init(address=addr, tenant=tenant)

    @ray_tpu.remote(num_cpus=1, max_retries=-1)
    def burn(t):
        time.sleep(t)
        return 1

    pending = []
    deadline = time.time() + secs
    while time.time() < deadline:
        while len(pending) < 8:
            pending.append(burn.remote(0.2))
        _done, pending = ray_tpu.wait(pending, num_returns=1, timeout=1.0)
    ray_tpu.shutdown()
    """
)


def main() -> int:
    import ray_tpu

    ray_tpu.init(num_cpus=6)
    worker = ray_tpu._private.worker.get_global_worker()
    gcs = worker.gcs_client
    address = worker.gcs_client.address

    quotas = {"smokeA": 4.0, "smokeB": 2.0}
    for name, q in quotas.items():
        out = gcs.call("tenant_set_quota", {"tenant": name, "quota": {"CPU": q}})
        assert out["quota"] == {"CPU": q}, out

    drill_s = 22.0
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _DRIVER, address, name, str(drill_s)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for name in quotas
    ]

    def usage(name):
        for t in gcs.call("list_tenants", None):
            if t["name"] == name:
                return t.get("usage", {}).get("CPU", 0.0)
        return 0.0

    try:
        time.sleep(7.0)  # ramp + first reconciliation passes
        samples = {name: [] for name in quotas}
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0:
            for name in quotas:
                samples[name].append(usage(name))
            time.sleep(0.4)
        for name, q in quotas.items():
            avg = sum(samples[name]) / max(1, len(samples[name]))
            assert abs(avg - q) <= 0.1 * q + 0.3, (
                f"{name}: steady usage {avg:.2f} vs quota {q} "
                f"(samples={samples[name][-8:]})"
            )
            over = [u for u in samples[name] if u > q + 1e-6]
            assert len(over) <= 2, f"{name}: quota exceeded persistently: {over}"
        print(
            "tenant smoke OK:",
            {n: round(sum(s) / len(s), 2) for n, s in samples.items()},
            "within 10% of quotas", quotas,
        )
    finally:
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
        ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
