#!/usr/bin/env bash
# Tier-1 verification — the exact command from ROADMAP.md.  CI and
# humans run this one script so the gate can't drift from the docs.
set -o pipefail
cd "$(dirname "$0")/.."

# graftlint (static analysis gate): the ray_tpu/ AND tests/ trees must
# carry zero unsuppressed invariant violations against .graftlint.toml,
# with no stale baseline entries (--strict), inside a 30 s budget.  Runs
# first: it is the cheapest signal and failures are line-precise.  The
# JSON report feeds the one-line gate summary (checker/violation counts)
# and stays in /tmp/_graftlint.json for CI artifacts.
if ! timeout -k 5 30 python -m ray_tpu.devtools.lint ray_tpu tests --strict --json \
    > /tmp/_graftlint.json; then
  python - <<'EOF' 2>/dev/null || cat /tmp/_graftlint.json
import json
r = json.load(open("/tmp/_graftlint.json"))
for v in r["violations"]:
    if not v.get("suppressed_by"):
        print(f"{v['path']}:{v['line']}: {v['check']}: {v['message']}")
for v in r["parse_errors"]:
    print(f"{v['path']}:{v['line']}: {v['check']}: {v['message']}")
for e in r["unused_baseline"]:
    print(f"stale baseline entry: {e['check']} @ {e['path']}")
EOF
  echo "graftlint gate failed (see docs/static_analysis.md)"
  exit 1
fi
python - <<'EOF'
import json
r = json.load(open("/tmp/_graftlint.json"))
firing = {k: n for k, n in r["by_check"].items() if n}
print(
    f"GRAFTLINT_GATE checks={len(r['checks_run'])} files={r['files_checked']} "
    f"unsuppressed={r['unsuppressed']} suppressed={r['suppressed']} "
    f"cache_hits={r['cache']['hits']} elapsed={r['elapsed_s']}s"
    + (f" firing={firing}" if firing else "")
)
EOF

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

# Observability smoke (flight recorder end-to-end): local cluster, 10
# traced tasks, /metrics parses, /api/timeline shows a cross-process
# trace.  Skippable via RAY_TPU_SKIP_OBS_SMOKE=1.
if [ "${RAY_TPU_SKIP_OBS_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
      python scripts/observability_smoke.py; then
    echo "observability smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Dataplane trace smoke (trace-context propagation end-to-end): 2-raylet
# cluster, one traced serve call over the channel dataplane + one traced
# compiled-DAG execution across a socket edge — both come back as single
# connected traces spanning >=2 processes with zero orphan spans.
# Skippable via RAY_TPU_SKIP_DATAPLANE_SMOKE=1.
if [ "${RAY_TPU_SKIP_DATAPLANE_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 150 env JAX_PLATFORMS=cpu \
      python scripts/dataplane_trace_smoke.py; then
    echo "dataplane trace smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Drain smoke (graceful node drain end-to-end): 2-node local cluster,
# drain a node hosting a live actor + sole-copy object, assert the actor
# migrates, the object survives the kill, and util.state + /api/nodes
# show DRAINING -> DEAD.  Skippable via RAY_TPU_SKIP_DRAIN_SMOKE=1.
if [ "${RAY_TPU_SKIP_DRAIN_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python scripts/drain_smoke.py; then
    echo "drain smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Tenant smoke (multi-tenant job plane end-to-end): two tenants with
# unequal quotas under sustained task demand — usage converges on the
# quota split within 10% and never exceeds a quota persistently.
# Skippable via RAY_TPU_SKIP_TENANT_SMOKE=1.
if [ "${RAY_TPU_SKIP_TENANT_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python scripts/tenant_smoke.py; then
    echo "tenant smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Serve LLM smoke (inference serving plane end-to-end): tiny GPT-2
# behind serve.run, 24 concurrent token streams + one mid-stream cancel,
# assert all completions exact, KV block pool balanced to zero, and the
# continuous batcher actually batched.  Skippable via
# RAY_TPU_SKIP_SERVE_LLM_SMOKE=1.
if [ "${RAY_TPU_SKIP_SERVE_LLM_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python scripts/serve_llm_smoke.py; then
    echo "serve llm smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Serve overload smoke (overload armor end-to-end over HTTP): hostile
# tenant floods at many times its token-rate quota while a victim tenant
# streams interactively — assert 429s attributed to the hostile tenant
# only, victim TTFT bounded, KV pool balanced to zero.  Skippable via
# RAY_TPU_SKIP_SERVE_OVERLOAD_SMOKE=1.
if [ "${RAY_TPU_SKIP_SERVE_OVERLOAD_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python scripts/serve_overload_smoke.py; then
    echo "serve overload smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Compiled-DAG smoke (zero-copy dataplane end-to-end): 2-raylet cluster,
# 3-actor fan-out with one socket edge + shm rings, exact results over
# 200 executions, sub-ms local round-trip p50 (multicore), teardown
# reclaims tmpfs.  Skippable via RAY_TPU_SKIP_DAG_SMOKE=1.
if [ "${RAY_TPU_SKIP_DAG_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
      python scripts/compiled_dag_smoke.py; then
    echo "compiled dag smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Dataplane chaos smoke (self-healing dataplane end-to-end): compiled
# DAG with a cross-raylet socket edge + serve calls and token streams
# under a seeded chan:* chaos spec (mid-frame torn writes, abrupt
# socket drops, a serve ring close) — every result exact via epoch
# reattach / RPC fallback, zero leaked shm.  Skippable via
# RAY_TPU_SKIP_DATAPLANE_CHAOS_SMOKE=1.
if [ "${RAY_TPU_SKIP_DATAPLANE_CHAOS_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
      python scripts/dataplane_chaos_smoke.py; then
    echo "dataplane chaos smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Checkpoint chaos smoke (durable checkpoint plane end-to-end): a JAX
# training loop SIGKILLed mid-shard and pre-commit (seeded ckpt:*
# rules) with a bit-flipped shard at rest restarts every time from the
# last COMMITTED checkpoint with byte-exact loss/parameter parity,
# never adopts corrupted state, and leaves zero debris after retention
# GC.  Skippable via RAY_TPU_SKIP_CHECKPOINT_CHAOS_SMOKE=1.
if [ "${RAY_TPU_SKIP_CHECKPOINT_CHAOS_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python scripts/checkpoint_chaos_smoke.py; then
    echo "checkpoint chaos smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# RLlib async smoke (podracer streaming plane end-to-end): 2 streaming
# env runners + learner over real channels, fixed seed, reward parity
# vs the synchronous PPO path on CartPole, and the IMPALA-style async
# config clearing the same bar.  Skippable via RAY_TPU_SKIP_RLLIB_SMOKE=1.
if [ "${RAY_TPU_SKIP_RLLIB_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/rllib_async_smoke.py; then
    echo "rllib async smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Profiling smoke (bottleneck-attribution plane end-to-end): actor under
# load, attach the sampling profiler, assert a non-empty merged
# flamegraph with the workload visible and valid speedscope output.
# Skippable via RAY_TPU_SKIP_PROFILING_SMOKE=1.
if [ "${RAY_TPU_SKIP_PROFILING_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 120 env JAX_PLATFORMS=cpu \
      python scripts/profiling_smoke.py; then
    echo "profiling smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Bench trajectory gate (warn-only): report like-for-like perf
# regressions across the checked-in BENCH lineage; cross-platform
# captures (on_tpu mismatch) are skipped loudly, never scored.  Warn
# mode: a human promotes warnings to blocks — perf boxes vary.
# Skippable via RAY_TPU_SKIP_BENCH_GATE=1.
if [ "${RAY_TPU_SKIP_BENCH_GATE:-0}" != "1" ]; then
  if ! timeout -k 5 30 python scripts/bench_gate.py --warn-only; then
    echo "bench gate step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Sharded train smoke (GSPMD + MPMD planes end-to-end on CPU devices):
# batch x model mesh loss parity vs data parallel, per-shard checkpoint
# re-shard across a mesh resize, and a 2-stage pipeline over real
# channels matching single-process loss.  Skippable via
# RAY_TPU_SKIP_SHARDED_SMOKE=1.
if [ "${RAY_TPU_SKIP_SHARDED_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/sharded_train_smoke.py; then
    echo "sharded train smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Partition smoke (membership plane end-to-end): asymmetric-partition
# drill (net:node2->gcs:cut — dataplane stays up, silent node declared
# DEAD past dead_conn_open_factor, zombie write fenced typed+counted,
# raylet rejoins as a new incarnation) and gray-failure drill
# (net:...:slow — SUSPECT -> QUARANTINED, never false DEAD, readmitted
# after heal within the flap budget).  Skippable via
# RAY_TPU_SKIP_PARTITION_SMOKE=1.
if [ "${RAY_TPU_SKIP_PARTITION_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 240 env JAX_PLATFORMS=cpu \
      python scripts/partition_smoke.py; then
    echo "partition smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi

# Elastic smoke (resize-on-preemption end-to-end): 2-node local cluster,
# elastic JaxTrainer (min_workers=1), preempt one rank's node mid-run,
# assert shrink -> resume -> completion with zero failure charges and
# resize events/spans recorded.  Skippable via RAY_TPU_SKIP_ELASTIC_SMOKE=1.
if [ "${RAY_TPU_SKIP_ELASTIC_SMOKE:-0}" != "1" ]; then
  if ! timeout -k 10 180 env JAX_PLATFORMS=cpu \
      python scripts/elastic_smoke.py; then
    echo "elastic smoke step failed"
    [ "$rc" -eq 0 ] && rc=1
  fi
fi
exit $rc
