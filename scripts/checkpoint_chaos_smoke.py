#!/usr/bin/env python
"""Checkpoint durability chaos smoke (wired into scripts/verify.sh).

End-to-end proof of the ISSUE 16 acceptance: a deterministic JAX
training loop persisting through the checkpoint plane is SIGKILLed
mid-write at two different phases (seeded ``ckpt:*`` chaos rules), has
a committed shard bit-flipped at rest between restarts, and still:

- restarts every time from the last COMMITTED checkpoint (the killed
  writes and the bit-flipped checkpoint are never adopted — the loader
  walks back, counted by ``checkpoint_restore_fallbacks_total``),
- finishes with EXACT loss + parameter parity against a never-killed
  run (byte-identical final state),
- leaves zero uncommitted debris and at most keep-K committed
  checkpoints after the final retention sweep.

The SIGKILL phase matrix and the async-writer contracts are drilled in
tier-1 (tests/test_checkpoint_plane.py); this smoke pins the
end-to-end restart-parity path with a real train step.
"""

import json
import os
import subprocess
import sys
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = 6
KEEP = 2

# The child: resume from the newest verified checkpoint, train to
# ``STEPS`` with a fixed data seed, persist + GC every step, print the
# final state fingerprint.  Runs under whatever ckpt:* chaos spec the
# parent put in the environment.
_CHILD = r"""
import json, os, pickle, sys
import jax
import jax.numpy as jnp
from ray_tpu.train import checkpoint_plane as cp

root, steps, keep = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

def loss_fn(w, x, y):
    return jnp.mean((x @ w - y) ** 2)

grad = jax.jit(jax.value_and_grad(loss_fn))
key = jax.random.PRNGKey(0)
w = jnp.zeros((4, 1))
start = 0
adopted = cp.resolve_restore(root=root)
if adopted:
    with open(os.path.join(adopted, "state.pkl"), "rb") as f:
        d = pickle.load(f)
    w, start = jnp.asarray(d["w"]), d["step"] + 1

losses = []
for step in range(start, steps):
    k = jax.random.fold_in(key, step)
    x = jax.random.normal(k, (16, 4))
    y = x @ jnp.ones((4, 1))
    l, g = grad(w, x, y)
    w = w - 0.1 * g
    losses.append(float(l))
    src = os.path.join(root, "_stage")
    os.makedirs(src, exist_ok=True)
    blob = pickle.dumps({"w": __import__("numpy").asarray(w), "step": step}, protocol=5)
    with open(os.path.join(src, "state.pkl"), "wb") as f:
        f.write(blob)
    dest = os.path.join(root, f"checkpoint_{step:06d}")
    cp.persist_dir(src, dest, meta={"step": step}, mode="sync")
    cp.gc_checkpoints(root, keep=keep, pinned=[dest], grace_s=9999)

import numpy as np
from ray_tpu._private import telemetry  # noqa: F401 — registry import
from ray_tpu.util import metrics as metrics_mod
fallbacks = metrics_mod._registry.get(("checkpoint_restore_fallbacks_total", ()))
print(json.dumps({
    "adopted": adopted,
    "final_loss": losses[-1] if losses else None,
    "w_crc": __import__("zlib").crc32(np.asarray(w).tobytes()) & 0xFFFFFFFF,
    "fallbacks": fallbacks["value"] if fallbacks else 0.0,
}))
"""


def run_child(root: str, chaos_spec: str = "", seed: str = "21") -> subprocess.CompletedProcess:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("RAY_TPU_testing_chaos_spec", None)
    env.pop("RAY_TPU_testing_chaos_seed", None)
    if chaos_spec:
        env["RAY_TPU_testing_chaos_spec"] = chaos_spec
        env["RAY_TPU_testing_chaos_seed"] = seed
    return subprocess.run(
        [sys.executable, "-c", _CHILD, root, str(STEPS), str(KEEP)],
        env=env, capture_output=True, timeout=300,
    )


def flip_byte(path: str) -> None:
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))


def main() -> int:
    import tempfile

    from ray_tpu.train import checkpoint_plane as cp

    with tempfile.TemporaryDirectory(prefix="ckpt_chaos_smoke_") as td:
        clean_root = os.path.join(td, "clean")
        chaos_root = os.path.join(td, "chaos")
        os.makedirs(clean_root)
        os.makedirs(chaos_root)

        # Reference: a never-killed run.
        ref = run_child(clean_root)
        assert ref.returncode == 0, ref.stderr.decode()
        ref_out = json.loads(ref.stdout.strip().splitlines()[-1])

        # Drill 1: SIGKILL mid-shard-write on step 3's checkpoint.
        p1 = run_child(chaos_root, "ckpt:shard:kill:at=4")
        assert p1.returncode == 137, (p1.returncode, p1.stderr.decode())

        # Bit-rot at rest: flip one byte of the newest COMMITTED shard.
        cands = cp.candidate_checkpoints(chaos_root)
        committed = [c for c in cands if cp.is_committed(c)]
        assert committed, "kill drill left no committed checkpoint"
        flip_byte(os.path.join(committed[0], "state.pkl"))

        # The one loader rejects the bit-flipped newest and falls back
        # to the previous committed checkpoint — asserted directly.
        assert cp.resolve_restore(root=chaos_root) == committed[1]

        # Drill 2: restart (falls back past the bit-flipped newest),
        # then get SIGKILLed again between shard and manifest.
        p2 = run_child(chaos_root, "ckpt:precommit:kill:at=2")
        assert p2.returncode == 137, (p2.returncode, p2.stderr.decode())

        # Final restart runs clean to completion.
        p3 = run_child(chaos_root)
        assert p3.returncode == 0, p3.stderr.decode()
        out = json.loads(p3.stdout.strip().splitlines()[-1])

        # Restarted-to-last-committed with EXACT parity: the final loss
        # and the final parameter bytes match the never-killed run.
        assert out["final_loss"] == ref_out["final_loss"], (out, ref_out)
        assert out["w_crc"] == ref_out["w_crc"], (out, ref_out)

        # The final restart resumed (it did not start over) and its
        # loader counted the fallback past the debris drill 2 left.
        assert out["adopted"] is not None
        assert out["fallbacks"] >= 1, out

        # Zero corrupted restores adopted: every checkpoint the chain
        # ever adopted verifies (the adopted one still on disk does).
        if os.path.isdir(out["adopted"]):
            cp.verify_checkpoint(out["adopted"])

        # Retention: after the final sweep (grace 0 for the smoke) there
        # is no uncommitted debris and at most KEEP committed groups.
        cp.gc_checkpoints(chaos_root, keep=KEEP, grace_s=0.0)
        left = [
            d for d in sorted(os.listdir(chaos_root))
            if d.startswith("checkpoint_")
        ]
        uncommitted = [
            d for d in left
            if not cp.is_committed(os.path.join(chaos_root, d))
        ]
        assert not uncommitted, f"debris survived GC: {uncommitted}"
        assert len(left) <= KEEP, left
        for d in left:
            cp.verify_checkpoint(os.path.join(chaos_root, d))

        # Replayability: the same (spec, seed) kills at the same ordinal.
        replay_root = os.path.join(td, "replay")
        os.makedirs(replay_root)
        r1 = run_child(replay_root, "ckpt:shard:kill:at=4")
        assert r1.returncode == 137
        r_cands = cp.candidate_checkpoints(replay_root)
        r_committed = [c for c in r_cands if cp.is_committed(c)]
        assert [os.path.basename(c) for c in r_committed] == [
            os.path.basename(c) for c in committed
        ], "seeded kill schedule did not replay"

    print("checkpoint chaos smoke: kill-restart parity exact, "
          "bit-flip never adopted, zero debris after GC, schedule replays")
    return 0


if __name__ == "__main__":
    sys.exit(main())
