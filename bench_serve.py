"""LLM serving-plane benchmark -> BENCH_serve.json.

Four phases against the tiny GPT-2 config (synthetic weights; the
numbers measure the SERVING plane — engine scheduling, streaming
transport, overload behavior — not model quality):

1. **throughput comparison** — continuous in-flight batching
   (``LLMServer``) vs the request-level ``@serve.batch`` baseline
   (``StaticBatchLLMServer``) at equal concurrency and equal decode
   width, mixed request lengths.  Continuous must win on tokens/s: the
   static batch pays the drain barrier (every batch runs to its LAST
   member while short members' lanes idle).
2. **stream drill** — 4k concurrent token streams through one
   deployment (stepping toward the 10k target): p50/p99 end-to-end
   latency, p50/p99 TTFT, aggregate tokens/s, all streams complete.
   Records stamp the stream count so bench_gate --compare refuses to
   score a resized drill against an older, smaller one.
3. **shed** — flood a small-queue deployment far past its bound: the
   overflow is shed with typed errors (engine) while every admitted
   request completes; records the shed rate.
4. **chaos** — 2 replicas under live stream load, one replica killed:
   every established stream on the survivor completes, new requests
   re-route, the controller replaces the dead replica.

Hardware caveats: same 1-core CI box as BENCH_micro — the transport
(per-token stream items through the object store) dominates over the
tiny model's decode math, and loadavg swings absolute numbers; every
record carries the loadavg annotation.

Run: python bench_serve.py [--out BENCH_serve.json] [--streams 4096]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve import llm
from ray_tpu.serve.exceptions import RequestShedError

NOTE = (
    "tiny GPT-2, synthetic weights, CPU backend on the 1-core CI box: "
    "serving-plane numbers (scheduling + streaming transport), not model "
    "math; host contention swings absolutes run-to-run"
)


def _pct(sorted_vals, p):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(p / 100.0 * len(sorted_vals)))
    return sorted_vals[i]


def record(out, metric, value, unit, **extra):
    from bench_common import provenance

    rec = {
        "metric": metric,
        "value": round(value, 2) if isinstance(value, float) else value,
        "unit": unit,
        # platform provenance first-class: bench_gate refuses
        # cross-platform comparisons keyed on on_tpu
        **provenance(),
        "loadavg_1m_at_capture": round(os.getloadavg()[0], 2),
        "note": NOTE,
    }
    rec.update(extra)
    out[metric] = rec
    print(json.dumps(rec))


# ----------------------------------------------------------------------
# phase 1: continuous vs static batching, equal concurrency
# ----------------------------------------------------------------------
def _drive_oneshot(handle, n_requests, concurrency, mixed_lengths):
    """n_requests one-shot completions, `concurrency` in flight, mixed
    max_tokens; returns (wall_s, total_tokens, latencies)."""
    lock = threading.Lock()
    state = {"next": 0, "tokens": 0, "lat": [], "errors": 0}

    def worker():
        while True:
            with lock:
                i = state["next"]
                if i >= n_requests:
                    return
                state["next"] = i + 1
            t0 = time.time()
            try:
                out = handle.remote(
                    {"prompt": [1, 2, 3, i % 7], "max_tokens": mixed_lengths[i]}
                ).result(timeout=300)
                dt = time.time() - t0
                with lock:
                    state["tokens"] += out["num_tokens"]
                    state["lat"].append(dt)
            except Exception:  # noqa: BLE001
                with lock:
                    state["errors"] += 1

    t0 = time.time()
    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.time() - t0, state["tokens"], sorted(state["lat"]), state["errors"]


def phase_throughput(out, n_requests=192, concurrency=48, width=16):
    mixed = [4 + (i * 7) % 28 for i in range(n_requests)]  # 4..31 tokens

    cont_app = llm.build_app(
        llm.LLMConfig(model="tiny", max_batch_size=width, num_blocks=512,
                      block_size=8, max_queue=4096, name="bench_cont")
    )
    handle = serve.run(cont_app, name="bench_cont_app")
    # warm the compile caches out of the measurement
    handle.remote({"prompt": [1], "max_tokens": 4}).result(timeout=120)
    wall, tokens, lat, errors = _drive_oneshot(handle, n_requests, concurrency, mixed)
    assert errors == 0, f"{errors} continuous requests failed"
    st = handle.stats.remote().result(timeout=30)
    assert st["kv_blocks_in_use"] == 0, st["kv_leak_report"]
    cont_tps = tokens / wall
    record(out, "serve_tokens_per_s_continuous", cont_tps, "tokens/s",
           requests=n_requests, concurrency=concurrency, batch_width=width,
           wall_s=round(wall, 2), engine_steps=st["steps"])
    serve.delete("bench_cont")

    static_dep = serve.deployment(
        name="bench_static", max_ongoing_requests=4096
    )(llm.StaticBatchLLMServer)
    s_handle = serve.run(
        static_dep.bind(
            llm.LLMConfig(model="tiny", max_batch_size=width,
                          name="bench_static").to_dict()
        ),
        name="bench_static_app",
    )
    s_handle.remote({"prompt": [1], "max_tokens": 4}).result(timeout=120)
    wall_s_, tokens_s_, lat_s, errors_s = _drive_oneshot(
        s_handle, n_requests, concurrency, mixed
    )
    assert errors_s == 0, f"{errors_s} static requests failed"
    static_tps = tokens_s_ / wall_s_
    record(out, "serve_tokens_per_s_static_batch", static_tps, "tokens/s",
           requests=n_requests, concurrency=concurrency, batch_width=width,
           wall_s=round(wall_s_, 2))
    record(out, "serve_continuous_vs_static_speedup", cont_tps / static_tps,
           "x", acceptance="continuous must beat static at equal concurrency")
    serve.delete("bench_static")
    return cont_tps, static_tps


# ----------------------------------------------------------------------
# phase 2: 4k concurrent stream drill (toward the 10k target)
# ----------------------------------------------------------------------
def phase_stream_drill(out, n_streams=4096, max_tokens=12, width=32):
    app = llm.build_app(
        llm.LLMConfig(model="tiny", max_batch_size=width, num_blocks=1024,
                      block_size=8, max_queue=n_streams + 64,
                      name="bench_drill"),
        max_ongoing_requests=2 * n_streams,
    )
    handle = serve.run(app, name="bench_drill_app")
    handle.remote({"prompt": [1], "max_tokens": 4}).result(timeout=120)

    t_start = time.time()
    streams = []
    stream_handle = handle.options(stream=True)
    for i in range(n_streams):
        gen = stream_handle.generate.remote(
            {"prompt": [1, 2, i % 11], "max_tokens": max_tokens}
        )
        streams.append({
            "gen": gen, "t_open": time.time(), "t_first": None,
            "t_done": None, "tokens": 0, "failed": False,
        })
    t_opened = time.time()

    open_set = list(streams)
    deadline = time.time() + 600
    while open_set and time.time() < deadline:
        for s in list(open_set):
            try:
                ev = s["gen"].try_next()
            except StopIteration:
                s["t_done"] = s["t_done"] or time.time()
                open_set.remove(s)
                continue
            except Exception:  # noqa: BLE001
                s["failed"] = True
                open_set.remove(s)
                continue
            if ev is None:
                continue
            if isinstance(ev, dict) and "token" in ev:
                s["tokens"] += 1
                if s["t_first"] is None:
                    s["t_first"] = time.time()
    t_end = time.time()

    failed = [s for s in streams if s["failed"] or s["t_done"] is None]
    done = [s for s in streams if s["t_done"] is not None and not s["failed"]]
    assert len(failed) == 0, f"{len(failed)} of {n_streams} streams failed"
    total_tokens = sum(s["tokens"] for s in done)
    lat = sorted(s["t_done"] - s["t_open"] for s in done)
    ttft = sorted(s["t_first"] - s["t_open"] for s in done if s["t_first"])
    wall = t_end - t_start
    # workload provenance: `streams` on every drill record lets
    # bench_gate --compare refuse latency comparisons across drill
    # resizes (a 4x-larger drill is a workload change, not a perf one)
    record(out, "serve_stream_drill_streams", len(done), "streams",
           requested=n_streams, open_time_s=round(t_opened - t_start, 2))
    record(out, "serve_stream_drill_tokens_per_s", total_tokens / wall,
           "tokens/s", total_tokens=total_tokens, wall_s=round(wall, 2),
           streams=n_streams)
    record(out, "serve_stream_drill_latency_p50", _pct(lat, 50), "s",
           streams=n_streams)
    record(out, "serve_stream_drill_latency_p99", _pct(lat, 99), "s",
           streams=n_streams)
    record(out, "serve_stream_drill_ttft_p50", _pct(ttft, 50), "s",
           streams=n_streams)
    record(out, "serve_stream_drill_ttft_p99", _pct(ttft, 99), "s",
           streams=n_streams)
    st = handle.stats.remote().result(timeout=30)
    assert st["kv_blocks_in_use"] == 0, st["kv_leak_report"]
    record(out, "serve_stream_drill_kv_blocks_after", st["kv_blocks_in_use"],
           "blocks", acceptance="zero KV-block leak after the drill")
    serve.delete("bench_drill")


# ----------------------------------------------------------------------
# phase 2b: router→replica channel dataplane A/B (ROADMAP item 1 wiring:
# per-token stream items through the object store were the bottleneck —
# route token streaming over compiled-DAG channels, record before/after)
# ----------------------------------------------------------------------
def _run_stream_batch(handle, n_streams, max_tokens):
    stream_handle = handle.options(stream=True)
    t_start = time.time()
    streams = []
    for i in range(n_streams):
        gen = stream_handle.generate.remote(
            {"prompt": [1, 2, i % 11], "max_tokens": max_tokens}
        )
        streams.append({"gen": gen, "tokens": 0, "done": False})
    open_set = list(streams)
    deadline = time.time() + 300
    while open_set and time.time() < deadline:
        for s in list(open_set):
            try:
                ev = s["gen"].try_next()
            except StopIteration:
                s["done"] = True
                open_set.remove(s)
                continue
            except Exception:  # noqa: BLE001
                open_set.remove(s)
                continue
            if ev is not None and isinstance(ev, dict) and "token" in ev:
                s["tokens"] += 1
    wall = time.time() - t_start
    assert all(s["done"] for s in streams), "streams failed in A/B phase"
    return sum(s["tokens"] for s in streams), wall


def phase_dataplane_ab(out, n_streams=192, max_tokens=16, width=16):
    """The same token-stream workload over both transports: per-token
    object-store items (RPC path, dataplane off) vs multiplexed channel
    frames (dataplane on).  Fresh app per arm so neither inherits the
    other's attach state."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.serve._private.router import _routers

    results = {}
    for arm, enabled in (("rpc", False), ("dataplane", True)):
        CONFIG._overrides["serve_channel_dataplane"] = enabled
        app = llm.build_app(
            llm.LLMConfig(model="tiny", max_batch_size=width, num_blocks=512,
                          block_size=8, max_queue=n_streams + 64,
                          name=f"bench_ab_{arm}"),
            max_ongoing_requests=2 * n_streams,
        )
        handle = serve.run(app, name=f"bench_ab_{arm}_app")
        handle.remote({"prompt": [1], "max_tokens": 4}).result(timeout=120)
        tokens, wall = _run_stream_batch(handle, n_streams, max_tokens)
        if enabled:
            router = _routers.get(handle.deployment_name)
            engaged = bool(
                router
                and any(
                    getattr(v, "replica_id", None) is not None
                    for v in router._dataplanes.values()
                )
            )
            assert engaged, "dataplane arm did not attach channel clients"
        results[arm] = tokens / wall
        record(out, f"serve_stream_tokens_per_s_{arm}", tokens / wall,
               "tokens/s", streams=n_streams, max_tokens=max_tokens)
        serve.delete(f"bench_ab_{arm}")
    CONFIG._overrides["serve_channel_dataplane"] = True
    record(out, "serve_stream_dataplane_speedup",
           results["dataplane"] / results["rpc"], "x",
           acceptance="token streaming over compiled channels vs object-store hops")
    return results


# ----------------------------------------------------------------------
# phase 3: shed rate far past the bound
# ----------------------------------------------------------------------
def phase_shed(out, n_requests=256, max_queue=48):
    app = llm.build_app(
        llm.LLMConfig(model="tiny", max_batch_size=8, num_blocks=256,
                      block_size=8, max_queue=max_queue, name="bench_shed"),
        max_ongoing_requests=2 * n_requests,
    )
    handle = serve.run(app, name="bench_shed_app")
    handle.remote({"prompt": [1], "max_tokens": 4}).result(timeout=120)
    responses = [
        handle.remote({"prompt": [i % 5], "max_tokens": 12})
        for i in range(n_requests)
    ]
    shed = completed = 0
    for r in responses:
        try:
            r.result(timeout=300)
            completed += 1
        except RequestShedError:
            shed += 1
    assert shed + completed == n_requests
    assert shed > 0, "flood never shed — the bound is not enforced"
    assert completed >= max_queue, "admitted requests must complete"
    record(out, "serve_shed_rate", shed / n_requests, "fraction",
           flood=n_requests, queue_bound=max_queue, shed=shed,
           completed=completed,
           acceptance="overflow sheds typed + retryable; admitted work completes")
    st = handle.stats.remote().result(timeout=30)
    assert st["kv_blocks_in_use"] == 0, st["kv_leak_report"]
    serve.delete("bench_shed")


# ----------------------------------------------------------------------
# phase 4: chaos — replica kill mid-load
# ----------------------------------------------------------------------
def phase_chaos(out, n_streams=128, max_tokens=60):
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    app = llm.build_app(
        llm.LLMConfig(model="tiny", max_batch_size=8, num_blocks=512,
                      block_size=8, max_queue=4 * n_streams,
                      name="bench_chaos"),
        num_replicas=2,
        max_ongoing_requests=4 * n_streams,
    )
    handle = serve.run(app, name="bench_chaos_app")
    controller = ray_tpu.get_actor(CONTROLLER_NAME, "serve")
    reps = ray_tpu.get(controller.get_replicas.remote("bench_chaos"))
    assert len(reps) == 2
    actors = {r["replica_id"]: ray_tpu.get_actor(r["actor_name"], "serve")
              for r in reps}

    streams = []
    stream_handle = handle.options(stream=True)
    for i in range(n_streams):
        gen = stream_handle.generate.remote(
            {"prompt": [2, 3, i % 5], "max_tokens": max_tokens}
        )
        streams.append({"gen": gen, "established": False, "tokens": 0,
                        "failed": False, "done": False})
    # establish: every stream has a first token
    open_set = list(streams)
    deadline = time.time() + 120
    while time.time() < deadline and any(not s["established"] for s in streams):
        for s in streams:
            if s["established"] or s["failed"]:
                continue
            try:
                ev = s["gen"].try_next()
            except StopIteration:
                s["done"] = s["established"] = True
                continue
            except Exception:  # noqa: BLE001
                s["failed"] = True
                continue
            if isinstance(ev, dict) and "token" in ev:
                s["tokens"] += 1
                s["established"] = True
    established = [s for s in streams if s["established"] and not s["done"]]
    counts = {rid: ray_tpu.get(a.stats.remote()).get("total", 0)
              for rid, a in actors.items()}
    victim = max(counts, key=counts.get)
    t_kill = time.time()
    ray_tpu.kill(actors[victim])

    open_set = [s for s in established if not s["done"]]
    deadline = time.time() + 300
    while open_set and time.time() < deadline:
        for s in list(open_set):
            try:
                ev = s["gen"].try_next()
            except StopIteration:
                s["done"] = True
                open_set.remove(s)
                continue
            except Exception:  # noqa: BLE001
                s["failed"] = True
                open_set.remove(s)
                continue
            if isinstance(ev, dict) and "token" in ev:
                s["tokens"] += 1
    survivors_done = sum(1 for s in established if s["done"])
    victim_failed = sum(1 for s in established if s["failed"])
    stuck = sum(1 for s in established if not s["done"] and not s["failed"])
    assert stuck == 0, f"{stuck} streams neither finished nor failed"
    # acceptance: zero failed established streams on SURVIVING replicas —
    # every failure must be attributable to the killed replica's share
    assert victim_failed < len(established), "every stream failed — survivor hit too"
    assert survivors_done > 0, "no established stream survived the kill"

    # new requests re-route (router evicts on observed death)
    t0 = time.time()
    ok = False
    while time.time() - t0 < 60:
        try:
            handle.remote({"prompt": [9], "max_tokens": 4}).result(timeout=60)
            ok = True
            break
        except Exception:  # noqa: BLE001
            time.sleep(0.2)
    assert ok, "re-route never converged after the kill"
    reroute_s = time.time() - t_kill

    # controller replaces the dead replica
    deadline = time.time() + 120
    while time.time() < deadline:
        reps = ray_tpu.get(controller.get_replicas.remote("bench_chaos"))
        if len(reps) == 2 and all(r["replica_id"] != victim for r in reps):
            break
        time.sleep(0.5)
    assert len(reps) == 2, "dead replica never replaced"
    record(out, "serve_chaos_survivor_streams_completed", survivors_done,
           "streams", established=len(established),
           failed_on_victim=victim_failed,
           recovery_s=round(time.time() - t_kill, 2),
           reroute_s=round(reroute_s, 2),
           acceptance="zero failed established streams on surviving replicas")
    serve.delete("bench_chaos")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_serve.json")
    ap.add_argument("--streams", type=int, default=4096)
    ap.add_argument("--skip-chaos", action="store_true")
    args = ap.parse_args()

    ray_tpu.init(num_cpus=4)
    out = {}
    try:
        cont, static = phase_throughput(out)
        phase_stream_drill(out, n_streams=args.streams)
        phase_dataplane_ab(out)
        phase_shed(out)
        if not args.skip_chaos:
            phase_chaos(out)
        assert cont > static, (
            f"continuous batching ({cont:.0f} tok/s) did not beat the static "
            f"@serve.batch baseline ({static:.0f} tok/s)"
        )
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray_tpu.shutdown()
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {args.out} ({len(out)} records)")


if __name__ == "__main__":
    main()
