"""ResNet-50 / CIFAR-10 training throughput on TPU (BASELINE.json
configs[1]: "Ray Train JaxTrainer ResNet-50 / CIFAR-10 (single v5e-8)").

The reference publishes no TPU numbers (BASELINE.md: published = {});
``vs_baseline`` normalizes MFU against the ~40% MFU the reference's
GPU-era torch-DDP ResNet stack typically achieves, i.e. vs_baseline =
measured_mfu / 0.40 — > 1.0 means better hardware utilization than the
reference stack, independent of chip generation.

FLOPs per step come from XLA's own cost model
(compiled.cost_analysis()["flops"]), not a hand formula, so MFU reflects
the program actually executed (bf16 convs, BatchNorm, SGD update).
Peak is taken as 197 TFLOPs bf16 (v5e); on CPU fallback MFU is omitted.

Prints ONE JSON line (same contract as bench.py).  Run standalone or
via BENCH_RESNET=1 environments; kept out of bench.py's critical path
so the flagship GPT-2 number never waits on this.
"""

from __future__ import annotations

import json
import time

PEAK_BF16_FLOPS = 197e12  # v5e chip
REFERENCE_STACK_MFU = 0.40


def main():
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the environment pins the axon TPU plugin via sitecustomize,
        # overriding the env var — re-pin in-process (same dance as
        # tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import resnet

    on_tpu = jax.default_backend() == "tpu"
    n_dev = len(jax.devices())
    if on_tpu:
        cfg = resnet.ResNetConfig.resnet50()
        B, steps = 512, 30
    else:
        # XLA:CPU emulates bf16 convs at glacial speed — f32 for the
        # correctness-only CPU fallback
        cfg = resnet.ResNetConfig.resnet18(dtype=jnp.float32)
        B, steps = 16, 2

    variables = resnet.init_variables(cfg, image_shape=(1, 32, 32, 3))
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = optax.sgd(0.1, momentum=0.9)
    opt_state = opt.init(params)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, 32, 32, 3), np.float32))
    y = jnp.asarray(rng.integers(0, cfg.num_classes, B, np.int32))

    # AOT-compile once; cost_analysis reads the SAME executable that runs
    step = (
        jax.jit(resnet.make_train_step(cfg, opt), donate_argnums=(0, 1, 2))
        .lower(params, batch_stats, opt_state, x, y)
        .compile()
    )
    cost = step.cost_analysis()
    flops_per_step = float(cost.get("flops", 0.0)) if cost else 0.0

    for _ in range(3):
        params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state, x, y)
    float(jax.device_get(loss))  # sync
    t0 = time.perf_counter()
    for _ in range(steps):
        params, batch_stats, opt_state, loss = step(params, batch_stats, opt_state, x, y)
    float(jax.device_get(loss))
    dt = time.perf_counter() - t0

    images_s_chip = B * steps / dt / n_dev
    from bench_common import provenance

    rec = {
        "metric": "resnet50_cifar10_train_images_per_sec_per_chip",
        "value": round(images_s_chip, 1),
        "unit": "images/s/chip",
        # platform provenance first-class: bench_gate refuses
        # cross-platform comparisons keyed on on_tpu
        **provenance(),
        "batch_size": B,
        "flops_per_step": flops_per_step,
    }
    if on_tpu and flops_per_step:
        mfu = flops_per_step * steps / dt / n_dev / PEAK_BF16_FLOPS
        rec["mfu"] = round(mfu, 4)
        rec["vs_baseline"] = round(mfu / REFERENCE_STACK_MFU, 4)
    else:
        rec["vs_baseline"] = 0.0
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
