"""Shared bench provenance: every bench record header must say what
platform it was captured on, prominently, so bench_gate.py and human
readers can never mistake a CPU capture for a TPU regression (the
BENCH_r04/r05 confusion class — ROADMAP environment note).

Usage in every bench*.py:

    from bench_common import provenance
    rec = {"metric": ..., "value": ..., **provenance()}

``provenance()`` probes the live jax backend once (cached) and returns
``{"on_tpu": bool, "platform": str}``; processes without jax report
``platform="none"``.
"""

from __future__ import annotations

import functools


@functools.lru_cache(maxsize=1)
def provenance() -> dict:
    try:
        import jax

        try:
            jax.devices()
        except RuntimeError:
            # A pinned-but-dead accelerator plugin: fall back to whatever
            # backend initializes (mirrors bench.py's probe fallback).
            jax.config.update("jax_platforms", "")
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — bench boxes without jax still stamp
        backend = "none"
    return {"on_tpu": backend == "tpu", "platform": backend}
