"""North-star metric #2: RLlib PPO env-steps/sec on the TPU.

BASELINE.json names two headline metrics; this measures the second
("RLlib PPO env-steps/sec", ref: rllib/tuned_examples/ppo/atari_ppo.py +
release/release_tests.yaml rllib throughput suites — the reference
publishes no absolute TPU numbers, so the value stands on its own and
vs_baseline is omitted).

Two configs, both driven through the REAL Algorithm.training_step (not a
stripped loop), single process owning the chip (num_env_runners=0 inline
runner — the env-runner actor plane is benched separately in
BENCH_micro.json's actor numbers):

  cartpole   — CartPole-v1, 32 vector envs, MLP 64x64.  The classic
               small-obs config: throughput is env-stepping + per-step
               inference latency bound, the learner update is noise.
  pong_scale — synthetic 84x84x4 uint8 image env (ALE isn't shipped in
               this image; the env is a fixed-length random-pixel
               stepper so the number isolates the FRAMEWORK + model
               cost, not emulator speed), Nature-CNN torso, 32 envs.
               Throughput is inference/update (MXU) bound.

The phase split (env stepping vs policy inference vs learner update) is
measured by instrumenting the inline runner's envs.step and explore_fn —
the decomposition VERDICT r3 asked for; results land in PERF_ANALYSIS.md.

Prints one JSON object with both configs + phase splits.
"""

from __future__ import annotations

import json
import time


def _make_cartpole_cfg():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=0,
            num_envs_per_env_runner=32,
            rollout_fragment_length=128,
        )
        .training(lr=3e-4, train_batch_size=4096, minibatch_size=1024, num_epochs=4)
    )


class _RandomImageEnv:
    """Pong-scale synthetic env: 84x84x4 uint8 observations, 6 discrete
    actions, 512-step episodes.  Steps in O(1) (obs buffer reused with a
    cheap in-place mutation) so the measurement isolates framework +
    model throughput from emulator speed."""

    metadata = {"render_modes": []}
    render_mode = None
    spec = None

    def __init__(self):
        import gymnasium as gym
        import numpy as np

        self.observation_space = gym.spaces.Box(0, 255, (84, 84, 4), np.uint8)
        self.action_space = gym.spaces.Discrete(6)
        self._rng = np.random.default_rng(0)
        self._obs = self._rng.integers(0, 255, (84, 84, 4), np.uint8)
        self._t = 0

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return self._obs, {}

    def step(self, action):
        import numpy as np

        self._t += 1
        # cheap obs mutation: roll one row so consecutive frames differ
        self._obs = np.roll(self._obs, 1, axis=0)
        reward = float(action == 2)
        terminated = False
        truncated = self._t >= 512
        return self._obs, reward, terminated, truncated, {}

    def close(self):
        pass


def _make_pong_cfg():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment(env_creator=lambda: _RandomImageEnv())
        .env_runners(
            num_env_runners=0,
            num_envs_per_env_runner=32,
            rollout_fragment_length=64,
        )
        .training(
            lr=2.5e-4,
            train_batch_size=2048,
            minibatch_size=512,
            num_epochs=2,
            model={
                # Nature-CNN (Mnih et al.) — the reference atari_ppo stack
                "conv_filters": ((32, 8, 4), (64, 4, 2), (64, 3, 1)),
                "hidden": (512,),
                "vf_share_layers": True,
            },
        )
    )


def _instrument(runner, learner_group):
    """Wrap the inline runner's env stepping + policy inference and the
    learner update with accumulating timers; returns the timer dict."""
    t = {"env": 0.0, "infer": 0.0, "update": 0.0}
    real_update = learner_group.update_from_batch

    def timed_update(batch, **kw):
        t0 = time.perf_counter()
        out = real_update(batch, **kw)
        t["update"] += time.perf_counter() - t0
        return out

    learner_group.update_from_batch = timed_update
    real_env_step = runner.envs.step
    real_explore = runner._explore_fn
    real_infer = runner._infer_fn

    def timed_env_step(actions):
        t0 = time.perf_counter()
        out = real_env_step(actions)
        t["env"] += time.perf_counter() - t0
        return out

    def timed_explore(params, obs, rng):
        t0 = time.perf_counter()
        out = real_explore(params, obs, rng)
        # block so the timer captures device time, not dispatch time
        out[0].block_until_ready()
        t["infer"] += time.perf_counter() - t0
        return out

    def timed_infer(params, obs):
        t0 = time.perf_counter()
        out = real_infer(params, obs)
        out[1].block_until_ready()
        t["infer"] += time.perf_counter() - t0
        return out

    runner.envs.step = timed_env_step
    runner._explore_fn = timed_explore
    runner._infer_fn = timed_infer
    return t


def _make_cartpole_podracer_cfg():
    """Podracer cartpole, like-for-like with the sync config's update
    schedule: the same 4096-step × 1024-minibatch × 4-epoch fused
    update, fed by 4 streaming runners × 32 envs.  Env stepping,
    inference, and (now in-jit) GAE run in parallel runner processes
    instead of serialized with the update — this is the profile shape
    (update itself cheap, everything else overhead) where the podracer
    split pays on ANY box."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=4,
            num_envs_per_env_runner=32,
            rollout_fragment_length=32,
        )
        .podracer()
        .training(lr=3e-4, train_batch_size=4096, minibatch_size=1024, num_epochs=4)
    )


def _make_pong_podracer_cfg(algo: str = "ppo"):
    """The podracer restructure of pong_scale: 2 streaming env-runner
    actors × 16 vector envs over compiled channels into the fused
    learner (docs/rllib.md).  Like-for-like with the sync config: same
    env, same Nature-CNN model, same total train_batch_size per update."""
    model = {
        "conv_filters": ((32, 8, 4), (64, 4, 2), (64, 3, 1)),
        "hidden": (512,),
        "vf_share_layers": True,
    }
    if algo == "impala":
        from ray_tpu.rllib.algorithms.impala import IMPALAConfig

        return (
            IMPALAConfig()
            .environment(env_creator=lambda: _RandomImageEnv())
            .env_runners(num_env_runners=2, num_envs_per_env_runner=16)
            .podracer()
            .training(lr=2.5e-4, rollout_fragment_length=32, model=model)
        )
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment(env_creator=lambda: _RandomImageEnv())
        .env_runners(
            num_env_runners=2,
            num_envs_per_env_runner=16,
            rollout_fragment_length=32,
        )
        .podracer()
        .training(
            lr=2.5e-4,
            train_batch_size=2048,
            minibatch_size=512,
            num_epochs=2,
            model=model,
        )
    )


def bench_config(name: str, cfg, iters: int = 3) -> dict:
    import jax

    algo = cfg.build()
    runner = algo.env_runner_group.local_runner
    # warmup: compiles explore/infer/update fns
    algo.train()
    timers = _instrument(runner, algo.learner_group)
    t0 = time.perf_counter()
    steps = 0
    for _ in range(iters):
        out = algo.train()
        steps += out["num_env_steps_sampled"]
    wall = time.perf_counter() - t0
    algo.cleanup()
    t_other = wall - timers["env"] - timers["infer"] - timers["update"]
    return {
        "config": name,
        "env_steps_per_sec": round(steps / wall, 1),
        "steps": steps,
        "wall_s": round(wall, 3),
        "pct_env_step": round(100 * timers["env"] / wall, 1),
        "pct_inference": round(100 * timers["infer"] / wall, 1),
        "pct_learner_update": round(100 * timers["update"] / wall, 1),
        "pct_gae_and_bookkeeping": round(100 * t_other / wall, 1),
    }


def bench_podracer_config(name: str, cfg, iters: int = 6, warmup: int = 2) -> dict:
    """Podracer plane throughput: steady-state env-steps/s consumed by
    the learner off the streaming fragments.  The phase split of the
    sync bench is replaced by the plane's own attribution: the learner's
    idle fraction and the queue occupancy say which side bounds."""
    algo = cfg.build()
    for _ in range(warmup):
        algo.train()
    drv = algo._podracer
    t0 = time.perf_counter()
    steps = 0
    out = {}
    for _ in range(iters):
        out = algo.train()
        steps += out["num_env_steps_sampled"]
    wall = time.perf_counter() - t0
    plane = drv.metrics()
    algo.cleanup()
    return {
        "config": name,
        "env_steps_per_sec": round(steps / wall, 1),
        "steps": steps,
        "wall_s": round(wall, 3),
        "weight_generation": plane["weight_generation"],
        "stale_fragments_dropped": plane["stale_fragments_dropped"],
        "fragments_received": plane["fragments_received"],
        "trajectory_queue_depth_at_end": plane["trajectory_queue_depth"],
        "runner_deaths": plane["runner_deaths"],
    }


def best_of(fn, n: int) -> dict:
    """Best-of-N like-for-like capture (the 1-core CI box swings
    multi-process numbers 2-5x run-to-run; every record carries all N
    runs so the spread is visible)."""
    runs = [fn() for _ in range(n)]
    best = max(runs, key=lambda r: r["env_steps_per_sec"])
    best["best_of"] = n
    best["runs_env_steps_per_sec"] = [r["env_steps_per_sec"] for r in runs]
    return best


def main(repeat: int = 2) -> dict:
    import os

    import jax

    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "")
    from bench_common import provenance

    import ray_tpu

    ray_tpu.init(num_cpus=max(4, os.cpu_count() or 1))
    try:
        out = {
            "metric": "ppo_env_steps_per_sec",
            "unit": "env_steps/s",
            # platform provenance first-class (on_tpu + platform): bench_gate
            # refuses cross-platform comparisons keyed on it
            **provenance(),
            "loadavg_1m_at_capture": round(os.getloadavg()[0], 2),
            "cartpole": best_of(
                lambda: bench_config("cartpole", _make_cartpole_cfg()), repeat
            ),
            "cartpole_podracer": best_of(
                lambda: bench_podracer_config(
                    "cartpole_podracer", _make_cartpole_podracer_cfg(), iters=25
                ),
                repeat,
            ),
            "pong_scale": best_of(
                lambda: bench_config("pong_scale", _make_pong_cfg()), repeat
            ),
            "pong_scale_podracer": best_of(
                lambda: bench_podracer_config(
                    "pong_scale_podracer", _make_pong_podracer_cfg("ppo"),
                    iters=3, warmup=1,
                ),
                repeat,
            ),
            "pong_scale_impala_async": best_of(
                lambda: bench_podracer_config(
                    "pong_scale_impala_async", _make_pong_podracer_cfg("impala"),
                    iters=4, warmup=1,
                ),
                repeat,
            ),
        }
    finally:
        ray_tpu.shutdown()
    # the podracer restructure's like-for-like before/after, this box
    for sync_key, pod_keys in (
        ("pong_scale", ("pong_scale_podracer", "pong_scale_impala_async")),
        ("cartpole", ("cartpole_podracer",)),
    ):
        sync = out[sync_key]["env_steps_per_sec"]
        if sync:
            for k in pod_keys:
                out[k]["vs_sync"] = round(out[k]["env_steps_per_sec"] / sync, 2)
    out["value"] = out["cartpole"]["env_steps_per_sec"]
    return out


if __name__ == "__main__":
    print(json.dumps(main()))
