"""North-star metric #2: RLlib PPO env-steps/sec on the TPU.

BASELINE.json names two headline metrics; this measures the second
("RLlib PPO env-steps/sec", ref: rllib/tuned_examples/ppo/atari_ppo.py +
release/release_tests.yaml rllib throughput suites — the reference
publishes no absolute TPU numbers, so the value stands on its own and
vs_baseline is omitted).

Two configs, both driven through the REAL Algorithm.training_step (not a
stripped loop), single process owning the chip (num_env_runners=0 inline
runner — the env-runner actor plane is benched separately in
BENCH_micro.json's actor numbers):

  cartpole   — CartPole-v1, 32 vector envs, MLP 64x64.  The classic
               small-obs config: throughput is env-stepping + per-step
               inference latency bound, the learner update is noise.
  pong_scale — synthetic 84x84x4 uint8 image env (ALE isn't shipped in
               this image; the env is a fixed-length random-pixel
               stepper so the number isolates the FRAMEWORK + model
               cost, not emulator speed), Nature-CNN torso, 32 envs.
               Throughput is inference/update (MXU) bound.

The phase split (env stepping vs policy inference vs learner update) is
measured by instrumenting the inline runner's envs.step and explore_fn —
the decomposition VERDICT r3 asked for; results land in PERF_ANALYSIS.md.

Prints one JSON object with both configs + phase splits.
"""

from __future__ import annotations

import json
import time


def _make_cartpole_cfg():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=0,
            num_envs_per_env_runner=32,
            rollout_fragment_length=128,
        )
        .training(lr=3e-4, train_batch_size=4096, minibatch_size=1024, num_epochs=4)
    )


class _RandomImageEnv:
    """Pong-scale synthetic env: 84x84x4 uint8 observations, 6 discrete
    actions, 512-step episodes.  Steps in O(1) (obs buffer reused with a
    cheap in-place mutation) so the measurement isolates framework +
    model throughput from emulator speed."""

    metadata = {"render_modes": []}
    render_mode = None
    spec = None

    def __init__(self):
        import gymnasium as gym
        import numpy as np

        self.observation_space = gym.spaces.Box(0, 255, (84, 84, 4), np.uint8)
        self.action_space = gym.spaces.Discrete(6)
        self._rng = np.random.default_rng(0)
        self._obs = self._rng.integers(0, 255, (84, 84, 4), np.uint8)
        self._t = 0

    def reset(self, *, seed=None, options=None):
        self._t = 0
        return self._obs, {}

    def step(self, action):
        import numpy as np

        self._t += 1
        # cheap obs mutation: roll one row so consecutive frames differ
        self._obs = np.roll(self._obs, 1, axis=0)
        reward = float(action == 2)
        terminated = False
        truncated = self._t >= 512
        return self._obs, reward, terminated, truncated, {}

    def close(self):
        pass


def _make_pong_cfg():
    from ray_tpu.rllib.algorithms.ppo import PPOConfig

    return (
        PPOConfig()
        .environment(env_creator=lambda: _RandomImageEnv())
        .env_runners(
            num_env_runners=0,
            num_envs_per_env_runner=32,
            rollout_fragment_length=64,
        )
        .training(
            lr=2.5e-4,
            train_batch_size=2048,
            minibatch_size=512,
            num_epochs=2,
            model={
                # Nature-CNN (Mnih et al.) — the reference atari_ppo stack
                "conv_filters": ((32, 8, 4), (64, 4, 2), (64, 3, 1)),
                "hidden": (512,),
                "vf_share_layers": True,
            },
        )
    )


def _instrument(runner, learner_group):
    """Wrap the inline runner's env stepping + policy inference and the
    learner update with accumulating timers; returns the timer dict."""
    t = {"env": 0.0, "infer": 0.0, "update": 0.0}
    real_update = learner_group.update_from_batch

    def timed_update(batch, **kw):
        t0 = time.perf_counter()
        out = real_update(batch, **kw)
        t["update"] += time.perf_counter() - t0
        return out

    learner_group.update_from_batch = timed_update
    real_env_step = runner.envs.step
    real_explore = runner._explore_fn
    real_infer = runner._infer_fn

    def timed_env_step(actions):
        t0 = time.perf_counter()
        out = real_env_step(actions)
        t["env"] += time.perf_counter() - t0
        return out

    def timed_explore(params, obs, rng):
        t0 = time.perf_counter()
        out = real_explore(params, obs, rng)
        # block so the timer captures device time, not dispatch time
        out[0].block_until_ready()
        t["infer"] += time.perf_counter() - t0
        return out

    def timed_infer(params, obs):
        t0 = time.perf_counter()
        out = real_infer(params, obs)
        out[1].block_until_ready()
        t["infer"] += time.perf_counter() - t0
        return out

    runner.envs.step = timed_env_step
    runner._explore_fn = timed_explore
    runner._infer_fn = timed_infer
    return t


def bench_config(name: str, cfg, iters: int = 3) -> dict:
    import jax

    algo = cfg.build()
    runner = algo.env_runner_group.local_runner
    # warmup: compiles explore/infer/update fns
    algo.train()
    timers = _instrument(runner, algo.learner_group)
    t0 = time.perf_counter()
    steps = 0
    for _ in range(iters):
        out = algo.train()
        steps += out["num_env_steps_sampled"]
    wall = time.perf_counter() - t0
    algo.cleanup()
    t_other = wall - timers["env"] - timers["infer"] - timers["update"]
    return {
        "config": name,
        "env_steps_per_sec": round(steps / wall, 1),
        "steps": steps,
        "wall_s": round(wall, 3),
        "pct_env_step": round(100 * timers["env"] / wall, 1),
        "pct_inference": round(100 * timers["infer"] / wall, 1),
        "pct_learner_update": round(100 * timers["update"] / wall, 1),
        "pct_gae_and_bookkeeping": round(100 * t_other / wall, 1),
    }


def main() -> dict:
    import jax

    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "")
    from bench_common import provenance

    out = {
        "metric": "ppo_env_steps_per_sec",
        "unit": "env_steps/s",
        # platform provenance first-class (on_tpu + platform): bench_gate
        # refuses cross-platform comparisons keyed on it
        **provenance(),
        "cartpole": bench_config("cartpole", _make_cartpole_cfg()),
        "pong_scale": bench_config("pong_scale", _make_pong_cfg()),
    }
    out["value"] = out["cartpole"]["env_steps_per_sec"]
    return out


if __name__ == "__main__":
    print(json.dumps(main()))
