"""Graceful node drain + preemption-aware recovery plane (ISSUE 3).

Layers drilled here:

1. Plane determinism: the ``preempt`` chaos action and pubsub-channel
   chaos rules replay identically under the same seed.
2. Core drain path (tier-1): ``drain_node`` moves a node
   ALIVE -> DRAINING, the raylet stops granting leases and bundle
   reservations, restartable actors migrate, sole-copy objects are
   re-replicated, and the node's later death loses nothing.
3. Drain-under-chaos matrix (``-m chaos``):
   - preemption notice honored: zero loss, no lineage reconstruction;
   - notice chaos-dropped: the reactive heartbeat path recovers
     (lineage reconstruction still repairs the lost object);
   - deadline expiry mid-task: in-flight tasks retried via the
     idempotent submit machinery;
   - JaxTrainer proactive checkpoint: a drain notice covering a rank
     triggers an immediate checkpoint + whole-group restart that resumes
     AHEAD of the last periodic checkpoint and burns none of
     FailureConfig.max_failures.
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def env_guard():
    """Set env vars scoped to the test; restore (and reset the chaos
    plane) afterwards."""
    saved = {}

    def set_env(env: dict):
        for k, v in env.items():
            saved.setdefault(k, os.environ.get(k))
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    yield set_env
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old
    from ray_tpu._private.chaos import CHAOS

    CHAOS.reset()


@pytest.fixture()
def drain_cluster(env_guard):
    """Cluster factory with PER-PROCESS chaos env: head (GCS) and each
    worker node can carry different fault specs — a preemption rule must
    hit exactly one raylet, not every process in the session."""
    created = []

    def make(head_env=None, head_args=None, nodes=()):
        env_guard(head_env or {})
        c = Cluster(initialize_head=True, head_node_args=head_args or {"num_cpus": 1})
        # Head (GCS) is up with its env; later spawns must not inherit it.
        env_guard({k: None for k in (head_env or {})})
        handles = []
        for kw in nodes:
            kw = dict(kw)
            node_env = kw.pop("node_env", {})
            env_guard(node_env)
            handles.append(c.add_node(**kw))
            env_guard({k: None for k in node_env})
        c.wait_for_nodes()
        ray_tpu.init(address=c.address)
        created.append(c)
        return c, handles

    yield make
    ray_tpu.shutdown()
    for c in created:
        c.shutdown()


def _nodes_by_id():
    from ray_tpu.util import state

    return {n["node_id"]: n for n in state.list_nodes()}


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


# ==========================================================================
# 1. Plane determinism for the new fault axes
# ==========================================================================


def test_preempt_and_pubsub_rules_deterministic(env_guard):
    from ray_tpu._private.chaos import ChaosPlane

    env_guard(
        {
            "RAY_TPU_testing_chaos_spec": (
                "@raylet.tick:preempt:at=3:ms=2500,"
                "pubsub:nodes:drop_req:p=0.5:n=-1,"
                "pubsub:actors:delay_req:ms=20:n=2"
            ),
            "RAY_TPU_testing_chaos_seed": "77",
        }
    )

    def drive(plane):
        out = []
        for _ in range(10):
            out.append(plane.maybe_preempt("raylet.tick"))
            out.append(plane.decide("pubsub:nodes", "req"))
            out.append(plane.decide("pubsub:actors", "req"))
        return out, plane.schedule_snapshot(), plane.schedule_digest()

    o1, s1, h1 = drive(ChaosPlane())
    o2, s2, h2 = drive(ChaosPlane())
    assert o1 == o2 and s1 == s2 and h1 == h2
    # The preempt rule fires exactly once, on the 3rd tick, with its
    # notice window (ms=2500).
    notices = [v for v in o1[0::3] if v is not None]
    assert notices == [2.5]
    assert o1[0::3][2] == 2.5  # the 3rd maybe_preempt call
    # The actors-channel delay rule fires on its first two matches only;
    # preempt rules never leak into request/reply decisions.
    actor_decisions = o1[2::3]
    assert [d.delay_s for d in actor_decisions[:2]] == [0.02, 0.02]
    assert all(d.clean for d in actor_decisions[2:])
    # pubsub drop rule fired at least once at p=0.5 over 10 matches.
    assert any(d.drop for d in o1[1::3])


# ==========================================================================
# 2. Core drain path (tier-1)
# ==========================================================================


def test_drain_node_migrates_actor_and_objects(drain_cluster):
    """drain_node: leases/bundles rejected on the draining raylet, the
    restartable actor is restarted elsewhere, the sole-copy object is
    re-replicated, and the node's death loses nothing."""
    from ray_tpu._private import rpc
    from ray_tpu.util import state

    c, handles = drain_cluster(
        head_args={"num_cpus": 1},
        nodes=[{"num_cpus": 2}, {"num_cpus": 2}],
    )
    worker = ray_tpu._private.worker.get_global_worker()

    @ray_tpu.remote(num_cpus=2, max_restarts=1)
    class Keeper:
        def make(self):
            return ray_tpu.put(np.arange(150_000))

        def home(self):
            return ray_tpu.get_runtime_context().get_node_id()

    keeper = Keeper.remote()
    home = ray_tpu.get(keeper.home.remote(), timeout=60)
    data_ref = ray_tpu.get(keeper.make.remote(), timeout=60)

    reply = worker.gcs_client.call(
        "drain_node",
        {"node_id": bytes.fromhex(home), "reason": "PREEMPTION", "deadline_s": 25},
    )
    assert reply["accepted"] and reply["state"] == "DRAINING"
    # Idempotent: a duplicate drain joins the in-flight one.
    again = worker.gcs_client.call(
        "drain_node",
        {"node_id": bytes.fromhex(home), "reason": "PREEMPTION", "deadline_s": 25},
    )
    assert again["accepted"] and again["state"] == "DRAINING"

    rec = _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") == "DRAINING"
        and _nodes_by_id()[home],
        15, "DRAINING in state API",
    )
    assert rec["drain_reason"] == "PREEMPTION"

    # No lease granted post-drain: a direct lease request against the
    # draining raylet is rejected (spill hint or flat refusal), and new
    # placement-group reservations are refused.
    raylet_addr = rec["raylet_address"]
    client = rpc.RpcClient(raylet_addr)
    try:
        lease = client.call(
            "request_worker_lease",
            {
                "resources": {"CPU": 1},
                "job_id": worker.job_id.binary(),
                "runtime_env": None,
                "token": os.urandom(16),
            },
            timeout=15,
        )
        assert not (lease and lease.get("worker_id")), lease
        assert lease and lease.get("draining")
        assert not client.call(
            "prepare_bundle",
            {"pg_id": b"drainpg", "bundle_index": 0, "resources": {"CPU": 1}},
            timeout=15,
        )
        stats = client.call("node_stats", {})
        assert stats["draining"] and stats["drain_reason"] == "PREEMPTION"
    finally:
        client.close()

    # Actor restarted elsewhere, proactively (node still alive!).
    _wait(
        lambda: any(
            a["state"] == "ALIVE"
            and a["node_id"] != home
            and a["class_name"].endswith("Keeper")
            for a in state.list_actors()
        ),
        30, "proactive actor migration",
    )
    assert ray_tpu.get(keeper.home.remote(), timeout=60) != home

    # Migration (incl. object re-replication) completes before the kill.
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("drain_complete"),
        30, "drain_complete",
    )

    # Kill the drained node: DRAINING -> DEAD, and the object is still
    # readable from its replica — no ObjectLostError, no reconstruction.
    victim = next(
        h for h in handles if h.raylet_address == rec["raylet_address"]
    )
    c.remove_node(victim)
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") == "DEAD",
        30, "DEAD after kill",
    )
    arr = ray_tpu.get(data_ref, timeout=60)
    assert int(arr.sum()) == 11249925000


def test_drain_reschedules_created_pg_before_kill(drain_cluster):
    """ROADMAP follow-up (PR 3): a CREATED placement group with a bundle
    on a DRAINING node moves ONLY that bundle to a live node AHEAD of
    the kill — the unaffected bundle (and anything running in it) stays
    exactly where it was, instead of the whole group bouncing at node
    death."""
    from ray_tpu.util.placement_group import placement_group

    c, handles = drain_cluster(
        head_args={"num_cpus": 1},
        nodes=[{"num_cpus": 2}, {"num_cpus": 2}, {"num_cpus": 2}],
    )
    worker = ray_tpu._private.worker.get_global_worker()

    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="SPREAD")
    assert pg.wait(30)

    def pg_info():
        return worker.gcs_client.call("get_placement_group", pg.id.binary())

    info = pg_info()
    assert info["state"] == "CREATED"
    home = info["bundles"][0]["node_id"].hex()
    other = info["bundles"][1]["node_id"].hex()
    assert home != other  # SPREAD onto two of the three nodes

    reply = worker.gcs_client.call(
        "drain_node",
        {"node_id": bytes.fromhex(home), "reason": "PREEMPTION", "deadline_s": 30},
    )
    assert reply["accepted"]

    # Bundle 0 lands on the free third node while the drained one is
    # STILL DRAINING (proactive), back in CREATED state; bundle 1 has
    # not moved.
    def moved():
        i = pg_info()
        b0 = i["bundles"][0]["node_id"]
        return (
            i["state"] == "CREATED"
            and b0 is not None
            and b0.hex() != home
            and _nodes_by_id().get(home, {}).get("state") == "DRAINING"
        )

    _wait(moved, 20, "PG bundle rescheduled off the draining node pre-kill")
    assert pg_info()["bundles"][1]["node_id"].hex() == other, (
        "unaffected bundle must keep its reservation"
    )

    # The node's eventual death must NOT bounce the group again.
    victim = next(
        h for h in handles
        if h.raylet_address == _nodes_by_id()[home]["raylet_address"]
    )
    c.remove_node(victim)
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") == "DEAD",
        30, "DEAD after kill",
    )
    final = pg_info()
    assert final["state"] == "CREATED"
    assert final["bundles"][0]["node_id"].hex() != home
    assert final["bundles"][1]["node_id"].hex() == other


# ==========================================================================
# 3. Drain-under-chaos matrix
# ==========================================================================


@pytest.mark.chaos
def test_preemption_notice_honored_zero_loss(drain_cluster):
    """A seeded preemption fault drains the node with advance notice:
    the actor and sole-copy object are off the node before the kill, so
    nothing is lost and nothing is reconstructed."""
    from ray_tpu.util import state

    c, [doomed] = drain_cluster(
        head_args={"num_cpus": 1},
        nodes=[
            {
                "num_cpus": 2,
                # ~8 s of ticks of headroom to set the scene, then a 5 s
                # notice before the hard kill.
                "node_env": {
                    "RAY_TPU_testing_chaos_spec": "@raylet.tick:preempt:at=40:ms=5000",
                    "RAY_TPU_testing_chaos_seed": "1234",
                },
            }
        ],
    )

    @ray_tpu.remote(num_cpus=2, max_restarts=1)
    class Keeper:
        def make(self):
            return ray_tpu.put(np.full(120_000, 3.0))

        def home(self):
            return ray_tpu.get_runtime_context().get_node_id()

    keeper = Keeper.remote()  # only the doomed node has 2 free CPUs
    home = ray_tpu.get(keeper.home.remote(), timeout=60)
    data_ref = ray_tpu.get(keeper.make.remote(), timeout=60)

    # Migration target comes up while the preemption clock ticks.
    c.add_node(num_cpus=2)
    c.wait_for_nodes()

    # The chaos preemption delivers the drain notice on its own.
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") in ("DRAINING", "DEAD"),
        30, "chaos preemption drain notice",
    )
    assert _nodes_by_id()[home].get("drain_reason") == "PREEMPTION"
    # ...and the node dies at the deadline without any test-side kill.
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") == "DEAD",
        40, "preempted node death at deadline",
    )

    # Zero loss: actor alive elsewhere (migrated, not reconstructed from
    # scratch), object readable with no ObjectLostError.
    _wait(
        lambda: any(
            a["state"] == "ALIVE" and a["node_id"] != home
            for a in state.list_actors()
            if a["class_name"].endswith("Keeper")
        ),
        60, "actor migrated off the preempted node",
    )
    arr = ray_tpu.get(data_ref, timeout=60)
    assert float(arr.sum()) == 3.0 * 120_000
    assert ray_tpu.get(keeper.home.remote(), timeout=60) != home


@pytest.mark.chaos
def test_preemption_notice_dropped_heartbeat_fallback(drain_cluster, tmp_path):
    """The drain notice itself is chaos-dropped at the GCS: the node
    dies with no warning and the REACTIVE path (disconnect/heartbeat ->
    node death -> lineage reconstruction) must still recover the work."""
    marker = str(tmp_path / "produced.log")
    c, [doomed] = drain_cluster(
        head_env={
            # The GCS never hears the drain: every drain_node request is
            # eaten.  Fast heartbeat so the fallback fires quickly.
            "RAY_TPU_testing_chaos_spec": "drain_node:drop_req:n=-1",
            "RAY_TPU_testing_chaos_seed": "9",
            "RAY_TPU_health_check_timeout_ms": "4000",
        },
        head_args={"num_cpus": 2},
        nodes=[
            {
                "num_cpus": 2,
                "resources": {"doomed": 1},
                "node_env": {
                    "RAY_TPU_testing_chaos_spec": "@raylet.tick:preempt:at=40:ms=2000",
                    "RAY_TPU_testing_chaos_seed": "9",
                },
            }
        ],
    )

    @ray_tpu.remote(resources={"doomed": 0.1}, max_retries=3)
    def produce():
        with open(marker, "a") as f:
            f.write("ran\n")
        return np.full(120_000, 7.0)

    ref = produce.remote()
    # Do NOT get() before the death: a fetch would replicate the result
    # to the head store and the kill would lose nothing.  The marker file
    # proves the task ran; the only copy stays on the doomed node.
    _wait(lambda: os.path.exists(marker), 60, "produce side effect")
    time.sleep(1.0)  # let the result seal + report its location

    home = None
    for n in _nodes_by_id().values():
        if n["resources_total"].get("doomed"):
            home = n["node_id"]
    assert home is not None

    # The node dies at its (unheard) deadline; the notice never landed,
    # so it goes straight ALIVE -> DEAD with no DRAINING in between.
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") == "DEAD",
        60, "reactive death detection",
    )
    assert not _nodes_by_id()[home].get("drain_reason")

    # Replacement capacity; the owner's get repairs via lineage.
    c.remove_node(doomed)  # reap the self-killed node's handle
    c.add_node(num_cpus=2, resources={"doomed": 1})
    c.wait_for_nodes()
    assert float(ray_tpu.get(ref, timeout=120).sum()) == 7.0 * 120_000
    with open(marker) as f:
        runs = len(f.readlines())
    assert runs == 2, f"expected a lineage re-run (got {runs} execution(s))"


@pytest.mark.chaos
def test_drain_deadline_expiry_mid_task(drain_cluster):
    """Tasks still running when the preemption deadline kills the node
    are retried via the idempotent submit machinery and all complete."""
    drain_cluster(
        head_args={"num_cpus": 2},
        nodes=[
            {
                "num_cpus": 2,
                "node_env": {
                    "RAY_TPU_testing_chaos_spec": "@raylet.tick:preempt:at=25:ms=1500",
                    "RAY_TPU_testing_chaos_seed": "4321",
                },
            }
        ],
    )

    @ray_tpu.remote(max_retries=5)
    def slow(i):
        time.sleep(0.4)
        return i * 11

    refs = [slow.remote(i) for i in range(16)]  # spreads across both nodes
    out = ray_tpu.get(refs, timeout=180)
    assert out == [i * 11 for i in range(16)]


@pytest.mark.chaos
def test_pubsub_drain_notice_dropped(drain_cluster):
    """Satellite: pubsub deliveries route through the chaos plane — the
    nodes-channel DRAINING notice is dropped, so subscribers (the
    driver's node listeners) never hear it, while the GCS-side drain and
    the reactive death path still converge."""
    c, [node] = drain_cluster(
        head_env={
            # Drop every nodes-channel publish AFTER the two ALIVE
            # registrations (head + worker) that wait_for_nodes needs.
            "RAY_TPU_testing_chaos_spec": "pubsub:nodes:drop_req:after=2:n=-1",
            "RAY_TPU_testing_chaos_seed": "3",
        },
        head_args={"num_cpus": 2},
        nodes=[{"num_cpus": 1, "resources": {"side": 1}}],
    )
    worker = ray_tpu._private.worker.get_global_worker()
    heard = []
    worker.add_node_listener(lambda state_, node_: heard.append(state_))

    home = None
    for n in _nodes_by_id().values():
        if n["resources_total"].get("side"):
            home = n["node_id"]
    reply = worker.gcs_client.call(
        "drain_node",
        {"node_id": bytes.fromhex(home), "reason": "IDLE_TERMINATION", "deadline_s": 10},
    )
    assert reply["accepted"]
    # The GCS itself drains (RPC-visible state), but the pubsub notice
    # never reaches subscribers.
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") == "DRAINING",
        15, "RPC-visible DRAINING",
    )
    time.sleep(1.0)
    assert "DRAINING" not in heard, heard
    # Reactive fallback: the kill is still detected and the node dies.
    c.remove_node(node)
    _wait(
        lambda: _nodes_by_id().get(home, {}).get("state") == "DEAD",
        30, "reactive DEAD without pubsub",
    )


# ==========================================================================
# JaxTrainer proactive-checkpoint drill
# ==========================================================================


def _drain_ckpt_loop(config):
    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    ctx = train.get_context()
    resume = train.get_checkpoint()
    start = 0
    resumed_from = -1
    if resume is not None:
        resumed_from = resume.to_pytree()["step"]
        start = resumed_from
    node_id = ray_tpu.get_runtime_context().get_node_id()
    drain_ckpt_done = resumed_from >= 0
    for step in range(start + 1, config["total_steps"] + 1):
        time.sleep(0.15)
        ckpt = None
        if step == config["periodic_step"] and resumed_from < 0:
            ckpt = Checkpoint.from_pytree({"step": step})  # periodic
        if ctx.drain_requested() and not drain_ckpt_done:
            # Immediate best-effort checkpoint at the drain notice.
            ckpt = Checkpoint.from_pytree({"step": step})
            drain_ckpt_done = True
        with open(config["progress"], "w") as f:
            f.write(f"{node_id} {step}")
        train.report({"step": step, "resumed_from": resumed_from}, checkpoint=ckpt)


@pytest.mark.chaos
def test_jaxtrainer_drain_proactive_checkpoint(drain_cluster, tmp_path):
    """A drain notice covering a rank triggers an immediate checkpoint +
    one proactive whole-group restart: the run resumes from a step
    STRICTLY AFTER the last periodic checkpoint and, with
    max_failures=0, provably burns none of the failure budget."""
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    c, _handles = drain_cluster(
        head_args={"num_cpus": 1},
        nodes=[{"num_cpus": 2}, {"num_cpus": 2}],
    )
    worker = ray_tpu._private.worker.get_global_worker()
    progress = str(tmp_path / "progress")
    periodic_step = 5

    stop = threading.Event()
    drained_node = []

    def drainer():
        # Once the loop passes step 8, drain the node hosting the rank.
        while not stop.is_set():
            try:
                with open(progress) as f:
                    node_id, step = f.read().split()
                if int(step) >= 8:
                    worker.gcs_client.call(
                        "drain_node",
                        {
                            "node_id": bytes.fromhex(node_id),
                            "reason": "PREEMPTION",
                            "deadline_s": 60,
                        },
                    )
                    drained_node.append(node_id)
                    return
            except (OSError, ValueError):
                pass
            time.sleep(0.1)

    t = threading.Thread(target=drainer, daemon=True)
    t.start()
    try:
        trainer = JaxTrainer(
            _drain_ckpt_loop,
            train_loop_config={
                "total_steps": 20,
                "periodic_step": periodic_step,
                "progress": progress,
            },
            scaling_config=ScalingConfig(
                num_workers=1, resources_per_worker={"CPU": 2}
            ),
            run_config=RunConfig(
                name="drain_ckpt",
                storage_path=str(tmp_path),
                # ZERO failure budget: if the proactive path failed and
                # the restart were charged as a failure, fit() raises.
                failure_config=FailureConfig(max_failures=0),
            ),
        )
        result = trainer.fit()
    finally:
        stop.set()
        t.join(timeout=5)

    assert drained_node, "the drill never drained a node"
    assert result.metrics["step"] == 20
    resumed_from = result.metrics["resumed_from"]
    # Resumed from the drain-triggered checkpoint (taken at step >= 8),
    # strictly ahead of the last periodic checkpoint (step 5).
    assert resumed_from >= 8, result.metrics
    assert resumed_from > periodic_step
