"""Chaos tests: inject RPC drops via the testing_rpc_failure hook and
kill raylets mid-run (reference: src/ray/rpc/rpc_chaos.h:23 +
RayletKiller in python/ray/_private/test_utils.py:1496).

The hook spec "method:kind:count" drops the first `count` requests
(kind=req: handler never runs) or replies (kind=rep: handler ran, caller
never hears) of `method`, independently in each server process.  It is
configured through the RAY_TPU_testing_rpc_failure env var, which every
spawned cluster process inherits; rpc_call_timeout_s is lowered so
dropped calls fail fast instead of waiting out the 120 s default.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def chaos_cluster(request):
    """Per-test cluster factory: set chaos env vars BEFORE processes
    spawn, clean them up after."""
    created = []
    saved = {}

    def make(env: dict, head_args=None, nodes=()):
        for k, v in env.items():
            saved.setdefault(k, os.environ.get(k))
            os.environ[k] = v
        c = Cluster(
            initialize_head=True, head_node_args=head_args or {"num_cpus": 2}
        )
        handles = [c.add_node(**kw) for kw in nodes]
        c.wait_for_nodes()
        ray_tpu.init(address=c.address)
        created.append(c)
        return c, handles

    yield make
    ray_tpu.shutdown()
    for c in created:
        c.shutdown()
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old


def test_location_report_dropped(chaos_cluster):
    """The raylet's object_location_add push to the GCS is dropped once;
    the bounded retry must land it so a cross-node get still works."""
    _, _ = chaos_cluster(
        {"RAY_TPU_testing_rpc_failure": "object_location_add:req:1"},
        nodes=[{"num_cpus": 1, "resources": {"side": 1}}],
    )

    @ray_tpu.remote(resources={"side": 0.1})
    def make():
        return ray_tpu.put(np.arange(200_000))

    inner = ray_tpu.get(make.remote(), timeout=60)
    # Fetch the put object across nodes: requires the (retried) location.
    arr = ray_tpu.get(inner, timeout=90)
    assert int(arr.sum()) == 19999900000


def test_lost_check_dropped_during_recovery(chaos_cluster):
    """Lineage reconstruction still happens when the GCS drops the first
    object_lost_check probes — the pull loop keeps asking."""
    c, [node] = chaos_cluster(
        {"RAY_TPU_testing_rpc_failure": "object_lost_check:req:2"},
        nodes=[{"num_cpus": 1, "resources": {"doomed": 1}}],
    )

    @ray_tpu.remote(resources={"doomed": 0.1}, max_retries=3)
    def produce():
        return np.full(150_000, 7.0)

    ref = produce.remote()
    assert ray_tpu.get(ref, timeout=60).sum() == 7.0 * 150_000
    c.remove_node(node)
    c.add_node(num_cpus=1, resources={"doomed": 1})
    c.wait_for_nodes()
    # Every copy died with the node; the owner must resubmit produce()
    # even though the first lost-checks are eaten.
    assert ray_tpu.get(ref, timeout=120).sum() == 7.0 * 150_000


def test_pg_prepare_reply_dropped(chaos_cluster):
    """2-phase PG creation: a dropped prepare reply looks like a failed
    node; the GCS must roll back and retry until the group commits."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "prepare_bundle:rep:1",
            "RAY_TPU_rpc_call_timeout_s": "6",
        },
        head_args={"num_cpus": 4},
    )
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=90)
    assert len(pg.bundle_specs) == 2


def test_pg_commit_reply_dropped(chaos_cluster):
    """A dropped commit reply must not wedge the group in PENDING: the
    GCS rolls the bundles back and reschedules."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "commit_bundle:rep:1",
            "RAY_TPU_rpc_call_timeout_s": "6",
        },
        head_args={"num_cpus": 4},
    )
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=90)

    # The committed group is actually usable.
    @ray_tpu.remote(num_cpus=1)
    def inside():
        return "ok"

    from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

    ref = inside.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg)
    ).remote()
    assert ray_tpu.get(ref, timeout=60) == "ok"


def test_worker_lease_reply_dropped(chaos_cluster):
    """Direct task submission: a dropped lease grant strands a LEASED
    worker on the raylet side and returns None to the submitter — the
    submitter's reaper must re-request and tasks still complete."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "request_worker_lease:rep:1",
            "RAY_TPU_worker_lease_timeout_ms": "6000",
        }
    )

    @ray_tpu.remote
    def f(i):
        return i * 2

    out = ray_tpu.get([f.remote(i) for i in range(20)], timeout=120)
    assert out == [i * 2 for i in range(20)]


def test_register_worker_reply_dropped(chaos_cluster):
    """A worker whose registration reply is eaten dies; the pool must
    spawn a replacement and tasks still run."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "register_worker:rep:1",
            "RAY_TPU_rpc_call_timeout_s": "6",
        }
    )

    @ray_tpu.remote
    def f():
        return os.getpid()

    assert ray_tpu.get(f.remote(), timeout=90) > 0


def test_raylet_killer_tasks_retry(chaos_cluster):
    """Kill a node's raylet (SIGKILL) while its tasks are in flight;
    retriable tasks reschedule onto the surviving node."""
    c, [node] = chaos_cluster(
        {},
        head_args={"num_cpus": 2},
        nodes=[{"num_cpus": 2}],
    )

    @ray_tpu.remote(max_retries=5)
    def slow(i):
        time.sleep(0.4)
        return i

    refs = [slow.remote(i) for i in range(16)]
    time.sleep(1.0)  # let tasks spread to both nodes
    c.remove_node(node)  # SIGKILL mid-flight
    out = ray_tpu.get(refs, timeout=180)
    assert out == list(range(16))
