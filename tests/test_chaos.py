"""Chaos tests: the deterministic fault-injection plane (chaos.py) plus
the legacy testing_rpc_failure drop hook (reference:
src/ray/rpc/rpc_chaos.h:23 + RayletKiller in
python/ray/_private/test_utils.py:1496).

Three layers of drills:

1. Determinism: the same seed + spec replays the identical fault
   schedule, asserted both on the plane directly and through a real
   RpcServer dispatch.
2. Chaos matrix (``-m chaos``): drop x delay x dup against the
   submit / lease / get paths on a live cluster — everything must still
   complete, with no hangs.
3. Idempotency: duplicated submit/exec deliveries must not run a task
   twice (the at-least-once discipline of docs/failure_semantics.md).

Fault specs are configured through RAY_TPU_testing_chaos_spec /
RAY_TPU_testing_rpc_failure env vars, which every spawned cluster
process inherits; rpc_call_timeout_s is lowered so dropped calls fail
fast instead of waiting out the 120 s default.
"""

import os
import tempfile
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def chaos_cluster(request):
    """Per-test cluster factory: set chaos env vars BEFORE processes
    spawn, clean them up after."""
    created = []
    saved = {}

    def make(env: dict, head_args=None, nodes=()):
        for k, v in env.items():
            saved.setdefault(k, os.environ.get(k))
            os.environ[k] = v
        c = Cluster(
            initialize_head=True, head_node_args=head_args or {"num_cpus": 2}
        )
        handles = [c.add_node(**kw) for kw in nodes]
        c.wait_for_nodes()
        ray_tpu.init(address=c.address)
        created.append(c)
        return c, handles

    yield make
    ray_tpu.shutdown()
    for c in created:
        c.shutdown()
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old


def test_location_report_dropped(chaos_cluster):
    """The raylet's object_location_add push to the GCS is dropped once;
    the bounded retry must land it so a cross-node get still works."""
    _, _ = chaos_cluster(
        {"RAY_TPU_testing_rpc_failure": "object_location_add:req:1"},
        nodes=[{"num_cpus": 1, "resources": {"side": 1}}],
    )

    @ray_tpu.remote(resources={"side": 0.1})
    def make():
        return ray_tpu.put(np.arange(200_000))

    inner = ray_tpu.get(make.remote(), timeout=60)
    # Fetch the put object across nodes: requires the (retried) location.
    arr = ray_tpu.get(inner, timeout=90)
    assert int(arr.sum()) == 19999900000


def test_lost_check_dropped_during_recovery(chaos_cluster):
    """Lineage reconstruction still happens when the GCS drops the first
    object_lost_check probes — the pull loop keeps asking."""
    c, [node] = chaos_cluster(
        {"RAY_TPU_testing_rpc_failure": "object_lost_check:req:2"},
        nodes=[{"num_cpus": 1, "resources": {"doomed": 1}}],
    )

    @ray_tpu.remote(resources={"doomed": 0.1}, max_retries=3)
    def produce():
        return np.full(150_000, 7.0)

    ref = produce.remote()
    assert ray_tpu.get(ref, timeout=60).sum() == 7.0 * 150_000
    c.remove_node(node)
    c.add_node(num_cpus=1, resources={"doomed": 1})
    c.wait_for_nodes()
    # Every copy died with the node; the owner must resubmit produce()
    # even though the first lost-checks are eaten.
    assert ray_tpu.get(ref, timeout=120).sum() == 7.0 * 150_000


def test_pg_prepare_reply_dropped(chaos_cluster):
    """2-phase PG creation: a dropped prepare reply looks like a failed
    node; the GCS must roll back and retry until the group commits."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "prepare_bundle:rep:1",
            "RAY_TPU_rpc_call_timeout_s": "6",
        },
        head_args={"num_cpus": 4},
    )
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=90)
    assert len(pg.bundle_specs) == 2


def test_pg_commit_reply_dropped(chaos_cluster):
    """A dropped commit reply must not wedge the group in PENDING: the
    GCS rolls the bundles back and reschedules."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "commit_bundle:rep:1",
            "RAY_TPU_rpc_call_timeout_s": "6",
        },
        head_args={"num_cpus": 4},
    )
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=90)

    # The committed group is actually usable.
    @ray_tpu.remote(num_cpus=1)
    def inside():
        return "ok"

    from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

    ref = inside.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(placement_group=pg)
    ).remote()
    assert ray_tpu.get(ref, timeout=60) == "ok"


def test_worker_lease_reply_dropped(chaos_cluster):
    """Direct task submission: a dropped lease grant strands a LEASED
    worker on the raylet side and returns None to the submitter — the
    submitter's reaper must re-request and tasks still complete."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "request_worker_lease:rep:1",
            "RAY_TPU_worker_lease_timeout_ms": "6000",
        }
    )

    @ray_tpu.remote
    def f(i):
        return i * 2

    out = ray_tpu.get([f.remote(i) for i in range(20)], timeout=120)
    assert out == [i * 2 for i in range(20)]


def test_register_worker_reply_dropped(chaos_cluster):
    """A worker whose registration reply is eaten dies; the pool must
    spawn a replacement and tasks still run."""
    chaos_cluster(
        {
            "RAY_TPU_testing_rpc_failure": "register_worker:rep:1",
            "RAY_TPU_rpc_call_timeout_s": "6",
        }
    )

    @ray_tpu.remote
    def f():
        return os.getpid()

    assert ray_tpu.get(f.remote(), timeout=90) > 0


def test_raylet_killer_tasks_retry(chaos_cluster):
    """Kill a node's raylet (SIGKILL) while its tasks are in flight;
    retriable tasks reschedule onto the surviving node."""
    c, [node] = chaos_cluster(
        {},
        head_args={"num_cpus": 2},
        nodes=[{"num_cpus": 2}],
    )

    @ray_tpu.remote(max_retries=5)
    def slow(i):
        time.sleep(0.4)
        return i

    refs = [slow.remote(i) for i in range(16)]
    time.sleep(1.0)  # let tasks spread to both nodes
    c.remove_node(node)  # SIGKILL mid-flight
    out = ray_tpu.get(refs, timeout=180)
    assert out == list(range(16))


# ==========================================================================
# Determinism drills: the same seed + spec must replay the identical
# fault schedule (ISSUE 1 acceptance: logged and asserted).
# ==========================================================================

_DET_ENV = ("RAY_TPU_testing_chaos_spec", "RAY_TPU_testing_chaos_seed")


@pytest.fixture()
def chaos_env():
    """Set chaos env vars for in-process plane/RPC drills; restore after."""
    saved = {k: os.environ.get(k) for k in _DET_ENV}

    def set_env(spec: str, seed: str):
        os.environ["RAY_TPU_testing_chaos_spec"] = spec
        os.environ["RAY_TPU_testing_chaos_seed"] = seed

    yield set_env
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    from ray_tpu._private.chaos import CHAOS

    CHAOS.reset()


def test_chaos_schedule_deterministic(chaos_env):
    """Plane level: per-rule RNG streams + match counters make the fault
    schedule a pure function of (seed, spec, match ordinals)."""
    from ray_tpu._private.chaos import ChaosPlane

    chaos_env(
        "submit_task:dup_req:n=2,store_get:delay_req:ms=10:p=0.4:n=-1,"
        "request_worker_lease:drop_rep:p=0.5:n=-1,@worker.exec:kill:at=4",
        "1234",
    )

    def drive(plane):
        decisions = []
        for i in range(40):
            decisions.append(plane.decide("submit_task", "req"))
            decisions.append(plane.decide("store_get", "req"))
            decisions.append(plane.decide("request_worker_lease", "rep"))
            decisions.append(plane.maybe_kill("worker.exec"))
        return decisions, plane.schedule_snapshot(), plane.schedule_digest()

    d1, s1, h1 = drive(ChaosPlane())
    d2, s2, h2 = drive(ChaosPlane())
    assert d1 == d2
    assert s1 == s2 and h1 == h2
    assert any(e.endswith(":fire") for e in s1), "no fault ever fired"
    assert sum(1 for e in s1 if e.endswith(":kill")) == 1  # at=4 fires once

    # A different seed diverges on the probabilistic rules.
    chaos_env(os.environ["RAY_TPU_testing_chaos_spec"], "99")
    _d3, s3, _h3 = drive(ChaosPlane())
    assert s3 != s1


def _rpc_trace(n: int = 14):
    """Drive a fixed call trace through a REAL RpcServer dispatch with
    the process-global plane; returns (outcomes, handler executions,
    schedule snapshot)."""
    import asyncio

    from ray_tpu._private import rpc as rpc_mod
    from ray_tpu._private.chaos import CHAOS

    CHAOS.reset()

    class Handler:
        def __init__(self):
            self.executions = 0

        async def rpc_ping(self, payload, conn):
            self.executions += 1
            return payload * 2

    handler = Handler()
    sock = os.path.join(tempfile.mkdtemp(prefix="chaos_rpc_"), "s.sock")

    async def main():
        loop = asyncio.get_event_loop()
        server = rpc_mod.RpcServer(handler, f"unix:{sock}", loop)
        await server.start()
        client = await rpc_mod.AsyncRpcClient(f"unix:{sock}").connect()
        outcomes = []
        for i in range(n):
            try:
                outcomes.append(await client.call("ping", i, timeout=0.3))
            except rpc_mod.RpcError:
                outcomes.append("lost")
        await asyncio.sleep(0.1)  # let duplicated handlers settle
        client.close()
        await server.stop()
        return outcomes

    outcomes = asyncio.run(main())
    schedule = CHAOS.schedule_snapshot()
    return outcomes, handler.executions, schedule


def test_chaos_rpc_dispatch_deterministic(chaos_env):
    """End to end through rpc.RpcServer: same seed -> identical observable
    outcomes (which calls lost their reply, how many duplicate handler
    runs) AND identical logged schedule."""
    chaos_env("ping:drop_rep:p=0.4:n=-1,ping:dup_req:p=0.3:n=-1", "31")
    o1, x1, s1 = _rpc_trace()
    o2, x2, s2 = _rpc_trace()
    assert o1 == o2
    assert x1 == x2
    assert s1 == s2
    assert "lost" in o1, "drop_rep never fired"
    assert x1 > 14, "dup_req never duplicated a handler run"


# ==========================================================================
# Chaos matrix: drop x delay x dup against the submit/lease/get paths.
# Acceptance: all drills complete, no hangs.
# ==========================================================================

_MATRIX = {
    "drop": (
        "submit_task:drop_req:n=2,request_worker_lease:drop_rep:n=1,"
        "store_get:drop_req:n=2"
    ),
    "delay": (
        "submit_task:delay_req:ms=150:p=0.5:n=-1,"
        "request_worker_lease:delay_rep:ms=250:n=4,"
        "store_get:delay_req:ms=100:p=0.5:n=-1"
    ),
    "dup": (
        "submit_task:dup_req:n=3,request_worker_lease:dup_req:n=2,"
        "store_get:dup_req:n=6,exec_direct:dup_req:n=3"
    ),
    "drop+delay+dup": (
        "submit_task:dup_req:n=2,request_worker_lease:drop_rep:n=1,"
        "store_get:delay_req:ms=100:p=0.5:n=-1,exec_direct:dup_req:n=2,"
        "store_get:drop_req:n=1"
    ),
    "worker-kill": "@worker.exec:kill:at=2",
}


@pytest.mark.chaos
@pytest.mark.parametrize("axis", list(_MATRIX))
def test_chaos_matrix_progress(chaos_cluster, axis):
    """With faults active on submit/lease/get, tasks and puts/gets still
    complete inside their timeouts — retries + idempotency absorb every
    axis without double-running or hanging."""
    chaos_cluster(
        {
            "RAY_TPU_testing_chaos_spec": _MATRIX[axis],
            "RAY_TPU_testing_chaos_seed": "1234",
            "RAY_TPU_rpc_call_timeout_s": "6",
            "RAY_TPU_worker_lease_timeout_ms": "8000",
        }
    )

    @ray_tpu.remote(max_retries=5)
    def f(i):
        return i * 3

    # Condition-poll instead of one wall-clock gather: under load a
    # worker-kill axis pays worker respawn + re-lease on top of the
    # chaos delays, so assert *progress* against a generous deadline
    # and only fail when completion genuinely stalls.
    refs = [f.remote(i) for i in range(12)]
    deadline = time.monotonic() + 300
    pending = list(refs)
    while pending and time.monotonic() < deadline:
        done, pending = ray_tpu.wait(
            pending, num_returns=len(pending), timeout=5
        )
    assert not pending, f"{len(pending)} tasks still pending at deadline"
    out = ray_tpu.get(refs, timeout=60)
    assert out == [i * 3 for i in range(12)]
    ref = ray_tpu.put(np.arange(120_000))
    assert int(ray_tpu.get(ref, timeout=120).sum()) == 7199940000


@pytest.mark.chaos
def test_chaos_matrix_raylet_mediated(chaos_cluster):
    """The same fault axes against the raylet-mediated submit path
    (direct submission off), exercising submit_task end to end."""
    chaos_cluster(
        {
            "RAY_TPU_testing_chaos_spec": (
                "submit_task:drop_rep:n=2,submit_task:dup_req:n=2,"
                "store_get:delay_req:ms=100:p=0.5:n=-1"
            ),
            "RAY_TPU_testing_chaos_seed": "7",
            "RAY_TPU_direct_task_submission": "0",
            "RAY_TPU_rpc_call_timeout_s": "6",
        }
    )

    @ray_tpu.remote
    def g(i):
        return i + 100

    assert ray_tpu.get([g.remote(i) for i in range(8)], timeout=120) == [
        i + 100 for i in range(8)
    ]


# ==========================================================================
# Idempotency: a replayed/duplicated submission must not run a task twice.
# ==========================================================================


def _count_lines(path: str) -> int:
    with open(path) as f:
        return len(f.readlines())


@pytest.mark.chaos
def test_duplicate_submit_does_not_double_execute(chaos_cluster, tmp_path):
    """Raylet path: every submit_task delivery is duplicated, and every
    reply is eaten once (forcing a client-side retry on top) — yet each
    task's side effect happens exactly once."""
    marker = str(tmp_path / "ran.log")
    chaos_cluster(
        {
            "RAY_TPU_testing_chaos_spec": (
                "submit_task:dup_req:n=-1,submit_task:drop_rep:n=1"
            ),
            "RAY_TPU_testing_chaos_seed": "5",
            "RAY_TPU_direct_task_submission": "0",
            "RAY_TPU_rpc_call_timeout_s": "5",
        }
    )

    @ray_tpu.remote
    def effect(i):
        with open(marker, "a") as f:
            f.write(f"{i}\n")
        return i

    out = ray_tpu.get([effect.remote(i) for i in range(6)], timeout=120)
    assert sorted(out) == list(range(6))
    assert _count_lines(marker) == 6, "a duplicated submit re-ran a task"


@pytest.mark.chaos
def test_duplicate_exec_direct_does_not_double_execute(chaos_cluster, tmp_path):
    """Direct path: every exec_direct push is delivered twice; the leased
    worker's admission dedupe drops the replays."""
    marker = str(tmp_path / "ran_direct.log")
    chaos_cluster(
        {
            "RAY_TPU_testing_chaos_spec": "exec_direct:dup_req:n=-1",
            "RAY_TPU_testing_chaos_seed": "5",
        }
    )

    @ray_tpu.remote
    def effect(i):
        with open(marker, "a") as f:
            f.write(f"{i}\n")
        return i

    out = ray_tpu.get([effect.remote(i) for i in range(6)], timeout=90)
    assert sorted(out) == list(range(6))
    assert _count_lines(marker) == 6, "a duplicated exec_direct re-ran a task"
