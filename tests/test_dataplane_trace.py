"""Dataplane tracing: trace-context propagation over compiled channels.

The wire trailer carries (trace id, write-span id, writer timestamp)
across every channel kind, and each consumer re-parents from the
inbound frame — so one serve request over the channel dataplane is a
SINGLE connected trace spanning router, replica, and engine processes,
compiled-DAG executions re-parent per execution (not per actor start),
and a chaos-induced reattach shows up as an annotated span rather than
a broken tree.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.util import tracing


@pytest.fixture(scope="module")
def serve_cluster(ray_cluster):
    yield ray_cluster
    serve.shutdown()


def _orphans(group):
    ids = {s["span_id"] for s in group}
    return [
        s for s in group
        if s.get("parent_span_id") and s["parent_span_id"] not in ids
    ]


def _trace_group(trace_id, want_names, deadline_s=45.0):
    """Poll the cluster span table until trace ``trace_id`` contains all
    of ``want_names`` AND is fully connected (every parent resolves):
    spans ship on the 1 s flusher cadence from every process, so a hop's
    parent may land a beat after the hop itself."""
    from ray_tpu.util import state

    group, names = [], set()
    end = time.time() + deadline_s
    while time.time() < end:
        group = [s for s in state.spans() if s.get("trace_id") == trace_id]
        names = {s.get("name") for s in group}
        if want_names <= names and not _orphans(group):
            return group
        time.sleep(0.5)
    raise AssertionError(
        f"trace {trace_id}: wanted {sorted(want_names)}, have {sorted(names)}, "
        f"orphans {[(s['name'], s['parent_span_id']) for s in _orphans(group)]}"
    )


def _assert_no_orphans(group):
    """Every span's parent is either absent (root) or present in the
    same trace — the 'single connected trace' invariant."""
    assert _orphans(group) == [], [
        (s["name"], s["parent_span_id"]) for s in _orphans(group)
    ]


def test_dag_socket_hop_and_per_execution_reparenting():
    """Cross-raylet compiled-DAG executions: the trace context crosses
    the SOCKET hop, the resident executor re-parents per execution from
    the inbound frame (two traced executions land their dag.op spans in
    two different traces — the stale actor-start-context bug), and an
    untraced execution threads through without minting spans."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode
    from ray_tpu.util import state

    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=2, resources={"edge": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)

    @ray_tpu.remote(resources={"edge": 0.1})
    class Far:
        def step(self, x):
            return x * 2 + 1

    try:
        far = Far.bind()
        with InputNode() as inp:
            dag = far.step.bind(inp)
        compiled = dag.experimental_compile(max_inflight=4)
        assert compiled._channels_on
        assert "socket" in {d["kind"] for d in compiled._descs.values()}
        try:
            # untraced execution first: must not break, must not trace
            assert ray_tpu.get(compiled.execute(0), timeout=30) == 1
            roots = []
            for i in (1, 2):
                with tracing.start_span(f"dag.client.{i}") as root:
                    assert ray_tpu.get(compiled.execute(i), timeout=30) == i * 2 + 1
                roots.append(root.trace_id)
            groups = [
                _trace_group(tid, {"channel.write", "channel.read", "dag.op"})
                for tid in roots
            ]
            for group in groups:
                _assert_no_orphans(group)
                assert len({s.get("pid") for s in group}) >= 2
                kinds = {
                    (s.get("attributes") or {}).get("kind")
                    for s in group if s["name"].startswith("channel.")
                }
                assert "socket" in kinds, kinds
            # per-execution re-parent: each execution's dag.op lives in
            # ITS OWN trace (a stale actor-start context would pile both
            # into one)
            dag_ops = [
                {s["span_id"] for s in g if s["name"] == "dag.op"}
                for g in groups
            ]
            assert all(dag_ops) and not (dag_ops[0] & dag_ops[1])
            # the untraced execution minted no dag.op outside those traces
            all_spans = state.spans()
            stray = [
                s for s in all_spans
                if s.get("name") == "dag.op"
                and s.get("trace_id") not in roots
            ]
            assert stray == [], stray
        finally:
            compiled.teardown()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_chaos_reattach_is_annotated_span_not_broken_trace():
    """A chaos-cut socket edge heals by epoch reattach mid-run; the
    reattach surfaces as a channel.reattach span (result/epoch
    attributes) while the traced executions' trees stay connected."""
    import os as _os

    from ray_tpu._private.chaos import CHAOS
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode
    from ray_tpu.util import state

    saved = {
        k: _os.environ.get(k)
        for k in ("RAY_TPU_testing_chaos_spec", "RAY_TPU_testing_chaos_seed")
    }
    _os.environ["RAY_TPU_testing_chaos_spec"] = "chan:socket:*:close:at=3"
    _os.environ["RAY_TPU_testing_chaos_seed"] = "7"
    CHAOS.reset()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=2, resources={"edge": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)

    @ray_tpu.remote(resources={"edge": 0.1})
    class Far:
        def step(self, x):
            return x + 100

    try:
        far = Far.bind()
        with InputNode() as inp:
            dag = far.step.bind(inp)
        compiled = dag.experimental_compile(max_inflight=4)
        try:
            roots = []
            for i in range(8):
                with tracing.start_span(f"chaos.client.{i}") as root:
                    assert ray_tpu.get(compiled.execute(i), timeout=60) == i + 100
                roots.append(root.trace_id)
            # the cut really fired and healed
            epochs = [compiled._driver_in[0][0].epoch, compiled._driver_out[0].epoch]
            assert max(epochs) >= 2, epochs
            # reattach is an annotated span somewhere in the table...
            deadline = time.time() + 45
            reattaches = []
            while time.time() < deadline and not reattaches:
                reattaches = [
                    s for s in state.spans() if s.get("name") == "channel.reattach"
                ]
                time.sleep(0.5)
            assert reattaches, "no channel.reattach span recorded"
            att = reattaches[0].get("attributes") or {}
            assert att.get("result") in ("ok", "failed") and "epoch" in att
            # ...and the traced executions' trees are still whole
            for tid in roots[-2:]:
                _assert_no_orphans(
                    _trace_group(tid, {"channel.write", "channel.read"})
                )
        finally:
            compiled.teardown()
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        for k, old in saved.items():
            if old is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = old
        CHAOS.reset()


def test_serve_stream_single_connected_trace_with_critical_path(serve_cluster):
    """An LLM token stream over the channel dataplane produces ONE
    connected trace — client root → serve.router → channel hops →
    replica dispatch → engine prefill/decode → per-token stream writes —
    spanning at least two processes, and critical_path() decomposes it
    into segments that sum to (at most) the end-to-end latency without
    double counting."""
    from ray_tpu.serve import llm
    from ray_tpu.serve._private.dataplane import ChannelClient, ChannelStream
    from ray_tpu.serve._private.router import _routers
    from ray_tpu.util import state

    app = llm.build_app(
        llm.LLMConfig(
            model="tiny", name="llm_traced", max_batch_size=4,
            num_blocks=64, block_size=8, default_max_tokens=6,
        )
    )
    handle = serve.run(app, name="llm_traced_app")
    # warm the dataplane attach outside the traced request
    handle.remote({"prompt": [1, 2], "max_tokens": 2}).result(timeout=60)
    router = _routers[handle.deployment_name]
    assert any(isinstance(v, ChannelClient) for v in router._dataplanes.values())

    with tracing.start_span("client.request") as root:
        gen = handle.options(stream=True).generate.remote(
            {"prompt": "hi", "max_tokens": 6}
        )
        assert isinstance(gen._gen, ChannelStream)
        events = list(gen)
    assert events[-1]["done"]

    group = _trace_group(
        root.trace_id,
        {
            "client.request", "serve.router", "channel.write", "channel.read",
            "serve.replica.stream", "serve.request", "serve.prefill",
            "serve.decode",
        },
    )
    _assert_no_orphans(group)
    # the trace crosses the process boundary (driver + replica at least)
    assert len({s.get("pid") for s in group}) >= 2, group

    cp = state.critical_path(group)
    assert cp and cp[0]["name"] == "client.request"
    seg_total = sum(e["duration_s"] for e in cp if e["segment"])
    start = min(s["start_time"] for s in group)
    end = max(s["end_time"] for s in group)
    assert 0.0 < seg_total <= (end - start) + 0.05, (seg_total, end - start)
    cp_names = {e["name"] for e in cp}
    # the decomposition reaches through the channel hop into the engine
    assert cp_names & {"channel.read", "channel.write"}, cp_names
    assert cp_names & {"serve.prefill", "serve.decode", "serve.request",
                       "serve.replica.stream"}, cp_names
    # queue-wait attribution rides the read spans
    reads = [s for s in group if s["name"] == "channel.read"]
    assert reads and all(
        "queue_wait_s" in (s.get("attributes") or {}) for s in reads
    )
    serve.delete("llm_traced")


def test_untraced_serve_call_records_no_request_spans(serve_cluster):
    """Untraced requests stay untraced end to end: no ambient context on
    the driver → no trailer on the wire → zero channel/replica spans for
    that call (the overhead contract, observable at the span level)."""

    @serve.deployment(name="UntracedDep")
    class UntracedDep:
        def __call__(self, x):
            return x + 1

    h = serve.run(UntracedDep.bind(), name="untraced_dep")
    h.remote(1).result(timeout=30)  # attach + warm
    before = len(tracing.drain_spans())  # clear the local log
    assert tracing.current_context() is None
    assert h.remote(41).result(timeout=30) == 42
    local = [
        s for s in tracing.drain_spans()
        if s["name"].startswith(("channel.", "serve."))
    ]
    assert local == [], (before, local)
    serve.delete("untraced_dep")
