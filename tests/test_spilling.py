"""Object spilling: memory pressure moves LRU objects to disk and reads
serve from the spill files (reference: _private/external_storage.py
FileSystemStorage + raylet/local_object_manager.h SpillObjects)."""

import os

import numpy as np
import pytest


@pytest.fixture
def small_store_cluster(monkeypatch):
    # Cap the store far below the workload so puts force spilling.
    monkeypatch.setenv("RAY_TPU_object_store_memory_cap", str(48 * 1024 * 1024))
    import ray_tpu

    ray_tpu.init(num_cpus=2)
    yield ray_tpu
    ray_tpu.shutdown()


def _poll_stat(raylet, key, deadline_s=30.0):
    """Condition-poll a store stat until it goes positive.  Spilling runs
    in the background (off-loop IO racing eviction), so on a loaded box
    the counter lags the puts — poll instead of asserting a snapshot."""
    import time

    deadline = time.monotonic() + deadline_s
    stats = raylet.call("store_stats", None)
    while stats[key] <= 0 and time.monotonic() < deadline:
        time.sleep(0.1)
        stats = raylet.call("store_stats", None)
    return stats


def test_put_beyond_capacity_roundtrips_via_spill(small_store_cluster):
    ray_tpu = small_store_cluster
    arrays = [np.full(2_000_000, i, dtype=np.float64) for i in range(8)]  # 8 x 16MB
    refs = [ray_tpu.put(a) for a in arrays]
    # 128MB of puts into a 48MB store: earlier objects must have spilled
    # (eventually — the spill IO is background work).
    w = ray_tpu._private.worker.get_global_worker()
    stats = _poll_stat(w.store._raylet, "num_spilled")
    assert stats["num_spilled"] > 0, stats
    # Every object is still readable (spilled ones serve from disk).
    for i, ref in enumerate(refs):
        out = ray_tpu.get(ref, timeout=120)
        assert out[0] == i and out[-1] == i and out.shape == (2_000_000,)
    stats = _poll_stat(w.store._raylet, "num_restored")
    assert stats["num_restored"] > 0, stats


def test_task_returns_spill_and_restore(small_store_cluster):
    ray_tpu = small_store_cluster

    @ray_tpu.remote
    def make(i):
        return np.full(2_000_000, i, dtype=np.float64)  # 16MB

    refs = [make.remote(i) for i in range(8)]
    outs = ray_tpu.get(refs, timeout=120)
    for i, out in enumerate(outs):
        assert out[0] == i and out[-1] == i


def test_background_watermark_spilling(small_store_cluster):
    """Crossing the high watermark triggers spilling in the BACKGROUND
    (off-loop IO), without any further allocation forcing it."""
    import time

    ray_tpu = small_store_cluster
    w = ray_tpu._private.worker.get_global_worker()
    # ~42MB into a 48MB store: above the 0.8 watermark (38.4MB), but no
    # allocation pressure afterwards.
    refs = [ray_tpu.put(np.full(1_700_000, i, dtype=np.float64)) for i in range(3)]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        stats = w.store._raylet.call("store_stats", None)
        if stats["num_spilled"] > 0 and stats["used_bytes"] <= 0.65 * stats["capacity_bytes"]:
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"background spill never engaged: {stats}")
    # Spilled objects still read back correctly.
    for i, r in enumerate(refs):
        assert float(ray_tpu.get(r)[0]) == float(i)
