"""ray_tpu.cancel (reference: core_worker.cc CancelTask semantics)."""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def ray():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def test_cancel_running_task():
    """Cancelling a task genuinely blocked in a C-level call (time.sleep)
    must interrupt it promptly — the signal path, not just the
    queued-drop path."""

    @ray_tpu.remote
    def warm():
        import os as _os

        return _os.getpid()

    ray_tpu.get(warm.remote(), timeout=60)  # worker exists before submit

    started = time.monotonic()

    @ray_tpu.remote
    def sleeper():
        time.sleep(60)
        return "never"

    ref = sleeper.remote()
    time.sleep(2.0)  # well into the sleep on the warmed worker
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.monotonic() - t0 < 10  # interrupted, not waited out
    assert time.monotonic() - started < 40


def test_cancel_queued_task():
    @ray_tpu.remote(num_cpus=1)
    def busy():
        time.sleep(8)
        return "done"

    # Fill both CPUs, then queue one more and cancel it before it runs.
    running = [busy.remote() for _ in range(2)]
    queued = busy.remote()
    time.sleep(0.5)
    ray_tpu.cancel(queued)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(queued, timeout=30)
    assert ray_tpu.get(running, timeout=60) == ["done", "done"]


def test_cancel_force_kills_worker_no_retry():
    @ray_tpu.remote(max_retries=5)
    def stubborn():
        # Holds the GIL in C so the async-exception never lands: only
        # force (SIGKILL-level) cancellation can stop it.
        import numpy as np

        x = 1.0
        for _ in range(100):
            x += float(np.ones(20_000_000).sum())  # long C-level loops
        return x

    ref = stubborn.remote()
    time.sleep(1.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=60)


def test_cancel_finished_task_is_noop():
    @ray_tpu.remote
    def quick():
        return 41

    ref = quick.remote()
    assert ray_tpu.get(ref, timeout=30) == 41
    ray_tpu.cancel(ref)  # must not raise or corrupt the value
    assert ray_tpu.get(ref, timeout=30) == 41


def test_cancel_async_actor_task():
    """Cancelling a running coroutine cancels exactly that asyncio task;
    the actor keeps serving other calls."""

    @ray_tpu.remote
    class A:
        async def stuck(self):
            import asyncio

            await asyncio.sleep(60)
            return "never"

        async def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"
    ref = a.stuck.remote()
    time.sleep(0.5)
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    # The actor (and its loop) survived the cancel.
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"
    ray_tpu.kill(a)


def test_concurrency_groups_sync_actor():
    """Methods in a concurrency group run in parallel up to the group
    limit; ungrouped methods stay serialized on the default pool
    (reference: core_worker/concurrency_group_manager.h)."""

    @ray_tpu.remote(concurrency_groups={"io": 3})
    class Worker:
        def __init__(self):
            import threading as th

            self.live = 0
            self.peak = 0
            self.lock = th.Lock()

        @ray_tpu.method(concurrency_group="io")
        def io_call(self):
            with self.lock:
                self.live += 1
                self.peak = max(self.peak, self.live)
            time.sleep(0.5)
            with self.lock:
                self.live -= 1
            return "io"

        def peak_seen(self):
            return self.peak

    w = Worker.remote()
    refs = [w.io_call.remote() for _ in range(6)]
    assert ray_tpu.get(refs, timeout=60) == ["io"] * 6
    peak = ray_tpu.get(w.peak_seen.remote(), timeout=30)
    assert 2 <= peak <= 3, peak  # parallel, but never above the cap
    ray_tpu.kill(w)


def test_concurrency_groups_async_actor():
    @ray_tpu.remote(concurrency_groups={"limited": 2})
    class AsyncWorker:
        def __init__(self):
            self.live = 0
            self.peak = 0

        @ray_tpu.method(concurrency_group="limited")
        async def call(self):
            import asyncio

            self.live += 1
            self.peak = max(self.peak, self.live)
            await asyncio.sleep(0.4)
            self.live -= 1
            return "ok"

        async def peak_seen(self):
            return self.peak

    a = AsyncWorker.remote()
    refs = [a.call.remote() for _ in range(6)]
    assert ray_tpu.get(refs, timeout=60) == ["ok"] * 6
    assert ray_tpu.get(a.peak_seen.remote(), timeout=30) == 2
    ray_tpu.kill(a)
