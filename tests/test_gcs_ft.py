"""GCS fault tolerance: kill + restart the GCS mid-run.

The cluster must survive: raylets reconnect with backoff and resync,
drivers reattach their job, actors keep serving direct calls throughout
the outage, and work that needs the GCS (new function pushes) blocks and
completes once it's back (reference: redis-backed GCS restart,
gcs/store_client/redis_store_client.h:106, gcs_redis_failure_detector.cc;
test model: python/ray/tests external-redis GCS FT fixtures).
"""

import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.config import CONFIG


def _spawn_gcs(session_dir: str, gcs_address: str) -> subprocess.Popen:
    from ray_tpu._private.node import child_env

    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "ray_tpu._private.gcs_main",
            "--address", gcs_address,
            "--session-dir", session_dir,
            "--config", CONFIG.dump(),
        ],
        env=child_env(),
        start_new_session=True,
    )


def test_gcs_restart_mid_run():
    from ray_tpu._private import node as node_mod

    session_dir = node_mod.new_session_dir()
    gcs_address = f"unix:{session_dir}/sockets/gcs.sock"
    gcs = _spawn_gcs(session_dir, gcs_address)
    raylet_proc = None
    gcs2 = None
    try:
        raylet_proc, _ = node_mod.start_worker_node(
            gcs_address, session_dir, num_cpus=4, wait=True
        )
        ray_tpu.init(address=gcs_address)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        @ray_tpu.remote
        def f(x):
            return x + 1

        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
        assert ray_tpu.get(f.remote(1), timeout=60) == 2
        time.sleep(1.0)  # let the snapshot loop persist the state above

        # ---- kill the GCS hard ----
        gcs.kill()
        gcs.wait(timeout=10)

        # Running actors keep serving during the outage (direct channels
        # don't involve the GCS).
        assert ray_tpu.get(c.incr.remote(), timeout=30) == 2

        # Work needing the GCS (a NEW function's first push) blocks until
        # the GCS is back, then completes — no error surfaces.
        result = {}

        def submit_new_fn():
            @ray_tpu.remote
            def g(x):
                return x * 3

            result["v"] = ray_tpu.get(g.remote(7), timeout=90)

        t = threading.Thread(target=submit_new_fn, daemon=True)
        t.start()
        time.sleep(1.0)
        assert "v" not in result  # still blocked on the dead GCS

        # ---- restart the GCS against the same session dir ----
        gcs2 = _spawn_gcs(session_dir, gcs_address)
        t.join(timeout=90)
        assert result.get("v") == 21, "queued task did not complete after GCS restart"

        # The actor survived the restart with its state intact.
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 3
        # And the restarted GCS knows about it (restored from snapshot,
        # reconciled with the raylet's live_actors resync).
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        info = w.gcs_client.call("get_actor_info", c._actor_id.binary())
        assert info is not None and info["state"] == "ALIVE"
    finally:
        ray_tpu.shutdown()
        for p in (gcs2, gcs, raylet_proc):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
