"""GCS fault tolerance: kill + restart the GCS mid-run.

The cluster must survive: raylets reconnect with backoff and resync,
drivers reattach their job, actors keep serving direct calls throughout
the outage, and work that needs the GCS (new function pushes) blocks and
completes once it's back (reference: redis-backed GCS restart,
gcs/store_client/redis_store_client.h:106, gcs_redis_failure_detector.cc;
test model: python/ray/tests external-redis GCS FT fixtures).
"""

import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.config import CONFIG


def _spawn_gcs(session_dir: str, gcs_address: str) -> subprocess.Popen:
    from ray_tpu._private.node import child_env

    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "ray_tpu._private.gcs_main",
            "--address", gcs_address,
            "--session-dir", session_dir,
            "--config", CONFIG.dump(),
        ],
        env=child_env(),
        start_new_session=True,
    )


def test_gcs_restart_mid_run():
    from ray_tpu._private import node as node_mod

    session_dir = node_mod.new_session_dir()
    gcs_address = f"unix:{session_dir}/sockets/gcs.sock"
    gcs = _spawn_gcs(session_dir, gcs_address)
    raylet_proc = None
    gcs2 = None
    try:
        raylet_proc, _ = node_mod.start_worker_node(
            gcs_address, session_dir, num_cpus=4, wait=True
        )
        ray_tpu.init(address=gcs_address)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        @ray_tpu.remote
        def f(x):
            return x + 1

        c = Counter.remote()
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
        assert ray_tpu.get(f.remote(1), timeout=60) == 2
        time.sleep(1.0)  # let the snapshot loop persist the state above

        # ---- kill the GCS hard ----
        gcs.kill()
        gcs.wait(timeout=10)

        # Running actors keep serving during the outage (direct channels
        # don't involve the GCS).
        assert ray_tpu.get(c.incr.remote(), timeout=30) == 2

        # Work needing the GCS (a NEW function's first push) blocks until
        # the GCS is back, then completes — no error surfaces.
        result = {}

        def submit_new_fn():
            @ray_tpu.remote
            def g(x):
                return x * 3

            result["v"] = ray_tpu.get(g.remote(7), timeout=90)

        t = threading.Thread(target=submit_new_fn, daemon=True)
        t.start()
        time.sleep(1.0)
        assert "v" not in result  # still blocked on the dead GCS

        # ---- restart the GCS against the same session dir ----
        gcs2 = _spawn_gcs(session_dir, gcs_address)
        t.join(timeout=90)
        assert result.get("v") == 21, "queued task did not complete after GCS restart"

        # The actor survived the restart with its state intact.
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 3
        # And the restarted GCS knows about it (restored from snapshot,
        # reconciled with the raylet's live_actors resync).
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        info = w.gcs_client.call("get_actor_info", c._actor_id.binary())
        assert info is not None and info["state"] == "ALIVE"
    finally:
        ray_tpu.shutdown()
        for p in (gcs2, gcs, raylet_proc):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()


def test_gcs_restart_mid_stream():
    """Recovery drill (ISSUE 1): kill + restart the GCS while a streaming
    generator is mid-flight.  Stream items ride worker->owner pushes, not
    the GCS, so consumption must continue through the outage and the
    stream must complete after the restart — no hang, no lost items."""
    from ray_tpu._private import node as node_mod

    session_dir = node_mod.new_session_dir()
    gcs_address = f"unix:{session_dir}/sockets/gcs.sock"
    gcs = _spawn_gcs(session_dir, gcs_address)
    raylet_proc = None
    gcs2 = None
    try:
        raylet_proc, _ = node_mod.start_worker_node(
            gcs_address, session_dir, num_cpus=4, wait=True
        )
        ray_tpu.init(address=gcs_address)

        @ray_tpu.remote(num_returns="streaming")
        def slowgen(n):
            for i in range(n):
                time.sleep(0.5)
                yield i * 11

        g = slowgen.remote(10)
        got = [ray_tpu.get(next(g)) for _ in range(2)]

        # ---- kill the GCS hard, mid-stream ----
        gcs.kill()
        gcs.wait(timeout=10)

        # Items keep arriving during the outage.
        got.append(ray_tpu.get(next(g)))

        # ---- restart against the same session dir; drain the rest ----
        gcs2 = _spawn_gcs(session_dir, gcs_address)
        for r in g:
            got.append(ray_tpu.get(r, timeout=60))
        assert got == [i * 11 for i in range(10)]

        # The cluster is still fully functional after the restart.
        @ray_tpu.remote
        def probe():
            return "alive"

        assert ray_tpu.get(probe.remote(), timeout=90) == "alive"
    finally:
        ray_tpu.shutdown()
        for p in (gcs2, gcs, raylet_proc):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()


class _MiniRedis:
    """Threaded in-test RESP2 server: SET/GET/PING/AUTH on a dict —
    enough surface to prove RedisSnapshotStore's wire protocol without
    a redis binary (test model: the reference's external-redis FT
    fixtures, hermetic here)."""

    def __init__(self):
        import socket
        import threading as _t

        self.data = {}
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._thread = _t.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            f = conn.makefile("rb")
            try:
                while True:
                    line = f.readline()
                    if not line:
                        break
                    assert line[:1] == b"*", line
                    nargs = int(line[1:-2])
                    args = []
                    for _ in range(nargs):
                        hdr = f.readline()
                        assert hdr[:1] == b"$"
                        n = int(hdr[1:-2])
                        args.append(f.read(n + 2)[:-2])
                    cmd = args[0].upper()
                    if cmd == b"PING":
                        conn.sendall(b"+PONG\r\n")
                    elif cmd == b"AUTH":
                        conn.sendall(b"+OK\r\n")
                    elif cmd == b"SET":
                        self.data[args[1]] = args[2]
                        conn.sendall(b"+OK\r\n")
                    elif cmd == b"GET":
                        v = self.data.get(args[1])
                        if v is None:
                            conn.sendall(b"$-1\r\n")
                        else:
                            conn.sendall(b"$%d\r\n%s\r\n" % (len(v), v))
                    else:
                        conn.sendall(b"-ERR unknown\r\n")
            except Exception:
                pass
            finally:
                conn.close()

    def stop(self):
        self._stop = True
        self._srv.close()


def test_redis_snapshot_store_roundtrip():
    from ray_tpu._private.gcs_store import RedisSnapshotStore, make_snapshot_store

    srv = _MiniRedis()
    try:
        store = RedisSnapshotStore("127.0.0.1", srv.port, key="k1")
        assert store.ping()
        assert store.load() is None
        blob = b"\x00\x01binary\r\nsafe" * 1000
        store.save(blob)
        assert store.load() == blob
        # URI parsing picks the redis backend + custom key
        s2 = make_snapshot_store(f"redis://127.0.0.1:{srv.port}/custom", None)
        s2.save(b"x")
        assert srv.data[b"custom"] == b"x"
    finally:
        srv.stop()


def test_gcs_state_survives_head_node_loss_via_external_redis():
    """VERDICT r4 missing #7: with gcs_external_storage=redis://..., a
    REPLACEMENT head (fresh session dir — the old head's disk is gone)
    restores the durable tables from the external store (reference:
    redis_store_client.h head-loss recovery)."""
    from ray_tpu._private import node as node_mod
    from ray_tpu._private import rpc

    srv = _MiniRedis()
    CONFIG._overrides["gcs_external_storage"] = f"redis://127.0.0.1:{srv.port}"
    gcs = gcs2 = None
    raylet_proc = None
    try:
        session_dir = node_mod.new_session_dir()
        gcs_address = f"unix:{session_dir}/sockets/gcs.sock"
        gcs = _spawn_gcs(session_dir, gcs_address)
        raylet_proc, _ = node_mod.start_worker_node(
            gcs_address, session_dir, num_cpus=2, wait=True
        )
        ray_tpu.init(address=gcs_address, namespace="ftns")

        @ray_tpu.remote
        class Keeper:
            def ping(self):
                return "ok"

        k = Keeper.options(name="keeper", lifetime="detached").remote()
        assert ray_tpu.get(k.ping.remote(), timeout=60) == "ok"
        ray_tpu._private.worker.get_global_worker().gcs_client.call(
            "kv_put", ("ns", b"durable-key", b"durable-value", True)
        )
        time.sleep(1.2)  # snapshot loop cadence is 500ms
        assert srv.data, "no snapshot reached the external store"
        ray_tpu.shutdown()

        # ---- the whole head node is lost: kill GCS AND its session dir
        # is abandoned; the replacement head uses a FRESH session dir ----
        gcs.kill()
        gcs.wait(timeout=10)
        session2 = node_mod.new_session_dir()
        gcs2_address = f"unix:{session2}/sockets/gcs.sock"
        gcs2 = _spawn_gcs(session2, gcs2_address)

        deadline = time.time() + 30
        client = None
        while time.time() < deadline:
            try:
                client = rpc.RpcClient(gcs2_address)
                break
            except OSError:
                time.sleep(0.3)
        assert client is not None, "replacement GCS never came up"
        try:
            named = client.call("get_named_actor", ("ftns", "keeper"))
            assert named is not None, "detached actor lost with the head node"
            assert client.call("kv_get", ("ns", b"durable-key")) == b"durable-value"
        finally:
            client.close()
    finally:
        CONFIG._overrides.pop("gcs_external_storage", None)
        for p in (gcs, gcs2):
            if p is not None and p.poll() is None:
                p.kill()
        if raylet_proc is not None and raylet_proc.poll() is None:
            raylet_proc.terminate()
        srv.stop()
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
