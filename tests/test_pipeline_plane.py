"""MPMD pipeline plane (train/sharding/pipeline_plane.py): stage actors
over real compiled channels match single-process loss to fixed-seed
parity, per-stage timing/bubble metrics surface, and a chaos kill
mid-epoch recovers by whole-pipeline checkpoint-restart."""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.models import gpt2  # noqa: E402
from ray_tpu.train.sharding import (  # noqa: E402
    PipelineConfig,
    PipelinePlane,
    gpt2_pipeline_programs,
)
from ray_tpu.train.sharding.pipeline_plane import schedule_ops  # noqa: E402


def _cfg():
    return gpt2.GPT2Config(
        vocab_size=128, n_layer=2, n_head=2, d_model=32, max_seq_len=32,
        dtype=jnp.float32, remat=False,
    )


def _data(steps, batch=4, seq=17, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 128, (steps, batch, seq)).astype(np.int32)

    def data_fn(step):
        toks = data[step]
        return toks[:, :-1], toks[:, 1:]

    return data_fn


def _reference_losses(cfg, data_fn, steps, lr=1e-3, seed=0):
    params = gpt2.init_params(cfg, jax.random.PRNGKey(seed))
    opt = gpt2.make_adamw(lr)
    opt_state = opt.init(params)
    step_fn = jax.jit(gpt2.make_train_step(cfg, opt))
    out = []
    for s in range(steps):
        toks, tgts = data_fn(s)
        params, opt_state, loss = step_fn(
            params, opt_state, jnp.asarray(toks), jnp.asarray(tgts)
        )
        out.append(float(loss))
    return out


# ---------------------------------------------------------------------------
# schedule unit tests (no cluster)


def test_schedule_ops_1f1b_shape():
    # stage 0 of 3, M=4: 2 warmup F, 2 (F,B) pairs, 2 cooldown B
    assert schedule_ops(0, 3, 4) == ["F", "F", "F", "B", "F", "B", "B", "B"]
    # last stage: pure alternation
    assert schedule_ops(2, 3, 4) == ["F", "B"] * 4
    for s in range(3):
        ops = schedule_ops(s, 3, 4)
        assert ops.count("F") == 4 and ops.count("B") == 4
    # degenerate M < warmup window
    assert schedule_ops(0, 4, 2) == ["F", "F", "B", "B"]


def test_pipeline_config_validation():
    with pytest.raises(ValueError, match="2 stages"):
        PipelineConfig(stages=1)
    with pytest.raises(ValueError, match="microbatches"):
        PipelineConfig(stages=2, microbatches=0)


def test_gpt2_program_split_merge_roundtrip():
    cfg = _cfg()
    prog = gpt2_pipeline_programs(cfg, n_stages=2)
    params = prog.init_params()
    stages = [prog.split(params, s) for s in range(2)]
    assert "wte" in stages[0] and "lm_head" in stages[1]
    assert "h_0" in stages[0] and "h_1" in stages[1]
    merged = prog.merge(stages)
    for a, b in zip(
        jax.tree_util.tree_leaves(merged), jax.tree_util.tree_leaves(params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_durable_checkpoint_roundtrip_and_fallback(tmp_path):
    """PipelineConfig.checkpoint_dir makes the restart point DURABLE
    through the checkpoint plane: a fresh plane (driver restart) adopts
    the newest committed checkpoint, and a bit-flipped newest is skipped
    for the previous verified one — never adopted."""
    import os

    cfg = _cfg()
    prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
    pcfg = PipelineConfig(
        stages=2, microbatches=2, checkpoint_dir=str(tmp_path)
    )
    plane = PipelinePlane(prog, pcfg)
    params = prog.init_params()
    for step in (1, 2):
        plane._ckpt = (step, params, None)
        plane._persist_ckpt()
    # driver restart: a fresh plane resumes from the newest commit
    plane2 = PipelinePlane(gpt2_pipeline_programs(cfg, n_stages=2), pcfg)
    assert plane2._restore_durable_ckpt()
    assert plane2.steps_done == 2
    for a, b in zip(
        jax.tree_util.tree_leaves(plane2._ckpt[1]),
        jax.tree_util.tree_leaves(params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # bit-rot the newest: the loader walks back to step 1, never adopts
    newest = os.path.join(str(tmp_path), "checkpoint_000002")
    sp = os.path.join(newest, "state.pkl")
    with open(sp, "r+b") as f:
        f.seek(os.path.getsize(sp) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    plane3 = PipelinePlane(gpt2_pipeline_programs(cfg, n_stages=2), pcfg)
    assert plane3._restore_durable_ckpt()
    assert plane3.steps_done == 1


def test_gpt2_program_rejects_indivisible_layers():
    cfg = _cfg()  # n_layer=2
    prog = gpt2_pipeline_programs(cfg, n_stages=3)
    with pytest.raises(ValueError, match="divisible"):
        prog.split(gpt2.init_params(cfg), 0)


# ---------------------------------------------------------------------------
# cluster tests


@pytest.mark.parametrize("n_micro", [2, 4])
def test_pipeline_matches_single_process_loss(ray_cluster, n_micro):
    """Acceptance bar: an N-stage pipeline over real channels matches
    single-process loss to fixed-seed parity for M microbatches."""
    cfg = _cfg()
    steps = 3
    data_fn = _data(steps)
    prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
    plane = PipelinePlane(
        prog,
        PipelineConfig(stages=2, microbatches=n_micro, step_timeout_s=120.0),
    )
    try:
        losses = plane.run(data_fn, steps)
        stats = plane.stage_stats()
    finally:
        plane.stop()
    ref = _reference_losses(cfg, data_fn, steps)
    assert losses == pytest.approx(ref, abs=2e-5)
    # per-stage timing + bubble metrics exist and are sane
    assert len(stats) == 2
    for s in stats:
        assert s["steps"] == steps
        assert s["microbatches"] == steps * n_micro
        assert s["busy_s"] > 0
        assert 0.0 <= s["bubble_fraction"] <= 1.0


def test_pipeline_metrics_reach_cluster_state(ray_cluster):
    """pipeline_stage_seconds / pipeline_bubble_fraction surface via
    util.state.metrics() — the PR 10 profiling plane sees the stages."""
    from ray_tpu.util import state

    cfg = _cfg()
    data_fn = _data(2)
    prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
    plane = PipelinePlane(
        prog, PipelineConfig(stages=2, microbatches=2, step_timeout_s=120.0)
    )

    def _names():
        return {m.get("name") for m in state.metrics()}

    try:
        plane.run(data_fn, 2)
        # Stage actors stay alive here so their 2 s metric flusher ships
        # the series; only then tear the plane down.
        deadline = time.monotonic() + 30.0
        poll = 0.3
        names = _names()
        while (
            "pipeline_stage_seconds" not in names
            and time.monotonic() < deadline
        ):
            time.sleep(poll)
            names = _names()
    finally:
        plane.stop()
    assert "pipeline_stage_seconds" in names
    assert "pipeline_bubble_fraction" in names


@pytest.mark.chaos
@pytest.mark.slow  # ~25 s kill/restart drill: runs under `-m chaos`
def test_pipeline_chaos_kill_recovers_with_parity(ray_cluster):
    """Chaos drill: kill one stage actor mid-epoch (past the last
    checkpoint).  The plane restarts the WHOLE pipeline from its
    checkpoint, replays the uncheckpointed steps, and lands on the same
    losses as an undisturbed run — and the recovery is a restart, never
    a silent skip (restarts == 1).  The kill path must also reap the
    stage-side shm ring dirs (tmpfs is RAM; stop_loop never ran)."""
    import glob
    import os

    from ray_tpu.experimental.channel import ring_base_dir

    cfg = _cfg()
    steps = 5
    data_fn = _data(steps)
    rings_before = set(
        glob.glob(os.path.join(ring_base_dir(), "ray_tpu_pp*"))
    )

    def make_plane():
        prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
        return PipelinePlane(
            prog,
            PipelineConfig(
                stages=2, microbatches=2, step_timeout_s=8.0,
                checkpoint_every=2, max_restarts=1,
            ),
        )

    plane = make_plane()
    try:
        clean = plane.run(data_fn, steps)
    finally:
        plane.stop()

    plane = make_plane()
    try:
        part = plane.run(data_fn, 3)  # checkpoint landed at step 2
        ray_tpu.kill(plane.actors[1])  # step 3 is NOT checkpointed
        rest = plane.run(data_fn, steps)  # recovers + replays 2..4
        chaos = [part[i] if i < 2 else rest[i] for i in range(steps)]
        assert plane.restarts == 1
    finally:
        plane.stop()
    assert chaos == pytest.approx(clean, abs=2e-5)
    rings_after = set(
        glob.glob(os.path.join(ring_base_dir(), "ray_tpu_pp*"))
    )
    assert rings_after <= rings_before


@pytest.mark.chaos
@pytest.mark.slow  # ~10 s kill-past-budget drill: runs under `-m chaos`
def test_pipeline_restart_budget_exhausts_typed(ray_cluster):
    """Past max_restarts the failure propagates typed, not as a hang."""
    from ray_tpu.train.sharding.pipeline_plane import StageFailedError

    cfg = _cfg()
    data_fn = _data(4)
    prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
    plane = PipelinePlane(
        prog,
        PipelineConfig(
            stages=2, microbatches=2, step_timeout_s=4.0, max_restarts=0
        ),
    )
    try:
        plane.run(data_fn, 1)
        ray_tpu.kill(plane.actors[0])
        with pytest.raises(StageFailedError):
            plane.run(data_fn, 2)
    finally:
        plane.stop()
