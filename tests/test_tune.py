"""Tune library: searchers, schedulers, controller, resume.

Reference test model: python/ray/tune/tests/ (test_tune_restore.py,
test_trial_scheduler.py, test_searchers.py) — behavior parity checks over
a real local cluster.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.air.config import RunConfig


def _quadratic(config):
    # maximize -(x-3)^2: optimum at x=3
    for i in range(5):
        tune.report({"score": -((config["x"] - 3.0) ** 2) - 0.01 * (5 - i)})


class _StepTrainable(tune.Trainable):
    def setup(self, config):
        self.x = config["x"]
        self.total = 0.0

    def step(self):
        self.total += self.x
        return {"total": self.total}

    def save_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "state.txt"), "w") as f:
            f.write(str(self.total))

    def load_checkpoint(self, checkpoint_dir):
        with open(os.path.join(checkpoint_dir, "state.txt")) as f:
            self.total = float(f.read())


def test_variant_generation():
    from ray_tpu.tune.search.variant_generator import count_variants, generate_variants

    space = {
        "a": tune.grid_search([1, 2, 3]),
        "b": tune.uniform(0.0, 1.0),
        "nested": {"c": tune.choice(["x", "y"])},
    }
    variants = list(generate_variants(space, num_samples=2))
    assert len(variants) == 6 == count_variants(space, 2)
    assert {v["a"] for v in variants} == {1, 2, 3}
    for v in variants:
        assert 0.0 <= v["b"] <= 1.0
        assert v["nested"]["c"] in ("x", "y")


def test_function_trainable_sweep(ray_cluster, tmp_path):
    tuner = Tuner(
        _quadratic,
        param_space={"x": tune.grid_search([1.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="quad", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 3
    assert results.num_terminated == 3
    best = results.get_best_result()
    assert best.metrics["config"]["x"] == 3.0


def test_class_trainable_with_stop_and_checkpoint(ray_cluster, tmp_path):
    tuner = Tuner(
        _StepTrainable,
        param_space={"x": tune.grid_search([2.0, 7.0])},
        tune_config=TuneConfig(metric="total", mode="max"),
        run_config=RunConfig(
            name="steppy", storage_path=str(tmp_path), stop={"training_iteration": 4}
        ),
    )
    results = tuner.fit()
    assert len(results) == 2
    best = results.get_best_result()
    assert best.metrics["total"] == pytest.approx(4 * 7.0)
    # terminal checkpoint saved
    assert best.checkpoint is not None
    with open(os.path.join(best.checkpoint.path, "state.txt")) as f:
        assert float(f.read()) == pytest.approx(28.0)


def test_asha_stops_bad_trials(ray_cluster, tmp_path):
    def slow_quad(config):
        for i in range(16):
            # Keep the population running concurrently: with instant steps a
            # trial can reach max_t before later trials hit their first rung,
            # and async ASHA's first-arrival-survives rule then cuts nothing.
            time.sleep(0.05)
            tune.report({"score": -((config["x"] - 3.0) ** 2) + 0.05 * i})

    scheduler = tune.ASHAScheduler(max_t=16, grace_period=2, reduction_factor=2)
    bad_xs = [-6.0, -4.0, -2.0, 0.0, 1.0, 5.0]
    tuner = Tuner(
        slow_quad,
        param_space={"x": tune.grid_search(bad_xs + [2.5, 3.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=scheduler),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    iters = {
        r.metrics["config"]["x"]: r.metrics["training_iteration"] for r in results if r.metrics
    }
    # the best config survives to max_t; ASHA is asynchronous, so the first
    # arrival at each rung always survives — assert aggregate savings, not
    # per-trial cuts
    assert iters[3.0] == 16
    assert sum(iters[x] for x in bad_xs) < 16 * len(bad_xs) * 0.75
    assert min(iters[x] for x in bad_xs) <= 4


def test_tpe_searcher_improves(ray_cluster, tmp_path):
    space = {"x": tune.uniform(-10.0, 10.0)}
    searcher = tune.TPESearcher(space, metric="score", mode="max", n_startup_trials=6, seed=1)
    tuner = Tuner(
        _quadratic,
        param_space=space,
        tune_config=TuneConfig(metric="score", mode="max", search_alg=searcher, num_samples=20),
        run_config=RunConfig(name="tpe", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 20
    best = results.get_best_result()
    # TPE should concentrate near x=3 by the end
    assert abs(best.metrics["config"]["x"] - 3.0) < 1.5


def test_experiment_resume(ray_cluster, tmp_path):
    exp_dir = str(tmp_path / "resumable")

    def failing_once(config):
        marker = os.path.join(exp_dir, f"ran_{config['x']}")
        first_time = not os.path.exists(marker)
        with open(marker, "a") as f:
            f.write("x")
        if first_time and config["x"] == 99:
            raise RuntimeError("boom")
        tune.report({"score": config["x"], "done": True})

    tuner = Tuner(
        failing_once,
        param_space={"x": tune.grid_search([1, 99])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="resumable", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert results.num_errors == 1
    assert Tuner.can_restore(exp_dir)

    restored = Tuner.restore(exp_dir, failing_once, resume_errored=True)
    results2 = restored.fit()
    assert results2.num_errors == 0
    scores = sorted(r.metrics["score"] for r in results2 if r.metrics and "score" in r.metrics)
    assert scores == [1, 99]


def test_pbt_exploits(ray_cluster, tmp_path):
    class PBTTrainable(tune.Trainable):
        def setup(self, config):
            self.value = 0.0

        def step(self):
            # lr=good makes fast progress; PBT should propagate it.
            # The sleep keeps the population running concurrently: with
            # instant steps a trial can finish all 12 iterations before the
            # other trials report once, and PBT's quantile logic (correctly)
            # refuses to exploit without a full population.  0.25s and
            # 16 iterations keep the population overlapping even when
            # actor starts stagger by seconds on a loaded 1-core box.
            time.sleep(0.25)
            self.value += self.config["lr"]
            return {"value": self.value}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "v.txt"), "w") as f:
                f.write(str(self.value))

        def load_checkpoint(self, d):
            with open(os.path.join(d, "v.txt")) as f:
                self.value = float(f.read())

    pbt = tune.PopulationBasedTraining(
        metric="value",
        mode="max",
        perturbation_interval=3,
        hyperparam_mutations={"lr": tune.uniform(0.1, 10.0)},
        quantile_fraction=0.5,
        seed=0,
    )
    tuner = Tuner(
        # Fractional CPUs: PBT's quantile decisions need the whole
        # population reporting concurrently, even if earlier tests in the
        # shared module cluster leaked a CPU or two.
        tune.with_resources(PBTTrainable, {"cpu": 0.25}),
        param_space={"lr": tune.grid_search([0.1, 0.2, 5.0, 10.0])},
        tune_config=TuneConfig(metric="value", mode="max", scheduler=pbt),
        run_config=RunConfig(
            name="pbt", storage_path=str(tmp_path), stop={"training_iteration": 16}
        ),
    )
    results = tuner.fit()
    finals = [r.metrics["value"] for r in results if r.metrics and "value" in r.metrics]
    assert results.num_errors == 0
    # Exploitation: the bad trials (lr 0.1/0.2) clone a top trial's
    # checkpoint, so even the WORST final trajectory must beat the best
    # pure-bad-lr trajectory (12 * 0.2 = 2.4) by a wide margin.
    assert min(finals) > 16 * 0.2 * 2
    # Exploration: the exploited trials continue with a *mutated* config,
    # so some final lr must differ from every initial grid value.
    final_lrs = {r.metrics["config"]["lr"] for r in results if r.metrics}
    assert final_lrs - {0.1, 0.2, 5.0, 10.0}, f"no perturbed configs in {final_lrs}"


def test_pb2_exploits_with_gp_bandit(ray_cluster, tmp_path):
    """PB2: same exploit machinery as PBT, but new configs come from the
    GP-bandit over population history and must respect the bounds."""

    class PB2Trainable(tune.Trainable):
        def setup(self, config):
            self.value = 0.0

        def step(self):
            time.sleep(0.25)  # keep the population overlapping (see PBT test)
            self.value += self.config["lr"]
            return {"value": self.value}

        def save_checkpoint(self, d):
            with open(os.path.join(d, "v.txt"), "w") as f:
                f.write(str(self.value))

        def load_checkpoint(self, d):
            with open(os.path.join(d, "v.txt")) as f:
                self.value = float(f.read())

    pb2 = tune.PB2(
        metric="value",
        mode="max",
        perturbation_interval=3,
        hyperparam_bounds={"lr": [0.1, 10.0]},
        quantile_fraction=0.5,
        seed=0,
    )
    tuner = Tuner(
        tune.with_resources(PB2Trainable, {"cpu": 0.25}),
        param_space={"lr": tune.grid_search([0.1, 0.2, 5.0, 10.0])},
        tune_config=TuneConfig(metric="value", mode="max", scheduler=pb2),
        run_config=RunConfig(
            name="pb2", storage_path=str(tmp_path), stop={"training_iteration": 16}
        ),
    )
    results = tuner.fit()
    assert results.num_errors == 0
    finals = [r.metrics["value"] for r in results if r.metrics and "value" in r.metrics]
    assert min(finals) > 16 * 0.2 * 2  # bad trials exploited a top trial
    # the bandit saw population history
    assert len(pb2._history) > 0
    for r in results:
        assert 0.1 <= r.config["lr"] <= 10.0  # selections respect bounds


def test_bohb_searcher_with_hyperband(ray_cluster, tmp_path):
    """TuneBOHB + HyperBandForBOHB: suggestions respect the space, the
    KDE trains on intermediate (rung-budget) results, and the search
    converges toward the good region."""

    def objective(config):
        for i in range(6):
            tune.report({"score": -((config["x"] - 3.0) ** 2) - 0.1 * i ** 0.5})

    searcher = tune.TuneBOHB(
        space={"x": tune.uniform(-10.0, 10.0)},
        metric="score",
        mode="max",
        n_startup_trials=4,
        seed=1,
    )
    tuner = Tuner(
        objective,
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            search_alg=searcher,
            scheduler=tune.HyperBandForBOHB(metric="score", mode="max", max_t=6),
            num_samples=16,
        ),
        run_config=RunConfig(name="bohb", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert results.num_errors == 0
    best = results.get_best_result(metric="score", mode="max")
    assert abs(best.config["x"] - 3.0) < 3.0, best.config
    # the model observed multiple budget levels (BOHB's point)
    assert len(searcher._by_budget) >= 2, list(searcher._by_budget)
