"""Dashboard HTTP API + REST job submission (reference:
dashboard/dashboard.py routes, dashboard/modules/job/ REST + sdk)."""

import json
import time
from urllib import request

import pytest

import ray_tpu
from ray_tpu.dashboard import JobSubmissionClient


@pytest.fixture(scope="module")
def dash():
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    url = ctx.dashboard_url
    assert url, "head did not report a dashboard url"
    yield url
    ray_tpu.shutdown()


def _get(url, path):
    with request.urlopen(url + path, timeout=10) as r:
        return json.loads(r.read())


def test_state_endpoints(dash):
    @ray_tpu.remote
    class Marker:
        def ping(self):
            return 1

    m = Marker.remote()
    assert ray_tpu.get(m.ping.remote(), timeout=30) == 1

    status = _get(dash, "/api/cluster_status")
    assert status["nodes_alive"] >= 1
    assert status["resources_total"].get("CPU", 0) >= 2

    nodes = _get(dash, "/api/nodes")
    assert any(n["state"] == "ALIVE" for n in nodes)

    actors = _get(dash, "/api/actors")
    assert any(a["state"] == "ALIVE" and "Marker" in a["class_name"] for a in actors)

    assert isinstance(_get(dash, "/api/tasks"), list)
    assert isinstance(_get(dash, "/api/placement_groups"), list)
    ray_tpu.kill(m)


def test_index_serves_spa_and_metrics(dash):
    """GET / serves the self-contained SPA (tabbed tables polling the
    /api endpoints — reference: dashboard/client app)."""
    with request.urlopen(dash + "/", timeout=10) as r:
        page = r.read().decode()
    assert "<nav" in page and "/api/cluster_status" in page  # live-polling SPA
    for endpoint in ("/api/nodes", "/api/actors", "/api/tasks", "/api/jobs"):
        assert endpoint in page  # every entity tab wired to its API
    with request.urlopen(dash + "/metrics", timeout=10) as r:
        assert r.status == 200


def test_grafana_dashboard_endpoint(dash):
    """GET /api/grafana_dashboard returns importable Grafana JSON whose
    panels cover the families the cluster exports (reference:
    modules/metrics/grafana_dashboard_factory.py)."""
    model = _get(dash, "/api/grafana_dashboard")
    assert model["uid"] == "ray-tpu-default"
    assert model["templating"]["list"][0]["name"] == "datasource"
    with request.urlopen(dash + "/metrics", timeout=10) as r:
        metrics_text = r.read().decode()
    exported = {
        line.split(None, 3)[2]
        for line in metrics_text.splitlines()
        if line.startswith("# TYPE ")
    }
    paneled = set()
    for p in model["panels"]:
        for t in p["targets"]:
            expr = t["expr"]
            paneled.add(
                expr.split("rate(")[-1].split("[")[0].split("_bucket")[0]
                if "(" in expr else expr
            )
    missing = exported - paneled
    assert not missing, f"metrics with no panel: {missing}"


def test_grafana_factory_query_shapes():
    """Counters get rate() queries, histograms get quantile queries over
    _bucket, gauges are raw."""
    from ray_tpu.dashboard.grafana_dashboard_factory import generate_grafana_dashboard

    text = (
        "# HELP reqs total requests\n# TYPE reqs counter\nreqs 10\n"
        "# TYPE depth gauge\ndepth 3\n"
        "# TYPE lat histogram\nlat_bucket{le=\"1\"} 4\nlat_sum 2.0\nlat_count 4\n"
    )
    model = generate_grafana_dashboard(text)
    by_title = {p["title"]: p for p in model["panels"]}
    assert by_title["reqs"]["targets"][0]["expr"] == "rate(reqs[5m])"
    assert by_title["depth"]["targets"][0]["expr"] == "depth"
    lat_exprs = [t["expr"] for t in by_title["lat"]["targets"]]
    assert any("histogram_quantile(0.99" in e and "lat_bucket" in e for e in lat_exprs)
    assert len(lat_exprs) == 3
    assert by_title["reqs"]["description"] == "total requests"


def test_grafana_factory_training_robustness_panels_out_of_the_box():
    """ROADMAP follow-up (PR 2) + ISSUE 4: train_step_seconds and the
    drain_*/train_resize_* metrics get panels even when the exposition
    text predates their first event — and a live exposition of the same
    family does not duplicate the panel."""
    from ray_tpu.dashboard.grafana_dashboard_factory import generate_grafana_dashboard

    model = generate_grafana_dashboard("")  # nothing exported yet
    titles = {p["title"] for p in model["panels"]}
    for metric in (
        "train_step_seconds",
        "train_resize_events_total",
        "train_resize_seconds",
        "drain_events_total",
        "drain_migration_seconds",
        "chaos_injections_total",
    ):
        assert metric.replace("_", " ") in titles, metric
    # Histogram builtins get quantile queries; counters get rate().
    by_title = {p["title"]: p for p in model["panels"]}
    resize_exprs = [t["expr"] for t in by_title["train resize seconds"]["targets"]]
    assert any("histogram_quantile" in e for e in resize_exprs)
    events_exprs = [t["expr"] for t in by_title["train resize events total"]["targets"]]
    assert events_exprs == ["rate(train_resize_events_total[5m])"]

    # Live exposition wins without duplication.
    text = "# HELP train_step_seconds live\n# TYPE train_step_seconds histogram\n"
    model2 = generate_grafana_dashboard(text)
    step_panels = [
        p for p in model2["panels"] if p["title"] == "train step seconds"
    ]
    assert len(step_panels) == 1
    assert step_panels[0]["description"] == "live"


def test_job_submission_lifecycle(dash, tmp_path):
    client = JobSubmissionClient(dash)
    out = tmp_path / "job_out.txt"
    sid = client.submit_job(
        entrypoint=f"python -c \"open('{out}','w').write('done')\" && echo finished",
        metadata={"who": "test"},
    )
    status = client.wait_until_finished(sid, timeout=60)
    assert status == "SUCCEEDED"
    assert out.read_text() == "done"
    assert "finished" in client.get_job_logs(sid)
    info = client.get_job_info(sid)
    assert info["metadata"] == {"who": "test"}
    jobs = client.list_jobs()
    assert any(j["submission_id"] == sid for j in jobs)
    assert client.delete_job(sid)
    with pytest.raises(RuntimeError):
        client.get_job_status(sid)


def test_job_submission_runs_driver_against_cluster(dash, tmp_path):
    """The submitted entrypoint connects to THIS cluster via
    RAY_TPU_ADDRESS and runs real tasks."""
    client = JobSubmissionClient(dash)
    script = tmp_path / "driver.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init()  # RAY_TPU_ADDRESS is set by the job supervisor\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x * 3\n"
        "print('RESULT', ray_tpu.get(f.remote(14)))\n"
        "ray_tpu.shutdown()\n"
    )
    sid = client.submit_job(entrypoint=f"python {script}")
    assert client.wait_until_finished(sid, timeout=120) == "SUCCEEDED"
    assert "RESULT 42" in client.get_job_logs(sid)


def test_job_stop(dash):
    client = JobSubmissionClient(dash)
    sid = client.submit_job(entrypoint="sleep 120")
    deadline = time.monotonic() + 30
    while client.get_job_status(sid) == "PENDING" and time.monotonic() < deadline:
        time.sleep(0.2)
    assert client.get_job_status(sid) == "RUNNING"
    assert client.stop_job(sid)
    assert client.wait_until_finished(sid, timeout=30) == "STOPPED"


def test_failed_job_status(dash):
    client = JobSubmissionClient(dash)
    sid = client.submit_job(entrypoint="python -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(sid, timeout=60) == "FAILED"
    assert "code 3" in client.get_job_info(sid)["message"]


def test_usage_stats_endpoint_and_local_report(dash):
    """Usage stats (reference: dashboard/modules/usage_stats): LOCAL
    report only — /api/usage_stats collects a snapshot, persists it in
    the session dir, and never needs egress."""
    stats = _get(dash, "/api/usage_stats")
    assert stats["schema_version"] == 1
    assert stats["num_nodes_alive"] >= 1
    assert stats["total_num_cpus"] >= 2
    assert "ray_tpu.data" not in stats["libraries_used"]  # dashboard proc

    # persisted next to the session's other artifacts (by the loop)
    import os

    from ray_tpu._private.worker import get_global_worker

    sd = get_global_worker().session_info.get("session_dir")
    path = os.path.join(sd, "usage_stats.json")
    deadline = time.time() + 20  # loop writes once at startup
    while not os.path.exists(path) and time.time() < deadline:
        time.sleep(0.2)
    assert os.path.exists(path)
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["schema_version"] == 1
