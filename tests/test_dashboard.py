"""Dashboard HTTP API + REST job submission (reference:
dashboard/dashboard.py routes, dashboard/modules/job/ REST + sdk)."""

import json
import time
from urllib import request

import pytest

import ray_tpu
from ray_tpu.dashboard import JobSubmissionClient


@pytest.fixture(scope="module")
def dash():
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    url = ctx.dashboard_url
    assert url, "head did not report a dashboard url"
    yield url
    ray_tpu.shutdown()


def _get(url, path):
    with request.urlopen(url + path, timeout=10) as r:
        return json.loads(r.read())


def test_state_endpoints(dash):
    @ray_tpu.remote
    class Marker:
        def ping(self):
            return 1

    m = Marker.remote()
    assert ray_tpu.get(m.ping.remote(), timeout=30) == 1

    status = _get(dash, "/api/cluster_status")
    assert status["nodes_alive"] >= 1
    assert status["resources_total"].get("CPU", 0) >= 2

    nodes = _get(dash, "/api/nodes")
    assert any(n["state"] == "ALIVE" for n in nodes)

    actors = _get(dash, "/api/actors")
    assert any(a["state"] == "ALIVE" and "Marker" in a["class_name"] for a in actors)

    assert isinstance(_get(dash, "/api/tasks"), list)
    assert isinstance(_get(dash, "/api/placement_groups"), list)
    ray_tpu.kill(m)


def test_index_and_metrics(dash):
    with request.urlopen(dash + "/", timeout=10) as r:
        page = r.read().decode()
    assert "ray_tpu cluster" in page
    with request.urlopen(dash + "/metrics", timeout=10) as r:
        assert r.status == 200


def test_job_submission_lifecycle(dash, tmp_path):
    client = JobSubmissionClient(dash)
    out = tmp_path / "job_out.txt"
    sid = client.submit_job(
        entrypoint=f"python -c \"open('{out}','w').write('done')\" && echo finished",
        metadata={"who": "test"},
    )
    status = client.wait_until_finished(sid, timeout=60)
    assert status == "SUCCEEDED"
    assert out.read_text() == "done"
    assert "finished" in client.get_job_logs(sid)
    info = client.get_job_info(sid)
    assert info["metadata"] == {"who": "test"}
    jobs = client.list_jobs()
    assert any(j["submission_id"] == sid for j in jobs)
    assert client.delete_job(sid)
    with pytest.raises(RuntimeError):
        client.get_job_status(sid)


def test_job_submission_runs_driver_against_cluster(dash, tmp_path):
    """The submitted entrypoint connects to THIS cluster via
    RAY_TPU_ADDRESS and runs real tasks."""
    client = JobSubmissionClient(dash)
    script = tmp_path / "driver.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init()  # RAY_TPU_ADDRESS is set by the job supervisor\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x * 3\n"
        "print('RESULT', ray_tpu.get(f.remote(14)))\n"
        "ray_tpu.shutdown()\n"
    )
    sid = client.submit_job(entrypoint=f"python {script}")
    assert client.wait_until_finished(sid, timeout=120) == "SUCCEEDED"
    assert "RESULT 42" in client.get_job_logs(sid)


def test_job_stop(dash):
    client = JobSubmissionClient(dash)
    sid = client.submit_job(entrypoint="sleep 120")
    deadline = time.monotonic() + 30
    while client.get_job_status(sid) == "PENDING" and time.monotonic() < deadline:
        time.sleep(0.2)
    assert client.get_job_status(sid) == "RUNNING"
    assert client.stop_job(sid)
    assert client.wait_until_finished(sid, timeout=30) == "STOPPED"


def test_failed_job_status(dash):
    client = JobSubmissionClient(dash)
    sid = client.submit_job(entrypoint="python -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(sid, timeout=60) == "FAILED"
    assert "code 3" in client.get_job_info(sid)["message"]
