"""util compat shims: multiprocessing.Pool, joblib backend, dask
scheduler (reference: python/ray/util/{multiprocessing,joblib,dask}/
and their tests, shrunk to CI size)."""

import numpy as np
import pytest

import ray_tpu


def _sq(x):
    return x * x


def _add(a, b):
    return a + b


def _boom(x):
    raise RuntimeError(f"boom-{x}")


_INIT_FLAG = {"v": 0}


def _init(v):
    _INIT_FLAG["v"] = v


def _read_init(_):
    return _INIT_FLAG["v"]


def test_pool_map_apply_starmap(ray_cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(_sq, range(10)) == [i * i for i in range(10)]
        assert pool.apply(_add, (3, 4)) == 7
        assert pool.starmap(_add, [(1, 2), (3, 4)]) == [3, 7]
        r = pool.apply_async(_sq, (9,))
        assert r.get(timeout=30) == 81
        assert r.successful()
        # ordered and unordered lazy iterators
        assert list(pool.imap(_sq, range(6), chunksize=2)) == [i * i for i in range(6)]
        assert sorted(pool.imap_unordered(_sq, range(6), chunksize=2)) == [
            i * i for i in range(6)
        ]


def test_pool_initializer_and_errors(ray_cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2, initializer=_init, initargs=(42,)) as pool:
        # initializer ran in whichever worker served the task
        assert set(pool.map(_read_init, range(4))) == {42}
        with pytest.raises(RuntimeError, match="boom-3"):
            pool.map(_boom, [3])
        r = pool.apply_async(_boom, (7,))
        with pytest.raises(RuntimeError, match="boom-7"):
            r.get(timeout=30)
        assert not r.successful()
    with pytest.raises(ValueError):
        pool.map(_sq, [1])  # closed


def test_joblib_ray_backend(ray_cluster):
    import joblib

    from ray_tpu.util.joblib import register_ray

    register_ray()
    with joblib.parallel_backend("ray", n_jobs=2):
        out = joblib.Parallel()(joblib.delayed(_sq)(i) for i in range(12))
    assert out == [i * i for i in range(12)]


def test_joblib_sklearn_grid_search(ray_cluster):
    """The reference's headline joblib use case: sklearn fans its CV
    fits out through the backend."""
    import joblib
    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import GridSearchCV

    from ray_tpu.util.joblib import register_ray

    register_ray()
    rng = np.random.default_rng(0)
    X = rng.normal(size=(60, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    with joblib.parallel_backend("ray", n_jobs=2):
        gs = GridSearchCV(LogisticRegression(), {"C": [0.1, 1.0]}, cv=2)
        gs.fit(X, y)
    assert gs.best_score_ > 0.7


def test_dask_scheduler_graph(ray_cluster):
    from ray_tpu.util.dask import ray_dask_get

    def inc(x):
        return x + 1

    dsk = {
        "a": 1,
        "b": (inc, "a"),                # depends on a
        "c": (inc, "b"),
        "d": (_add, "b", "c"),          # join
        "e": (_add, (inc, "a"), 10),    # nested inline task
        "alias": "d",
        "lst": ["b", "c", (inc, 100)],  # list computation
    }
    assert ray_dask_get(dsk, "d") == 5   # b=2, c=3
    assert ray_dask_get(dsk, ["b", "e", "alias"]) == [2, 12, 5]
    assert ray_dask_get(dsk, "lst") == [2, 3, 101]


def test_dask_scheduler_detects_cycles(ray_cluster):
    from ray_tpu.util.dask import ray_dask_get

    def f(x):
        return x

    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get({"a": (f, "b"), "b": (f, "a")}, "a")
