"""Dataplane chaos drill matrix (the PR 14 tentpole acceptance): the
compiled-channel layer that now carries every hot path — serve calls and
token streams, podracer trajectory/weight streams, MPMD pipeline
activations, compiled-DAG edges — is drilled with the seeded
``chan:<path-glob>:<action>`` chaos rules, and every consumer must
recover with TYPED errors and ZERO corrupted values delivered to user
code.

The matrix:

    consumer          corrupt_frame        torn_write (mid-frame    close / socket drop
                                           writer kill)
    serve dataplane   typed timeout,       typed timeout,           transparent RPC
                      replica skips        replica skips            fallback, exact result
    serve (replica    typed ActorDied,     (same CRC path as        —
    response side)    lazy re-attach       corrupt)
    pipeline plane    checkpoint-restart,  checkpoint-restart,      reattach/StageFailed
                      loss parity          loss parity              (kill drill: test_pipeline_plane)
    podracer stream   edge retired +       (same CRC path)          reattach/respawn
                      respawn, no garbage                           (kill drill: test_rllib_podracer)
    compiled DAG      graph fails CLOSED   transparent epoch        transparent epoch
                      (multiplicity        reattach + seq replay,   reattach + seq replay,
                      unknowable), typed   exact                    exact

Chaos specs ride env vars set BEFORE ``ray_tpu.init`` so every spawned
worker process inherits the same seeded, replayable schedule (rule
ordinals are per-process, per-rule — see test_channels.py for the
seed-replay determinism assertions on the chan rule family).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import exceptions


@pytest.fixture()
def chaos_env():
    """Set a seeded chan:* chaos spec BEFORE cluster processes spawn;
    restore + deactivate after, whatever the test did."""
    saved = {}

    def set_spec(spec: str, seed: str = "7") -> None:
        for k, v in {
            "RAY_TPU_testing_chaos_spec": spec,
            "RAY_TPU_testing_chaos_seed": seed,
        }.items():
            saved.setdefault(k, os.environ.get(k))
            os.environ[k] = v
        from ray_tpu._private.chaos import CHAOS

        CHAOS.reset()

    yield set_spec
    try:
        ray_tpu.shutdown()
    except Exception:  # noqa: BLE001 — test may have shut down already
        pass
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old
    from ray_tpu._private.chaos import CHAOS

    CHAOS.reset()


def test_serve_dataplane_corrupt_torn_close_request_frames(chaos_env):
    """Router-side faults on the request ring: a corrupted frame and a
    torn (mid-write-killed) frame are consumed by the replica's CRC
    check and surface to the caller as typed GetTimeoutError — never a
    wrong value, never a wedged dataplane; a chaos close of the ring
    falls back to the RPC path with the EXACT result.  Streams keep
    working afterwards."""
    chaos_env(
        "chan:*ray_tpu_serve_*/req:corrupt_frame:at=3,"
        "chan:*ray_tpu_serve_*/req:torn_write:at=6,"
        "chan:*ray_tpu_serve_*/req:close:at=9"
    )
    ray_tpu.init(num_cpus=4)
    from ray_tpu import serve
    from ray_tpu.serve._private.dataplane import ChannelClient
    from ray_tpu.serve._private.router import _routers

    @serve.deployment(name="ReqDrill")
    class ReqDrill:
        def __call__(self, payload):
            return {"echo": payload}

        def tokens(self, n):
            for i in range(n):
                yield {"tok": i}

    try:
        h = serve.run(ReqDrill.bind(), name="req_drill")
        assert h.remote(0).result(timeout=30) == {"echo": 0}
        router = _routers[h.deployment_name]
        assert any(
            isinstance(v, ChannelClient) for v in router._dataplanes.values()
        ), "drill is vacuous: dataplane never attached"
        exact, typed = 0, 0
        for i in range(1, 12):
            try:
                assert h.remote(i).result(timeout=4.0) == {"echo": i}
                exact += 1
            except exceptions.GetTimeoutError:
                typed += 1  # the corrupted/torn request, consumed replica-side
        # corrupt + torn lost exactly their own frame each; the chaos
        # close fell back to RPC with the exact result (no user error)
        assert typed == 2 and exact == 9
        # the plane is healthy again: calls and streams exact
        assert h.remote("after").result(timeout=30) == {"echo": "after"}
        assert list(h.options(stream=True).tokens.remote(5)) == [
            {"tok": i} for i in range(5)
        ]
    finally:
        serve.shutdown()


def test_serve_dataplane_corrupt_response_frame_typed_and_reattaches(chaos_env):
    """Replica-side fault: one corrupted RESPONSE frame kills the
    router's demux (a response's request id is unknowable, so waiters
    would hang) — the affected call gets the typed ActorDiedError, the
    dataplane is evicted, and the next call re-attaches and is exact.
    Zero corrupted payloads ever reach user code."""
    chaos_env("chan:*ray_tpu_serve_*/resp:corrupt_frame:at=2")
    ray_tpu.init(num_cpus=4)
    from ray_tpu import serve

    @serve.deployment(name="RespDrill")
    class RespDrill:
        def __call__(self, payload):
            return {"echo": payload}

    try:
        h = serve.run(RespDrill.bind(), name="resp_drill")
        outcomes = []
        for i in range(6):
            try:
                r = h.remote(i).result(timeout=30)
                assert r == {"echo": i}, r  # exact or typed — never wrong
                outcomes.append("ok")
            except exceptions.ActorDiedError:
                outcomes.append("died")
        assert outcomes.count("died") == 1  # exactly the corrupted frame
        assert outcomes[0] == "ok" and outcomes[-1] == "ok"
    finally:
        serve.shutdown()


@pytest.mark.slow  # ~30 s restart-parity drill; dataplane chaos smoke covers it
def test_pipeline_plane_corrupt_and_torn_frames_restart_with_parity(chaos_env):
    """Driver-side faults on the pipeline's tgt edge: one corrupted
    frame and one torn (mid-write-killed) frame each surface in the
    reading stage as the typed ChannelCorruptionError, the plane
    restarts from its checkpoint (restarts == 2, one per fault), and
    the final losses match the undisturbed single-process reference —
    a corrupted microbatch can NEVER silently poison a training step."""
    jax = pytest.importorskip("jax")  # noqa: F841
    import jax.numpy as jnp
    from test_pipeline_plane import _cfg, _data, _reference_losses  # noqa: F401

    from ray_tpu.train.sharding import (
        PipelineConfig,
        PipelinePlane,
        gpt2_pipeline_programs,
    )

    # tgt_in is written ONLY by the driver, so the schedule is exactly
    # two faults (per-process ordinals; stage respawns can't re-fire it)
    chaos_env(
        "chan:*ray_tpu_pp_*/tgt_in:corrupt_frame:at=3,"
        "chan:*ray_tpu_pp_*/tgt_in:torn_write:at=7"
    )
    ray_tpu.init(num_cpus=4)
    cfg = _cfg()
    steps = 5
    data_fn = _data(steps)
    ref = _reference_losses(cfg, data_fn, steps)
    prog = gpt2_pipeline_programs(cfg, n_stages=2, lr=1e-3, seed=0)
    plane = PipelinePlane(
        prog,
        PipelineConfig(
            stages=2, microbatches=2, step_timeout_s=5.0,
            checkpoint_every=2, max_restarts=4,
        ),
    )
    try:
        losses = plane.run(data_fn, steps)
        assert plane.restarts == 2  # one checkpoint-restart per fault
        assert losses == pytest.approx(ref, abs=2e-5)
    finally:
        plane.stop()


@pytest.mark.slow  # ~20 s respawn drill; dataplane chaos smoke covers the path
def test_podracer_stream_corruption_retires_edge_and_respawns(chaos_env):
    """Runner-side fault: a corrupted trajectory fragment is caught by
    the intake's CRC check (typed, counted), the edge is retired and the
    runner respawned at the current generation; a corrupted weight
    broadcast is never adopted (the runner keeps its previous snapshot).
    Training proceeds through the churn with finite losses and zero
    garbage fragments (per-runner seq contiguity is asserted inside the
    plane)."""
    pytest.importorskip("jax")
    import numpy as np
    from test_rllib_podracer import _ppo_podracer_cfg

    chaos_env(
        "chan:*ray_tpu_rllib_*/traj:corrupt_frame:at=6,"
        "chan:*ray_tpu_rllib_*/weights:corrupt_frame:at=2"
    )
    ray_tpu.init(num_cpus=4)
    algo = _ppo_podracer_cfg().build()
    try:
        out = None
        for _ in range(4):
            out = algo.train()
            assert out["num_env_steps_sampled"] > 0
            assert np.isfinite(out["total_loss"])
        plane = algo.env_runner_group
        deadline = time.monotonic() + 60
        while plane.replacements < 1 and time.monotonic() < deadline:
            algo.train()
        # at least one runner hit its corrupted fragment, was retired
        # typed (never delivered) and replaced at the live generation
        assert plane.runner_deaths >= 1
        assert plane.replacements >= 1
        assert sum(rs.alive for rs in plane.streams) >= 1
        assert np.isfinite(algo.train()["total_loss"])
    finally:
        algo.cleanup()


def test_dag_socket_torn_and_drop_reattach_exact(chaos_env):
    """Cross-raylet compiled-DAG edges under mid-frame connection cuts
    (torn_write) and abrupt socket drops (close), on BOTH the driver's
    input edge and the remote actor's result edge: the writer re-dials
    its peer's listener with the pairing token at a bumped epoch and
    replays unacked frames, the reader re-accepts via the shared
    reattach() helper — every execution's result is EXACT, nothing is
    lost, duplicated, or reordered."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.dag import InputNode

    chaos_env(
        "chan:socket:*:torn_write:at=3,"
        "chan:socket:*:close:at=8"
    )
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=2, resources={"edge": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)

    @ray_tpu.remote(resources={"edge": 0.1})
    class Far:
        def step(self, x):
            return x * 2 + 1

    try:
        far = Far.bind()
        with InputNode() as inp:
            dag = far.step.bind(inp)
        compiled = dag.experimental_compile(max_inflight=4)
        assert compiled._channels_on
        assert "socket" in {d["kind"] for d in compiled._descs.values()}
        try:
            # per-process write ordinals: the driver's input writes hit
            # torn at 3 and close at 8; the actor's result writes hit
            # the same ordinals in ITS process — four faults total, all
            # healed by epoch reattach + seq replay, zero lost results
            for i in range(20):
                assert ray_tpu.get(compiled.execute(i), timeout=30) == i * 2 + 1
            # the faults really fired and really reattached: both
            # driver-side endpoints lived through at least one epoch bump
            epochs = [compiled._driver_in[0][0].epoch, compiled._driver_out[0].epoch]
            assert max(epochs) >= 2, epochs
        finally:
            compiled.teardown()
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_dag_ring_frame_corruption_fails_closed_never_wrong(chaos_env):
    """Frame corruption on compiled-DAG ring edges FAILS CLOSED: a
    corrupted frame's multiplicity is unknowable (it may have been a
    TAG_BATCH of K executions), so delivering any fixed number of error
    values would desync the per-edge FIFO and hand later executions'
    results to the wrong refs.  Every get() up to the fault is exact;
    the fault and everything after it raises TYPED (corruption or
    closed) — zero wrong values, and teardown still works."""
    from ray_tpu.dag import InputNode
    from ray_tpu.experimental.channel import (
        ChannelClosed,
        ChannelCorruptionError,
        ChannelTimeout,
    )

    # per-process ordinals: the driver's input writes hit at=5; the
    # actor's result writes hit at=5 in ITS process — the first fault
    # to land fail-closes the graph, whichever side it is
    chaos_env("chan:*ray_tpu_dag_*:corrupt_frame:at=5")
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    class Echo:
        def step(self, x):
            return x + 100

    echo = Echo.bind()
    with InputNode() as inp:
        dag = echo.step.bind(inp)
    compiled = dag.experimental_compile(max_inflight=4)
    assert compiled._channels_on
    try:
        exact, typed = 0, 0
        for i in range(10):
            try:
                assert ray_tpu.get(compiled.execute(i), timeout=15) == i + 100
                exact += 1
            except (ChannelCorruptionError, ChannelClosed, ChannelTimeout):
                typed += 1
        assert typed >= 1, "chaos never fired — drill is vacuous"
        assert exact >= 3  # the executions before the fault were exact
        # the graph stays fail-closed: no later get can mis-associate
        with pytest.raises((ChannelCorruptionError, ChannelClosed, ChannelTimeout)):
            ray_tpu.get(compiled.execute(99), timeout=5)
    finally:
        compiled.teardown()
