"""Native shared-memory arena store: unit tests for the C++ allocator /
index / eviction + cluster integration (reference test model:
src/ray/object_manager/plasma/ C++ tests + python/ray/tests/test_object_store.py).
"""

import os

import numpy as np
import pytest

from ray_tpu._native.arena import NativeArena, load_library

pytestmark = pytest.mark.skipif(load_library() is None, reason="no C++ toolchain")


@pytest.fixture
def arena(tmp_path):
    path = "/dev/shm/test_arena_%d" % os.getpid()
    if os.path.exists(path):
        os.unlink(path)
    a = NativeArena.create(path, 1 << 20)
    assert a is not None
    yield a
    a.close()
    os.unlink(path)


def test_alloc_seal_lookup_roundtrip(arena):
    buf = arena.alloc(b"id1", 64)
    buf[:11] = b"hello arena"
    del buf
    assert arena.seal(b"id1")
    arena.release_create(b"id1")  # drop creator ref (held from alloc)
    v = arena.lookup(b"id1")
    assert bytes(v[:11]) == b"hello arena" and len(v) == 64
    del v
    arena.decref(b"id1")


def test_unsealed_not_visible(arena):
    arena.alloc(b"id2", 10)
    assert not arena.contains(b"id2")
    assert arena.lookup(b"id2") is None
    arena.seal(b"id2")
    assert arena.contains(b"id2")


def test_duplicate_alloc_rejected(arena):
    arena.alloc(b"dup", 10)
    code, view = arena.alloc_status(b"dup", 10)
    assert code == -2 and view is None


def test_refcount_blocks_delete_and_eviction(arena):
    buf = arena.alloc(b"pinned", 500_000)
    del buf
    arena.seal(b"pinned")
    arena.release_create(b"pinned")
    v = arena.lookup(b"pinned")  # refcount 1
    assert not arena.delete(b"pinned")
    # eviction cannot reclaim it either: a too-big request must fail
    assert arena.evict_lru(900_000) is None
    del v
    arena.decref(b"pinned")
    assert arena.delete(b"pinned")


def test_free_space_reuse_and_coalescing(arena):
    for i in range(4):
        arena.alloc(b"b%d" % i, 200_000)
        arena.seal(b"b%d" % i)
        arena.release_create(b"b%d" % i)
    used_before = arena.used
    # delete middle neighbours -> coalesced 400k hole fits one 390k object
    assert arena.delete(b"b1")
    assert arena.delete(b"b2")
    buf = arena.alloc(b"big", 390_000)
    assert buf is not None
    assert arena.used == used_before - 2 * 200_000 + 390_000


def test_lru_eviction_order(arena):
    import time

    for i in range(5):
        arena.alloc(b"e%d" % i, 150_000)
        arena.seal(b"e%d" % i)
        arena.release_create(b"e%d" % i)
        time.sleep(0.002)
    # touch e0 so it becomes most-recently-used
    v = arena.lookup(b"e0")
    del v
    arena.decref(b"e0")
    evicted = arena.evict_lru(300_000)
    assert evicted is not None
    evicted_ids = {e[:2] for e in evicted}
    assert b"e0" not in evicted_ids  # the touched object survived
    assert b"e1" in evicted_ids  # the coldest went first


def test_attach_sees_other_process_writes(arena, tmp_path):
    import subprocess
    import sys

    path = "/dev/shm/test_arena_%d" % os.getpid()
    code = f"""
import sys
sys.path.insert(0, {repr(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))})
from ray_tpu._native.arena import NativeArena
a = NativeArena.attach({path!r})
buf = a.alloc(b"xproc", 32)
buf[:7] = b"fromsub"
del buf
a.seal(b"xproc")
a.release_create(b"xproc")
a.close()
"""
    subprocess.run([sys.executable, "-c", code], check=True, timeout=60)
    v = arena.lookup(b"xproc")
    assert v is not None and bytes(v[:7]) == b"fromsub"
    del v
    arena.decref(b"xproc")


def test_cluster_large_object_via_arena(ray_cluster):
    import ray_tpu

    w = ray_tpu._private.worker.get_global_worker()
    if w.store.arena is None:
        pytest.skip("arena unavailable in this cluster")
    arr = np.random.default_rng(0).normal(size=(512, 512))  # 2MB
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    assert ray_tpu.get(total.remote(ref)) == pytest.approx(float(arr.sum()))
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)


def test_eownerdead_repair(arena, tmp_path):
    """A client dying inside the critical section (mid-mutation) must not
    corrupt the arena: the next locker repairs the index/allocator from
    the sealed entries (reference: plasma store survives client death;
    here via robust-mutex EOWNERDEAD + repair pass)."""
    import subprocess
    import sys

    # A sealed object that must survive the repair.
    buf = arena.alloc(b"survivor", 128)
    buf[:4] = b"keep"
    del buf
    arena.seal(b"survivor")
    arena.release_create(b"survivor")
    path = "/dev/shm/test_arena_%d" % os.getpid()
    # Child: allocate WITHOUT sealing (mid-write garbage), grab the arena
    # mutex, and die holding it.
    code = f"""
import os
from ray_tpu._native.arena import NativeArena
a = NativeArena.attach({path!r})
buf = a.alloc(b"halfwritten", 256)
buf[:4] = b"junk"
del buf
a._test_lock_and_abandon()
os._exit(42)
"""
    proc = subprocess.run([sys.executable, "-c", code], timeout=60)
    # 42 proves the child really reached lock-and-abandon (a crash before
    # that would make the assertions below pass vacuously).
    assert proc.returncode == 42
    # Next lock observes EOWNERDEAD and repairs: the sealed object is
    # intact, the mid-write entry is gone, and allocation still works.
    v = arena.lookup(b"survivor")
    assert v is not None and bytes(v[:4]) == b"keep"
    del v
    arena.decref(b"survivor")
    assert not arena.contains(b"halfwritten")
    assert arena.num_objects == 1
    buf = arena.alloc(b"after", 64)
    buf[:2] = b"ok"
    del buf
    assert arena.seal(b"after")
    arena.release_create(b"after")
    assert arena.contains(b"after")
