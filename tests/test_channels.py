"""Compiled-DAG zero-copy channels (reference:
experimental_mutable_object_manager.h:48, shared_memory_channel.py,
per-actor schedules compiled_dag_node.py:1639)."""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import Channel, ChannelClosed, ChannelTimeout


@pytest.fixture(scope="module", autouse=True)
def ray():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Channel primitive


def test_channel_roundtrip(tmp_path):
    p = str(tmp_path / "c1")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"hello")
    assert r.read() == b"hello"
    w.write(b"world")
    assert r.read() == b"world"


def test_channel_multiple_inflight(tmp_path):
    """The ring holds many messages at once (pipelined executions)."""
    p = str(tmp_path / "c1b")
    Channel.create_file(p, 4096)
    w, r = Channel(p), Channel(p)
    for i in range(10):
        w.write(f"msg{i}".encode(), timeout=1)
    assert [r.read() for _ in range(10)] == [f"msg{i}".encode() for i in range(10)]


def test_channel_flow_control(tmp_path):
    p = str(tmp_path / "c2")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"x" * 700)
    with pytest.raises(ChannelTimeout):
        w.write(b"y" * 700, timeout=0.3)  # ring full, reader hasn't consumed
    assert r.read() == b"x" * 700
    w.write(b"y" * 700, timeout=5)
    assert r.read() == b"y" * 700


def test_channel_poison(tmp_path):
    p = str(tmp_path / "c3")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.close()
    with pytest.raises(ChannelClosed):
        r.read(timeout=5)


def test_channel_drains_before_close(tmp_path):
    """close() is drain-then-close: buffered messages stay readable,
    the reader sees ChannelClosed only after consuming the backlog."""
    p = str(tmp_path / "c4")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"last words")
    w.close()
    assert r.read(timeout=5) == b"last words"
    with pytest.raises(ChannelClosed):
        r.read(timeout=5)


# ---------------------------------------------------------------------------
# Compiled DAG over channels


def test_compiled_pipeline_two_actors():
    """A 2-actor pipeline: data flows A -> B entirely over channels,
    state persists, and results come back in submission order."""

    @ray_tpu.remote
    class Stage:
        def __init__(self, inc):
            self.inc = inc
            self.count = 0

        def step(self, x):
            self.count += 1
            return x + self.inc

        def calls(self):
            return self.count

    a, b = Stage.bind(1), Stage.bind(10)
    with InputNode() as inp:
        dag = b.step.bind(a.step.bind(inp))
    compiled = dag.experimental_compile(max_inflight=8)
    assert compiled._channels_on  # really on the channel plane
    refs = [compiled.execute(i) for i in range(5)]
    assert [ray_tpu.get(r) for r in refs] == [i + 11 for i in range(5)]
    compiled.teardown()


def test_compiled_multi_output_fan():
    @ray_tpu.remote
    class Math:
        def double(self, x):
            return x * 2

        def square(self, x):
            return x * x

    m1, m2 = Math.bind(), Math.bind()
    with InputNode() as inp:
        dag = MultiOutputNode([m1.double.bind(inp), m2.square.bind(inp)])
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    assert ray_tpu.get(compiled.execute(6)) == [12, 36]
    assert ray_tpu.get(compiled.execute(3)) == [6, 9]
    compiled.teardown()


def test_compiled_channel_throughput_beats_task_path():
    """The channel plane must clearly beat per-call task submission on a
    tiny-payload pipeline (that's its reason to exist)."""

    @ray_tpu.remote
    class Echo:
        def echo(self, x):
            return x

    with InputNode() as inp:
        dag = Echo.bind().echo.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    ray_tpu.get(compiled.execute(0))  # warm
    n = 200
    t0 = time.monotonic()
    for i in range(n):
        ray_tpu.get(compiled.execute(i))
    chan_rate = n / (time.monotonic() - t0)
    compiled.teardown()

    actor = Echo.remote()
    ray_tpu.get(actor.echo.remote(0))
    t0 = time.monotonic()
    for i in range(n):
        ray_tpu.get(actor.echo.remote(i))
    task_rate = n / (time.monotonic() - t0)
    ray_tpu.kill(actor)
    assert chan_rate > task_rate * 1.5, (chan_rate, task_rate)


def test_compiled_teardown_unblocks_actors():
    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(1)) == 1
    compiled.teardown()  # must not hang


def test_compiled_error_propagates_and_dag_survives():
    """An actor-method exception flows to the driver's get as the
    original error, and the DAG keeps working afterwards."""

    @ray_tpu.remote
    class Fragile:
        def f(self, x):
            if x < 0:
                raise ValueError("negative!")
            return x * 2

    with InputNode() as inp:
        dag = Fragile.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(4)) == 8
    with pytest.raises(ValueError):
        ray_tpu.get(compiled.execute(-1))
    assert ray_tpu.get(compiled.execute(5)) == 10  # still alive
    compiled.teardown()


def test_compiled_inflight_cap():
    @ray_tpu.remote
    class Slow:
        def f(self, x):
            time.sleep(0.3)
            return x

    with InputNode() as inp:
        dag = Slow.bind().f.bind(inp)
    compiled = dag.experimental_compile(max_inflight=2)
    r1 = compiled.execute(1)
    compiled.execute(2)
    with pytest.raises(RuntimeError, match="in flight"):
        compiled.execute(3)
    assert ray_tpu.get(r1) == 1
    compiled.teardown()


def test_compiled_teardown_cleans_tmpfs():
    import os

    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    chan_dir = compiled._chan_dir
    assert os.path.isdir(chan_dir)
    ray_tpu.get(compiled.execute(1))
    compiled.teardown()
    assert not os.path.exists(chan_dir)  # tmpfs reclaimed


def test_function_node_compiles_to_executor_loop():
    """Driver-side FunctionNodes ride the channel plane too: each one is
    hosted by a resident _FnExecutor actor instead of taking the
    per-call task path."""

    @ray_tpu.remote
    def plain(x):
        return x + 1

    @ray_tpu.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        dag = double.bind(plain.bind(inp))
    compiled = dag.experimental_compile()
    assert compiled._channels_on  # no task-path fallback anymore
    assert [ray_tpu.get(compiled.execute(i)) for i in range(4)] == [2, 4, 6, 8]
    compiled.teardown()


def test_mixed_function_and_actor_graph_compiles():
    """A FunctionNode feeding an actor method (and vice versa) is one
    compiled graph spanning executor + user actors."""

    @ray_tpu.remote
    def pre(x):
        return x + 1

    @ray_tpu.remote
    class Scale:
        def __init__(self, k):
            self.k = k

        def mul(self, x):
            return x * self.k

    @ray_tpu.remote
    def post(x):
        return x - 3

    with InputNode() as inp:
        dag = post.bind(Scale.bind(10).mul.bind(pre.bind(inp)))
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    assert ray_tpu.get(compiled.execute(4)) == 47  # (4+1)*10-3
    assert ray_tpu.get(compiled.execute(0)) == 7
    compiled.teardown()


def test_kwargs_fall_back_to_task_path():
    """Graphs outside the op schedule's vocabulary still execute via the
    per-node task path."""

    @ray_tpu.remote
    def f(x, k=1):
        return x + k

    with InputNode() as inp:
        dag = f.bind(inp, k=5)
    compiled = dag.experimental_compile()
    assert not compiled._channels_on
    assert ray_tpu.get(compiled.execute(10)) == 15
    compiled.teardown()


# ---------------------------------------------------------------------------
# Channel edge cases (ring + socket + wire format)


def test_ring_wraparound_under_sustained_load(tmp_path):
    """Thousands of variable-size messages through a small ring: the
    write position wraps the region many times and every payload
    survives byte-exact (wrap markers + implicit tail skips)."""
    import threading

    p = str(tmp_path / "wrap")
    Channel.create_file(p, 4096)
    w, r = Channel(p), Channel(p)
    n = 1500
    payloads = [bytes([i % 251]) * (1 + (i * 37) % 900) for i in range(n)]
    errs = []

    def writer():
        try:
            for pl in payloads:
                w.write(pl, timeout=30)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    for i in range(n):
        assert r.read(timeout=30) == payloads[i], f"payload {i} corrupted"
    t.join(10)
    assert not errs
    assert w.stats["writes"] == n and r.stats["reads"] == n
    assert w._get(0) > 4096  # really wrapped (wbytes past capacity)


def test_ring_value_wraparound_mixed_types(tmp_path):
    """write_value/read_value across wrap boundaries with every
    fast-path type mixed (encode-in-place must handle tail-bounded
    windows by wrapping, reader must skip markers)."""
    import numpy as np

    p = str(tmp_path / "wrapv")
    Channel.create_file(p, 2048)
    w, r = Channel(p), Channel(p)
    vals = []
    for i in range(300):
        vals.append(
            [i, float(i), f"s{i}" * (i % 20), {"k": i}, np.arange(i % 40)][i % 5]
        )
    import threading

    t = threading.Thread(
        target=lambda: [w.write_value(v, timeout=30) for v in vals], daemon=True
    )
    t.start()
    for i, expect in enumerate(vals):
        tag, got = r.read_value(timeout=30)
        assert tag == 0
        if isinstance(expect, np.ndarray):
            assert (got == expect).all()
        else:
            assert got == expect, i
    t.join(10)


def test_payload_larger_than_ring_is_typed_error_not_hang(tmp_path):
    from ray_tpu.experimental.channel import ChannelCapacityError

    p = str(tmp_path / "cap")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    with pytest.raises(ChannelCapacityError):
        w.write(b"x" * 5000, timeout=5)
    with pytest.raises(ChannelCapacityError):
        w.write_value(b"x" * 5000, timeout=5)
    # the ring stays coherent after the refused writes
    w.write_value({"ok": 1})
    assert r.read_value() == (0, {"ok": 1})


def test_reader_timeout_vs_writer_death_detection(tmp_path):
    """Ring: a silent writer is indistinguishable from a dead one —
    reads raise ChannelTimeout.  Socket: writer death is detected
    immediately as ChannelClosed (EOF), no timeout burned."""
    import threading

    from ray_tpu.experimental.channel import SocketListener, dial

    # ring: timeout (peer alive but silent)
    p = str(tmp_path / "silent")
    Channel.create_file(p, 1024)
    r = Channel(p)
    t0 = time.monotonic()
    with pytest.raises(ChannelTimeout):
        r.read(timeout=0.3)
    assert time.monotonic() - t0 >= 0.25

    # socket: death -> ChannelClosed well before any read timeout
    lst = SocketListener()
    out = {}

    def reader():
        ch = lst.accept("read", timeout=5)
        out["first"] = ch.read_value(timeout=5)
        t1 = time.monotonic()
        try:
            ch.read_value(timeout=30)
        except ChannelClosed:
            out["death_latency"] = time.monotonic() - t1

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    wch = dial(("127.0.0.1", lst.port), "write", timeout=5)
    wch.write_value("alive")
    time.sleep(0.2)
    wch._sock.close()  # simulate writer process death: RST/EOF, no poison
    t.join(10)
    assert out["first"] == (0, "alive")
    assert out["death_latency"] < 5.0  # detected, not timed out at 30s


def test_socket_rogue_dial_never_pairs(tmp_path):
    """The single-writer contract under the reattach-capable listener:
    a second (unauthenticated) dial during a healthy pairing is never
    paired — it gets no handshake reply, its frames never reach the
    consumer, and its writes fail typed (flow-control timeout) instead
    of corrupting the stream.  The legit edge is unaffected."""
    import threading

    from ray_tpu.experimental.channel import SocketListener, dial

    lst = SocketListener()
    got = {}

    def reader():
        ch = lst.accept("read", timeout=5)
        got["v1"] = ch.read_value(timeout=5)
        got["v2"] = ch.read_value(timeout=10)
        got["chan"] = ch

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    w = dial(("127.0.0.1", lst.port), "write", timeout=5)
    w.write_value(123)
    # A rogue dial connects at the TCP level (backlog) but is never
    # handshaken: its first write times out waiting for a pairing reply
    # that will never come — no rogue frame ever reaches the consumer.
    rogue = dial(("127.0.0.1", lst.port), "write", timeout=5)
    with pytest.raises((ChannelTimeout, ChannelClosed)):
        rogue.write_value("evil", timeout=0.5)
    w.write_value(456)
    t.join(10)
    assert got["v1"] == (0, 123) and got["v2"] == (0, 456)
    rogue.close()
    w.close()
    got["chan"].close()


def test_socket_epoch_reattach_resumes_unacked(tmp_path):
    """Transient TCP drop: the writer re-dials with the pairing token
    at a bumped epoch and replays unacked frames; the reader re-accepts
    via the shared reattach() helper.  Every frame arrives exactly once
    in order — no loss, no duplicates."""
    import threading

    from ray_tpu.experimental.channel import SocketListener, dial, reattach

    lst = SocketListener()
    out = {"vals": []}

    def reader():
        ch = lst.accept("read", timeout=5)
        out["chan"] = ch
        while len(out["vals"]) < 8:
            try:
                out["vals"].append(ch.read_value(timeout=10)[1])
            except ChannelClosed:
                assert reattach(ch, timeout=5)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    w = dial(("127.0.0.1", lst.port), "write", timeout=5)
    for i in range(4):
        w.write_value(i)
    time.sleep(0.3)
    w._sock.close()  # transient connection loss, both peers alive
    for i in range(4, 8):
        w.write_value(i)  # transparent writer-side reattach
    t.join(10)
    assert out["vals"] == list(range(8)), out["vals"]
    assert w.epoch == 2 and out["chan"].epoch == 2
    w.close()
    out["chan"].close()


def test_socket_reattach_rejects_bad_token_and_stale_epoch(tmp_path):
    """Reconnects without the pairing token (or at a non-advancing
    epoch) are rejected at the handshake: the listener closes the
    connection and keeps waiting for the authentic peer."""
    import socket as pysocket
    import threading

    from ray_tpu.experimental.channel import (
        _HELLO,
        _MAGIC,
        _REPLY,
        SocketListener,
        dial,
        reattach,
    )

    lst = SocketListener()
    out = {}

    def reader():
        ch = lst.accept("read", timeout=5)
        out["first"] = ch.read_value(timeout=5)
        try:
            ch.read_value(timeout=10)
        except ChannelClosed:
            out["reattached"] = reattach(ch, timeout=5)
            out["second"] = ch.read_value(timeout=5)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    w = dial(("127.0.0.1", lst.port), "write", timeout=5)
    w.write_value("a")
    time.sleep(0.3)
    w._sock.close()
    time.sleep(0.1)
    # Forged reconnects: wrong token at a bumped epoch, then the right
    # token at a stale epoch.  Neither may pair.
    for hello in (
        _HELLO.pack(_MAGIC, 99, b"\x00" * 16, 0),
        _HELLO.pack(_MAGIC, 1, lst.token, 0),
    ):
        s = pysocket.create_connection(("127.0.0.1", lst.port), timeout=2)
        s.sendall(hello)
        s.settimeout(2)
        assert s.recv(_REPLY.size) == b""  # closed without a reply
        s.close()
    # The authentic writer still reattaches fine afterwards.
    w.write_value("b")
    t.join(10)
    assert out["first"] == (0, "a")
    assert out.get("reattached") is True
    assert out.get("second") == (0, "b")
    w.close()


def test_ring_crc_corruption_is_typed_and_skipped(tmp_path):
    """A bit flip in a published record raises ChannelCorruptionError
    (never a garbage value); the garbage record is consumed so later
    records still flow."""
    from ray_tpu.experimental import channel as cm
    from ray_tpu.experimental.channel import ChannelCorruptionError

    p = str(tmp_path / "crc")
    Channel.create_file(p, 2048)
    w, r = Channel(p), Channel(p)
    w.write(b"good-1")
    w.write_value({"k": "evil"})
    w.write(b"good-3")
    # flip one payload byte of the SECOND record (first record occupies
    # 8 + align8(6 + 4) = 24 bytes)
    w._mm[cm.HEADER + 24 + 8] ^= 0xFF
    assert r.read(timeout=2) == b"good-1"
    with pytest.raises(ChannelCorruptionError) as ei:
        r.read_value(timeout=2)
    assert ei.value.advanced  # garbage consumed: skip-and-continue is safe
    assert r.read(timeout=2) == b"good-3"
    assert r.stats["corruptions"] == 1


def test_ring_torn_record_length_is_typed_not_garbage(tmp_path):
    """A torn/garbage length header (SIGKILLed writer mid-publish, shm
    corruption) raises typed instead of hanging or mis-framing."""
    import struct as pystruct

    from ray_tpu.experimental import channel as cm
    from ray_tpu.experimental.channel import ChannelCorruptionError

    p = str(tmp_path / "torn")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    # Forge a published record whose length field is garbage.
    pystruct.Struct("<Q").pack_into(w._mm, cm.HEADER, 0x7878787878787878)
    pystruct.Struct("<Q").pack_into(w._mm, cm._WOFF, 64)  # "published"
    with pytest.raises(ChannelCorruptionError) as ei:
        r.read(timeout=2)
    # the framing itself is broken: the reader CANNOT advance past it,
    # and consumers must run heavy recovery instead of retrying
    assert ei.value.advanced is False


def test_channel_chaos_actions_inject_and_replay(tmp_path):
    """chan:<glob> chaos rules fire on channel writes: corrupt_frame is
    caught by CRC, torn_write by the trailer, drop_frame vanishes, and
    the seeded schedule replays deterministically."""
    import os

    from ray_tpu._private.chaos import CHAOS, ChaosPlane
    from ray_tpu.experimental.channel import ChannelCorruptionError

    saved = {
        k: os.environ.get(k)
        for k in ("RAY_TPU_testing_chaos_spec", "RAY_TPU_testing_chaos_seed")
    }
    try:
        os.environ["RAY_TPU_testing_chaos_spec"] = (
            "chan:*chaosring*:corrupt_frame:at=2,"
            "chan:*chaosring*:torn_write:at=4,"
            "chan:*chaosring*:drop_frame:at=6"
        )
        os.environ["RAY_TPU_testing_chaos_seed"] = "11"
        CHAOS.reset()
        p = str(tmp_path / "chaosring")
        Channel.create_file(p, 8192)
        w, r = Channel(p), Channel(p)
        for i in range(7):
            w.write_value(i)
        got, corrupt = [], 0
        while len(got) + corrupt < 6:  # frame 6 was dropped entirely
            try:
                got.append(r.read_value(timeout=2)[1])
            except ChannelCorruptionError:
                corrupt += 1
        assert got == [0, 2, 4, 6] and corrupt == 2  # frames 1,3 corrupted/torn
        with pytest.raises(ChannelTimeout):
            r.read_value(timeout=0.3)  # frame 5 (at=6) really dropped
        # seed replay: the same seed + spec produces the same schedule
        def run_schedule(seed):
            plane = ChaosPlane()
            os.environ["RAY_TPU_testing_chaos_seed"] = str(seed)
            os.environ["RAY_TPU_testing_chaos_spec"] = (
                "chan:*x*:corrupt_frame:p=0.5:n=-1"
            )
            plane.reset()
            verdicts = [plane.decide_channel("/x/ring").corrupt for _ in range(40)]
            return verdicts, plane.schedule_digest()

        v1, d1 = run_schedule(123)
        v2, d2 = run_schedule(123)
        v3, d3 = run_schedule(321)
        assert v1 == v2 and d1 == d2
        assert v3 != v1  # a different seed reshuffles the schedule
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        CHAOS.reset()


def test_channel_default_timeout_config_knob(tmp_path):
    """Channel read/write default timeouts route through ONE config
    knob (channel_default_timeout_s) instead of per-call-site 30.0s."""
    import os

    p = str(tmp_path / "deft")
    Channel.create_file(p, 1024)
    r = Channel(p)
    os.environ["RAY_TPU_channel_default_timeout_s"] = "0.3"
    try:
        t0 = time.monotonic()
        with pytest.raises(ChannelTimeout):
            r.read()  # no per-call timeout: the knob governs
        assert time.monotonic() - t0 < 5.0
    finally:
        os.environ.pop("RAY_TPU_channel_default_timeout_s", None)


def test_orphan_shm_sweeper(tmp_path):
    """Directories whose registered owner PIDs are ALL dead are
    reclaimed; live or unregistered dirs are never touched."""
    import os

    from ray_tpu.experimental.channel import sweep_orphan_ring_dirs

    base = str(tmp_path)
    dead = os.path.join(base, "ray_tpu_dag_dead")
    os.makedirs(dead)
    with open(os.path.join(dead, "c1"), "wb") as f:
        f.write(b"\x00" * 256)
    with open(os.path.join(dead, "c2"), "wb") as f:
        f.write(b"\x00" * 256)
    with open(os.path.join(dead, ".pids"), "w") as f:
        f.write("4194300\n4194301\n")  # near pid_max: dead
    live = os.path.join(base, "ray_tpu_serve_live")
    os.makedirs(live)
    with open(os.path.join(live, "req"), "wb") as f:
        f.write(b"\x00" * 256)
    with open(os.path.join(live, ".pids"), "w") as f:
        f.write(f"{os.getpid()}\n")
    unregistered = os.path.join(base, "ray_tpu_pp_new")
    os.makedirs(unregistered)
    assert sweep_orphan_ring_dirs(base=base, grace_s=0.0) == 2
    assert not os.path.exists(dead)
    assert os.path.exists(live) and os.path.exists(unregistered)
    # grace window: a fresh dir with dead pids is left alone
    fresh = os.path.join(base, "ray_tpu_rllib_fresh")
    os.makedirs(fresh)
    with open(os.path.join(fresh, ".pids"), "w") as f:
        f.write("4194300\n")
    assert sweep_orphan_ring_dirs(base=base, grace_s=3600.0) == 0
    assert os.path.exists(fresh)


def test_fanout_dead_reader_evicted_unblocks_writer(tmp_path):
    """A SIGKILLed fan-out reader (dead registered PID, stale cursor)
    no longer wedges the writer: its cursor is evicted (metric-counted)
    and the broadcast proceeds for the survivors.  The evicted slot
    fails typed if it ever reads again."""
    import struct as pystruct

    from ray_tpu.experimental.channel import (
        ChannelClosed as CC,
        FanoutChannel,
        FanoutReader,
    )

    p = str(tmp_path / "fev")
    ch = FanoutChannel(p, 2, max_size=1 << 13, create=True)
    r0, r1 = FanoutReader(p, 0), FanoutReader(p, 1)
    ch.write(b"seed")
    assert r0.read(timeout=5) == b"seed"
    assert r1.read(timeout=5) == b"seed"
    # model r1's death: its registered pid is replaced by a dead one
    pystruct.Struct("<Q").pack_into(ch._mm, ch._pid_off(1), 4194300)
    payload = b"x" * 3000
    for _ in range(10):  # would wedge forever bounded by r1's cursor
        ch.write(payload, timeout=5)
        assert r0.read(timeout=5) == payload
    assert ch.stats["evictions"] == 1
    with pytest.raises(CC, match="evicted"):
        r1.read(timeout=1)
    # all readers dead -> typed close, not a silent write into the void
    pystruct.Struct("<Q").pack_into(ch._mm, ch._pid_off(0), 4194301)
    with pytest.raises(CC):
        for _ in range(20):
            ch.write(payload, timeout=5)


def test_socket_poison_close_vs_flow_control(tmp_path):
    """Orderly close drains buffered frames first (like the ring), and
    the unacked window applies backpressure per CONSUMED message."""
    import threading

    from ray_tpu.experimental.channel import SocketChannel, SocketListener, dial

    lst = SocketListener()
    res = {}

    def reader():
        ch = lst.accept("read", timeout=5)
        time.sleep(0.4)  # let the writer fill its window
        vals = []
        try:
            while True:
                vals.append(ch.read_value(timeout=5)[1])
        except ChannelClosed:
            res["vals"] = vals

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    w = dial(("127.0.0.1", lst.port), "write", timeout=5)
    for i in range(w._window):
        w.write_value(i, timeout=5)
    # window full + reader asleep: the next write must block
    with pytest.raises(ChannelTimeout):
        w.write_value(99, timeout=0.15)
    w.close()  # poison after the buffered frames
    t.join(10)
    assert res["vals"] == list(range(w._window))


def test_wire_roundtrip_property():
    """Property-style round-trip over the full fast-path type lattice +
    pickle fallback: decode(encode(v)) == v with types preserved."""
    import numpy as np

    from ray_tpu._private import wire

    cases = [
        None, True, False, 0, 1, -1, 2**62, -(2**62), 2**100, -(2**100),
        0.0, -1.5, float("inf"), 3.141592653589793,
        b"", b"\x00\xff" * 100, "", "ascii", "unicodé ☃", "x" * 10_000,
        (), (1,), (1, "two", 3.0, None, True), ((1, 2), (3, (4, 5))),
        [], [1, 2, 3], [[1], [2.0], ["3"]],
        {}, {"a": 1}, {"nested": {"k": [1, 2, {"deep": "v"}]}},
        {1: "int-key", "mixed": (1, b"b")},
        # fallback territory
        set([1, 2, 3]), frozenset("ab"), complex(1, 2), range(5),
        {"deep": {"deep": {"deep": {"deep": {"deep": 1}}}}},  # depth > 4
        tuple(range(100)),  # > MAX_ELEMS
        Exception("boom"),
    ]
    for v in cases:
        tag, out = wire.decode(memoryview(wire.encode(v, tag=1)))
        assert tag == 1
        if isinstance(v, Exception):
            assert type(out) is type(v) and out.args == v.args
        elif isinstance(v, float) and v != v:
            assert out != out
        else:
            assert out == v and type(out) is type(v), v
    # numpy arrays: dtype/shape/content exact, zero-dim and F-order too
    arrs = [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array(7, dtype=np.int8),
        np.zeros((0, 3), dtype=np.float64),
        np.asfortranarray(np.arange(6).reshape(2, 3)),
        np.array([True, False]),
        np.arange(4, dtype=np.complex128),
    ]
    for a in arrs:
        tag, out = wire.decode(memoryview(wire.encode(a)))
        assert tag == 0 and out.dtype == a.dtype and out.shape == a.shape
        assert (out == a).all()
    # NaN array content
    tag, out = wire.decode(memoryview(wire.encode(np.array([float("nan")]))))
    assert np.isnan(out).all()


def test_wire_error_tag_roundtrip():
    """TAG_ERROR + RayTaskError (the loop's error envelope) survives the
    wire through the pickle fallback."""
    from ray_tpu import exceptions
    from ray_tpu._private import serialization, wire

    try:
        raise ValueError("original")
    except ValueError as e:
        err = exceptions.RayTaskError.from_exception(e, "compiled_dag.m")
    tag, out = wire.decode(memoryview(wire.encode(err, tag=serialization.TAG_ERROR)))
    assert tag == serialization.TAG_ERROR
    with pytest.raises(ValueError, match="original"):
        raise out.as_instanceof_cause()


# ---------------------------------------------------------------------------
# Shared-memory fan-out (one writer, N same-node readers)


def test_fanout_every_reader_sees_every_message_once(tmp_path):
    from ray_tpu.experimental.channel import FanoutChannel, FanoutReader

    p = str(tmp_path / "f1")
    ch = FanoutChannel(p, 3, max_size=1 << 16, create=True)
    readers = [FanoutReader(p, i) for i in range(3)]
    import numpy as np

    for k in range(5):
        ch.write_value({"k": k, "arr": np.arange(4) + k})
    for r in readers:
        for k in range(5):
            _tag, v = r.read_value(timeout=5)
            assert v["k"] == k
            assert int(v["arr"][0]) == k
        assert not r.pending()
    assert ch.stats["writes"] == 5  # one write serves all three readers
    ch.close()
    for r in readers:
        r.close()


def test_fanout_flow_control_bounded_by_slowest_reader(tmp_path):
    """The writer's free space is min over reader cursors: two fast
    readers can't unblock a ring the slow third still holds."""
    from ray_tpu.experimental.channel import (
        ChannelTimeout as CT,
        FanoutChannel,
        FanoutReader,
    )

    p = str(tmp_path / "f2")
    ch = FanoutChannel(p, 3, max_size=1 << 14, create=True)
    readers = [FanoutReader(p, i) for i in range(3)]
    payload = b"x" * 3000
    wrote = 0
    with pytest.raises(CT):
        for _ in range(50):
            ch.write(payload, timeout=0.2)
            wrote += 1
    assert 0 < wrote < 50
    for r in readers[:2]:
        for _ in range(wrote):
            r.read(timeout=5)
    with pytest.raises(CT):  # slowest reader still pins the ring
        ch.write(payload, timeout=0.2)
    for _ in range(wrote):
        readers[2].read(timeout=5)
    ch.write(payload, timeout=5)  # now it fits
    for r in readers:
        assert r.read(timeout=5) == payload
        r.close()
    ch.close()


def test_fanout_wraps_and_drains_before_close(tmp_path):
    from ray_tpu.experimental.channel import (
        ChannelClosed as CC,
        FanoutChannel,
        FanoutReader,
    )

    p = str(tmp_path / "f3")
    ch = FanoutChannel(p, 2, max_size=1 << 12, create=True)
    readers = [FanoutReader(p, i) for i in range(2)]
    # force several wraps while readers keep pace
    for k in range(40):
        ch.write(bytes([k]) * 900, timeout=5)
        for r in readers:
            assert r.read(timeout=5) == bytes([k]) * 900
    ch.write(b"final")
    ch.close()
    for r in readers:
        assert r.read(timeout=5) == b"final"  # backlog drains first
        with pytest.raises(CC):
            r.read(timeout=1)
        r.close()


def test_fanout_capacity_and_index_validation(tmp_path):
    from ray_tpu.experimental.channel import (
        ChannelCapacityError,
        FanoutChannel,
        FanoutReader,
    )

    p = str(tmp_path / "f4")
    ch = FanoutChannel(p, 2, max_size=1 << 12, create=True)
    with pytest.raises(ChannelCapacityError):
        ch.write(b"x" * (1 << 13))
    with pytest.raises(ValueError, match="out of range"):
        FanoutReader(p, 2)
    with pytest.raises(ValueError, match="created for"):
        FanoutChannel(p, 3)
    ch.close()


def test_wire_fuzz_malformed_input_is_typed_never_garbage():
    """Seeded fuzz over every wire type code: truncated and bit-flipped
    encodings fed to ``wire.decode`` either raise the ONE typed
    ``WireFormatError`` or decode cleanly — never a raw struct/index/
    unicode error, never a hang (every decode loop is bounded by a
    length field that is bounds-checked before use).  Value-level
    integrity of flipped payload bytes is the channel CRC trailer's
    contract, tested above; this pins the decoder itself."""
    import random
    import time as _time

    import numpy as np

    from ray_tpu._private import wire

    exemplars = [  # at least one value per type code, PICKLE included
        None, True, False,                      # NONE / TRUE / FALSE
        5, -7, 2**100, -(2**90),                # I64 / BIGINT
        1.5,                                    # F64
        b"xyz-payload", "héllo wire",      # BYTES / STR
        (1, "a", 2.5, None), [1, b"b", (2, 3)], # TUPLE / LIST
        {"k": 1, 2: "v", "n": {"d": [1.0]}},    # DICT
        np.arange(6, dtype=np.float32).reshape(2, 3),   # NDARRAY
        np.array(7, dtype=np.int8),             # NDARRAY zero-dim
        set([1, 2, 3]),                         # PICKLE fallback
    ]
    rng = random.Random(0xC0FFEE)
    t0 = _time.monotonic()

    def check(buf):
        b = bytes(buf)
        try:
            _, out = wire.decode(memoryview(b))
            return "ok", out
        except wire.WireFormatError:
            return "typed", None
        except (ImportError, AttributeError, NameError):
            # PICKLE-path class resolution is app-level BY CONTRACT
            # (wire.decode lets it propagate so an unimportable class
            # can't masquerade as frame corruption) — permitted only
            # for pickle-framed buffers
            assert len(b) > 1 and b[1] == wire.PICKLE, b[:4]
            return "app", None
        # anything else propagates and fails the test

    for v in exemplars:
        enc = wire.encode(v, tag=1)
        # Every strict truncation of a fast-path encoding starves a
        # bounds-checked length field -> typed error.  The PICKLE
        # fallback may tolerate losing its unused trailing footer (past
        # the STOP opcode) — but then the value must be EXACTLY right.
        lengths = range(len(enc)) if len(enc) <= 64 else sorted(
            rng.sample(range(len(enc)), 64)
        )
        for n in lengths:
            verdict, out = check(enc[:n])
            if enc[1] == wire.PICKLE:
                if verdict == "ok":
                    assert out == v, (v, n, out)  # only the footer was cut
            else:
                assert verdict == "typed", (v, n, out)
        # seeded single-bit flips anywhere in the buffer
        for _ in range(150):
            b = bytearray(enc)
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            check(b)
        # multi-bit shotgun: up to 8 flips per trial
        for _ in range(50):
            b = bytearray(enc)
            for _ in range(rng.randint(2, 8)):
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            check(b)
    # pure-noise buffers (random type codes, random lengths)
    for _ in range(300):
        check(bytes(rng.randrange(256) for _ in range(rng.randint(0, 80))))
    assert _time.monotonic() - t0 < 60.0  # bounded: no decode may hang
