"""Compiled-DAG zero-copy channels (reference:
experimental_mutable_object_manager.h:48, shared_memory_channel.py,
per-actor schedules compiled_dag_node.py:1639)."""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import Channel, ChannelClosed, ChannelTimeout


@pytest.fixture(scope="module", autouse=True)
def ray():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Channel primitive


def test_channel_roundtrip(tmp_path):
    p = str(tmp_path / "c1")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"hello")
    assert r.read() == b"hello"
    w.write(b"world")
    assert r.read() == b"world"


def test_channel_multiple_inflight(tmp_path):
    """The ring holds many messages at once (pipelined executions)."""
    p = str(tmp_path / "c1b")
    Channel.create_file(p, 4096)
    w, r = Channel(p), Channel(p)
    for i in range(10):
        w.write(f"msg{i}".encode(), timeout=1)
    assert [r.read() for _ in range(10)] == [f"msg{i}".encode() for i in range(10)]


def test_channel_flow_control(tmp_path):
    p = str(tmp_path / "c2")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"x" * 700)
    with pytest.raises(ChannelTimeout):
        w.write(b"y" * 700, timeout=0.3)  # ring full, reader hasn't consumed
    assert r.read() == b"x" * 700
    w.write(b"y" * 700, timeout=5)
    assert r.read() == b"y" * 700


def test_channel_poison(tmp_path):
    p = str(tmp_path / "c3")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.close()
    with pytest.raises(ChannelClosed):
        r.read(timeout=5)


def test_channel_drains_before_close(tmp_path):
    """close() is drain-then-close: buffered messages stay readable,
    the reader sees ChannelClosed only after consuming the backlog."""
    p = str(tmp_path / "c4")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"last words")
    w.close()
    assert r.read(timeout=5) == b"last words"
    with pytest.raises(ChannelClosed):
        r.read(timeout=5)


# ---------------------------------------------------------------------------
# Compiled DAG over channels


def test_compiled_pipeline_two_actors():
    """A 2-actor pipeline: data flows A -> B entirely over channels,
    state persists, and results come back in submission order."""

    @ray_tpu.remote
    class Stage:
        def __init__(self, inc):
            self.inc = inc
            self.count = 0

        def step(self, x):
            self.count += 1
            return x + self.inc

        def calls(self):
            return self.count

    a, b = Stage.bind(1), Stage.bind(10)
    with InputNode() as inp:
        dag = b.step.bind(a.step.bind(inp))
    compiled = dag.experimental_compile(max_inflight=8)
    assert compiled._channels_on  # really on the channel plane
    refs = [compiled.execute(i) for i in range(5)]
    assert [ray_tpu.get(r) for r in refs] == [i + 11 for i in range(5)]
    compiled.teardown()


def test_compiled_multi_output_fan():
    @ray_tpu.remote
    class Math:
        def double(self, x):
            return x * 2

        def square(self, x):
            return x * x

    m1, m2 = Math.bind(), Math.bind()
    with InputNode() as inp:
        dag = MultiOutputNode([m1.double.bind(inp), m2.square.bind(inp)])
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    assert ray_tpu.get(compiled.execute(6)) == [12, 36]
    assert ray_tpu.get(compiled.execute(3)) == [6, 9]
    compiled.teardown()


def test_compiled_channel_throughput_beats_task_path():
    """The channel plane must clearly beat per-call task submission on a
    tiny-payload pipeline (that's its reason to exist)."""

    @ray_tpu.remote
    class Echo:
        def echo(self, x):
            return x

    with InputNode() as inp:
        dag = Echo.bind().echo.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    ray_tpu.get(compiled.execute(0))  # warm
    n = 200
    t0 = time.monotonic()
    for i in range(n):
        ray_tpu.get(compiled.execute(i))
    chan_rate = n / (time.monotonic() - t0)
    compiled.teardown()

    actor = Echo.remote()
    ray_tpu.get(actor.echo.remote(0))
    t0 = time.monotonic()
    for i in range(n):
        ray_tpu.get(actor.echo.remote(i))
    task_rate = n / (time.monotonic() - t0)
    ray_tpu.kill(actor)
    assert chan_rate > task_rate * 1.5, (chan_rate, task_rate)


def test_compiled_teardown_unblocks_actors():
    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(1)) == 1
    compiled.teardown()  # must not hang


def test_compiled_error_propagates_and_dag_survives():
    """An actor-method exception flows to the driver's get as the
    original error, and the DAG keeps working afterwards."""

    @ray_tpu.remote
    class Fragile:
        def f(self, x):
            if x < 0:
                raise ValueError("negative!")
            return x * 2

    with InputNode() as inp:
        dag = Fragile.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(4)) == 8
    with pytest.raises(ValueError):
        ray_tpu.get(compiled.execute(-1))
    assert ray_tpu.get(compiled.execute(5)) == 10  # still alive
    compiled.teardown()


def test_compiled_inflight_cap():
    @ray_tpu.remote
    class Slow:
        def f(self, x):
            time.sleep(0.3)
            return x

    with InputNode() as inp:
        dag = Slow.bind().f.bind(inp)
    compiled = dag.experimental_compile(max_inflight=2)
    r1 = compiled.execute(1)
    compiled.execute(2)
    with pytest.raises(RuntimeError, match="in flight"):
        compiled.execute(3)
    assert ray_tpu.get(r1) == 1
    compiled.teardown()


def test_compiled_teardown_cleans_tmpfs():
    import os

    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    chan_dir = compiled._chan_dir
    assert os.path.isdir(chan_dir)
    ray_tpu.get(compiled.execute(1))
    compiled.teardown()
    assert not os.path.exists(chan_dir)  # tmpfs reclaimed


def test_function_node_compiles_to_executor_loop():
    """Driver-side FunctionNodes ride the channel plane too: each one is
    hosted by a resident _FnExecutor actor instead of taking the
    per-call task path."""

    @ray_tpu.remote
    def plain(x):
        return x + 1

    @ray_tpu.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        dag = double.bind(plain.bind(inp))
    compiled = dag.experimental_compile()
    assert compiled._channels_on  # no task-path fallback anymore
    assert [ray_tpu.get(compiled.execute(i)) for i in range(4)] == [2, 4, 6, 8]
    compiled.teardown()


def test_mixed_function_and_actor_graph_compiles():
    """A FunctionNode feeding an actor method (and vice versa) is one
    compiled graph spanning executor + user actors."""

    @ray_tpu.remote
    def pre(x):
        return x + 1

    @ray_tpu.remote
    class Scale:
        def __init__(self, k):
            self.k = k

        def mul(self, x):
            return x * self.k

    @ray_tpu.remote
    def post(x):
        return x - 3

    with InputNode() as inp:
        dag = post.bind(Scale.bind(10).mul.bind(pre.bind(inp)))
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    assert ray_tpu.get(compiled.execute(4)) == 47  # (4+1)*10-3
    assert ray_tpu.get(compiled.execute(0)) == 7
    compiled.teardown()


def test_kwargs_fall_back_to_task_path():
    """Graphs outside the op schedule's vocabulary still execute via the
    per-node task path."""

    @ray_tpu.remote
    def f(x, k=1):
        return x + k

    with InputNode() as inp:
        dag = f.bind(inp, k=5)
    compiled = dag.experimental_compile()
    assert not compiled._channels_on
    assert ray_tpu.get(compiled.execute(10)) == 15
    compiled.teardown()


# ---------------------------------------------------------------------------
# Channel edge cases (ring + socket + wire format)


def test_ring_wraparound_under_sustained_load(tmp_path):
    """Thousands of variable-size messages through a small ring: the
    write position wraps the region many times and every payload
    survives byte-exact (wrap markers + implicit tail skips)."""
    import threading

    p = str(tmp_path / "wrap")
    Channel.create_file(p, 4096)
    w, r = Channel(p), Channel(p)
    n = 1500
    payloads = [bytes([i % 251]) * (1 + (i * 37) % 900) for i in range(n)]
    errs = []

    def writer():
        try:
            for pl in payloads:
                w.write(pl, timeout=30)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    for i in range(n):
        assert r.read(timeout=30) == payloads[i], f"payload {i} corrupted"
    t.join(10)
    assert not errs
    assert w.stats["writes"] == n and r.stats["reads"] == n
    assert w._get(0) > 4096  # really wrapped (wbytes past capacity)


def test_ring_value_wraparound_mixed_types(tmp_path):
    """write_value/read_value across wrap boundaries with every
    fast-path type mixed (encode-in-place must handle tail-bounded
    windows by wrapping, reader must skip markers)."""
    import numpy as np

    p = str(tmp_path / "wrapv")
    Channel.create_file(p, 2048)
    w, r = Channel(p), Channel(p)
    vals = []
    for i in range(300):
        vals.append(
            [i, float(i), f"s{i}" * (i % 20), {"k": i}, np.arange(i % 40)][i % 5]
        )
    import threading

    t = threading.Thread(
        target=lambda: [w.write_value(v, timeout=30) for v in vals], daemon=True
    )
    t.start()
    for i, expect in enumerate(vals):
        tag, got = r.read_value(timeout=30)
        assert tag == 0
        if isinstance(expect, np.ndarray):
            assert (got == expect).all()
        else:
            assert got == expect, i
    t.join(10)


def test_payload_larger_than_ring_is_typed_error_not_hang(tmp_path):
    from ray_tpu.experimental.channel import ChannelCapacityError

    p = str(tmp_path / "cap")
    Channel.create_file(p, 1024)
    w, r = Channel(p), Channel(p)
    with pytest.raises(ChannelCapacityError):
        w.write(b"x" * 5000, timeout=5)
    with pytest.raises(ChannelCapacityError):
        w.write_value(b"x" * 5000, timeout=5)
    # the ring stays coherent after the refused writes
    w.write_value({"ok": 1})
    assert r.read_value() == (0, {"ok": 1})


def test_reader_timeout_vs_writer_death_detection(tmp_path):
    """Ring: a silent writer is indistinguishable from a dead one —
    reads raise ChannelTimeout.  Socket: writer death is detected
    immediately as ChannelClosed (EOF), no timeout burned."""
    import threading

    from ray_tpu.experimental.channel import SocketListener, dial

    # ring: timeout (peer alive but silent)
    p = str(tmp_path / "silent")
    Channel.create_file(p, 1024)
    r = Channel(p)
    t0 = time.monotonic()
    with pytest.raises(ChannelTimeout):
        r.read(timeout=0.3)
    assert time.monotonic() - t0 >= 0.25

    # socket: death -> ChannelClosed well before any read timeout
    lst = SocketListener()
    out = {}

    def reader():
        ch = lst.accept("read", timeout=5)
        out["first"] = ch.read_value(timeout=5)
        t1 = time.monotonic()
        try:
            ch.read_value(timeout=30)
        except ChannelClosed:
            out["death_latency"] = time.monotonic() - t1

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    wch = dial(("127.0.0.1", lst.port), "write", timeout=5)
    wch.write_value("alive")
    time.sleep(0.2)
    wch._sock.close()  # simulate writer process death: RST/EOF, no poison
    t.join(10)
    assert out["first"] == (0, "alive")
    assert out["death_latency"] < 5.0  # detected, not timed out at 30s


def test_socket_reconnect_refused_semantics(tmp_path):
    """A compiled edge's listener accepts exactly one connection; once
    consumed (or dead), a new dial is refused with the typed error —
    silent reconnects could drop in-flight messages."""
    import threading

    from ray_tpu.experimental.channel import (
        ChannelConnectionError,
        SocketListener,
        dial,
    )

    lst = SocketListener()
    got = {}

    def reader():
        ch = lst.accept("read", timeout=5)
        got["v"] = ch.read_value(timeout=5)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    w = dial(("127.0.0.1", lst.port), "write", timeout=5)
    w.write_value(123)
    t.join(10)
    assert got["v"] == (0, 123)
    with pytest.raises(ChannelConnectionError):
        dial(("127.0.0.1", lst.port), "write", timeout=0.8)
    w.close()


def test_socket_poison_close_vs_flow_control(tmp_path):
    """Orderly close drains buffered frames first (like the ring), and
    the unacked window applies backpressure per CONSUMED message."""
    import threading

    from ray_tpu.experimental.channel import SocketChannel, SocketListener, dial

    lst = SocketListener()
    res = {}

    def reader():
        ch = lst.accept("read", timeout=5)
        time.sleep(0.4)  # let the writer fill its window
        vals = []
        try:
            while True:
                vals.append(ch.read_value(timeout=5)[1])
        except ChannelClosed:
            res["vals"] = vals

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    w = dial(("127.0.0.1", lst.port), "write", timeout=5)
    for i in range(w._window):
        w.write_value(i, timeout=5)
    # window full + reader asleep: the next write must block
    with pytest.raises(ChannelTimeout):
        w.write_value(99, timeout=0.15)
    w.close()  # poison after the buffered frames
    t.join(10)
    assert res["vals"] == list(range(w._window))


def test_wire_roundtrip_property():
    """Property-style round-trip over the full fast-path type lattice +
    pickle fallback: decode(encode(v)) == v with types preserved."""
    import numpy as np

    from ray_tpu._private import wire

    cases = [
        None, True, False, 0, 1, -1, 2**62, -(2**62), 2**100, -(2**100),
        0.0, -1.5, float("inf"), 3.141592653589793,
        b"", b"\x00\xff" * 100, "", "ascii", "unicodé ☃", "x" * 10_000,
        (), (1,), (1, "two", 3.0, None, True), ((1, 2), (3, (4, 5))),
        [], [1, 2, 3], [[1], [2.0], ["3"]],
        {}, {"a": 1}, {"nested": {"k": [1, 2, {"deep": "v"}]}},
        {1: "int-key", "mixed": (1, b"b")},
        # fallback territory
        set([1, 2, 3]), frozenset("ab"), complex(1, 2), range(5),
        {"deep": {"deep": {"deep": {"deep": {"deep": 1}}}}},  # depth > 4
        tuple(range(100)),  # > MAX_ELEMS
        Exception("boom"),
    ]
    for v in cases:
        tag, out = wire.decode(memoryview(wire.encode(v, tag=1)))
        assert tag == 1
        if isinstance(v, Exception):
            assert type(out) is type(v) and out.args == v.args
        elif isinstance(v, float) and v != v:
            assert out != out
        else:
            assert out == v and type(out) is type(v), v
    # numpy arrays: dtype/shape/content exact, zero-dim and F-order too
    arrs = [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.array(7, dtype=np.int8),
        np.zeros((0, 3), dtype=np.float64),
        np.asfortranarray(np.arange(6).reshape(2, 3)),
        np.array([True, False]),
        np.arange(4, dtype=np.complex128),
    ]
    for a in arrs:
        tag, out = wire.decode(memoryview(wire.encode(a)))
        assert tag == 0 and out.dtype == a.dtype and out.shape == a.shape
        assert (out == a).all()
    # NaN array content
    tag, out = wire.decode(memoryview(wire.encode(np.array([float("nan")]))))
    assert np.isnan(out).all()


def test_wire_error_tag_roundtrip():
    """TAG_ERROR + RayTaskError (the loop's error envelope) survives the
    wire through the pickle fallback."""
    from ray_tpu import exceptions
    from ray_tpu._private import serialization, wire

    try:
        raise ValueError("original")
    except ValueError as e:
        err = exceptions.RayTaskError.from_exception(e, "compiled_dag.m")
    tag, out = wire.decode(memoryview(wire.encode(err, tag=serialization.TAG_ERROR)))
    assert tag == serialization.TAG_ERROR
    with pytest.raises(ValueError, match="original"):
        raise out.as_instanceof_cause()


# ---------------------------------------------------------------------------
# Shared-memory fan-out (one writer, N same-node readers)


def test_fanout_every_reader_sees_every_message_once(tmp_path):
    from ray_tpu.experimental.channel import FanoutChannel, FanoutReader

    p = str(tmp_path / "f1")
    ch = FanoutChannel(p, 3, max_size=1 << 16, create=True)
    readers = [FanoutReader(p, i) for i in range(3)]
    import numpy as np

    for k in range(5):
        ch.write_value({"k": k, "arr": np.arange(4) + k})
    for r in readers:
        for k in range(5):
            _tag, v = r.read_value(timeout=5)
            assert v["k"] == k
            assert int(v["arr"][0]) == k
        assert not r.pending()
    assert ch.stats["writes"] == 5  # one write serves all three readers
    ch.close()
    for r in readers:
        r.close()


def test_fanout_flow_control_bounded_by_slowest_reader(tmp_path):
    """The writer's free space is min over reader cursors: two fast
    readers can't unblock a ring the slow third still holds."""
    from ray_tpu.experimental.channel import (
        ChannelTimeout as CT,
        FanoutChannel,
        FanoutReader,
    )

    p = str(tmp_path / "f2")
    ch = FanoutChannel(p, 3, max_size=1 << 14, create=True)
    readers = [FanoutReader(p, i) for i in range(3)]
    payload = b"x" * 3000
    wrote = 0
    with pytest.raises(CT):
        for _ in range(50):
            ch.write(payload, timeout=0.2)
            wrote += 1
    assert 0 < wrote < 50
    for r in readers[:2]:
        for _ in range(wrote):
            r.read(timeout=5)
    with pytest.raises(CT):  # slowest reader still pins the ring
        ch.write(payload, timeout=0.2)
    for _ in range(wrote):
        readers[2].read(timeout=5)
    ch.write(payload, timeout=5)  # now it fits
    for r in readers:
        assert r.read(timeout=5) == payload
        r.close()
    ch.close()


def test_fanout_wraps_and_drains_before_close(tmp_path):
    from ray_tpu.experimental.channel import (
        ChannelClosed as CC,
        FanoutChannel,
        FanoutReader,
    )

    p = str(tmp_path / "f3")
    ch = FanoutChannel(p, 2, max_size=1 << 12, create=True)
    readers = [FanoutReader(p, i) for i in range(2)]
    # force several wraps while readers keep pace
    for k in range(40):
        ch.write(bytes([k]) * 900, timeout=5)
        for r in readers:
            assert r.read(timeout=5) == bytes([k]) * 900
    ch.write(b"final")
    ch.close()
    for r in readers:
        assert r.read(timeout=5) == b"final"  # backlog drains first
        with pytest.raises(CC):
            r.read(timeout=1)
        r.close()


def test_fanout_capacity_and_index_validation(tmp_path):
    from ray_tpu.experimental.channel import (
        ChannelCapacityError,
        FanoutChannel,
        FanoutReader,
    )

    p = str(tmp_path / "f4")
    ch = FanoutChannel(p, 2, max_size=1 << 12, create=True)
    with pytest.raises(ChannelCapacityError):
        ch.write(b"x" * (1 << 13))
    with pytest.raises(ValueError, match="out of range"):
        FanoutReader(p, 2)
    with pytest.raises(ValueError, match="created for"):
        FanoutChannel(p, 3)
    ch.close()
