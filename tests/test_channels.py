"""Compiled-DAG zero-copy channels (reference:
experimental_mutable_object_manager.h:48, shared_memory_channel.py,
per-actor schedules compiled_dag_node.py:1639)."""

import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import Channel, ChannelClosed, ChannelTimeout


@pytest.fixture(scope="module", autouse=True)
def ray():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# Channel primitive


def test_channel_roundtrip(tmp_path):
    p = str(tmp_path / "c1")
    with open(p, "wb") as f:
        f.truncate(32 + 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"hello")
    assert r.read() == b"hello"
    w.write(b"world")  # ack allowed the second write
    assert r.read() == b"world"


def test_channel_flow_control(tmp_path):
    p = str(tmp_path / "c2")
    with open(p, "wb") as f:
        f.truncate(32 + 1024)
    w, r = Channel(p), Channel(p)
    w.write(b"a")
    with pytest.raises(ChannelTimeout):
        w.write(b"b", timeout=0.3)  # reader hasn't consumed
    assert r.read() == b"a"
    w.write(b"b", timeout=5)
    assert r.read() == b"b"


def test_channel_poison(tmp_path):
    p = str(tmp_path / "c3")
    with open(p, "wb") as f:
        f.truncate(32 + 1024)
    w, r = Channel(p), Channel(p)
    w.close()
    with pytest.raises(ChannelClosed):
        r.read(timeout=5)


# ---------------------------------------------------------------------------
# Compiled DAG over channels


def test_compiled_pipeline_two_actors():
    """A 2-actor pipeline: data flows A -> B entirely over channels,
    state persists, and results come back in submission order."""

    @ray_tpu.remote
    class Stage:
        def __init__(self, inc):
            self.inc = inc
            self.count = 0

        def step(self, x):
            self.count += 1
            return x + self.inc

        def calls(self):
            return self.count

    a, b = Stage.bind(1), Stage.bind(10)
    with InputNode() as inp:
        dag = b.step.bind(a.step.bind(inp))
    compiled = dag.experimental_compile(max_inflight=8)
    assert compiled._channels_on  # really on the channel plane
    refs = [compiled.execute(i) for i in range(5)]
    assert [ray_tpu.get(r) for r in refs] == [i + 11 for i in range(5)]
    compiled.teardown()


def test_compiled_multi_output_fan():
    @ray_tpu.remote
    class Math:
        def double(self, x):
            return x * 2

        def square(self, x):
            return x * x

    m1, m2 = Math.bind(), Math.bind()
    with InputNode() as inp:
        dag = MultiOutputNode([m1.double.bind(inp), m2.square.bind(inp)])
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    assert ray_tpu.get(compiled.execute(6)) == [12, 36]
    assert ray_tpu.get(compiled.execute(3)) == [6, 9]
    compiled.teardown()


def test_compiled_channel_throughput_beats_task_path():
    """The channel plane must clearly beat per-call task submission on a
    tiny-payload pipeline (that's its reason to exist)."""

    @ray_tpu.remote
    class Echo:
        def echo(self, x):
            return x

    with InputNode() as inp:
        dag = Echo.bind().echo.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled._channels_on
    ray_tpu.get(compiled.execute(0))  # warm
    n = 200
    t0 = time.monotonic()
    for i in range(n):
        ray_tpu.get(compiled.execute(i))
    chan_rate = n / (time.monotonic() - t0)
    compiled.teardown()

    actor = Echo.remote()
    ray_tpu.get(actor.echo.remote(0))
    t0 = time.monotonic()
    for i in range(n):
        ray_tpu.get(actor.echo.remote(i))
    task_rate = n / (time.monotonic() - t0)
    ray_tpu.kill(actor)
    assert chan_rate > task_rate * 1.5, (chan_rate, task_rate)


def test_compiled_teardown_unblocks_actors():
    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(1)) == 1
    compiled.teardown()  # must not hang


def test_compiled_error_propagates_and_dag_survives():
    """An actor-method exception flows to the driver's get as the
    original error, and the DAG keeps working afterwards."""

    @ray_tpu.remote
    class Fragile:
        def f(self, x):
            if x < 0:
                raise ValueError("negative!")
            return x * 2

    with InputNode() as inp:
        dag = Fragile.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    assert ray_tpu.get(compiled.execute(4)) == 8
    with pytest.raises(ValueError):
        ray_tpu.get(compiled.execute(-1))
    assert ray_tpu.get(compiled.execute(5)) == 10  # still alive
    compiled.teardown()


def test_compiled_inflight_cap():
    @ray_tpu.remote
    class Slow:
        def f(self, x):
            time.sleep(0.3)
            return x

    with InputNode() as inp:
        dag = Slow.bind().f.bind(inp)
    compiled = dag.experimental_compile(max_inflight=2)
    r1 = compiled.execute(1)
    compiled.execute(2)
    with pytest.raises(RuntimeError, match="in flight"):
        compiled.execute(3)
    assert ray_tpu.get(r1) == 1
    compiled.teardown()


def test_compiled_teardown_cleans_tmpfs():
    import os

    @ray_tpu.remote
    class S:
        def f(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    chan_dir = compiled._chan_dir
    assert os.path.isdir(chan_dir)
    ray_tpu.get(compiled.execute(1))
    compiled.teardown()
    assert not os.path.exists(chan_dir)  # tmpfs reclaimed


def test_function_node_falls_back_to_task_path():
    @ray_tpu.remote
    def plain(x):
        return x + 1

    with InputNode() as inp:
        dag = plain.bind(inp)
    compiled = dag.experimental_compile()
    assert not compiled._channels_on
    assert ray_tpu.get(compiled.execute(41)) == 42
    compiled.teardown()
