"""Partition-rule matching (train/sharding/rules.py): regex precedence,
unmatched-leaf typed error, scalar replication, mesh-divisibility
clipping, and the tested GPT-2 rule set."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from ray_tpu.train.sharding import (  # noqa: E402
    ShardingConfig,
    UnmatchedParamError,
    gpt2_partition_rules,
    match_partition_rules,
)


def _leaf(*shape):
    return jnp.zeros(shape, dtype=jnp.float32)


def test_first_match_wins_precedence():
    """Rules are ORDERED: an earlier, broader rule shadows a later,
    more specific one — precedence is the list order, not specificity."""
    params = {"attn": {"qkv": {"kernel": _leaf(8, 24)}}}
    spec = match_partition_rules(
        [(r"kernel", ("model", None)), (r"qkv/kernel", (None, "model"))], params
    )
    assert spec["attn"]["qkv"]["kernel"] == P("model", None)
    # Reversed order: the specific rule now wins.
    spec = match_partition_rules(
        [(r"qkv/kernel", (None, "model")), (r"kernel", ("model", None))], params
    )
    assert spec["attn"]["qkv"]["kernel"] == P(None, "model")


def test_unmatched_leaf_raises_typed_error_naming_all_gaps():
    params = {
        "a": {"kernel": _leaf(4, 4)},
        "b": {"mystery": _leaf(4, 4)},
        "c": {"enigma": _leaf(4,)},
    }
    with pytest.raises(UnmatchedParamError) as ei:
        match_partition_rules([(r"kernel", (None, "model"))], params)
    # One failure names EVERY gap, with paths.
    assert sorted(ei.value.paths) == ["b/mystery", "c/enigma"]
    assert "b/mystery" in str(ei.value)


def test_scalars_and_size_one_replicate_without_rules():
    params = {"count": _leaf(), "one": _leaf(1)}
    spec = match_partition_rules([], params)
    assert spec["count"] == P()
    assert spec["one"] == P()


def test_non_strict_replicates_unmatched():
    params = {"mystery": _leaf(4, 4)}
    spec = match_partition_rules([], params, strict=False)
    assert spec["mystery"] == P()


def test_spec_clipped_to_rank_and_mesh_divisibility():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("batch", "model"))
    params = {
        "v": {"kernel": _leaf(6)},          # rank 1 < spec rank 2
        "odd": {"kernel": _leaf(7, 8)},     # 7 % 2 != 0 -> dim replicates
        "ghost": {"kernel": _leaf(8, 8)},   # axis not in mesh -> dropped
    }
    spec = match_partition_rules(
        [
            (r"v/kernel", (None, "model")),
            (r"odd/kernel", ("model", "model")),
            (r"ghost/kernel", ("expert", "model")),
        ],
        params,
        mesh,
    )
    assert spec["v"]["kernel"] == P(None)
    assert spec["odd"]["kernel"] == P(None, "model")
    assert spec["ghost"]["kernel"] == P(None, "model")


def test_gpt2_rule_set_covers_and_shards_gpt2_tiny():
    """The shipped rule set must cover EVERY gpt2 leaf (no
    UnmatchedParamError) and produce the Megatron pairing."""
    from ray_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny(remat=False)
    params = jax.eval_shape(lambda: gpt2.init_params(cfg))
    spec = match_partition_rules(gpt2_partition_rules(), params)
    assert spec["wte"]["embedding"] == P("model", None)
    assert spec["wpe"]["embedding"] == P(None, None)
    blk = spec["h_0"]
    assert blk["attn"]["qkv"]["kernel"] == P(None, "model")
    assert blk["attn"]["attn_out"]["kernel"] == P("model", None)
    assert blk["mlp"]["mlp_up"]["kernel"] == P(None, "model")
    assert blk["mlp"]["mlp_down"]["kernel"] == P("model", None)
    assert spec["lm_head"]["kernel"] == P(None, "model")
    # norms/biases replicate (specs pad to rank: P(None) == replicated)
    assert all(a is None for a in blk["ln_1"]["scale"])
    assert all(a is None for a in blk["attn"]["qkv"]["bias"])
    assert all(a is None for a in spec["ln_f"]["bias"])


def test_sharding_config_validation_and_defaults():
    with pytest.raises(ValueError, match="batch_axis"):
        ShardingConfig(mesh=("data", "model"), batch_axis="batch")
    with pytest.raises(ValueError, match="mesh_shape"):
        ShardingConfig(mesh_shape={"expert": 2})
    cfg = ShardingConfig()
    shape = cfg.resolve_shape(8)
    assert shape == {"batch": -1, "model": 8} or shape["model"] in (2, 4, 8)
    # A partial shape must not silently idle devices: the unpinned
    # batch axis absorbs the remainder ({"model": 2} on 8 devices is a
    # 4x2 mesh, not 1x2 with 6 chips dark).
    cfg = ShardingConfig(mesh_shape={"model": 2})
    assert cfg.resolve_shape(8) == {"model": 2, "batch": -1}
    # ... unless the batch axis is pinned, or another axis already
    # carries the -1 (at most one absorber).
    cfg = ShardingConfig(mesh_shape={"batch": 4})
    assert cfg.resolve_shape(8) == {"batch": 4, "model": 1}
    cfg = ShardingConfig(mesh_shape={"model": -1})
    assert cfg.resolve_shape(8) == {"model": -1, "batch": 1}
