"""Autoscaler: demand scheduler unit tests + fake-multinode integration
(reference test model: python/ray/tests/test_resource_demand_scheduler.py,
test_autoscaler_fake_multinode.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    FakeMultiNodeProvider,
    Monitor,
    StandardAutoscaler,
    get_nodes_to_launch,
)


def test_demand_scheduler_bin_packing():
    node_types = {
        "small": {"resources": {"CPU": 2}},
        "big": {"resources": {"CPU": 8}},
    }
    # 5 x 1-CPU demands, 1 free CPU in cluster -> 4 CPUs needed -> 2 small
    to_launch = get_nodes_to_launch(
        [{"CPU": 1}] * 5,
        [{"CPU": 1}],
        node_types,
        pending_launches={},
        max_workers=10,
        current_workers=0,
    )
    assert to_launch == {"small": 2}


def test_demand_scheduler_prefers_smallest_fit():
    node_types = {
        "cpu": {"resources": {"CPU": 4}},
        "tpu_host": {"resources": {"CPU": 4, "TPU": 4}},
    }
    to_launch = get_nodes_to_launch(
        [{"TPU": 4}],
        [],
        node_types,
        pending_launches={},
        max_workers=10,
        current_workers=0,
    )
    assert to_launch == {"tpu_host": 1}


def test_demand_scheduler_respects_max_workers():
    node_types = {"small": {"resources": {"CPU": 1}}}
    to_launch = get_nodes_to_launch(
        [{"CPU": 1}] * 10,
        [],
        node_types,
        pending_launches={},
        max_workers=3,
        current_workers=1,
    )
    assert sum(to_launch.values()) == 2


def test_demand_scheduler_counts_pending_launches():
    node_types = {"small": {"resources": {"CPU": 4}}}
    to_launch = get_nodes_to_launch(
        [{"CPU": 1}] * 3,
        [],
        node_types,
        pending_launches={"small": 1},
        max_workers=10,
        current_workers=0,
    )
    assert to_launch == {}  # the in-flight node covers the demand


def test_autoscaler_scales_up_for_pending_actors(ray_cluster):
    """Pending actors that do not fit the head node must pull up a fake
    worker node, after which they get scheduled."""
    worker = ray_tpu._private.worker.get_global_worker()
    session_dir = worker.session_info.get("session_dir")
    gcs_address = worker.gcs_client.address

    provider = FakeMultiNodeProvider(
        {"gcs_address": gcs_address, "session_dir": session_dir}
    )
    autoscaler = StandardAutoscaler(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=2,
        idle_timeout_s=9999,
        gcs_client=worker.gcs_client,
    )
    monitor = Monitor(autoscaler, interval_s=1.0)
    monitor.start()
    try:
        # the module cluster has 4 CPUs; demand 6 CPUs of actors
        @ray_tpu.remote(num_cpus=2)
        class Chunk:
            def ping(self):
                return "ok"

        actors = [Chunk.remote() for _ in range(3)]
        refs = [a.ping.remote() for a in actors]
        out = ray_tpu.get(refs, timeout=120)
        assert out == ["ok"] * 3
        assert autoscaler.num_launches >= 1
        assert len(ray_tpu.nodes()) >= 2
        for a in actors:
            ray_tpu.kill(a)
    finally:
        monitor.stop()
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)


def test_tpu_provider_slice_lifecycle_mock():
    """Unit: slices are atomic — create/ready/terminate via the mocked
    TPU API, with slice-topology resources advertised."""
    from ray_tpu.autoscaler import MockTpuClient, TPUNodeProvider, slice_resources
    from ray_tpu.autoscaler.node_provider import TAG_NODE_KIND, TAG_NODE_STATUS

    client = MockTpuClient()
    provider = TPUNodeProvider({"tpu_client": client}, cluster_name="t")
    ids = provider.create_node(
        {"accelerator_type": "v5litepod-16"}, {TAG_NODE_KIND: "worker"}, 2
    )
    assert len(ids) == 2
    assert all(provider.is_running(i) for i in ids)  # mock: READY instantly
    # pending → up-to-date promotion happens on the reconcile read
    provider.non_terminated_nodes({})
    assert provider.node_tags(ids[0])[TAG_NODE_STATUS] == "up-to-date"
    res = slice_resources("v5litepod-16", ids[0])
    assert res["TPU"] == 16.0
    assert res["TPU-v5litepod-16-head"] == 1.0
    assert provider.internal_ip(ids[0]) is not None
    provider.terminate_node(ids[0])
    assert provider.non_terminated_nodes({TAG_NODE_KIND: "worker"}) == [ids[1]]
    assert client.get(ids[0]) is None  # API-side delete happened


@pytest.mark.slow
def test_autoscaler_scales_tpu_slice_up_and_down(ray_cluster):
    """VERDICT r4 #10 e2e: demand for a v5e-16 slice head pulls a whole
    slice up (API-mocked, backed by a local raylet advertising the
    slice's resources); idle timeout scales it back down."""
    from ray_tpu.autoscaler import (
        Monitor,
        MockTpuClient,
        StandardAutoscaler,
        TPUNodeProvider,
    )

    worker = ray_tpu._private.worker.get_global_worker()
    session_dir = worker.session_info.get("session_dir")
    gcs_address = worker.gcs_client.address

    client = MockTpuClient()
    provider = TPUNodeProvider(
        {
            "tpu_client": client,
            "launch_local_raylets": True,
            "gcs_address": gcs_address,
            "session_dir": session_dir,
        },
        cluster_name="v5e",
    )
    autoscaler = StandardAutoscaler(
        provider,
        node_types={
            "tpu_v5e_16": {
                # slice hosts have CPUs too — tasks carry an implicit
                # CPU: 1, so the node type must cover it to bin-pack
                "resources": {"CPU": 4, "TPU": 16, "TPU-v5litepod-16-head": 1},
                "node_config": {"accelerator_type": "v5litepod-16"},
            }
        },
        max_workers=2,
        idle_timeout_s=5.0,
        gcs_client=worker.gcs_client,
    )
    monitor = Monitor(autoscaler, interval_s=1.0)
    monitor.start()
    try:
        # gang-style demand: one slice-head + chips, unmet by the head node
        @ray_tpu.remote(resources={"TPU-v5litepod-16-head": 1, "TPU": 4})
        def on_slice():
            return "on-slice"

        assert ray_tpu.get(on_slice.remote(), timeout=180) == "on-slice"
        assert autoscaler.num_launches >= 1
        assert len(client.list()) >= 1  # a slice exists in the (mock) API
        # scale-down: demand gone, slice idles out
        deadline = time.time() + 90
        while time.time() < deadline:
            if autoscaler.num_terminations >= 1 and not client.list():
                break
            time.sleep(1.0)
        assert autoscaler.num_terminations >= 1, "idle slice never terminated"
        assert client.list() == [], "slice not deleted from the API"
    finally:
        monitor.stop()
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)


# ==========================================================================
# Capacity return (ISSUE 4): preempted-node resources are relaunched even
# with no pending demand (an elastic trainer that shrank queues nothing).
# ==========================================================================


class _RecordingProvider:
    """Minimal provider for unit-driving the reconcile loop."""

    def __init__(self):
        self.created = []  # (tags, count)
        self._nodes = {}
        self._next = 0

    def create_node(self, node_config, tags, count):
        self.created.append((dict(tags), count))
        ids = []
        for _ in range(count):
            nid = f"n{self._next}"
            self._nodes[nid] = dict(tags)
            self._next += 1
            ids.append(nid)
        return ids

    def is_running(self, node_id):
        return node_id in self._nodes

    def non_terminated_nodes(self, tag_filters):
        return [
            nid for nid, tags in self._nodes.items()
            if all(tags.get(k) == v for k, v in tag_filters.items())
        ]

    def terminate_node(self, node_id):
        self._nodes.pop(node_id, None)

    def raylet_address(self, node_id):
        return None


def test_autoscaler_v1_capacity_return_relaunches_preempted():
    provider = _RecordingProvider()
    autoscaler = StandardAutoscaler(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}},
                    "big_worker": {"resources": {"CPU": 16}}},
        max_workers=4,
    )
    lost = {
        "pending_demands": [],
        "nodes": {},
        "lost_capacity": [
            {"node_id": "deadbeef01", "resources_total": {"CPU": 2},
             "reason": "PREEMPTION", "time": 0.0}
        ],
    }
    autoscaler.update(load_metrics=lost)
    # Smallest covering type relaunched, once, with zero pending demand.
    assert autoscaler.num_capacity_returns == 1
    assert len(provider.created) == 1
    assert provider.created[0][1] == 1
    assert "cpu_worker" in provider.created[0][0].values()
    # The log entry is processed exactly once: a second tick with the
    # same feed (the GCS keeps a bounded log) launches nothing new.
    autoscaler.update(load_metrics=lost)
    assert autoscaler.num_capacity_returns == 1
    assert len(provider.created) == 1


def test_autoscaler_v2_capacity_return_queues_replacement():
    from ray_tpu.autoscaler.v2.autoscaler import AutoscalerV2

    provider = _RecordingProvider()
    autoscaler = AutoscalerV2(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=4,
    )
    lost = {
        "pending_demands": [],
        "nodes": {},
        "lost_capacity": [
            {"node_id": "deadbeef02", "resources_total": {"CPU": 2},
             "reason": "PREEMPTION", "time": 0.0}
        ],
    }
    autoscaler.update(load_metrics=lost)
    assert autoscaler.num_capacity_returns == 1
    assert len(provider.created) == 1  # reconcile drove the queued launch
    autoscaler.update(load_metrics=lost)
    assert autoscaler.num_capacity_returns == 1


def test_pick_replacement_type_smallest_cover():
    from ray_tpu.autoscaler.autoscaler import pick_replacement_type

    types = {
        "small": {"resources": {"CPU": 2}},
        "big": {"resources": {"CPU": 16}},
        "tpu": {"resources": {"TPU": 4, "CPU": 8}},
    }
    assert pick_replacement_type(types, {"CPU": 2}) == "small"
    assert pick_replacement_type(types, {"CPU": 8}) == "big"
    assert pick_replacement_type(types, {"TPU": 4}) == "tpu"
    assert pick_replacement_type(types, {"GPU": 1}) is None
    # Auto-detected extras on a REGISTERED node (memory from sysconf,
    # per-node markers) must not defeat the fit — only resource kinds
    # some node type declares participate.
    assert pick_replacement_type(
        types, {"CPU": 2, "memory": 8 * 1024**3, "node:10.0.0.4": 1}
    ) == "small"
    assert pick_replacement_type(types, {"memory": 8 * 1024**3}) is None


def test_replacement_launches_prune_survives_budget_break():
    """The consumed-once prune must be computed against the FULL feed: a
    budget break mid-iteration must not forget already-replaced ids past
    the break point (that would double-launch them next tick)."""
    from ray_tpu.autoscaler.autoscaler import replacement_launches

    types = {"w": {"resources": {"CPU": 2}}}
    feed = [
        {"node_id": "A", "resources_total": {"CPU": 2}},
        {"node_id": "B", "resources_total": {"CPU": 2}},
    ]
    processed = {"B"}  # B already replaced; A pending (its launch failed)
    assert replacement_launches(types, feed, processed, budget=0) == []
    assert "B" in processed  # remembered despite the budget break at A
    out = replacement_launches(types, feed, processed, budget=2)
    assert [o[0] for o in out] == ["A"]  # A launches once, B never again
    # Aged-out entries DO get pruned once the GCS TTL drops them.
    assert replacement_launches(types, [], processed, budget=2) == []
    assert processed == set()


def test_grow_hint_rpc_roundtrip(ray_cluster):
    """train_grow_hint publishes into the load-metrics feed; count 0
    clears; stale hints age out by TTL at read time."""
    worker = ray_tpu._private.worker.get_global_worker()
    gcs = worker.gcs_client
    assert gcs.call(
        "train_grow_hint",
        {"name": "exp_grow", "count": 2, "resources": {"CPU": 1.0}},
    )
    hints = gcs.call("get_load_metrics")["grow_hints"]
    assert [h["name"] for h in hints] == ["exp_grow"]
    assert hints[0]["count"] == 2
    assert hints[0]["resources"] == {"CPU": 1.0}
    # refresh replaces in place (no duplicates)
    gcs.call(
        "train_grow_hint",
        {"name": "exp_grow", "count": 1, "resources": {"CPU": 1.0}},
    )
    hints = gcs.call("get_load_metrics")["grow_hints"]
    assert len(hints) == 1 and hints[0]["count"] == 1
    gcs.call("train_grow_hint", {"name": "exp_grow", "count": 0})
    assert gcs.call("get_load_metrics")["grow_hints"] == []
    # nameless publishes are refused, not stored
    assert gcs.call("train_grow_hint", {"count": 3}) is False


def test_autoscaler_launches_for_grow_hints():
    """A grow hint alone — zero pending task demand — pulls up worker
    capacity sized to the hinted shape, so the elastic trainer's
    epoch-boundary grow finds it warm."""
    provider = _RecordingProvider()
    autoscaler = StandardAutoscaler(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=4,
    )
    feed = {
        "pending_demands": [],
        "nodes": {},
        "lost_capacity": [],
        "grow_hints": [
            {"name": "exp", "count": 2, "resources": {"CPU": 1.0},
             "time": 0.0}
        ],
    }
    autoscaler.update(load_metrics=feed)
    assert len(provider.created) == 1
    # one 2-CPU worker covers both hinted 1-CPU shapes
    assert provider.created[0][1] == 1
    # empty shapes are ignored rather than minting zero-resource demand
    provider.created.clear()
    autoscaler2 = StandardAutoscaler(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=4,
    )
    autoscaler2.update(load_metrics={
        "pending_demands": [], "nodes": {}, "lost_capacity": [],
        "grow_hints": [{"name": "e", "count": 2, "resources": {}}],
    })
    assert provider.created == []


def test_grow_hint_deduped_against_capacity_return():
    """A preemption that shrank an elastic trainer logs BOTH a
    lost_capacity entry and a grow hint for the same worker — the
    replacement launch must not be doubled by the hint."""
    provider = _RecordingProvider()
    autoscaler = StandardAutoscaler(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=8,
    )
    feed = {
        "pending_demands": [],
        "nodes": {},
        "lost_capacity": [
            {"node_id": "deadbeef03", "resources_total": {"CPU": 2},
             "reason": "PREEMPTION", "time": 0.0}
        ],
        "grow_hints": [
            {"name": "exp", "count": 1, "resources": {"CPU": 1.0},
             "time": 0.0}
        ],
    }
    autoscaler.update(load_metrics=feed)
    # one node total: the capacity return already covers the hinted worker
    assert autoscaler.num_capacity_returns == 1
    assert len(provider.created) == 1
    assert provider.created[0][1] == 1
    # Hint demand BEYOND what the lost entry covers still launches: 3
    # hinted 1-CPU workers minus the one absorbed leaves 2, bin-packed
    # onto one 2-CPU node alongside the replacement.
    provider = _RecordingProvider()
    autoscaler = StandardAutoscaler(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=8,
    )
    feed["grow_hints"][0]["count"] = 3
    autoscaler.update(load_metrics=feed)
    assert autoscaler.num_capacity_returns == 1
    assert sum(c for _, c in provider.created) == 2


def test_autoscaler_v2_launches_for_grow_hints():
    """v2 folds hints through the same shared helper as v1."""
    from ray_tpu.autoscaler.v2.autoscaler import AutoscalerV2

    provider = _RecordingProvider()
    autoscaler = AutoscalerV2(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=4,
    )
    autoscaler.update(load_metrics={
        "pending_demands": [], "nodes": {}, "lost_capacity": [],
        "grow_hints": [
            {"name": "exp", "count": 2, "resources": {"CPU": 1.0},
             "time": 0.0}
        ],
    })
    assert len(provider.created) == 1
