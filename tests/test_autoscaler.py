"""Autoscaler: demand scheduler unit tests + fake-multinode integration
(reference test model: python/ray/tests/test_resource_demand_scheduler.py,
test_autoscaler_fake_multinode.py)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    FakeMultiNodeProvider,
    Monitor,
    StandardAutoscaler,
    get_nodes_to_launch,
)


def test_demand_scheduler_bin_packing():
    node_types = {
        "small": {"resources": {"CPU": 2}},
        "big": {"resources": {"CPU": 8}},
    }
    # 5 x 1-CPU demands, 1 free CPU in cluster -> 4 CPUs needed -> 2 small
    to_launch = get_nodes_to_launch(
        [{"CPU": 1}] * 5,
        [{"CPU": 1}],
        node_types,
        pending_launches={},
        max_workers=10,
        current_workers=0,
    )
    assert to_launch == {"small": 2}


def test_demand_scheduler_prefers_smallest_fit():
    node_types = {
        "cpu": {"resources": {"CPU": 4}},
        "tpu_host": {"resources": {"CPU": 4, "TPU": 4}},
    }
    to_launch = get_nodes_to_launch(
        [{"TPU": 4}],
        [],
        node_types,
        pending_launches={},
        max_workers=10,
        current_workers=0,
    )
    assert to_launch == {"tpu_host": 1}


def test_demand_scheduler_respects_max_workers():
    node_types = {"small": {"resources": {"CPU": 1}}}
    to_launch = get_nodes_to_launch(
        [{"CPU": 1}] * 10,
        [],
        node_types,
        pending_launches={},
        max_workers=3,
        current_workers=1,
    )
    assert sum(to_launch.values()) == 2


def test_demand_scheduler_counts_pending_launches():
    node_types = {"small": {"resources": {"CPU": 4}}}
    to_launch = get_nodes_to_launch(
        [{"CPU": 1}] * 3,
        [],
        node_types,
        pending_launches={"small": 1},
        max_workers=10,
        current_workers=0,
    )
    assert to_launch == {}  # the in-flight node covers the demand


def test_autoscaler_scales_up_for_pending_actors(ray_cluster):
    """Pending actors that do not fit the head node must pull up a fake
    worker node, after which they get scheduled."""
    worker = ray_tpu._private.worker.get_global_worker()
    session_dir = worker.session_info.get("session_dir")
    gcs_address = worker.gcs_client.address

    provider = FakeMultiNodeProvider(
        {"gcs_address": gcs_address, "session_dir": session_dir}
    )
    autoscaler = StandardAutoscaler(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=2,
        idle_timeout_s=9999,
        gcs_client=worker.gcs_client,
    )
    monitor = Monitor(autoscaler, interval_s=1.0)
    monitor.start()
    try:
        # the module cluster has 4 CPUs; demand 6 CPUs of actors
        @ray_tpu.remote(num_cpus=2)
        class Chunk:
            def ping(self):
                return "ok"

        actors = [Chunk.remote() for _ in range(3)]
        refs = [a.ping.remote() for a in actors]
        out = ray_tpu.get(refs, timeout=120)
        assert out == ["ok"] * 3
        assert autoscaler.num_launches >= 1
        assert len(ray_tpu.nodes()) >= 2
        for a in actors:
            ray_tpu.kill(a)
    finally:
        monitor.stop()
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)
