"""ray_tpu.data tests (reference test strategy: python/ray/data/tests/
test_basic.py / test_map.py / test_sort.py / test_consumption.py,
shrunk to the 1-core CI box)."""

import time

import numpy as np
import pytest


def test_range_count_take(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_map_batches_and_filter(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(100, parallelism=4)
    out = (
        ds.map_batches(lambda b: {"id": b["id"] * 2})
        .filter(lambda row: row["id"] % 4 == 0)
        .take_all()
    )
    assert sorted(r["id"] for r in out) == [i * 2 for i in range(100) if (i * 2) % 4 == 0]


def test_map_and_flat_map(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.from_items([1, 2, 3], parallelism=2)
    out = ds.map(lambda r: {"item": r["item"] + 10}).take_all()
    assert sorted(r["item"] for r in out) == [11, 12, 13]

    out = ds.flat_map(lambda r: [{"x": r["item"]}, {"x": -r["item"]}]).take_all()
    assert sorted(r["x"] for r in out) == [-3, -2, -1, 1, 2, 3]


def test_columns_ops(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(10, parallelism=2).add_column("sq", lambda b: b["id"] ** 2)
    assert set(ds.columns()) == {"id", "sq"}
    row = ds.select_columns(["sq"]).take(1)[0]
    assert row == {"sq": 0}
    renamed = ds.rename_columns({"sq": "square"}).columns()
    assert "square" in renamed
    dropped = ds.drop_columns(["sq"]).columns()
    assert dropped == ["id"]


def test_sort_and_shuffle(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(50, parallelism=4).random_shuffle(seed=7)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(50))
    assert vals != list(range(50))  # actually shuffled

    s = rd.from_items([5, 3, 9, 1, 7], parallelism=2).sort("item")
    assert [r["item"] for r in s.take_all()] == [1, 3, 5, 7, 9]
    s = rd.from_items([5, 3, 9, 1, 7], parallelism=2).sort("item", descending=True)
    assert [r["item"] for r in s.take_all()] == [9, 7, 5, 3, 1]


def test_repartition_union_zip(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(20, parallelism=2).repartition(5)
    mat = ds.materialize()
    assert mat.num_blocks() == 5
    assert mat.count() == 20

    u = rd.range(3).union(rd.range(3))
    assert u.count() == 6

    left = rd.range(10, parallelism=2)
    right = rd.range(10, parallelism=3).map_batches(lambda b: {"val": b["id"] * 10})
    z = left.zip(right)
    rows = z.take_all()
    assert sorted((r["id"], r["val"]) for r in rows) == [(i, i * 10) for i in range(10)]


def test_groupby(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.from_items(
        [{"k": i % 3, "v": i} for i in range(12)], parallelism=3
    )
    out = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    expect = {k: sum(i for i in range(12) if i % 3 == k) for k in range(3)}
    assert out == expect
    cnt = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert cnt == {0: 4, 1: 4, 2: 4}


def test_aggregates(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(10, parallelism=3)
    assert ds.sum("id") == 45
    assert ds.min("id") == 0
    assert ds.max("id") == 9
    assert ds.mean("id") == 4.5
    assert abs(ds.std("id") - np.std(np.arange(10), ddof=1)) < 1e-9


def test_limit_streaming(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(1000, parallelism=8).limit(17)
    assert ds.count() == 17


def test_iter_batches_rebatching(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(100, parallelism=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32, drop_last=True)]
    assert sizes == [32, 32, 32]
    all_ids = np.concatenate([b["id"] for b in ds.iter_batches(batch_size=32)])
    assert sorted(all_ids.tolist()) == list(range(100))


def test_tensor_blocks(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range_tensor(16, shape=(2, 3), parallelism=2)
    batch = ds.take_batch(4)
    assert batch["data"].shape == (4, 2, 3)
    out = ds.map_batches(lambda b: {"data": b["data"] * 2}).take_batch(4)
    assert out["data"].shape == (4, 2, 3)
    assert out["data"][1, 0, 0] == 2


def test_iter_jax_batches(ray_cluster):
    import jax.numpy as jnp

    import ray_tpu.data as rd

    ds = rd.range_tensor(32, shape=(4,), parallelism=2)
    batches = list(ds.iter_jax_batches(batch_size=8, dtypes={"data": np.float32}))
    assert len(batches) == 4
    b = batches[0]["data"]
    assert isinstance(b, jnp.ndarray)
    assert b.shape == (8, 4)
    assert b.dtype == jnp.float32


def test_iter_jax_batches_sharded(ray_cluster):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import ray_tpu.data as rd
    from ray_tpu.parallel import create_mesh

    mesh = create_mesh({"dp": 8}, jax.devices())
    sharding = NamedSharding(mesh, P("dp"))
    ds = rd.range_tensor(64, shape=(4,), parallelism=2)
    for batch in ds.iter_jax_batches(batch_size=16, sharding=sharding):
        assert batch["data"].sharding == sharding
        assert batch["data"].shape == (16, 4)


def test_file_roundtrip(ray_cluster, tmp_path):
    import ray_tpu.data as rd

    ds = rd.range(30, parallelism=3).add_column("x", lambda b: b["id"] * 1.5)
    pq_dir = str(tmp_path / "pq")
    ds.write_parquet(pq_dir)
    back = rd.read_parquet(pq_dir)
    assert back.count() == 30
    assert abs(back.sum("x") - sum(i * 1.5 for i in range(30))) < 1e-9

    csv_dir = str(tmp_path / "csv")
    ds.write_csv(csv_dir)
    assert rd.read_csv(csv_dir).count() == 30

    js_dir = str(tmp_path / "json")
    ds.write_json(js_dir)
    assert rd.read_json(js_dir + "/*.json").count() == 30


def test_from_pandas_numpy_arrow(ray_cluster):
    import pandas as pd
    import pyarrow as pa

    import ray_tpu.data as rd

    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    assert rd.from_pandas(df).count() == 3
    assert rd.from_numpy(np.ones((5, 2))).take_batch(5)["data"].shape == (5, 2)
    t = pa.table({"c": [1.0, 2.0]})
    assert rd.from_arrow(t).sum("c") == 3.0
    out_df = rd.from_pandas(df).to_pandas()
    assert list(out_df["a"]) == [1, 2, 3]


def test_split_and_streaming_split(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(40, parallelism=4)
    parts = ds.split(2)
    assert sum(p.count() for p in parts) == 40

    its = ds.streaming_split(2)
    seen = []
    for it in its:
        for b in it.iter_batches(batch_size=None, prefetch_batches=0):
            seen.extend(b["id"].tolist())
    assert sorted(seen) == list(range(40))


def test_streaming_split_equal(ray_cluster):
    """equal=True must give every split the same row count even with
    uneven blocks (regression: flag was silently ignored)."""
    import ray_tpu.data as rd

    # 3 blocks of uneven sizes: 10+10+10 → equal slices of each block
    ds = rd.range(30, parallelism=3)
    its = ds.streaming_split(2, equal=True)
    counts = []
    for it in its:
        total = 0
        for b in it.iter_batches(batch_size=None, prefetch_batches=0):
            total += len(b["id"])
        counts.append(total)
    assert counts[0] == counts[1] > 0


def test_sort_empty_blocks(ray_cluster):
    """Sorting a fully filtered dataset must not crash (regression:
    np.concatenate([]) in bulk_sort)."""
    import ray_tpu.data as rd

    ds = rd.range(20, parallelism=2).filter(lambda r: False).sort("id")
    assert ds.count() == 0


def test_iter_batches_early_break_no_leak(ray_cluster):
    """Abandoning iter_batches mid-stream must not leak the producer
    (regression: _prefetch thread blocked on a full queue forever)."""
    import threading

    import ray_tpu.data as rd

    def live_names():
        # The submitter's lease-req pool is a bounded one-time pool that
        # grows lazily to 8 threads — not a leak; exclude it (and compare
        # by NAME, not count, so threads that legitimately exited during
        # the run don't mask new leaks or create phantom ones).
        return {t.name for t in threading.enumerate() if not t.name.startswith("lease-req")}

    # Warm up the runtime's other one-time threads (rpc readers etc).
    rd.range(10, parallelism=2).take_all()
    time.sleep(1.5)
    before = live_names()
    for _ in range(3):
        for b in rd.range(1000, parallelism=4).iter_batches(batch_size=10, prefetch_batches=2):
            break
    # Leases idle out after ~1s; poll past that (fixed sleeps flake on a
    # loaded box where transient rpc-reader threads linger) so they don't
    # count as leaks.
    deadline = time.time() + 12.0
    while True:
        leaked = live_names() - before
        if len(leaked) <= 1 or time.time() > deadline:
            break
        time.sleep(0.5)
    assert len(leaked) <= 1, f"leaked threads: {sorted(leaked)}"


def test_streaming_split_multi_epoch(ray_cluster):
    """Two concurrent consumers over two epochs: every epoch must deliver
    the full dataset exactly once across splits."""
    import threading

    import ray_tpu.data as rd

    its = rd.range(24, parallelism=4).streaming_split(2)
    per_epoch = [[], []]

    def consume(idx):
        for epoch in range(2):
            for b in its[idx].iter_batches(batch_size=None, prefetch_batches=0):
                per_epoch[epoch].extend(b["id"].tolist())

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "streaming_split consumer hung"
    for epoch in range(2):
        assert sorted(per_epoch[epoch]) == list(range(24))


def test_groupby_string_keys(ray_cluster):
    """String keys must hash identically across worker processes
    (regression: salted str hash scattered groups over partitions)."""
    import ray_tpu.data as rd

    rows = [{"city": c, "x": i} for i, c in enumerate(["nyc", "sf", "la"] * 8)]
    ds = rd.from_items(rows, parallelism=4)
    out = {r["city"]: r["sum(x)"] for r in ds.groupby("city").sum("x").take_all()}
    expect = {}
    for i, c in enumerate(["nyc", "sf", "la"] * 8):
        expect[c] = expect.get(c, 0) + i
    assert out == expect


def test_map_batches_actor_compute(ray_cluster):
    import ray_tpu.data as rd

    class AddConst:
        def __init__(self):
            self.c = 100

        def __call__(self, batch):
            return {"id": batch["id"] + self.c}

    ds = rd.range(20, parallelism=2)
    out = ds.map_batches(AddConst, concurrency=2).take_all()
    assert sorted(r["id"] for r in out) == [i + 100 for i in range(20)]


def test_random_sample_and_unique(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.from_items([1, 2, 2, 3, 3, 3], parallelism=2)
    assert ds.unique("item") == [1, 2, 3]

    big = rd.range(200, parallelism=2).random_sample(0.5, seed=0)
    n = big.count()
    assert 50 < n < 150


def test_train_test_split(ray_cluster):
    import ray_tpu.data as rd

    train, test = rd.range(100, parallelism=4).train_test_split(0.2)
    assert train.count() == 80
    assert test.count() == 20


def test_block_order_preserved_under_skew(ray_cluster):
    """Blocks must come back in submission order even when later tasks
    finish first (VERDICT r2 weak #1: completion-order emission race).
    Early blocks sleep longest, so task completion order is inverted."""
    import ray_tpu.data as rd

    n_blocks = 6

    def slow_early(batch):
        # Block i contains ids starting at i * 4; earlier blocks sleep more.
        first = int(batch["id"][0])
        block_idx = first // 4
        time.sleep(0.3 * (n_blocks - block_idx) / n_blocks)
        return {"id": batch["id"] * 2}

    ds = rd.range(4 * n_blocks, parallelism=n_blocks).map_batches(slow_early)
    out = [r["id"] for r in ds.take_all()]
    assert out == [i * 2 for i in range(4 * n_blocks)], out


def test_read_text(ray_cluster, tmp_path):
    import ray_tpu.data as rd

    (tmp_path / "a.txt").write_text("hello\nworld\n\nthree\n")
    (tmp_path / "b.txt").write_text("four\n")
    ds = rd.read_text([str(tmp_path / "a.txt"), str(tmp_path / "b.txt")])
    rows = sorted(r["text"] for r in ds.take_all())
    assert rows == ["four", "hello", "three", "world"]


def test_push_based_shuffle_pipelines(ray_cluster):
    """A 100+-block shuffle must overlap merges with still-running maps
    under a bounded unmerged-piece inventory (reference:
    push_based_shuffle_task_scheduler.py map/merge overlap)."""
    import time

    import ray_tpu.data as rd
    from ray_tpu.data._internal.executor import (
        PushBasedShuffleOperator,
        Topology,
        execute_streaming,
    )
    from ray_tpu.data._internal.planner import Planner
    from ray_tpu.data.context import DataContext

    n_blocks = 112
    ctx = DataContext.get_current()
    assert ctx.shuffle_strategy == "push"

    def slow_map(batch):
        time.sleep(0.01)  # keep maps running while merges start
        return batch

    ds = rd.range(4 * n_blocks, parallelism=n_blocks).map_batches(slow_map).random_shuffle(seed=7)
    from ray_tpu.data._internal import logical as L

    physical = Planner(ds._ctx).plan(L.LogicalPlan(ds._dag))
    # find the shuffle op in the physical topology
    shuffle_ops = [
        op for op in Topology(physical).ops if isinstance(op, PushBasedShuffleOperator)
    ]
    assert len(shuffle_ops) == 1, "RandomShuffle should lower to the push operator"
    shuffle = shuffle_ops[0]

    ids = []
    for bundle in execute_streaming(physical):
        import ray_tpu

        block = ray_tpu.get(bundle.block_ref)
        from ray_tpu.data.block import BlockAccessor

        ids.extend(BlockAccessor.for_block(block).to_numpy()["id"].tolist())

    # correctness: a permutation of the input
    assert sorted(ids) == list(range(4 * n_blocks))
    assert ids != list(range(4 * n_blocks)), "not shuffled"
    # pipelining: merges began while upstream maps were still producing
    assert shuffle.merges_started_before_input_done > 0, (
        "no merge overlapped the map phase"
    )
    # memory bound: unmerged inventory stayed far below blocks × partitions
    total_pieces = n_blocks * shuffle._n
    assert shuffle.max_outstanding_pieces < total_pieces / 2, (
        f"{shuffle.max_outstanding_pieces} outstanding of {total_pieces} total"
    )


def test_push_shuffle_through_dataset_api(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.range(200, parallelism=20).random_shuffle(seed=3)
    out = [r["id"] for r in ds.take_all()]
    assert sorted(out) == list(range(200))
    assert out != list(range(200))
    # determinism: the same seed reproduces the SAME order even though
    # merges overlap maps in nondeterministic task-completion order
    out2 = [r["id"] for r in rd.range(200, parallelism=20).random_shuffle(seed=3).take_all()]
    assert out == out2


def test_read_sql_sqlite(ray_cluster, tmp_path):
    import sqlite3

    import ray_tpu.data as rd

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE users (id INTEGER, name TEXT, score REAL)")
    conn.executemany(
        "INSERT INTO users VALUES (?, ?, ?)",
        [(i, f"user{i}", i * 1.5) for i in range(50)],
    )
    conn.commit()
    conn.close()

    ds = rd.read_sql(
        "SELECT id, score FROM users WHERE id < 40",
        lambda: sqlite3.connect(db),
        parallelism=4,
    )
    rows = sorted(ds.take_all(), key=lambda r: r["id"])
    assert len(rows) == 40
    assert rows[10] == {"id": 10, "score": 15.0}


def test_from_huggingface(ray_cluster):
    datasets = pytest.importorskip("datasets")

    import ray_tpu.data as rd

    hf = datasets.Dataset.from_dict(
        {"text": [f"doc {i}" for i in range(30)], "label": list(range(30))}
    )
    ds = rd.from_huggingface(hf, parallelism=3)
    rows = sorted(ds.take_all(), key=lambda r: r["label"])
    assert len(rows) == 30
    assert rows[7]["text"] == "doc 7"
    # flows through the normal pipeline
    n = rd.from_huggingface(hf).filter(lambda r: r["label"] % 2 == 0).count()
    assert n == 15


def test_read_webdataset(ray_cluster, tmp_path):
    import io
    import json
    import tarfile

    import ray_tpu.data as rd

    def add(tf, name, data: bytes):
        info = tarfile.TarInfo(name)
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))

    for t in range(2):
        with tarfile.open(tmp_path / f"shard{t}.tar", "w") as tf:
            for i in range(5):
                key = f"{t}_{i:04d}"
                add(tf, f"{key}.img", bytes([t, i]) * 10)
                add(tf, f"{key}.json", json.dumps({"label": i}).encode())
                add(tf, f"{key}.txt", f"caption {i}".encode())

    ds = rd.read_webdataset(str(tmp_path))
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert len(rows) == 10
    assert rows[0]["__key__"] == "0_0000"
    assert rows[0]["json"] == {"label": 0}
    assert rows[0]["txt"] == "caption 0"
    assert bytes(rows[0]["img"]) == bytes([0, 0]) * 10


def test_memory_budget_backpressure(ray_cluster):
    """With a tiny streaming memory budget the executor still completes
    (policies pause dispatch, never deadlock)."""
    import ray_tpu.data as rd
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    old = ctx.streaming_memory_budget_bytes
    ctx.streaming_memory_budget_bytes = 1  # absurdly small: worst case
    try:
        out = [r["id"] for r in rd.range(64, parallelism=8).map_batches(lambda b: b).take_all()]
        assert sorted(out) == list(range(64))
    finally:
        ctx.streaming_memory_budget_bytes = old
