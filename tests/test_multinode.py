"""Multi-node behavior on one machine via cluster_utils.Cluster
(reference test pattern: python/ray/tests/conftest.py ray_start_cluster)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2, resources={"special": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_two_nodes_visible(cluster):
    nodes = ray_tpu.nodes()
    assert sum(1 for n in nodes if n["Alive"]) == 2
    assert ray_tpu.cluster_resources()["CPU"] == 4.0


def test_task_spillback_to_remote_node(cluster):
    @ray_tpu.remote(resources={"special": 0.1})
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    # "special" exists only on the worker node → must spill over.
    node_id = ray_tpu.get(where.remote())
    head_id = ray_tpu.get_runtime_context().get_node_id()
    assert node_id != head_id


def test_cross_node_object_transfer(cluster):
    @ray_tpu.remote(resources={"special": 0.1})
    def produce():
        return np.arange(500_000, dtype=np.float64)  # 4MB, via shm store

    @ray_tpu.remote
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    # Consume with no affinity: may pull across raylets.
    total = ray_tpu.get(consume.remote(ref))
    assert total == float(np.arange(500_000, dtype=np.float64).sum())
    # Driver-side get also pulls to the head node store.
    arr = ray_tpu.get(ref)
    assert arr.shape == (500_000,)


def test_spread_scheduling(cluster):
    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def where():
        time.sleep(0.3)
        return ray_tpu.get_runtime_context().get_node_id()

    refs = [where.remote() for _ in range(4)]
    nodes = set(ray_tpu.get(refs))
    assert len(nodes) >= 2, f"SPREAD used only {nodes}"


@pytest.mark.slow  # ~60 s node-death drill; drain/elastic smokes cover it
def test_actor_on_remote_node_and_node_death(cluster):
    node = cluster.add_node(num_cpus=1, resources={"doomed": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"doomed": 1}, max_restarts=0)
    class Pinned:
        def ping(self):
            return "pong"

    a = Pinned.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"

    cluster.remove_node(node)
    # GCS health check marks the node dead; pending calls must fail.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(a.ping.remote(), timeout=5)
        except ray_tpu.exceptions.RayActorError:
            break
        except ray_tpu.exceptions.GetTimeoutError:
            pass
        time.sleep(0.5)
    else:
        pytest.fail("actor on dead node never reported as dead")


def test_lineage_reconstruction_simple(cluster):
    """An object whose only copy dies with its node is rebuilt by
    resubmitting the creating task (reference:
    core_worker/object_recovery_manager.h + task_manager.h:212)."""
    node = cluster.add_node(num_cpus=1, resources={"fragile": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"fragile": 0.1}, max_retries=3)
    def produce():
        # Big enough to live in the shm store (not inline in the GCS).
        return np.full(200_000, 7.0)

    @ray_tpu.remote(resources={"fragile": 0.1})
    def check(a):
        return float(a.sum())

    ref = produce.remote()
    # Consume on the SAME node so the only copy stays there (a driver get
    # would pull a surviving replica to the head node).
    assert ray_tpu.get(check.remote(ref), timeout=60) == 7.0 * 200_000
    # Kill the node holding the only copy; a replacement node joins with
    # the same resources (the resubmitted task needs somewhere to run).
    cluster.remove_node(node)
    cluster.add_node(num_cpus=1, resources={"fragile": 1})
    cluster.wait_for_nodes()
    out = ray_tpu.get(ref, timeout=120)
    assert float(out.sum()) == 7.0 * 200_000


def test_lineage_reconstruction_transitive(cluster):
    """Recovering an object whose creating task's ARGS are also lost
    recovers the whole chain."""
    node = cluster.add_node(num_cpus=2, resources={"fragile2": 2})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"fragile2": 0.1}, max_retries=3)
    def base():
        return np.ones(150_000)

    @ray_tpu.remote(resources={"fragile2": 0.1}, max_retries=3)
    def double(a):
        return a * 2.0

    @ray_tpu.remote(resources={"fragile2": 0.1})
    def check(x):
        return float(x.sum())

    a = base.remote()
    b = double.remote(a)
    # Consume on the fragile node: both a and b live only there.
    assert ray_tpu.get(check.remote(b), timeout=60) == 2.0 * 150_000
    cluster.remove_node(node)
    cluster.add_node(num_cpus=2, resources={"fragile2": 2})
    cluster.wait_for_nodes()
    out = ray_tpu.get(b, timeout=120)
    assert float(out.sum()) == 2.0 * 150_000


def test_put_object_lost_is_unrecoverable(cluster):
    """ray.put objects have no lineage: losing every copy raises
    ObjectLostError (matches the reference's semantics)."""
    node = cluster.add_node(num_cpus=1, resources={"fragile3": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"fragile3": 0.1})
    def put_remote():
        return ray_tpu.put(np.zeros(150_000))

    inner = ray_tpu.get(put_remote.remote(), timeout=60)
    # The put lives only on the doomed node (driver never fetched it).
    cluster.remove_node(node)
    time.sleep(1.0)
    with pytest.raises(ray_tpu.exceptions.ObjectLostError):
        ray_tpu.get(inner, timeout=60)


def test_lineage_reconstruction_error_path(cluster):
    """A dependent task submitted AFTER its arg was lost stores an
    ObjectLostError-caused error; the owner's get unwraps it, rebuilds
    the chain, and resubmits the dependent task."""
    node = cluster.add_node(num_cpus=1, resources={"fragile4": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"fragile4": 0.1}, max_retries=3)
    def produce():
        return np.full(150_000, 3.0)

    @ray_tpu.remote(resources={"fragile4": 0.1})
    def touch(a):
        return float(a.sum())

    ref = produce.remote()
    assert ray_tpu.get(touch.remote(ref), timeout=60) == 3.0 * 150_000
    cluster.remove_node(node)
    cluster.add_node(num_cpus=1, resources={"fragile4": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_retries=3)
    def consume(a):
        return float(a.sum())

    # consume lands on a live node, discovers the arg is lost, and errors;
    # the driver's get triggers chain reconstruction and a resubmit.
    assert ray_tpu.get(consume.remote(ref), timeout=120) == 3.0 * 150_000


@pytest.mark.slow  # ~17 s 1 GiB cross-node transfer: tier-2
def test_chunked_cross_node_transfer_1gib(cluster):
    """A >1GiB object crosses nodes in bounded-parallel 4MB chunks — no
    single whole-object frame, no event-loop stall (reference:
    push_manager.h:30; VERDICT r1 item 5)."""
    n = 1_100_000_000  # ~1.02 GiB, deliberately not chunk-aligned

    @ray_tpu.remote(resources={"special": 0.1})
    def produce_big():
        a = np.zeros(n, dtype=np.uint8)
        a[0], a[-1], a[n // 2] = 7, 9, 5
        return a

    ref = produce_big.remote()
    # Driver get pulls the object from the worker node to the head store.
    out = ray_tpu.get(ref, timeout=600)
    assert out.nbytes == n
    assert (int(out[0]), int(out[-1]), int(out[n // 2])) == (7, 9, 5)
    assert int(out.sum()) == 21
    del out, ref


def test_node_label_scheduling(cluster):
    """NODE_LABEL tasks run only on matching nodes; no match fails with a
    clear error (reference: NodeLabelSchedulingStrategy)."""
    from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy

    cluster.add_node(num_cpus=1, labels={"tier": "gold", "zone": "a"})
    cluster.wait_for_nodes()

    @ray_tpu.remote(
        scheduling_strategy=NodeLabelSchedulingStrategy(hard={"tier": "gold"})
    )
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    ran_on = {ray_tpu.get(where.remote(), timeout=60) for _ in range(3)}
    gcs = ray_tpu._private.worker.get_global_worker().gcs_client
    info = gcs.call("get_cluster_info")
    gold = {
        ray_tpu.NodeID(n["node_id"]).hex()
        for n in info["nodes"].values()
        if n.get("labels", {}).get("tier") == "gold"
    }
    assert gold and ran_on <= gold, (ran_on, gold)

    @ray_tpu.remote(
        scheduling_strategy=NodeLabelSchedulingStrategy(hard={"tier": "platinum"}),
        max_retries=0,
    )
    def nowhere():
        return 1

    with pytest.raises(ray_tpu.exceptions.RaySystemError):
        ray_tpu.get(nowhere.remote(), timeout=60)
