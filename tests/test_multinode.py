"""Multi-node behavior on one machine via cluster_utils.Cluster
(reference test pattern: python/ray/tests/conftest.py ray_start_cluster)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2, resources={"special": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_two_nodes_visible(cluster):
    nodes = ray_tpu.nodes()
    assert sum(1 for n in nodes if n["Alive"]) == 2
    assert ray_tpu.cluster_resources()["CPU"] == 4.0


def test_task_spillback_to_remote_node(cluster):
    @ray_tpu.remote(resources={"special": 0.1})
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    # "special" exists only on the worker node → must spill over.
    node_id = ray_tpu.get(where.remote())
    head_id = ray_tpu.get_runtime_context().get_node_id()
    assert node_id != head_id


def test_cross_node_object_transfer(cluster):
    @ray_tpu.remote(resources={"special": 0.1})
    def produce():
        return np.arange(500_000, dtype=np.float64)  # 4MB, via shm store

    @ray_tpu.remote
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    # Consume with no affinity: may pull across raylets.
    total = ray_tpu.get(consume.remote(ref))
    assert total == float(np.arange(500_000, dtype=np.float64).sum())
    # Driver-side get also pulls to the head node store.
    arr = ray_tpu.get(ref)
    assert arr.shape == (500_000,)


def test_spread_scheduling(cluster):
    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def where():
        time.sleep(0.3)
        return ray_tpu.get_runtime_context().get_node_id()

    refs = [where.remote() for _ in range(4)]
    nodes = set(ray_tpu.get(refs))
    assert len(nodes) >= 2, f"SPREAD used only {nodes}"


def test_actor_on_remote_node_and_node_death(cluster):
    node = cluster.add_node(num_cpus=1, resources={"doomed": 1})
    cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"doomed": 1}, max_restarts=0)
    class Pinned:
        def ping(self):
            return "pong"

    a = Pinned.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"

    cluster.remove_node(node)
    # GCS health check marks the node dead; pending calls must fail.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(a.ping.remote(), timeout=5)
        except ray_tpu.exceptions.RayActorError:
            break
        except ray_tpu.exceptions.GetTimeoutError:
            pass
        time.sleep(0.5)
    else:
        pytest.fail("actor on dead node never reported as dead")
