"""SPMD pipeline parallelism (parallel/pipeline.py + models/gpt2_pp.py).

Correctness contract: the microbatched ppermute pipeline computes
exactly what the sequential stack computes (forward AND gradients), on
a real multi-device mesh.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from ray_tpu.models import gpt2  # noqa: E402
from ray_tpu.models.gpt2_pp import (  # noqa: E402
    make_pp_loss_fn,
    merge_pipeline_params,
    split_pipeline_params,
)
from ray_tpu.parallel.pipeline import (  # noqa: E402
    microbatch,
    pipeline_spmd,
    stack_stage_params,
)


def _mesh(pp):
    devs = jax.devices()
    if len(devs) < pp:
        pytest.skip(f"needs {pp} devices")
    return Mesh(np.array(devs[:pp]), ("pp",))


@pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 8)])
def test_pipeline_matches_sequential(pp, n_micro):
    mesh = _mesh(pp)
    rng = np.random.default_rng(0)
    Ws = [jnp.asarray(rng.standard_normal((8, 8)) * 0.3) for _ in range(pp)]
    bs = [jnp.asarray(rng.standard_normal(8) * 0.1) for _ in range(pp)]
    stage_params = stack_stage_params([{"w": w, "b": b} for w, b in zip(Ws, bs)])

    def stage_fn(p, x):
        return jax.nn.relu(x @ p["w"] + p["b"])

    pipe = pipeline_spmd(stage_fn, mesh, "pp")
    x = jnp.asarray(rng.standard_normal((16, 8)))
    out = jax.jit(pipe)(stage_params, microbatch(x, n_micro)).reshape(16, 8)
    ref = x
    for w, b in zip(Ws, bs):
        ref = jax.nn.relu(ref @ w + b)
    assert jnp.allclose(out, ref, atol=1e-5)


def test_pipeline_gradients_match():
    """grad through the scan+ppermute schedule == grad of the stack."""
    mesh = _mesh(2)
    rng = np.random.default_rng(1)
    Ws = [jnp.asarray(rng.standard_normal((6, 6)) * 0.3) for _ in range(2)]
    stage_params = stack_stage_params([{"w": w} for w in Ws])

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    pipe = pipeline_spmd(stage_fn, mesh, "pp")
    x = jnp.asarray(rng.standard_normal((8, 6)))

    def loss_pipe(sp):
        return (pipe(sp, microbatch(x, 4)) ** 2).sum()

    def loss_ref(sp):
        h = x
        for i in range(2):
            h = jnp.tanh(h @ sp["w"][i])
        return (h**2).sum()

    g_pipe = jax.jit(jax.grad(loss_pipe))(stage_params)
    g_ref = jax.grad(loss_ref)(stage_params)
    err = jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), g_pipe, g_ref
    )
    assert all(v < 1e-4 for v in jax.tree.leaves(err)), err


def test_gpt2_pp_loss_matches_unpipelined():
    pp = 2
    mesh = _mesh(pp)
    cfg = gpt2.GPT2Config.tiny(remat=False)
    params = gpt2.init_params(cfg)
    stage_params, rest = split_pipeline_params(params, cfg, pp)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (4, 32), dtype=np.int32)
    )
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    ref_loss = float(gpt2.loss_fn(params, inputs, targets, cfg))
    pp_loss_fn = make_pp_loss_fn(cfg, mesh, n_micro=2)
    pp_loss = float(jax.jit(pp_loss_fn)(stage_params, rest, inputs, targets))
    assert abs(pp_loss - ref_loss) < 1e-3, (pp_loss, ref_loss)
    # Round-trip of the param split (checkpoint interop).
    merged = merge_pipeline_params(stage_params, rest, cfg)
    err = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), merged, params)
    assert all(v == 0.0 for v in jax.tree.leaves(err))


def test_gpt2_pp_grads_flow():
    pp = 2
    mesh = _mesh(pp)
    cfg = gpt2.GPT2Config.tiny(remat=False)
    params = gpt2.init_params(cfg)
    stage_params, rest = split_pipeline_params(params, cfg, pp)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (4, 32), dtype=np.int32)
    )
    loss_fn = make_pp_loss_fn(cfg, mesh, n_micro=4)
    grads = jax.jit(jax.grad(loss_fn, argnums=(0, 1)))(
        stage_params, rest, tokens[:, :-1], tokens[:, 1:]
    )
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree.leaves(grads)]
    assert all(np.isfinite(n) for n in norms)
    assert sum(norms) > 0


@pytest.mark.parametrize("pp,v,n_micro", [(4, 1, 4), (4, 2, 4), (2, 2, 2), (4, 2, 2), (4, 2, 3)])
def test_gpt2_pp_interleaved_matches_unpipelined(pp, v, n_micro):
    """Non-uniform stages (embed/head IN the pipeline) + interleaved
    virtual chunks must still compute exactly the sequential loss."""
    from ray_tpu.models.gpt2_pp import (
        make_pp_loss_fn_interleaved,
        split_pipeline_params_interleaved,
    )

    mesh = _mesh(pp)
    cfg = gpt2.GPT2Config(
        vocab_size=128, n_layer=pp * v, n_head=2, d_model=32, max_seq_len=32,
        remat=False,
    )
    params = gpt2.init_params(cfg)
    first, chunks, last = split_pipeline_params_interleaved(params, cfg, pp, v)
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (n_micro * 2, 17), dtype=np.int32)
    )
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    ref_loss = float(gpt2.loss_fn(params, inputs, targets, cfg))
    loss_fn = make_pp_loss_fn_interleaved(cfg, mesh, n_micro=n_micro, n_virtual=v)
    pp_loss = float(jax.jit(loss_fn)(first, chunks, last, inputs, targets))
    assert abs(pp_loss - ref_loss) < 1e-3, (pp_loss, ref_loss)


def test_gpt2_pp_interleaved_grads_flow_through_all_stages():
    from ray_tpu.models.gpt2_pp import (
        make_pp_loss_fn_interleaved,
        split_pipeline_params_interleaved,
    )

    pp, v = 4, 2
    mesh = _mesh(pp)
    cfg = gpt2.GPT2Config(
        vocab_size=128, n_layer=pp * v, n_head=2, d_model=32, max_seq_len=32,
        remat=False,
    )
    params = gpt2.init_params(cfg)
    first, chunks, last = split_pipeline_params_interleaved(params, cfg, pp, v)
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, cfg.vocab_size, (8, 17), dtype=np.int32)
    )
    loss_fn = make_pp_loss_fn_interleaved(cfg, mesh, n_micro=4, n_virtual=v)
    grads = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))(
        first, chunks, last, tokens[:, :-1], tokens[:, 1:]
    )
    # EVERY stage's params must receive gradient — embed (first), all
    # pp*v block chunks, and the head (last)
    g_first, g_chunks, g_last = grads
    assert float(jnp.linalg.norm(g_first["wte"]["embedding"])) > 0
    assert float(jnp.linalg.norm(g_last["lm_head"]["kernel"])) > 0
    chunk_norms = jax.tree.map(lambda g: jnp.linalg.norm(g.reshape(pp * v, -1), axis=-1), g_chunks)
    per_chunk = sum(jax.tree.leaves(jax.tree.map(lambda n: np.asarray(n), chunk_norms)))
    assert (np.asarray(per_chunk) > 0).all(), per_chunk


def test_interleaved_bubble_fraction_smaller():
    """Same S=8 total stages: interleaving v=2 over pp=4 shrinks the
    bubble vs plain GPipe over 8 stages (the scheduling win the
    interleaved schedule exists for)."""
    from ray_tpu.parallel.pipeline import bubble_fraction

    m = 4
    gpipe = bubble_fraction(8, m, 1)          # 8 devices, 1 chunk each
    interleaved = bubble_fraction(4, m, 2)    # 4 devices, 2 chunks each
    assert interleaved < gpipe
    assert abs(interleaved - 3 / 11) < 1e-9
    assert abs(gpipe - 7 / 11) < 1e-9
