"""LLM serving plane: paged KV cache accounting, continuous batching,
decode parity, autoscaling, load shedding, chaos replica-kill.

Reference test model: vLLM engine tests + ray serve autoscaling tests,
scaled to CI size.  Engine-level tests run without a cluster (asyncio
only); the cluster tests ride the shared module fixture.
"""

import asyncio
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.exceptions import RequestShedError
from ray_tpu.serve.llm import BlockManager, LLMConfig, LLMEngine
from ray_tpu.serve.llm.engine import FINISHED
from ray_tpu.serve.llm.kv_cache import NoFreeBlocksError


@pytest.fixture(scope="module")
def serve_cluster(ray_cluster):
    yield ray_cluster
    serve.shutdown()


def _tiny(**kw) -> LLMConfig:
    base = dict(model="tiny", max_batch_size=4, num_blocks=64, block_size=8,
                default_max_tokens=8)
    base.update(kw)
    return LLMConfig(**base)


async def _drain(req):
    toks = []
    while True:
        ev = await req.out.get()
        if ev is FINISHED:
            return toks
        toks.append(ev["token"])


# ----------------------------------------------------------------------
# block manager: pure accounting
# ----------------------------------------------------------------------
def test_block_manager_accounting():
    bm = BlockManager(num_blocks=8, block_size=4)
    assert bm.free_blocks == 7  # block 0 reserved
    bm.allocate("a", 10)  # 3 blocks
    bm.allocate("b", 4)  # 1 block
    assert bm.blocks_in_use == 4
    # scratch block 0 is never handed out
    bm.advance("a", 10)
    assert all(bm.phys_index("a", p) >= bm.block_size for p in range(10))
    # growth beyond the reservation is refused, not silently corrupting
    with pytest.raises(NoFreeBlocksError):
        bm.advance("a", 3)
    # the pool bound is enforced
    with pytest.raises(NoFreeBlocksError):
        bm.allocate("c", 100)
    assert bm.free("a") == 3
    assert bm.free("a") == 0  # idempotent
    bm.free("b")
    assert bm.blocks_in_use == 0
    assert bm.leak_report()["total_allocs"] == bm.leak_report()["total_frees"]


def test_block_manager_phys_indices_padding():
    bm = BlockManager(num_blocks=8, block_size=4)
    bm.allocate("s", 6)
    bm.advance("s", 6)
    idx = bm.phys_indices("s", 6, 12)
    assert list(idx[6:]) == [0] * 6  # padded with the scratch slot
    # positions within one block are contiguous
    assert idx[1] == idx[0] + 1


# ----------------------------------------------------------------------
# engine: generation, parity, continuous batching, cancel, shed
# ----------------------------------------------------------------------
def test_engine_greedy_matches_full_forward():
    """The paged prefill/decode path must produce the SAME greedy tokens
    as re-running the full model over the growing sequence."""
    import jax

    from ray_tpu.models import gpt2

    async def main():
        eng = LLMEngine(_tiny(temperature=0.0))
        prompt = [3, 1, 4, 1, 5]
        req = await eng.add_request(prompt, max_tokens=6)
        toks = await _drain(req)
        await eng.stop()
        return eng, toks

    eng, toks = asyncio.run(main())
    cfg = eng.model_cfg
    params = gpt2.init_params(cfg, rng=jax.random.PRNGKey(eng.config.seed))
    import jax.numpy as jnp

    oracle = gpt2.generate_greedy(params, cfg, jnp.asarray([[3, 1, 4, 1, 5]]), 6)
    assert toks == [int(t) for t in oracle[0]], (toks, oracle)


def test_engine_no_leak_after_mixed_requests():
    async def main():
        eng = LLMEngine(_tiny())
        reqs = [
            await eng.add_request([1 + i, 2, 3], max_tokens=3 + (i % 5))
            for i in range(12)
        ]
        outs = await asyncio.gather(*[_drain(r) for r in reqs])
        for r, out in zip(reqs, outs):
            assert len(out) == r.max_tokens
            assert r.finish_reason == "length"
        report = eng.bm.leak_report()
        await eng.stop()
        return report

    report = asyncio.run(main())
    assert report["blocks_in_use"] == 0
    assert report["live_sequences"] == 0
    assert report["total_allocs"] == 12
    assert report["total_frees"] == 12


def test_engine_continuous_batch_join_at_step_boundary():
    """A late request must join the RUNNING batch at a step boundary and
    decode concurrently — not wait for the batch to drain."""

    async def main():
        eng = LLMEngine(_tiny(max_batch_size=2))
        long_req = await eng.add_request([1, 2], max_tokens=60)
        # let the long request get well into decode
        while long_req.generated < 5:
            await asyncio.sleep(0.01)
        late = await eng.add_request([3, 4], max_tokens=5)
        await asyncio.gather(_drain(long_req), _drain(late))
        report = eng.bm.leak_report()
        await eng.stop()
        return long_req, late, report

    long_req, late, report = asyncio.run(main())
    assert late.join_step < long_req.finish_step, (
        f"late joined at step {late.join_step}, long finished at "
        f"{long_req.finish_step} — no in-flight join happened"
    )
    assert late.finish_step <= long_req.finish_step
    assert report["blocks_in_use"] == 0


def test_engine_cancel_frees_blocks():
    async def main():
        eng = LLMEngine(_tiny())
        # cancel while WAITING (tiny batch keeps it queued)
        eng2 = LLMEngine(_tiny(max_batch_size=1))
        a = await eng2.add_request([1], max_tokens=200)
        b = await eng2.add_request([2], max_tokens=200)
        while a.generated < 1:
            await asyncio.sleep(0.01)
        assert b.slot < 0  # still waiting behind a
        eng2.cancel(b.request_id)
        ev = await b.out.get()
        assert ev is FINISHED
        assert b.finish_reason == "cancelled"
        # cancel while RUNNING (disconnect path: generator finally)
        while a.generated < 3:
            await asyncio.sleep(0.01)
        eng2.cancel(a.request_id)
        await _drain(a)
        # cancel settles at the next step boundary
        deadline = time.monotonic() + 5
        while eng2.bm.blocks_in_use and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        r2 = eng2.bm.leak_report()
        await eng2.stop()
        await eng.stop()
        return r2

    report = asyncio.run(main())
    assert report["blocks_in_use"] == 0
    assert report["live_sequences"] == 0


def test_engine_sheds_past_queue_bound():
    async def main():
        eng = LLMEngine(_tiny(max_batch_size=1, max_queue=2))
        first = await eng.add_request([0], max_tokens=120)
        while first.generated < 1:  # occupies the single lane
            await asyncio.sleep(0.01)
        held = [first] + [await eng.add_request([i], max_tokens=120) for i in (1, 2)]
        with pytest.raises(RequestShedError):
            await eng.add_request([9], max_tokens=4)
        for r in held:
            eng.cancel(r.request_id)
        for r in held:
            await _drain(r)
        await eng.stop()
        return eng.bm.leak_report()

    report = asyncio.run(main())
    assert report["blocks_in_use"] == 0


def test_engine_kv_pool_admission_blocks_then_completes():
    """When the pool can't hold another sequence the head-of-line waits
    (no overtaking) and is admitted once completions free blocks."""

    async def main():
        # pool: 15 usable blocks * 4 = 60 slots; each request needs
        # 2 + 30 tokens -> 8 blocks, so only one fits at a time
        eng = LLMEngine(LLMConfig(model="tiny", max_batch_size=4,
                                  num_blocks=16, block_size=4,
                                  max_model_len=32))
        a = await eng.add_request([1, 2], max_tokens=30)
        b = await eng.add_request([3, 4], max_tokens=30)
        while a.generated < 2:
            await asyncio.sleep(0.01)
        assert b.slot < 0  # parked on KV capacity, not a free lane
        out_a, out_b = await asyncio.gather(_drain(a), _drain(b))
        assert len(out_a) == 30 and len(out_b) == 30
        report = eng.bm.leak_report()
        await eng.stop()
        return report

    report = asyncio.run(main())
    assert report["blocks_in_use"] == 0


# ----------------------------------------------------------------------
# per-trace critical path (PR 2 carried follow-up)
# ----------------------------------------------------------------------
def test_critical_path_sequential_children():
    from ray_tpu.util.state import critical_path, group_traces

    def span(name, sid, parent, t0, t1):
        return {"name": name, "span_id": sid, "parent_span_id": parent,
                "trace_id": "t1", "start_time": t0, "end_time": t1, "pid": 1}

    group = [
        span("serve.request", "root", None, 0.0, 10.0),
        span("serve.queue", "q", "root", 0.0, 2.0),
        span("serve.prefill", "p", "root", 2.0, 3.0),
        span("serve.decode", "d", "root", 3.0, 10.0),
        # a concurrent sibling that overlaps decode: NOT on the path
        span("other", "o", "root", 4.0, 5.0),
    ]
    path = critical_path(group)
    names = [e["name"] for e in path]
    assert names == ["serve.request", "serve.queue", "serve.prefill", "serve.decode"]
    total = sum(e["duration_s"] for e in path if e["segment"])
    assert total == pytest.approx(10.0)
    traces = group_traces(group)
    assert traces[0]["critical_path_s"] == pytest.approx(10.0)
    assert [e["name"] for e in traces[0]["critical_path"]] == names


def test_engine_records_request_spans():
    """The engine's per-request spans land in the process span log and
    group into a trace whose critical path attributes queue/prefill/
    decode."""
    from ray_tpu.util import tracing
    from ray_tpu.util.state import group_traces

    tracing.drain_spans()  # isolate

    async def main():
        eng = LLMEngine(_tiny())
        req = await eng.add_request([1, 2, 3], max_tokens=4)
        await _drain(req)
        await eng.stop()

    asyncio.run(main())
    spans = tracing.drain_spans()
    mine = [s for s in spans if s["name"].startswith("serve.")]
    names = {s["name"] for s in mine}
    assert {"serve.request", "serve.queue", "serve.prefill", "serve.decode"} <= names
    traces = group_traces(mine)
    t = next(tr for tr in traces if "serve.request" in tr["root_names"])
    cp_names = [e["name"] for e in t["critical_path"]]
    assert cp_names[0] == "serve.request"
    assert "serve.decode" in cp_names


# ----------------------------------------------------------------------
# @serve.batch fixes (satellite): running-loop binding + shutdown cancel
# ----------------------------------------------------------------------
def test_batch_queue_binds_running_loop():
    """The batch worker must bind the loop the first call RUNS on — a
    non-default loop here (the old get_event_loop() bound the thread
    default and the worker never woke)."""

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.01)
    async def doubler(items):
        return [i * 2 for i in items]

    loop = asyncio.new_event_loop()  # NOT the thread's default loop
    try:
        out = loop.run_until_complete(asyncio.wait_for(doubler(21), timeout=5))
        for q in doubler._serve_batch_queues.values():
            q.shutdown()
        loop.run_until_complete(asyncio.sleep(0))  # let cancellation land
    finally:
        loop.close()
    assert out == 42


def test_replica_prepare_shutdown_cancels_batch_worker():
    from ray_tpu.serve._private.replica import Replica

    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.01)
        async def handle(self, items):
            return [i + 1 for i in items]

        async def __call__(self, x):
            return await self.handle(x)

    async def main():
        rep = Replica("r1", "dep", (Batched, (), {}), None, 10)
        out = await rep.handle_request("__call__", (1,), {})
        assert out == 2
        queues = rep.callable.handle._serve_batch_queues
        workers = [q._worker for q in queues.values() if q._worker is not None]
        assert workers and not any(w.done() for w in workers)
        await rep.prepare_shutdown()
        await asyncio.sleep(0)  # let cancellation propagate
        return workers

    workers = asyncio.run(main())
    assert all(w.done() for w in workers), "batch worker task leaked past shutdown"


# ----------------------------------------------------------------------
# cluster: serve integration, autoscaling, shedding, chaos
# ----------------------------------------------------------------------
def test_llm_serve_stream_and_oneshot(serve_cluster):
    from ray_tpu.serve import llm

    app = llm.build_app(_tiny(name="llm_basic"))
    handle = serve.run(app, name="llm_basic_app")
    out = handle.remote({"prompt": [1, 2, 3], "max_tokens": 5}).result(timeout=60)
    assert out["num_tokens"] == 5 and len(out["tokens"]) == 5
    events = list(handle.options(stream=True).generate.remote(
        {"prompt": "hi", "max_tokens": 4}
    ))
    assert [e["token"] for e in events if "token" in e.keys()][:4]
    assert events[-1]["done"] and events[-1]["num_tokens"] == 4
    # explicit cancel mid-stream frees blocks on the replica
    gen = handle.options(stream=True).generate.remote(
        {"prompt": "xy", "max_tokens": 400}
    )
    it = iter(gen)
    first = next(it)
    handle.cancel.remote(first["request_id"]).result(timeout=30)
    list(it)  # drains to the cancelled sentinel
    deadline = time.time() + 10
    while time.time() < deadline:
        st = handle.stats.remote().result(timeout=30)
        if st["kv_blocks_in_use"] == 0:
            break
        time.sleep(0.2)
    assert st["kv_blocks_in_use"] == 0, st["kv_leak_report"]
    serve.delete("llm_basic")


def test_autoscale_up_down_from_queue_depth(serve_cluster):
    """Synthetic queue depth reported via __serve_stats__ drives real
    replica add/remove through the controller's autoscaling_config."""

    @serve.deployment(
        name="synthload",
        num_replicas=1,
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 3,
            "target_ongoing_requests": 2.0,
            "upscale_delay_s": 0.5,
            "downscale_delay_s": 1.0,
        },
    )
    class SynthLoad:
        def __init__(self):
            self.depth = 0

        def set_depth(self, d):
            self.depth = d
            return d

        def __serve_stats__(self):
            return {"queued": self.depth}

        def __call__(self, payload):
            return "ok"

    handle = serve.run(SynthLoad.bind(), name="synthload_app")

    def running():
        return serve.status()["synthload"]["num_running"]

    # every replica reports depth 10 >> target 2 -> scale to max
    handle.set_depth.remote(10).result(timeout=30)
    deadline = time.time() + 60
    while time.time() < deadline and running() < 3:
        # new replicas start at depth 0; keep pushing load to all of them
        try:
            handle.set_depth.remote(10).result(timeout=30)
        except Exception:
            pass
        time.sleep(0.5)
    assert running() == 3, f"never scaled up: {running()} running"
    # drain: depth 0 everywhere -> scale back down to min
    for _ in range(6):
        try:
            handle.set_depth.remote(0).result(timeout=30)
        except Exception:
            pass
        time.sleep(0.3)
    deadline = time.time() + 60
    while time.time() < deadline and running() > 1:
        try:
            handle.set_depth.remote(0).result(timeout=30)
        except Exception:
            pass
        time.sleep(0.5)
    assert running() == 1, f"never scaled down: {running()} running"
    serve.delete("synthload")


def test_proxy_sheds_past_queue_bound(serve_cluster):
    """Past max_queued_requests the proxy sheds with 503 + Retry-After
    instead of queueing unboundedly; capacity returning un-sheds."""

    @serve.deployment(name="shedme", max_queued_requests=2, route_prefix="/shedme")
    class Slow:
        async def __call__(self, payload):
            await asyncio.sleep(1.0)
            return {"ok": True}

    serve.run(Slow.bind(), name="shed_app", http_port=18127)

    def call(results, i):
        req = urllib.request.Request(
            "http://127.0.0.1:18127/shedme",
            data=json.dumps({"i": i}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                results[i] = ("ok", resp.status, None)
        except urllib.error.HTTPError as e:
            results[i] = ("http_error", e.code, e.headers.get("Retry-After"))
        except Exception as e:  # noqa: BLE001
            results[i] = ("error", None, str(e))

    # wait until the route is live
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                "http://127.0.0.1:18127/-/routes", timeout=5
            ) as r:
                if "/shedme" in json.loads(r.read()):
                    break
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.3)
    results = {}
    threads = [
        threading.Thread(target=call, args=(results, i), daemon=True)
        for i in range(8)
    ]
    for t in threads:
        t.start()
        time.sleep(0.02)  # establish arrival order: first 2 admitted
    for t in threads:
        t.join(timeout=60)
    oks = [r for r in results.values() if r[0] == "ok"]
    sheds = [r for r in results.values() if r[0] == "http_error" and r[1] == 503]
    assert oks, results
    assert sheds, f"no 503s under overload: {results}"
    assert all(r[2] == "1" for r in sheds), "503 without Retry-After"
    # overload gone: requests flow again
    results2 = {}
    call(results2, 0)
    assert results2[0][0] == "ok", results2
    serve.delete("shedme")


def test_engine_shed_maps_to_503_over_http(serve_cluster):
    """A RequestShedError raised in the ENGINE (inside the replica, so
    it crosses the task boundary as a derived RayTaskError) must still
    surface as 503 + Retry-After at the proxy."""
    from ray_tpu.serve import llm

    app = llm.build_app(
        LLMConfig(model="tiny", max_batch_size=1, num_blocks=64, block_size=8,
                  max_queue=1, name="llm_eshed"),
        route_prefix="/eshed",
        max_ongoing_requests=64,
    )
    serve.run(app, name="llm_eshed_app", http_port=18127)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                "http://127.0.0.1:18127/-/routes", timeout=5
            ) as r:
                if "/eshed" in json.loads(r.read()):
                    break
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.3)

    def call(results, i):
        req = urllib.request.Request(
            "http://127.0.0.1:18127/eshed",
            data=json.dumps({"prompt": [i], "max_tokens": 100}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                results[i] = ("ok", resp.status, None)
        except urllib.error.HTTPError as e:
            results[i] = ("http_error", e.code, e.headers.get("Retry-After"))
        except Exception as e:  # noqa: BLE001
            results[i] = ("error", None, str(e))

    results = {}
    threads = [
        threading.Thread(target=call, args=(results, i), daemon=True)
        for i in range(10)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    sheds = [r for r in results.values() if r[1] == 503]
    oks = [r for r in results.values() if r[0] == "ok"]
    others = [r for r in results.values() if r[0] == "error" or r[1] not in (200, 503)]
    assert not others, f"engine shed surfaced as non-503: {results}"
    assert sheds, f"flood never shed through the engine bound: {results}"
    assert all(r[2] == "1" for r in sheds), f"503 without Retry-After: {sheds}"
    assert oks, results
    serve.delete("llm_eshed")


def test_llm_http_token_streaming_and_disconnect(serve_cluster):
    """HTTP chunked token streaming (one NDJSON event per token, the
    transport meta item stripped by the proxy), and client disconnect
    mid-stream releasing the request's KV blocks via the proxy's
    disconnect-cancel contract."""
    import http.client

    from ray_tpu.serve import llm

    app = llm.build_app(
        LLMConfig(model="tiny", max_batch_size=4, num_blocks=64,
                  block_size=8, name="llm_http"),
        route_prefix="/llm",
    )
    # the proxy is a singleton: reuse the module's proxy port
    serve.run(app, name="llm_http_app", http_port=18127)
    # wait for the route
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                "http://127.0.0.1:18127/-/routes", timeout=5
            ) as r:
                if "/llm" in json.loads(r.read()):
                    break
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.3)
    req = urllib.request.Request(
        "http://127.0.0.1:18127/llm",
        data=json.dumps({"prompt": "hey", "max_tokens": 5}).encode(),
        headers={"Content-Type": "application/json", "x-serve-stream": "1"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        lines = [json.loads(l) for l in resp.read().decode().splitlines() if l]
    tokens = [e for e in lines if "token" in e]
    assert len(tokens) == 5, lines
    assert lines[-1].get("done"), lines
    assert not any("__serve_stream_meta__" in e for e in lines), (
        "transport meta leaked to the client"
    )

    # disconnect mid-stream: read a little, then drop the connection —
    # the proxy must cancel the request so its blocks free
    conn = http.client.HTTPConnection("127.0.0.1", 18127, timeout=30)
    body = json.dumps({"prompt": "long", "max_tokens": 120})
    conn.request("POST", "/llm", body=body,
                 headers={"Content-Type": "application/json",
                          "x-serve-stream": "1"})
    resp = conn.getresponse()
    resp.read(40)  # a few token events
    conn.close()  # abandon the stream

    handle = serve.get_deployment_handle("llm_http")
    deadline = time.time() + 30
    st = None
    while time.time() < deadline:
        st = handle.stats.remote().result(timeout=30)
        if st["kv_blocks_in_use"] == 0 and st["waiting"] == 0 and st["running"] == 0:
            break
        time.sleep(0.3)
    assert st["kv_blocks_in_use"] == 0, f"KV leak after disconnect: {st['kv_leak_report']}"
    # the proxy stays healthy and keeps serving
    out = handle.remote({"prompt": [1], "max_tokens": 3}).result(timeout=60)
    assert out["num_tokens"] == 3
    serve.delete("llm_http")


@pytest.mark.slow  # ~17 s replica-kill drill: runs under `-m chaos`
@pytest.mark.chaos
def test_chaos_replica_kill_mid_stream(serve_cluster):
    """Kill one replica mid-load: its streams fail, streams on the
    survivor are unaffected, new requests re-route, the controller
    replaces the dead replica, and KV accounting on the survivor still
    balances to zero."""
    from ray_tpu.serve import llm
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    app = llm.build_app(
        LLMConfig(model="tiny", max_batch_size=4, num_blocks=128,
                  block_size=8, name="llm_chaos"),
        num_replicas=2,
    )
    handle = serve.run(app, name="llm_chaos_app")
    controller = ray_tpu.get_actor(CONTROLLER_NAME, "serve")

    def replica_actors():
        reps = ray_tpu.get(controller.get_replicas.remote("llm_chaos"))
        return {
            r["replica_id"]: ray_tpu.get_actor(r["actor_name"], "serve")
            for r in reps
        }

    deadline = time.time() + 60
    while time.time() < deadline and len(replica_actors()) < 2:
        time.sleep(0.5)
    actors = replica_actors()
    assert len(actors) == 2

    # open LONG streams (120 tokens ~ seconds of decode runway) so they
    # are genuinely in flight at kill time; "total" is the replica's
    # monotonic stream-request counter, so spread detection can't race
    # completions
    streams = []
    counts = {rid: 0 for rid in actors}
    deadline = time.time() + 60
    while time.time() < deadline and (
        len(streams) < 8 or not all(c >= 2 for c in counts.values())
    ):
        gen = handle.options(stream=True).generate.remote(
            {"prompt": [1, 2, 3], "max_tokens": 120}
        )
        it = iter(gen)
        first = next(it)  # established: first token arrived
        streams.append({"it": it, "first": first, "tokens": [first["token"]]})
        counts = {
            rid: ray_tpu.get(a.stats.remote()).get("total", 0)
            for rid, a in actors.items()
        }
        if len(streams) >= 20:
            break
    assert all(c >= 1 for c in counts.values()), f"streams never spread: {counts}"

    victim_id = max(counts, key=counts.get)
    survivor_id = next(rid for rid in counts if rid != victim_id)
    ray_tpu.kill(actors[victim_id])

    # drain every open stream: survivors complete, victim's streams fail
    completed, failed = 0, 0
    for s in streams:
        try:
            done_ev = None
            for ev in s["it"]:
                if "token" in ev:
                    s["tokens"].append(ev["token"])
                if ev.get("done"):
                    done_ev = ev
            assert done_ev is not None and done_ev["num_tokens"] == 120
            completed += 1
        except AssertionError:
            raise
        except Exception:  # noqa: BLE001 — the killed replica's streams
            failed += 1
    assert completed >= 1, "no stream survived the kill"
    assert failed >= 1, "the killed replica's streams vanished silently?"

    # new requests re-route to live replicas: the first attempt may race
    # the stale membership, but observing the death evicts the replica
    # from the router so retries converge immediately
    deadline = time.time() + 30
    out = None
    while time.time() < deadline:
        try:
            out = handle.remote({"prompt": [9], "max_tokens": 4}).result(timeout=60)
            break
        except Exception:  # noqa: BLE001 — raced the dead replica
            time.sleep(0.2)
    assert out is not None and out["num_tokens"] == 4, "re-route never converged"

    # the controller replaces the dead replica
    deadline = time.time() + 60
    while time.time() < deadline:
        reps = ray_tpu.get(controller.get_replicas.remote("llm_chaos"))
        if len(reps) == 2 and all(r["replica_id"] != victim_id for r in reps):
            break
        time.sleep(0.5)
    assert len(reps) == 2, f"dead replica never replaced: {reps}"

    # KV accounting on the survivor balances to zero
    survivor = actors[survivor_id]
    deadline = time.time() + 30
    while time.time() < deadline:
        st = ray_tpu.get(survivor.stats.remote())
        if st.get("kv_blocks_in_use") == 0:
            break
        time.sleep(0.3)
    assert st.get("kv_blocks_in_use") == 0, st.get("kv_leak_report")
    serve.delete("llm_chaos")
