"""Scalability-envelope stress tests (reference:
release/benchmarks/distributed/test_many_{actors,tasks,pgs}.py, scaled
to this one-core CI box; the full-size envelope numbers live in
BENCH_micro.json's stress_* entries, produced by bench_stress.py).

What must hold even under saturation:
- everything COMPLETES (no deadlocks, no lost tasks/actors/PGs)
- the GCS control plane degrades gracefully: its event-loop lag stays
  bounded (VERDICT r3 weak #3 — no death spiral)
- worker-spawn flow control keeps actor creation bursts from blowing
  registration deadlines (the failure mode this suite originally found)
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private import rpc
from ray_tpu.cluster_utils import Cluster

N_NODES = int(os.environ.get("STRESS_NODES", "20"))
N_ACTORS = int(os.environ.get("STRESS_ACTORS", "48"))
N_TASKS = int(os.environ.get("STRESS_TASKS", "5000"))
N_PGS = int(os.environ.get("STRESS_PGS", "40"))


@pytest.fixture(scope="module")
def big_cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    for _ in range(N_NODES - 1):
        c.add_node(num_cpus=4)
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _gcs_stats(cluster) -> dict:
    client = rpc.RpcClient(cluster.address)
    try:
        return client.call("gcs_stats", None, timeout=30)
    finally:
        client.close()


@pytest.mark.slow
def test_many_queued_tasks_complete(big_cluster):
    """Thousands of tasks queued at once across 20 raylets: all results
    arrive, none lost, GCS stays responsive."""

    @ray_tpu.remote(num_cpus=0.01, max_retries=3)
    def tiny(i):
        return i

    t0 = time.time()
    refs = [tiny.remote(i) for i in range(N_TASKS)]
    out = ray_tpu.get(refs, timeout=600)
    dt = time.time() - t0
    assert out == list(range(N_TASKS))
    stats = _gcs_stats(big_cluster)
    assert stats["num_nodes"] == N_NODES
    # graceful degradation bound: the control-plane loop may wobble
    # under a 5k-task storm on one core, but must not seize up
    assert stats["event_loop_lag_max_ms"] < 5000, stats
    print(f"\n{N_TASKS} tasks in {dt:.1f}s -> {N_TASKS / dt:.0f} tasks/s; gcs={stats}")


@pytest.mark.slow
def test_many_actors_create_and_respond(big_cluster):
    """An actor-creation burst completes without 'failed to start'
    (spawn flow control) and every actor answers."""

    @ray_tpu.remote(num_cpus=0.01)
    class Tiny:
        def ping(self):
            return os.getpid()

    t0 = time.time()
    actors = [Tiny.remote() for _ in range(N_ACTORS)]
    pids = ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
    dt = time.time() - t0
    assert len(set(pids)) == N_ACTORS  # each actor its own process
    stats = _gcs_stats(big_cluster)
    assert stats["event_loop_lag_max_ms"] < 5000, stats
    print(f"\n{N_ACTORS} actors in {dt:.1f}s -> {N_ACTORS / dt:.2f} actors/s; gcs={stats}")
    for a in actors:
        ray_tpu.kill(a)


@pytest.mark.slow
def test_placement_group_churn(big_cluster):
    """Create/use/remove placement groups in a loop — the 2-phase
    commit path must not leak bundles or wedge under churn."""
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    t0 = time.time()
    for i in range(N_PGS):
        pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
        assert pg.wait(timeout_seconds=60), f"pg {i} never ready"
        remove_placement_group(pg)
    dt = time.time() - t0
    stats = _gcs_stats(big_cluster)
    assert stats["num_placement_groups"] == 0, "removed PGs accumulated"
    assert stats["event_loop_lag_max_ms"] < 5000, stats
    print(f"\n{N_PGS} PG create/remove cycles in {dt:.1f}s -> {N_PGS / dt:.1f}/s; gcs={stats}")
