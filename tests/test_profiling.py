"""Profiling & bottleneck-attribution plane: the on-demand sampling
profiler (attach / dump / merge / export), its lifecycle edges
(conflict, dies mid-capture, raylet kill), the <5% attached-overhead
guard, JAX/XLA introspection, dataplane counters, and the bench
trajectory gate (reference: `ray timeline` + py-spy attach workflows).
"""

import json
import os
import signal
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import profiling as profiling_mod
from ray_tpu.util import state
from ray_tpu.util.profiling import ProfilerConflictError

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


@ray_tpu.remote
class Burner:
    """CPU-bound workload whose frames the profiler must attribute."""

    def burn_workload(self, seconds: float) -> int:
        deadline = time.monotonic() + seconds
        acc = 0
        while time.monotonic() < deadline:
            acc += sum(i * i for i in range(500))
        return acc

    def timed_burn(self, iters: int) -> float:
        t0 = time.perf_counter()
        acc = 0
        for _ in range(iters):
            acc += sum(i * i for i in range(2000))
        return time.perf_counter() - t0

    def getpid(self) -> int:
        return os.getpid()


def _busy_thread(seconds: float) -> threading.Thread:
    def busy():
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            sum(i * i for i in range(1000))

    t = threading.Thread(target=busy, daemon=True, name="busy-probe")
    t.start()
    return t


# ----------------------------------------------------------------------
# sampler core (in-process, no cluster)
# ----------------------------------------------------------------------
def test_sampler_captures_busy_thread_and_exports():
    _busy_thread(1.2)
    rep = profiling_mod.handle_profile_start(
        {"duration_s": 0.8, "hz": 100, "label": "local"}
    )
    time.sleep(0.9)
    rec = profiling_mod.handle_profile_dump({"session_id": rep["session_id"]})
    assert rec["sample_count"] > 0 and rec["ticks"] > 0
    collapsed = profiling_mod.collapse(rec)
    assert "busy" in collapsed
    # Every line is "stack count" with the label as root frame.
    for line in collapsed.strip().splitlines():
        stack, count = line.rsplit(" ", 1)
        assert stack.startswith("local;") and int(count) > 0
    ss = profiling_mod.speedscope([rec])
    json.dumps(ss)  # serializable
    prof = ss["profiles"][0]
    assert prof["type"] == "sampled" and len(prof["samples"]) == len(prof["weights"])
    assert all(
        i < len(ss["shared"]["frames"]) for s in prof["samples"] for i in s
    )


def test_sampler_cpu_mode_filters_idle_threads():
    """mode=cpu drops parked threads (per-thread CPU clocks): a sleeping
    thread contributes ~nothing, a spinning one dominates."""
    _busy_thread(2.0)
    rep = profiling_mod.handle_profile_start(
        {"duration_s": 1.2, "hz": 80, "mode": "cpu", "label": "cpu"}
    )
    time.sleep(1.3)
    rec = profiling_mod.handle_profile_dump({"session_id": rep["session_id"]})
    assert rec["sample_count"] > 0
    # The pytest main thread is parked in time.sleep during the whole
    # capture; with CPU filtering it must not dominate.
    busy = sum(c for s, c in rec["samples"].items() if "busy" in s)
    assert busy / rec["sample_count"] >= 0.5, rec["samples"]


def test_concurrent_attach_gets_typed_conflict_error():
    rep = profiling_mod.handle_profile_start({"duration_s": 5.0, "label": "first"})
    try:
        with pytest.raises(ProfilerConflictError) as err:
            profiling_mod.handle_profile_start({"duration_s": 1.0, "label": "second"})
        assert err.value.session_id == rep["session_id"]
    finally:
        profiling_mod.handle_profile_stop({"session_id": rep["session_id"]})
    # The stopped session frees the slot: a new attach succeeds (no leak).
    time.sleep(0.1)
    rep2 = profiling_mod.handle_profile_start({"duration_s": 0.2, "label": "third"})
    assert rep2["session_id"] != rep["session_id"]
    time.sleep(0.3)


def test_dump_after_natural_end_returns_cached_record():
    rep = profiling_mod.handle_profile_start({"duration_s": 0.2, "hz": 50, "label": "x"})
    time.sleep(0.5)  # capture ended on its own
    rec = profiling_mod.handle_profile_dump({"session_id": rep["session_id"]})
    assert rec["running"] is False
    assert rec["session_id"] == rep["session_id"]


def test_merge_records_keys_cluster_profile_by_label():
    a = {"label": "actor:tenantA/Foo", "samples": {"f1;f2": 3}, "sample_count": 3}
    b = {"label": "raylet:abcd1234", "samples": {"f1;f2": 2, "g": 1}, "sample_count": 3}
    merged = profiling_mod.merge_records([a, b])
    assert merged["actor:tenantA/Foo;f1;f2"] == 3
    assert merged["raylet:abcd1234;f1;f2"] == 2
    assert merged["raylet:abcd1234;g"] == 1


# ----------------------------------------------------------------------
# orchestrated capture on a live cluster (the acceptance criterion)
# ----------------------------------------------------------------------
def test_profile_live_actor_attributes_workload(cluster):
    """util.state.profile() on a live actor under load: the merged
    profile's top frames attribute >=80% of samples to the actor's
    actual workload, exported as both collapsed-stack and speedscope."""
    actor = Burner.remote()
    ray_tpu.get(actor.burn_workload.remote(0.01), timeout=60)  # actor up
    ref = actor.burn_workload.remote(8.0)

    result = state.profile(actor, duration_s=2.0, mode="cpu")
    assert result.errors == []
    assert result.total_samples > 0
    attribution = result.attribution("burn_workload")
    assert attribution >= 0.8, (
        f"only {attribution:.0%} of samples in the workload; "
        f"top: {result.top_frames(8)}"
    )
    collapsed = result.collapsed()
    assert collapsed.startswith("actor:") and "burn_workload" in collapsed
    ss = result.speedscope()
    assert ss["profiles"] and ss["profiles"][0]["samples"]
    json.dumps(ss)
    ray_tpu.get(ref, timeout=60)


def test_profile_ships_record_to_gcs_table(cluster):
    """End-of-capture records land in the GCS profile table
    (state.profiles) via the report channel — capture outlives driver."""
    actor = Burner.remote()
    ray_tpu.get(actor.burn_workload.remote(0.01), timeout=60)
    ref = actor.burn_workload.remote(3.0)
    result = state.profile(actor, duration_s=1.0)
    assert result.profiles, result.errors
    sid = result.profiles[0]["session_id"]
    deadline = time.monotonic() + 15
    shipped = []
    while time.monotonic() < deadline and not shipped:
        shipped = state.profiles(session_id=sid)
        # graftlint: disable=retry-gate -- deadline-bounded assertion poll; 0.3 s is the scan resolution, not a retry delay
        time.sleep(0.3)
    assert shipped and shipped[0]["session_id"] == sid
    ray_tpu.get(ref, timeout=60)


@pytest.mark.slow  # ~38 s kill drill: runs under `-m chaos`
@pytest.mark.chaos
def test_profiled_worker_dies_mid_capture_partial_no_leak(cluster):
    """SIGKILL the profiled worker mid-capture: the orchestration
    returns a partial result with an errors entry (no exception), and
    the next capture works — nothing leaks client-side."""
    victim = Burner.remote()
    pid = ray_tpu.get(victim.getpid.remote(), timeout=60)
    victim.burn_workload.remote(20.0)

    from ray_tpu.util import profiling as up

    gcs_call = state._gcs().call
    targets = up.resolve_targets(victim, gcs_call)

    killer = threading.Timer(1.0, lambda: os.kill(pid, signal.SIGKILL))
    killer.start()
    result = up.run_profile(
        targets, gcs_call, state._node_call, duration_s=3.0
    )
    killer.join()
    # The dump hit a dead socket: an errors entry, not an exception
    # (unless the end-of-capture ship beat the kill, which yields a
    # recovered record instead).
    assert result.errors or result.profiles

    # The plane still works for a fresh target afterwards.
    survivor = Burner.remote()
    ray_tpu.get(survivor.burn_workload.remote(0.01), timeout=60)
    ref = survivor.burn_workload.remote(4.0)
    again = state.profile(survivor, duration_s=1.0)
    assert again.profiles and again.total_samples > 0
    ray_tpu.get(ref, timeout=60)


def test_dashboard_profile_endpoint(cluster):
    """/api/profile drives the same orchestration with the dashboard's
    own clients (no connected driver) in all three formats."""
    from urllib import request as urlrequest

    url = cluster.dashboard_url
    if not url:
        pytest.skip("no dashboard in this session")
    actor = Burner.remote()
    ray_tpu.get(actor.burn_workload.remote(0.01), timeout=60)
    ref = actor.burn_workload.remote(6.0)
    aid = actor._actor_id.hex()
    with urlrequest.urlopen(
        f"{url}/api/profile?target={aid}&duration_s=1", timeout=30
    ) as r:
        body = json.loads(r.read())
    assert body["total_samples"] > 0 and not body["errors"]
    assert body["collapsed"].startswith("actor:")
    with urlrequest.urlopen(
        f"{url}/api/profile?target={aid}&duration_s=0.5&format=collapsed", timeout=30
    ) as r:
        assert b"burn_workload" in r.read()
    with urlrequest.urlopen(f"{url}/api/profiles", timeout=10) as r:
        assert isinstance(json.loads(r.read()), list)
    ray_tpu.get(ref, timeout=60)


# ----------------------------------------------------------------------
# overhead guard (the PR 2 <5% budget, extended to the attached profiler)
# ----------------------------------------------------------------------
def test_profiler_overhead_budget(cluster):
    """An actor workload with the profiler attached at the default Hz
    must run <5% slower than detached.  Wall-clock comparisons on the
    shared CI box swing with host load, so each condition takes the
    MINIMUM of several runs (the classic noise floor estimator) and the
    workload is timed inside the actor process."""
    actor = Burner.remote()
    iters = 150
    ray_tpu.get(actor.timed_burn.remote(iters), timeout=60)  # warm

    def best_of(n):
        return min(
            ray_tpu.get(actor.timed_burn.remote(iters), timeout=60) for _ in range(n)
        )

    base = best_of(4)
    # Attach at the default Hz for the whole measured window.
    info = state._gcs().call("get_actor_info", actor._actor_id.binary())
    start = state._node_call(
        info["worker_address"], "profile_start",
        {"duration_s": 60.0, "label": "overhead"},
    )
    try:
        attached = best_of(4)
    finally:
        state._node_call(
            info["worker_address"], "profile_dump",
            {"session_id": start["session_id"], "stop": True},
        )
    overhead = (attached - base) / base
    assert overhead < 0.05, (
        f"attached profiler overhead {overhead:.1%} >= 5% "
        f"(base {base * 1e3:.1f}ms, attached {attached * 1e3:.1f}ms)"
    )


def test_profiler_detached_zero_cost():
    """Detached = zero cost: no sampler thread survives a capture, no
    interpreter-level profile/trace hook is ever installed, and the
    execution path carries no per-call hooks (attach is a pure RPC
    surface)."""
    rep = profiling_mod.handle_profile_start({"duration_s": 0.2, "hz": 50, "label": "z"})
    time.sleep(0.5)
    rec = profiling_mod.handle_profile_dump({"session_id": rep["session_id"]})
    assert rec["running"] is False
    time.sleep(0.2)
    assert profiling_mod.active_session_id() is None
    assert not any(
        t.name.startswith("profile-sampler") and t.is_alive()
        for t in threading.enumerate()
    )
    assert sys.getprofile() is None and sys.gettrace() is None


# ----------------------------------------------------------------------
# JAX/XLA introspection
# ----------------------------------------------------------------------
def test_instrument_jit_counts_compiles_and_retraces():
    jax = pytest.importorskip("jax")
    jnp = jax.numpy

    f = profiling_mod.instrument_jit("probe_fn", jax.jit(lambda x: x * 3))
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))  # cached: no compile
    f(jnp.ones((16,)))  # new shape: retrace
    rec = profiling_mod.jit_stats("probe_fn")
    assert rec["compiles"] == 2
    assert rec["retraces"] == 1
    assert rec["compile_seconds"] > 0
    # cost_analysis captured at first trace (CPU supports it).
    assert rec["flops"] is not None


def test_instrument_jit_kill_switch_returns_unwrapped():
    jax = pytest.importorskip("jax")
    from ray_tpu._private.config import CONFIG

    CONFIG._overrides["jax_introspection"] = False
    try:
        jfn = jax.jit(lambda x: x + 1)
        assert profiling_mod.instrument_jit("killed", jfn) is jfn
    finally:
        CONFIG._overrides.pop("jax_introspection", None)


def test_report_device_memory_cpu_safe():
    pytest.importorskip("jax")
    # Must be a no-op (no exception) on backends without memory_stats.
    profiling_mod.report_device_memory(min_interval_s=0.0)


# ----------------------------------------------------------------------
# dataplane counters
# ----------------------------------------------------------------------
def test_channel_counters_and_occupancy(tmp_path):
    from ray_tpu.experimental.channel import Channel, ChannelTimeout

    path = str(tmp_path / "chan")
    Channel.create_file(path, 1 << 16)
    w = Channel(path)
    r = Channel(path)
    assert w.pending() is False
    w.write(b"x" * 100)
    assert w.pending() is True  # published, not yet acked
    assert r.read() == b"x" * 100
    assert w.pending() is False
    assert w.stats["writes"] == 1 and w.stats["bytes_written"] == 100
    assert r.stats["reads"] == 1 and r.stats["bytes_read"] == 100
    # A read with nothing published blocks, then times out -> counted.
    with pytest.raises(ChannelTimeout):
        r.read(timeout=0.1)
    assert r.stats["read_timeouts"] == 1
    assert r.stats["read_blocked_s"] > 0
    w.close()
    r.close()


def test_compiled_dag_stats_expose_dataplane(cluster):
    @ray_tpu.remote
    class Doubler:
        def double(self, x):
            return x * 2

    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        node = Doubler.bind().double.bind(inp)
    dag = node.experimental_compile()
    try:
        for i in range(5):
            assert ray_tpu.get(dag.execute(i)) == i * 2
        s = dag.stats()
        assert s["compiled"] is True
        assert s["executions"] == 5 and s["inflight"] == 0
        assert s["input_channels"][0]["writes"] == 5
        assert s["output_channels"][0]["reads"] == 5
    finally:
        dag.teardown()


# ----------------------------------------------------------------------
# bench trajectory gate
# ----------------------------------------------------------------------
def _gate():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_gate",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "scripts", "bench_gate.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_gate_refuses_cross_platform_comparison():
    gate = _gate()
    lineage = [
        {"round": 1, "parsed": {"metric": "m"}, "metric": "m", "value": 100.0,
         "on_tpu": True},
        {"round": 2, "parsed": {"metric": "m"}, "metric": "m", "value": 10.0,
         "on_tpu": False},  # 10x lower but CPU: must be a SKIP, not a regression
    ]
    result = gate.check_lineage(lineage)
    assert result["regressions"] == []
    assert any("CROSS-PLATFORM" in s["reason"] for s in result["skips"])


def test_bench_gate_skips_missing_provenance():
    gate = _gate()
    lineage = [
        {"round": 1, "parsed": {"metric": "m"}, "metric": "m", "value": 100.0,
         "on_tpu": None},
    ]
    result = gate.check_lineage(lineage)
    assert result["regressions"] == [] and result["ok"] == []
    assert any("PROVENANCE" in s["reason"] for s in result["skips"])


def test_bench_gate_flags_like_for_like_regression():
    gate = _gate()
    lineage = [
        {"round": 1, "parsed": {"metric": "m"}, "metric": "m", "value": 100.0,
         "on_tpu": True},
        {"round": 2, "parsed": {"metric": "m"}, "metric": "m", "value": 80.0,
         "on_tpu": True},  # -20% on the same platform
        {"round": 3, "parsed": {"metric": "m"}, "metric": "m", "value": 79.0,
         "on_tpu": True},  # -1.2% vs round 2: fine
    ]
    result = gate.check_lineage(lineage)
    assert len(result["regressions"]) == 1
    reg = result["regressions"][0]
    assert reg["from_round"] == 1 and reg["to_round"] == 2
    assert len(result["ok"]) == 1


def test_bench_gate_rate_metrics_are_throughputs():
    """`*_per_s` / `*_per_sec` metrics end in a seconds-ish suffix but
    are throughputs: a drop must flag, a rise must not (the BENCH_micro
    `put_small_per_s` class)."""
    gate = _gate()
    assert gate._higher_is_better("put_small_per_s")
    assert gate._higher_is_better("ppo_env_steps_per_sec")
    assert not gate._higher_is_better("serve_ttft_seconds")
    result = gate.compare_metric_dicts(
        {"put_small_per_s": {"value": 1900.0, "on_tpu": False}},
        {"put_small_per_s": {"value": 1000.0, "on_tpu": False}},
    )
    assert len(result["regressions"]) == 1  # 47% throughput drop flags
    result_up = gate.compare_metric_dicts(
        {"put_small_per_s": {"value": 1900.0, "on_tpu": False}},
        {"put_small_per_s": {"value": 2500.0, "on_tpu": False}},
    )
    assert result_up["regressions"] == []  # improvement is not a regression


def test_bench_gate_latency_direction():
    gate = _gate()
    lineage = [
        {"round": 1, "parsed": {"metric": "p99_latency_seconds"},
         "metric": "p99_latency_seconds", "value": 1.0, "on_tpu": False},
        {"round": 2, "parsed": {"metric": "p99_latency_seconds"},
         "metric": "p99_latency_seconds", "value": 1.5, "on_tpu": False},
    ]
    result = gate.check_lineage(lineage)
    assert len(result["regressions"]) == 1  # latency UP = regression


def test_bench_gate_platform_field_beats_on_tpu():
    """Two non-TPU captures on DIFFERENT backends (gpu vs cpu) must not
    be scored like-for-like just because on_tpu is False on both."""
    gate = _gate()
    lineage = [
        {"round": 1, "parsed": {"metric": "m"}, "metric": "m", "value": 100.0,
         "on_tpu": False, "platform": "gpu"},
        {"round": 2, "parsed": {"metric": "m"}, "metric": "m", "value": 10.0,
         "on_tpu": False, "platform": "cpu"},
    ]
    result = gate.check_lineage(lineage)
    assert result["regressions"] == []
    assert any("CROSS-PLATFORM" in s["reason"] for s in result["skips"])


def test_bench_gate_legacy_on_tpu_comparable_with_platform_stamped():
    """A legacy on_tpu-only capture must still score against a newer
    platform-stamped capture of the same on_tpu value (the coarse
    evidence doesn't contradict the fine) — r05 (on_tpu:false) vs a
    new platform:'cpu' capture is the live case."""
    gate = _gate()
    lineage = [
        {"round": 5, "parsed": {"metric": "m"}, "metric": "m", "value": 100.0,
         "on_tpu": False},  # legacy: no platform field
        {"round": 6, "parsed": {"metric": "m"}, "metric": "m", "value": 50.0,
         "on_tpu": False, "platform": "cpu"},
    ]
    result = gate.check_lineage(lineage)
    assert len(result["regressions"]) == 1  # scored, and the -50% flags
    # And a TPU capture after a CPU blip still scores against the last
    # TPU point, not the blip.
    lineage2 = [
        {"round": 3, "parsed": {"metric": "m"}, "metric": "m", "value": 100.0,
         "on_tpu": True, "platform": "tpu"},
        {"round": 5, "parsed": {"metric": "m"}, "metric": "m", "value": 10.0,
         "on_tpu": False, "platform": "cpu"},
        {"round": 6, "parsed": {"metric": "m"}, "metric": "m", "value": 95.0,
         "on_tpu": True, "platform": "tpu"},
    ]
    result2 = gate.check_lineage(lineage2)
    assert result2["regressions"] == []
    assert any(c["from_round"] == 3 and c["to_round"] == 6 for c in result2["ok"])


def test_profile_foreign_session_is_error_not_shared(cluster):
    """A conflict with a session some OTHER operator started must
    surface as an error (the target's samples are missing from this
    result), not as a benign co-hosted 'shared' note."""
    actor = Burner.remote()
    ray_tpu.get(actor.burn_workload.remote(0.01), timeout=60)
    info = state._gcs().call("get_actor_info", actor._actor_id.binary())
    foreign = state._node_call(
        info["worker_address"], "profile_start",
        {"duration_s": 30.0, "label": "operator-A"},
    )
    try:
        result = state.profile(actor, duration_s=0.5)
        assert result.shared == []
        assert result.errors and "busy" in result.errors[0]["error"]
        assert foreign["session_id"] in result.errors[0]["error"]
    finally:
        state._node_call(
            info["worker_address"], "profile_stop",
            {"session_id": foreign["session_id"]},
        )


def test_bench_gate_compare_refuses_missing_provenance():
    """--compare on provenance-less metric dicts must skip loudly, not
    score (same contract as the lineage path)."""
    gate = _gate()
    result = gate.compare_metric_dicts(
        {"m": {"value": 100.0}}, {"m": {"value": 10.0}}
    )
    assert result["regressions"] == []
    assert any("PROVENANCE" in s["reason"] for s in result["skips"])


def test_bench_gate_skips_error_records():
    """An infra-failure record (error key, value 0) must never score as
    a like-for-like regression against a real capture."""
    gate = _gate()
    lineage = [
        {"round": 1, "parsed": {"metric": "m"}, "metric": "m", "value": 100.0,
         "on_tpu": False},
        {"round": 2, "parsed": {"metric": "m", "error": "tunnel wedged"},
         "metric": "m", "value": 0.0, "on_tpu": False},
    ]
    result = gate.check_lineage(lineage)
    assert result["regressions"] == []
    assert any("BENCH FAILED" in s["reason"] for s in result["skips"])
    dict_result = gate.compare_metric_dicts(
        {"m": {"value": 100.0, "on_tpu": False}},
        {"m": {"value": 0.0, "on_tpu": False, "error": "oom"}},
    )
    assert dict_result["regressions"] == []
    assert any("BENCH FAILED" in s["reason"] for s in dict_result["skips"])


def test_resolve_targets_rejects_unknown_types():
    """A wrong-typed target must raise, not silently widen to a
    cluster-wide capture."""
    from ray_tpu.util import profiling as up

    def must_not_call(method, payload, *a):
        raise AssertionError(f"gcs_call reached for bad target: {method}")

    with pytest.raises(ValueError):
        up.resolve_targets(123, must_not_call)
    with pytest.raises(ValueError):
        up.resolve_targets(b"\x01\x02", must_not_call)


def test_bench_gate_warn_only_exit_code(tmp_path):
    gate = _gate()
    # A real regression in a scratch lineage: strict fails, warn passes.
    for n, value in ((1, 100.0), (2, 50.0)):
        with open(tmp_path / f"BENCH_r0{n}.json", "w") as f:
            json.dump({"n": n, "parsed": {
                "metric": "m", "value": value, "on_tpu": True}}, f)
    assert gate.main(["--repo", str(tmp_path)]) == 1
    assert gate.main(["--repo", str(tmp_path), "--warn-only"]) == 0


def test_bench_gate_checked_in_lineage_warn_only():
    """The verify.sh invocation must succeed against the real lineage
    (r04/r05 off-TPU captures are skips, not regressions)."""
    gate = _gate()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert gate.main(["--repo", repo, "--warn-only"]) == 0


# ----------------------------------------------------------------------
# chaos drill: capture survives its raylet dying
# ----------------------------------------------------------------------
@pytest.mark.slow  # ~39 s raylet-kill drill: runs under `-m chaos`
@pytest.mark.chaos
def test_profile_worker_through_raylet_kill():
    """SIGKILL the raylet of the node hosting the profiled actor while
    a capture is running.  The worker's direct RPC endpoint is
    independent of the raylet, so the attach either rides out the kill
    (dump succeeds with workload samples) or degrades to the partial
    path (errors entry) — never an exception, and the plane stays
    usable on the surviving node."""
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()  # the module fixture's single-node session
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    node = c.add_node(num_cpus=1, resources={"side": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    try:
        @ray_tpu.remote(resources={"side": 0.5})
        class SideBurner:
            def burn_workload(self, seconds):
                deadline = time.monotonic() + seconds
                acc = 0
                while time.monotonic() < deadline:
                    acc += sum(i * i for i in range(500))
                return acc

        actor = SideBurner.remote()
        ray_tpu.get(actor.burn_workload.remote(0.01), timeout=60)
        actor.burn_workload.remote(20.0)

        from ray_tpu.util import profiling as up

        gcs_call = state._gcs().call
        targets = up.resolve_targets(actor, gcs_call)
        killer = threading.Timer(0.8, lambda: c.remove_node(node))
        killer.start()
        result = up.run_profile(
            targets, gcs_call, state._node_call, duration_s=2.5
        )
        killer.join()
        assert result.profiles or result.errors
        if result.profiles:
            # The worker outlived its raylet: samples attribute to the
            # workload as usual.
            assert result.total_samples > 0
            assert "burn_workload" in result.collapsed()

        # Plane still works on the head node afterwards.
        head_actor = Burner.remote()
        ray_tpu.get(head_actor.burn_workload.remote(0.01), timeout=60)
        ref = head_actor.burn_workload.remote(4.0)
        again = state.profile(head_actor, duration_s=1.0)
        assert again.profiles and again.total_samples > 0
        ray_tpu.get(ref, timeout=60)
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_bench_gate_compare_metric_dicts_cross_platform():
    gate = _gate()
    old = {"m": {"value": 100.0, "on_tpu": True}}
    new = {"m": {"value": 10.0, "on_tpu": False}}
    result = gate.compare_metric_dicts(old, new)
    assert result["regressions"] == []
    assert any("CROSS-PLATFORM" in s["reason"] for s in result["skips"])
    # like-for-like regression flags
    result2 = gate.compare_metric_dicts(
        {"m": {"value": 100.0, "on_tpu": False}},
        {"m": {"value": 60.0, "on_tpu": False}},
    )
    assert len(result2["regressions"]) == 1
