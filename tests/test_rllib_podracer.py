"""Podracer RLlib streaming plane: fragments over compiled channels,
staleness bound, runner-kill chaos drill, flow-control backpressure.

Reference test model: the PR 11 channel edge-case suite applied to the
rllib workload — the drills here are the acceptance criteria of the
podracer restructure (ISSUE 12): a dead runner never stalls or corrupts
the learner, a stale runner is refreshed before its data is consumed,
and a slow learner parks runners without dropping or reordering."""

import time

import numpy as np
import pytest

import ray_tpu


def _ppo_podracer_cfg(**overrides):
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=2, num_envs_per_env_runner=4, rollout_fragment_length=32
        )
        .podracer()
        .training(lr=3e-4, train_batch_size=256, minibatch_size=64, num_epochs=2)
        .debugging(seed=1)
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def test_podracer_ppo_streams_over_channels(ray_cluster):
    """The restructured PPO trains off streamed fragments: channels
    attached (ring transport on one node), generations advance, GAE is
    no longer computed host-side (fragments carry raw columns)."""
    algo = _ppo_podracer_cfg().build()
    try:
        out1 = algo.train()
        out2 = algo.train()
        assert out1["num_env_steps_sampled"] > 0
        assert out2["weight_generation"] > out1["weight_generation"]
        assert out2["fragments_received"] > 0
        plane = algo.env_runner_group
        # same-node runners ride shm rings (compile-time placement rule)
        assert all(rs.traj.kind == "ring" for rs in plane.streams if rs.alive)
        assert np.isfinite(out2["total_loss"])
    finally:
        algo.cleanup()


def test_podracer_impala_async_updates(ray_cluster):
    """IMPALA podracer: per-fragment fused V-trace updates off the
    stream; sampling never waits on SGD (generation outruns iteration
    count when multiple fragments drain per step)."""
    from ray_tpu.rllib import IMPALAConfig

    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4)
        .podracer()
        .training(lr=5e-4, rollout_fragment_length=32)
        .debugging(seed=3)
    )
    algo = cfg.build()
    try:
        steps = 0
        for _ in range(4):
            out = algo.train()
            steps += out["num_env_steps_sampled"]
        assert steps > 0
        assert out["num_updates"] >= 4
        assert np.isfinite(out["total_loss"])
    finally:
        algo.cleanup()


def test_podracer_chaos_runner_kill_mid_stream(ray_cluster):
    """Kill one env runner mid-stream: the learner keeps consuming the
    survivor's fragments (zero failed updates), and the replacement
    runner joins at the CURRENT weight generation."""
    algo = _ppo_podracer_cfg().build()
    try:
        algo.train()
        plane = algo.env_runner_group
        drv = algo._podracer
        victim = plane.streams[0]
        gen_at_kill = drv.generation
        ray_tpu.kill(victim.actor)
        time.sleep(1.0)  # death report propagates to the GCS actor table
        # learner keeps training through the death + replacement window
        updates_before = drv.updates
        for _ in range(3):
            out = algo.train()
            assert out["num_env_steps_sampled"] > 0
        assert drv.updates == updates_before + 3  # zero failed updates
        assert plane.runner_deaths >= 1
        assert plane.replacements >= 1
        # the replacement joined at (or past) the generation current at
        # respawn time — never at the dead runner's stale generation
        assert plane.streams[0].alive
        assert plane.streams[0].last_gen >= gen_at_kill
        # and its fragments flow: both worker indices appear again
        workers = set()
        deadline = time.monotonic() + 60
        while len(workers) < 2 and time.monotonic() < deadline:
            for frag in drv.collect(2):
                workers.add(frag["worker"])
        assert workers == {1, 2}
    finally:
        algo.cleanup()


def test_podracer_staleness_bound_refreshes_runner(ray_cluster):
    """A runner more than max_weight_lag generations behind is refreshed
    BEFORE its fragments are consumed: over-stale fragments are dropped,
    the refresh pushes current weights, and the next consumed fragment
    is inside the bound."""
    cfg = _ppo_podracer_cfg(max_weight_lag=1)
    algo = cfg.build()
    try:
        algo.train()
        plane = algo.env_runner_group
        drv = algo._podracer
        # Simulate the learner racing ahead of the broadcast plane: bump
        # generations with publishes suppressed so every in-flight
        # fragment goes over-stale.
        real_broadcast = plane.broadcast
        plane.broadcast = lambda *a, **k: None
        try:
            for _ in range(4):
                drv.after_update()  # gen += 4, nothing published
        finally:
            plane.broadcast = real_broadcast
        dropped_before = drv.stale_dropped
        frags = drv.collect(2, timeout=60.0)
        # stale fragments were dropped and their runners refreshed
        # (refresh writes directly, bypassing the suppressed broadcast)
        assert drv.stale_dropped > dropped_before
        for frag in frags:
            assert drv.generation - frag["gen"] <= 1
    finally:
        algo.cleanup()


def test_podracer_backpressure_parks_never_drops(ray_cluster):
    """A slow learner parks runners via channel flow control: with a
    tiny ring + bounded queue the runner stalls after the pipeline
    fills, and once draining resumes every fragment arrives exactly
    once, in order (per-runner seq contiguous from 1)."""
    import jax

    from ray_tpu.rllib import RLModuleSpec
    from ray_tpu.rllib.core.stream import TrajectoryPlane

    import gymnasium as gym

    creator = lambda: gym.make("CartPole-v1")  # noqa: E731
    probe = creator()
    spec = RLModuleSpec.from_gym_env(probe, hidden=(8,))
    probe.close()
    plane = TrajectoryPlane(
        creator,
        spec,
        num_env_runners=1,
        num_envs_per_runner=2,
        fragment_length=16,
        seed=0,
        trajectory_queue_size=2,
        traj_capacity=48 * 1024,  # a few dozen fragments, then the park
    )
    module = spec.build()
    weights = module.get_weights(module.init(jax.random.PRNGKey(0)))
    try:
        plane.start(weights, generation=1)
        # do NOT consume: pipeline fills (queue 2 + ring), runner parks
        time.sleep(2.5)
        # Freeze production so the drain below counts exactly what the
        # parked pipeline held (buffered ring records survive writer
        # death: wbytes publishes only after the payload is in place).
        plane.restart_failed = False
        ray_tpu.kill(plane.streams[0].actor)
        time.sleep(2.5)  # graceful-exit push escalates to SIGKILL at 2 s
        seqs = []
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            frag = plane.get_fragment(timeout=0.5)
            if frag is None:
                break
            seqs.append(frag["seq"])
            if len(seqs) > 200:
                break
        # parked: a free-running CartPole runner makes hundreds of
        # fragments in 2.5 s; flow control bounded it to the pipeline
        # depth (queue 2 + what a 48 KiB ring holds)
        assert 2 <= len(seqs) <= 64, seqs
        # never dropped, never reordered: contiguous from 1
        assert seqs == list(range(1, len(seqs) + 1)), seqs
    finally:
        plane.stop()


def test_podracer_same_node_weight_fanout(ray_cluster):
    """Same-node anakin runners share ONE fan-out weight ring: a single
    broadcast write covers the whole cohort (no per-runner snapshot
    copies) and generations keep advancing for every member."""
    algo = _ppo_podracer_cfg().build()
    try:
        out1 = algo.train()
        plane = algo.env_runner_group
        # both same-node runners were placed on the shared fan-out ring
        assert plane._fanout is not None
        cohort = [rs for rs in plane.streams if rs.fanout_index is not None]
        assert len(cohort) == 2
        assert sorted(rs.fanout_index for rs in cohort) == [0, 1]
        assert all(rs.weights is plane._fanout for rs in cohort)
        # one shared write advances the whole cohort's generation
        out2 = algo.train()
        assert out2["weight_generation"] > out1["weight_generation"]
        assert all(rs.last_gen > 0 for rs in cohort)
    finally:
        algo.cleanup()


@pytest.mark.chaos
@pytest.mark.slow  # replacement runner pays a cold JIT compile (~1 min)
def test_podracer_fanout_member_kill_replacement(ray_cluster):
    """A killed fan-out cohort member's replacement comes back on a
    DEDICATED ring (fan-out reader slots tombstone on eviction) while
    the survivor keeps streaming from the shared ring."""
    algo = _ppo_podracer_cfg().build()
    try:
        algo.train()
        plane = algo.env_runner_group
        cohort = [rs for rs in plane.streams if rs.fanout_index is not None]
        assert len(cohort) == 2
        # kill one cohort member: the replacement must NOT rejoin the
        # shared ring (its reader slot is evicted/tombstoned) — it gets
        # a dedicated weight channel and still receives broadcasts
        victim = cohort[0]
        ray_tpu.kill(victim.actor)
        time.sleep(1.0)  # death report propagates to the GCS actor table
        for _ in range(3):
            algo.train()
        assert plane.replacements >= 1
        replaced = plane.streams[victim.index]
        assert replaced.alive
        assert replaced.fanout_index is None
        assert replaced.weights is not plane._fanout
        # fragments flow from both worker indices again (generous
        # deadline: the replacement runner pays a cold JIT compile)
        workers = set()
        drv = algo._podracer
        deadline = time.monotonic() + 120
        while len(workers) < 2 and time.monotonic() < deadline:
            for frag in drv.collect(2):
                workers.add(frag["worker"])
        assert workers == {1, 2}
    finally:
        algo.cleanup()


@pytest.mark.slow
def test_podracer_sebulba_inference_server(ray_cluster):
    """Sebulba split: action selection served by the shared
    continuous-batching inference server; fragments carry the server's
    weight generation."""
    cfg = _ppo_podracer_cfg(policy_mode="sebulba")
    cfg.rollout_fragment_length = 16
    cfg.train_batch_size = 128
    algo = cfg.build()
    try:
        out = algo.train()
        assert out["num_env_steps_sampled"] > 0
        out = algo.train()
        assert out["weight_generation"] >= 2
        assert np.isfinite(out["total_loss"])
    finally:
        algo.cleanup()


@pytest.mark.slow
def test_podracer_ppo_learns_cartpole(ray_cluster):
    """Reward gate: the streaming pipeline (in-jit GAE + staleness bound
    + async weight publish) must still learn CartPole."""
    cfg = _ppo_podracer_cfg()
    cfg.train_batch_size = 1024
    cfg.num_epochs = 6
    cfg.entropy_coeff = 0.01
    algo = cfg.build()
    best = 0.0
    try:
        for _ in range(30):
            out = algo.train()
            if out.get("episode_return_mean"):
                best = max(best, out["episode_return_mean"])
            if best > 120:
                break
    finally:
        algo.cleanup()
    assert best > 120, f"streaming PPO failed to learn CartPole: best={best}"


def test_in_jit_gae_matches_host_gae():
    """The fused update's in-jit GAE (prepare_fragments) must match the
    synchronous path's per-episode host GAE on the same data, including
    a mid-fragment termination and the fragment-end bootstrap."""
    import jax.numpy as jnp

    from ray_tpu.rllib.algorithms.ppo import PPOLearner
    from ray_tpu.rllib.core.rl_module import RLModuleSpec
    from ray_tpu.rllib.utils.postprocessing import compute_gae
    from ray_tpu.rllib.utils.sample_batch import (
        ACTIONS,
        ADVANTAGES,
        LOGP,
        LOSS_MASK,
        OBS,
        REWARDS,
        SampleBatch,
        TERMINATEDS,
        TRUNCATEDS,
        VALUE_TARGETS,
        VF_PREDS,
    )

    spec = RLModuleSpec(observation_dim=4, action_dim=2, discrete=True, hidden=(8,))
    lrn = PPOLearner(spec, {"gamma": 0.9, "lambda_": 0.95})
    T = 8
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=(T, 1)).astype(np.float32)
    values = rng.normal(size=(T, 1)).astype(np.float32)
    term = np.zeros((T, 1), bool)
    term[3, 0] = True
    trunc = np.zeros((T, 1), bool)
    last_v = np.array([0.37], np.float32)
    cols = {
        VF_PREDS: jnp.asarray(values),
        REWARDS: jnp.asarray(rewards),
        TERMINATEDS: jnp.asarray(term),
        TRUNCATEDS: jnp.asarray(trunc),
        LOSS_MASK: jnp.ones((T, 1), jnp.float32),
        OBS: jnp.zeros((T, 1, 4)),
        ACTIONS: jnp.zeros((T, 1), jnp.int32),
        LOGP: jnp.zeros((T, 1)),
    }
    out = lrn.prepare_fragments(cols, jnp.asarray(last_v))
    adv_jit = np.asarray(out[ADVANTAGES])[:, 0]
    tgt_jit = np.asarray(out[VALUE_TARGETS])[:, 0]
    b1 = compute_gae(
        SampleBatch({REWARDS: rewards[:4, 0], VF_PREDS: values[:4, 0],
                     TERMINATEDS: term[:4, 0], TRUNCATEDS: trunc[:4, 0]}),
        0.0, 0.9, 0.95,
    )
    b2 = compute_gae(
        SampleBatch({REWARDS: rewards[4:, 0], VF_PREDS: values[4:, 0],
                     TERMINATEDS: term[4:, 0], TRUNCATEDS: trunc[4:, 0]}),
        float(last_v[0]), 0.9, 0.95,
    )
    adv_host = np.concatenate([b1[ADVANTAGES], b2[ADVANTAGES]])
    tgt_host = np.concatenate([b1[VALUE_TARGETS], b2[VALUE_TARGETS]])
    np.testing.assert_allclose(tgt_jit, tgt_host, rtol=1e-5)
    std = (adv_host - adv_host.mean()) / max(1e-8, adv_host.std())
    np.testing.assert_allclose(adv_jit, std, rtol=1e-4, atol=1e-5)
