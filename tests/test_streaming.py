"""Streaming generators (num_returns="streaming") + streaming Data reads.

Reference semantics being matched: ObjectRefGenerator / generator_waiter.h
(python/ray/_raylet.pyx) — refs are yielded in order as the task produces
them, errors re-raise at the failure position, and Data consumes read
streams so the first block arrives before the last file is read.
"""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def ray():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def test_stream_basic_order():
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(8)
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    assert [ray_tpu.get(r) for r in g] == [i * 10 for i in range(8)]


def test_stream_incremental_arrival():
    """The first yield is consumable while the producer still runs."""

    @ray_tpu.remote(num_returns="streaming")
    def slowgen():
        for i in range(3):
            yield i
            time.sleep(0.8)

    g = slowgen.remote()
    t0 = time.monotonic()
    assert ray_tpu.get(next(g)) == 0
    assert time.monotonic() - t0 < 0.7  # producer needs ~2.4s total
    assert [ray_tpu.get(r) for r in g] == [1, 2]


def test_stream_empty():
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        return iter(())

    assert list(empty.remote()) == []


def test_stream_error_after_items():
    """Items yielded before the failure stay consumable; the error
    re-raises at the failure position."""

    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad():
        yield "ok"
        raise ValueError("boom")

    g = bad.remote()
    assert ray_tpu.get(next(g)) == "ok"
    with pytest.raises(ray_tpu.exceptions.RayTaskError):
        next(g)


def test_stream_large_items():
    """Items above the inline cap go through the object store."""
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def bigs():
        for i in range(3):
            yield np.full(300_000, i, dtype=np.float64)

    sums = [float(ray_tpu.get(r).sum()) for r in bigs.remote()]
    assert sums == [0.0, 300_000.0, 600_000.0]


def test_stream_non_generator_errors():
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def notgen():
        return 42

    g = notgen.remote()
    with pytest.raises(ray_tpu.exceptions.RayTaskError):
        next(g)


def test_stream_next_timeout():
    @ray_tpu.remote(num_returns="streaming")
    def stuck():
        time.sleep(5)
        yield 1

    g = stuck.remote()
    with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
        g.next(timeout=0.3)
    # ... and the stream still works afterwards.
    assert ray_tpu.get(g.next(timeout=30)) == 1


def test_stream_raylet_mediated_path():
    """Non-DEFAULT scheduling strategies bypass direct submission — no
    stream_item pushes exist, so the generator must fall back to probing
    the object directory."""

    @ray_tpu.remote(num_returns="streaming", scheduling_strategy="SPREAD")
    def gen(n):
        for i in range(n):
            yield i + 100

    assert [ray_tpu.get(r) for r in gen.remote(4)] == [100, 101, 102, 103]


def test_actor_streaming_method():
    @ray_tpu.remote
    class Counter:
        def countdown(self, n):
            while n:
                yield n
                n -= 1

    c = Counter.remote()
    g = c.countdown.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r) for r in g] == [4, 3, 2, 1]
    ray_tpu.kill(c)


def test_async_actor_streaming_method():
    @ray_tpu.remote
    class AsyncGen:
        async def agen(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * 2

    a = AsyncGen.remote()
    g = a.agen.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r) for r in g] == [0, 2, 4]
    ray_tpu.kill(a)


def test_data_streaming_read_first_block_early():
    """A Data read over a slow multi-block datasource delivers the first
    batch before the datasource finishes producing."""
    import numpy as np
    import pyarrow as pa

    from ray_tpu.data.block import BlockMetadata
    from ray_tpu.data.datasource import Datasource, ReadTask

    class SlowSource(Datasource):
        def get_read_tasks(self, parallelism):
            def read():
                for i in range(4):
                    if i:
                        time.sleep(0.8)  # later "files" are slow
                    yield pa.table({"x": np.full(10, i)})

            meta = BlockMetadata(num_rows=40, size_bytes=40 * 8, schema=None, input_files=None)
            return [ReadTask(read, meta)]

    import ray_tpu.data as rd

    ds = rd.read_datasource(SlowSource(), parallelism=1)
    t0 = time.monotonic()
    it = ds.iter_batches(batch_size=10)
    first = next(iter(it))
    dt = time.monotonic() - t0
    assert len(first["x"]) == 10
    # Producer needs ~2.4 s for the remaining blocks; the first one must
    # arrive well before that.
    assert dt < 1.5, f"first batch took {dt:.2f}s — read is not streaming"
