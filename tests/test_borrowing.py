"""Borrowing-lite eager free (reference: core_worker/reference_count.h:64).

A ref passed as a direct-path task arg registers a borrow; when the task
completes and the owner's local refs are gone, the object frees
immediately — it must NOT linger until job-end GC.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module", autouse=True)
def ray():
    ray_tpu.init(
        num_cpus=4,
        object_store_memory=80 * 1024 * 1024,
        ignore_reinit_error=True,
    )
    yield ray_tpu
    ray_tpu.shutdown()


def _store_stats():
    w = ray_tpu.get_global_worker()
    return w.raylet_client.call("store_stats", None)


def _stored_bytes():
    s = _store_stats()
    for k in ("bytes_in_use", "used_bytes", "bytes_used", "size"):
        if k in s:
            return s[k]
    raise AssertionError(f"no usage key in {s}")


def test_arg_freed_after_task_completes():
    @ray_tpu.remote
    def consume(a):
        return float(a.sum())

    before = _stored_bytes()
    ref = ray_tpu.put(np.ones(2_000_000))  # 16 MB
    assert ray_tpu.get(consume.remote(ref), timeout=60) == 2_000_000.0
    del ref
    gc.collect()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if _stored_bytes() <= before + 1_000_000:
            return
        time.sleep(0.2)
    raise AssertionError(
        f"arg not freed after borrow returned: {_stored_bytes()} > {before}"
    )


def test_arg_kept_while_task_inflight():
    """Dropping the local ref while the consumer still runs must NOT free
    the argument out from under it."""

    @ray_tpu.remote
    def slow_consume(a):
        time.sleep(2.0)
        return float(a.sum())

    ref = ray_tpu.put(np.ones(1_000_000))
    fut = slow_consume.remote(ref)
    del ref
    gc.collect()
    assert ray_tpu.get(fut, timeout=60) == 1_000_000.0


def test_freed_object_reads_as_lost_not_never_sealed():
    """A freed id stays in the GCS sealed-ever set so it reads as LOST
    (recoverable via lineage), not never-sealed (which would hang pulls
    and break lineage recovery of dependents whose args were eagerly
    freed)."""
    w = ray_tpu.get_global_worker()
    ref = ray_tpu.put(np.ones(1_000_000))
    oid = ref.id.binary()
    # Sealed and located: not lost.
    assert w.gcs_client.call("object_lost_check", oid) is False
    del ref
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if w.gcs_client.call("object_lost_check", oid):
            return  # freed → "lost" (owner-recoverable), NOT "never sealed"
        time.sleep(0.2)
    raise AssertionError("freed object still reads as never-sealed in the GCS")


def test_data_streams_many_times_store_capacity():
    """VERDICT contract: a Data job streaming ~10x the object-store
    capacity completes with stable store usage and (near) zero spilling,
    because consumed blocks free as their borrows return."""
    import ray_tpu.data as rd

    spilled_before = _store_stats().get("num_spilled", 0)
    # 64 blocks x ~12.8 MB = ~800 MB through an 80 MB store.
    n_rows = 800
    ds = rd.range_tensor(n_rows, shape=(2000,), parallelism=64).map_batches(
        lambda b: {"data": b["data"] * 2.0}, batch_format="numpy"
    )
    total_rows = 0
    for batch in ds.iter_batches(batch_size=50, prefetch_batches=1):
        total_rows += len(batch["data"])
    assert total_rows == n_rows
    spilled_after = _store_stats().get("num_spilled", 0)
    # Eager free keeps the working set bounded: allow a handful of spills
    # for scheduling jitter, not the ~10x overflow.
    assert spilled_after - spilled_before < 16, (
        f"spilled {spilled_after - spilled_before} objects — blocks are "
        f"not being freed eagerly"
    )


def test_refcounter_survives_gc_in_critical_section(ray_start_regular):
    """Regression: ObjectRef.__del__ used to take the ReferenceCounter
    lock directly; a cyclic-GC pass firing inside an allocating
    statement of add_owned() (same thread, same non-reentrant lock)
    deadlocked the whole process — intermittently, under memory
    pressure.  __del__ now enqueues to a lock-free deque.  This test
    forces constant GC passes over ref cycles; before the fix it hung
    within a few iterations."""
    import gc

    import ray_tpu

    @ray_tpu.remote
    def produce(x):
        return [x] * 20

    old = gc.get_threshold()
    gc.set_threshold(25, 2, 2)
    try:
        for i in range(60):
            class _Holder:
                pass

            h = _Holder()
            h.refs = [produce.remote(i) for _ in range(6)]
            h.me = h  # cycle: only the GC can reclaim these refs
            assert ray_tpu.get(list(h.refs))[0][0] == i
            del h
    finally:
        gc.set_threshold(*old)
