"""Serve: deployments, handles, routing, batching, HTTP proxy, scaling.

Reference test model: python/ray/serve/tests/ (test_deploy.py,
test_handle.py, test_batching.py, test_proxy.py) scaled to CI size.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster(ray_cluster):
    yield ray_cluster
    serve.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(payload):
        return {"got": payload}

    handle = serve.run(echo.bind())
    out = handle.remote({"x": 1}).result(timeout=30)
    assert out == {"got": {"x": 1}}


def test_class_deployment_with_methods(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.value = start

        def incr(self, by=1):
            self.value += by
            return self.value

        def __call__(self, payload):
            return {"value": self.value}

    handle = serve.run(Counter.bind(10), name="counter")
    v = handle.incr.remote(5).result(timeout=30)
    assert v == 15
    out = handle.remote({}).result(timeout=30)
    assert "value" in out
    st = serve.status()
    assert st["Counter"]["num_running"] == 2


def test_handle_composition(serve_cluster):
    @serve.deployment(name="inner")
    def inner(x):
        return x * 2

    @serve.deployment(name="outer")
    class Outer:
        def __init__(self, inner_handle):
            self.inner = inner_handle

        def __call__(self, x):
            return self.inner.remote(x).result(timeout=30) + 1

    inner_handle = serve.run(inner.bind())
    handle = serve.run(Outer.bind(inner_handle))
    assert handle.remote(21).result(timeout=30) == 43


def test_load_balancing_across_replicas(serve_cluster):
    import os

    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self, payload):
            return os.getpid()

    handle = serve.run(WhoAmI.bind(), name="whoami")
    # Both replicas must serve traffic.  The router's replica cache may
    # briefly know only one replica right after deploy (refresh is
    # rate-limited), so keep sending until the second shows up.
    import time

    pids = set()
    deadline = time.time() + 30
    while len(pids) < 2 and time.time() < deadline:
        pids.add(handle.remote({}).result(timeout=30))
    assert len(pids) == 2  # both replicas served traffic


def test_batching(serve_cluster):
    @serve.deployment
    class BatchedModel:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def get_batch_sizes(self):
            return self.batch_sizes

    handle = serve.run(BatchedModel.bind(), name="batched")
    responses = [handle.remote(i) for i in range(16)]
    results = [r.result(timeout=30) for r in responses]
    assert sorted(results) == [i * 10 for i in range(16)]
    sizes = handle.get_batch_sizes.remote().result(timeout=30)
    assert max(sizes) > 1  # at least one real batch formed


def test_http_proxy(serve_cluster):
    @serve.deployment(route_prefix="/api")
    def api(payload):
        return {"echo": payload, "ok": True}

    serve.run(api.bind(), http_port=18123)
    # route table may lag one refresh; retry briefly
    deadline = time.time() + 15
    last = None
    while time.time() < deadline:
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:18123/api",
                data=json.dumps({"q": 1}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                out = json.loads(resp.read())
            assert out == {"echo": {"q": 1}, "ok": True}
            return
        except AssertionError:
            raise
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.5)
    raise AssertionError(f"proxy never became reachable: {last}")


def test_rolling_update(serve_cluster):
    @serve.deployment(name="versioned", version="1")
    def v1(payload):
        return "v1"

    handle = serve.run(v1.bind())
    assert handle.remote({}).result(timeout=30) == "v1"

    @serve.deployment(name="versioned", version="2")
    def v2(payload):
        return "v2"

    handle = serve.run(v2.bind())
    deadline = time.time() + 30
    while time.time() < deadline:
        if handle.remote({}).result(timeout=30) == "v2":
            return
        time.sleep(0.3)
    raise AssertionError("rolling update never converged to v2")


def test_grpc_proxy(serve_cluster):
    """Generic-bytes gRPC route through the full serve stack (reference:
    proxy.py:538 gRPCProxy)."""
    import grpc

    @serve.deployment
    class GrpcModel:
        def __call__(self, x):
            return {"doubled": x * 2}

        def describe(self):
            return "grpc-model"

    serve.run(GrpcModel.bind(), grpc_port=19456)
    channel = grpc.insecure_channel("127.0.0.1:19456")
    call = channel.unary_unary("/ray_tpu.serve.UserDefinedService/GrpcModel")
    deadline = time.time() + 15
    last = None
    while time.time() < deadline:
        try:
            out = json.loads(call(json.dumps({"args": [21]}).encode(), timeout=10))
            assert out == {"doubled": 42}
            break
        except AssertionError:
            raise
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.5)
    else:
        raise AssertionError(f"grpc proxy never became reachable: {last}")
    # non-__call__ dispatch via metadata
    out = json.loads(
        call(json.dumps({"args": []}).encode(), timeout=10,
             metadata=(("method", "describe"),))
    )
    assert out == "grpc-model"
    channel.close()


def test_multiplexed_model_swap(serve_cluster):
    """LRU model multiplexing on one replica + handle model routing
    (reference: serve/multiplex.py + handle multiplexed_model_id)."""

    @serve.deployment(num_replicas=2)
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"weights": f"model-{model_id}"}

        async def __call__(self, payload):
            mid = serve.get_multiplexed_model_id()
            model = await self.get_model(mid)
            return {"model": model["weights"], "loads": list(self.loads)}

    handle = serve.run(MultiModel.bind())
    # same model id repeatedly: ONE load (cache hit + replica affinity)
    outs = [
        handle.options(multiplexed_model_id="a").remote(None).result(timeout=30)
        for _ in range(4)
    ]
    assert all(o["model"] == "model-a" for o in outs)
    assert outs[-1]["loads"].count("a") == 1, outs[-1]["loads"]
    # third model on the same replica evicts the LRU (max 2)
    for mid in ("b", "c", "a"):
        out = handle.options(multiplexed_model_id=mid).remote(None).result(timeout=30)
        assert out["model"] == f"model-{mid}"
    loads = out["loads"]
    # "a" was evicted by b/c (capacity 2) and re-loaded on this replica
    # if all routed to one replica; across 2 replicas affinity may have
    # spread them — either way every answer was correct and total loads
    # stayed bounded
    assert 1 <= loads.count("a") <= 2


def test_long_poll_pushes_replica_set(serve_cluster):
    """Routers learn replica-set changes via long-poll push, not just
    the 1s polling fallback (reference: long_poll.py)."""
    from ray_tpu.serve._private.controller import CONTROLLER_NAME, lp_replicas_key
    from ray_tpu.serve._private.long_poll import LongPollClient

    @serve.deployment(num_replicas=1, version="v1")
    def pushed(payload):
        return "v1"

    serve.run(pushed.bind())
    controller = ray_tpu.get_actor(CONTROLLER_NAME, "serve")

    seen = []
    client = LongPollClient(
        controller, {lp_replicas_key("pushed"): lambda snap: seen.append(snap)}
    )
    # scale up: the push must arrive without any poll from us
    serve.run(pushed.options(num_replicas=2, version="v1").bind())
    deadline = time.time() + 20
    while time.time() < deadline:
        if any(len(s) == 2 for s in seen):
            break
        time.sleep(0.2)
    client.stop()
    assert any(len(s) == 2 for s in seen), f"no 2-replica snapshot pushed: {seen}"


def test_local_testing_mode():
    """serve.run(_local_testing_mode=True) needs NO cluster: the
    deployment runs in-process with the normal handle convention,
    including async methods and multiplexed model routing (reference:
    serve/_private/local_testing_mode.py)."""

    @serve.deployment
    class Local:
        def __init__(self, base):
            self.base = base

        async def __call__(self, x):
            return self.base + x

        def describe(self):
            return "local"

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, mid):
            return f"m-{mid}"

        async def which_model(self, _):
            return await self.get_model(serve.get_multiplexed_model_id())

    h = serve.run(Local.bind(10), _local_testing_mode=True)
    assert h.remote(5).result() == 15
    assert h.describe.remote().result() == "local"
    out = h.options(multiplexed_model_id="z").which_model.remote(None).result()
    assert out == "m-z"

    # errors propagate like DeploymentResponse.result does
    @serve.deployment
    def boom(payload):
        raise ValueError("kapow")

    hb = serve.run(boom.bind(), _local_testing_mode=True)
    with pytest.raises(ValueError):
        hb.remote(1).result()


def test_streaming_deployment_handle(serve_cluster):
    """Generator deployments stream items through the handle
    (reference: serve/handle.py DeploymentResponseGenerator over a
    streaming replica call)."""
    @serve.deployment(name="TokenStream")
    class TokenStream:
        def __call__(self, n):
            for i in range(int(n)):
                yield {"token": i}

        async def agen(self, n):
            for i in range(int(n)):
                yield i * 10

    handle = serve.run(TokenStream.bind(), name="stream_app")
    items = list(handle.options(stream=True).remote(4))
    assert items == [{"token": i} for i in range(4)]
    # async generator method, method dispatch through the same option
    vals = list(handle.options(stream=True).agen.remote(3))
    assert vals == [0, 10, 20]
    # non-stream calls on the same deployment still work (one-shot path)
    sync_handle = handle.options(stream=False)
    assert hasattr(sync_handle.remote(1), "result")


def test_streaming_http_chunked(serve_cluster):
    """x-serve-stream: 1 streams each yield as a chunk (reference:
    StreamingResponse over the HTTP proxy)."""
    @serve.deployment(name="HttpStream")
    class HttpStream:
        def __call__(self, payload):
            for i in range(3):
                yield f"chunk-{i};"

    # the proxy is a singleton: reuse the module's proxy port (first
    # http_port wins; later ports are ignored by _ensure_proxy)
    serve.run(HttpStream.bind(), name="http_stream", route_prefix="/hs",
              http_port=18123)
    req = urllib.request.Request(
        "http://127.0.0.1:18123/hs", headers={"x-serve-stream": "1"}
    )
    with urllib.request.urlopen(req, timeout=20) as r:
        body = r.read().decode()
    assert body == "chunk-0;chunk-1;chunk-2;"


def test_streaming_local_testing_mode(serve_cluster):
    """Local mode streams generator yields like the cluster path."""
    @serve.deployment
    class LocalGen:
        def __call__(self, n):
            yield from range(n)

    h = serve.run(LocalGen.bind(), _local_testing_mode=True)
    assert list(h.options(stream=True).remote(3)) == [0, 1, 2]


def test_channel_dataplane_engaged_and_exact(serve_cluster):
    """The router→replica hot path rides compiled channels: calls and
    token streams go through the per-replica ChannelClient (no per-call
    RPC, no per-token object-store items) with exact results, errors
    surfacing as their original type, and the disconnect-cancel contract
    intact."""
    from ray_tpu.serve._private.dataplane import ChannelClient, ChannelStream
    from ray_tpu.serve._private.router import _routers

    @serve.deployment(name="DataplaneDep")
    class DataplaneDep:
        def __call__(self, payload):
            if payload == "boom":
                raise ValueError("boom")
            return {"echo": payload}

        def tokens(self, n):
            for i in range(n):
                yield {"tok": i}

    h = serve.run(DataplaneDep.bind(), name="dataplane_dep")
    assert h.remote({"a": 1}).result(timeout=30) == {"echo": {"a": 1}}
    router = _routers[h.deployment_name]
    dps = [v for v in router._dataplanes.values() if isinstance(v, ChannelClient)]
    assert dps, "dataplane did not attach"
    # streams multiplex over the same channel pair
    gen = h.options(stream=True).tokens.remote(6)
    assert isinstance(gen._gen, ChannelStream)
    assert list(gen) == [{"tok": i} for i in range(6)]
    # errors keep their original type across the channel boundary
    with pytest.raises(ValueError):
        h.remote("boom").result(timeout=30)
    # concurrent streams interleave on one channel without crosstalk
    gens = [h.options(stream=True).tokens.remote(4) for _ in range(8)]
    outs = [list(g) for g in gens]
    assert all(o == [{"tok": i} for i in range(4)] for o in outs)
    # early close sends the cancel frame and releases the waiter slot
    g = h.options(stream=True).tokens.remote(1000)
    g.close()
    dp = dps[0]
    assert not dp.dead
