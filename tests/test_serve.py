"""Serve: deployments, handles, routing, batching, HTTP proxy, scaling.

Reference test model: python/ray/serve/tests/ (test_deploy.py,
test_handle.py, test_batching.py, test_proxy.py) scaled to CI size.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster(ray_cluster):
    yield ray_cluster
    serve.shutdown()


def test_function_deployment(serve_cluster):
    @serve.deployment
    def echo(payload):
        return {"got": payload}

    handle = serve.run(echo.bind())
    out = handle.remote({"x": 1}).result(timeout=30)
    assert out == {"got": {"x": 1}}


def test_class_deployment_with_methods(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.value = start

        def incr(self, by=1):
            self.value += by
            return self.value

        def __call__(self, payload):
            return {"value": self.value}

    handle = serve.run(Counter.bind(10), name="counter")
    v = handle.incr.remote(5).result(timeout=30)
    assert v == 15
    out = handle.remote({}).result(timeout=30)
    assert "value" in out
    st = serve.status()
    assert st["Counter"]["num_running"] == 2


def test_handle_composition(serve_cluster):
    @serve.deployment(name="inner")
    def inner(x):
        return x * 2

    @serve.deployment(name="outer")
    class Outer:
        def __init__(self, inner_handle):
            self.inner = inner_handle

        def __call__(self, x):
            return self.inner.remote(x).result(timeout=30) + 1

    inner_handle = serve.run(inner.bind())
    handle = serve.run(Outer.bind(inner_handle))
    assert handle.remote(21).result(timeout=30) == 43


def test_load_balancing_across_replicas(serve_cluster):
    import os

    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self, payload):
            return os.getpid()

    handle = serve.run(WhoAmI.bind(), name="whoami")
    # Both replicas must serve traffic.  The router's replica cache may
    # briefly know only one replica right after deploy (refresh is
    # rate-limited), so keep sending until the second shows up.
    import time

    pids = set()
    deadline = time.time() + 30
    while len(pids) < 2 and time.time() < deadline:
        pids.add(handle.remote({}).result(timeout=30))
    assert len(pids) == 2  # both replicas served traffic


def test_batching(serve_cluster):
    @serve.deployment
    class BatchedModel:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def get_batch_sizes(self):
            return self.batch_sizes

    handle = serve.run(BatchedModel.bind(), name="batched")
    responses = [handle.remote(i) for i in range(16)]
    results = [r.result(timeout=30) for r in responses]
    assert sorted(results) == [i * 10 for i in range(16)]
    sizes = handle.get_batch_sizes.remote().result(timeout=30)
    assert max(sizes) > 1  # at least one real batch formed


def test_http_proxy(serve_cluster):
    @serve.deployment(route_prefix="/api")
    def api(payload):
        return {"echo": payload, "ok": True}

    serve.run(api.bind(), http_port=18123)
    # route table may lag one refresh; retry briefly
    deadline = time.time() + 15
    last = None
    while time.time() < deadline:
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:18123/api",
                data=json.dumps({"q": 1}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                out = json.loads(resp.read())
            assert out == {"echo": {"q": 1}, "ok": True}
            return
        except AssertionError:
            raise
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.5)
    raise AssertionError(f"proxy never became reachable: {last}")


def test_rolling_update(serve_cluster):
    @serve.deployment(name="versioned", version="1")
    def v1(payload):
        return "v1"

    handle = serve.run(v1.bind())
    assert handle.remote({}).result(timeout=30) == "v1"

    @serve.deployment(name="versioned", version="2")
    def v2(payload):
        return "v2"

    handle = serve.run(v2.bind())
    deadline = time.time() + 30
    while time.time() < deadline:
        if handle.remote({}).result(timeout=30) == "v2":
            return
        time.sleep(0.3)
    raise AssertionError("rolling update never converged to v2")
