"""RLlib: PPO/DQN/IMPALA learning + components.

Reference test model: rllib learning_tests (tuned_examples asserting
reward thresholds, rllib/BUILD:153-164) scaled down to CI size, plus
unit tests for sample batches / GAE / replay buffers.
"""

import numpy as np
import pytest

import ray_tpu


def test_sample_batch_ops():
    from ray_tpu.rllib import SampleBatch

    b = SampleBatch({"obs": np.arange(10).reshape(5, 2), "rew": np.ones(5)})
    assert b.count == 5
    sliced = b.slice(1, 3)
    assert sliced.count == 2
    cat = SampleBatch.concat_samples([b, b])
    assert cat.count == 10
    mbs = list(cat.minibatches(4, np.random.default_rng(0)))
    assert len(mbs) == 2 and all(m.count == 4 for m in mbs)


def test_gae_matches_manual():
    from ray_tpu.rllib.utils.postprocessing import compute_gae
    from ray_tpu.rllib.utils.sample_batch import SampleBatch

    batch = SampleBatch(
        {
            "rewards": np.array([1.0, 1.0, 1.0], np.float32),
            "vf_preds": np.array([0.5, 0.4, 0.3], np.float32),
            "terminateds": np.array([False, False, True]),
            "truncateds": np.array([False, False, False]),
        }
    )
    out = compute_gae(batch, last_value=0.0, gamma=0.9, lambda_=1.0)
    # terminal step: delta = 1 - 0.3 = 0.7
    # t1: 1 + 0.9*0.3 - 0.4 + 0.9*0.7 = 1.50
    # t0: 1 + 0.9*0.4 - 0.5 + 0.9*1.50 = 2.21
    np.testing.assert_allclose(out["advantages"], [2.21, 1.5, 0.7], rtol=1e-5)


def test_replay_buffer_wraps():
    from ray_tpu.rllib import ReplayBuffer, SampleBatch

    buf = ReplayBuffer(capacity=8, seed=0)
    for i in range(3):
        buf.add(SampleBatch({"x": np.arange(4) + 4 * i}))
    assert len(buf) == 8
    s = buf.sample(16)
    assert s.count == 16
    assert s["x"].min() >= 4  # first batch was overwritten


def test_prioritized_buffer_prefers_high_priority():
    from ray_tpu.rllib import PrioritizedReplayBuffer, SampleBatch

    buf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, seed=0)
    buf.add(SampleBatch({"x": np.arange(64)}))
    # element 7 gets huge priority
    prios = np.full(64, 0.001)
    prios[7] = 100.0
    buf.update_priorities(np.arange(64), prios)
    s = buf.sample(256)
    frac_7 = (s["x"] == 7).mean()
    assert frac_7 > 0.5


def test_rl_module_shapes():
    import jax

    from ray_tpu.rllib import RLModuleSpec

    spec = RLModuleSpec(observation_dim=4, action_dim=2, discrete=True, hidden=(8,))
    mod = spec.build()
    params = mod.init(jax.random.PRNGKey(0))
    obs = np.zeros((3, 4), np.float32)
    actions, logp, value = mod.forward_exploration(params, obs, jax.random.PRNGKey(1))
    assert actions.shape == (3,) and logp.shape == (3,) and value.shape == (3,)
    a2, v2 = mod.forward_inference(params, obs)
    assert a2.shape == (3,)
    lp, ent, v = mod.forward_train(params, obs, np.zeros(3, np.int32))
    assert float(ent.mean()) > 0


@pytest.mark.slow
def test_ppo_cartpole_learns(ray_cluster):
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2, num_cpus_per_env_runner=1)
        .training(
            lr=3e-4,
            train_batch_size=1024,
            minibatch_size=128,
            num_epochs=6,
            entropy_coeff=0.01,
        )
        .debugging(seed=1)
    )
    algo = cfg.build()
    best = 0.0
    for i in range(30):
        out = algo.train()
        if out.get("episode_return_mean"):
            best = max(best, out["episode_return_mean"])
        if best > 120:
            break
    algo.cleanup()
    assert best > 120, f"PPO failed to learn CartPole: best={best}"


@pytest.mark.slow
def test_ppo_checkpoint_restore(ray_cluster, tmp_path):
    from ray_tpu.rllib import PPO, PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
        .training(train_batch_size=256, minibatch_size=64, num_epochs=2)
    )
    algo = cfg.build()
    algo.train()
    w_before = algo.get_policy_weights()
    ckpt = str(tmp_path / "ppo_ckpt")
    import os

    os.makedirs(ckpt, exist_ok=True)
    algo.save_checkpoint(ckpt)
    algo.cleanup()

    algo2 = PPO.from_checkpoint(ckpt)
    w_after = algo2.get_policy_weights()
    import jax

    leaves_eq = jax.tree_util.tree_map(lambda a, b: np.allclose(a, b), w_before, w_after)
    assert all(jax.tree_util.tree_leaves(leaves_eq))
    algo2.cleanup()


def test_connectors_pipeline():
    from ray_tpu.rllib import (
        ClipActions,
        ConnectorPipelineV2,
        FlattenObservations,
        NormalizeObservations,
    )

    pipe = ConnectorPipelineV2([FlattenObservations(), NormalizeObservations(clip=5.0)])
    obs = np.random.default_rng(0).normal(3.0, 2.0, (16, 2, 2)).astype(np.float32)
    out = pipe(obs)
    assert out.shape == (16, 4)
    # after enough batches the running filter should roughly whiten
    for _ in range(50):
        out = pipe(np.random.default_rng(1).normal(3.0, 2.0, (16, 2, 2)))
    assert abs(out.mean()) < 0.5
    clip = ClipActions(low=-1.0, high=1.0)
    np.testing.assert_allclose(clip(np.array([-5.0, 0.5, 5.0])), [-1.0, 0.5, 1.0])
    state = pipe.get_state()
    pipe2 = ConnectorPipelineV2([FlattenObservations(), NormalizeObservations(clip=5.0)])
    pipe2.set_state(state)
    assert pipe2.connectors[1]._count == pipe.connectors[1]._count


def test_connectors_wired_through_config(ray_cluster):
    from ray_tpu.rllib import ConnectorPipelineV2, FlattenObservations, PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(
            num_env_runners=0,
            num_envs_per_env_runner=2,
            rollout_fragment_length=16,
            env_to_module=ConnectorPipelineV2([FlattenObservations()]),
        )
        .training(train_batch_size=32, minibatch_size=16, num_epochs=1)
    )
    algo = cfg.build()
    assert algo.env_runner_group.local_runner.env_to_module is not None
    out = algo.train()
    assert out["num_env_steps_sampled"] > 0
    algo.cleanup()


def test_multi_agent_checkpoint_roundtrip(ray_cluster, tmp_path):
    """Callable config fields (env_creator, policy_mapping_fn) must
    survive save_checkpoint → from_checkpoint (cloudpickled config)."""
    import os

    from ray_tpu.rllib import PPO, PPOConfig

    cfg = (
        PPOConfig()
        .environment(env_creator=lambda: _DoubleCartPole())
        .env_runners(num_env_runners=0, rollout_fragment_length=32)
        .multi_agent(
            policies={"p0": None, "p1": None},
            policy_mapping_fn=lambda agent_id: "p" + agent_id.split("_")[1],
        )
        .training(train_batch_size=64, minibatch_size=32, num_epochs=1)
    )
    algo = cfg.build()
    algo.train()
    w_before = algo.get_policy_weights()
    ckpt = str(tmp_path / "ma_ckpt")
    os.makedirs(ckpt, exist_ok=True)
    algo.save_checkpoint(ckpt)
    algo.cleanup()

    algo2 = PPO.from_checkpoint(ckpt)
    w_after = algo2.get_policy_weights()
    import jax

    for pid in ("p0", "p1"):
        eq = jax.tree_util.tree_map(lambda a, b: np.allclose(a, b), w_before[pid], w_after[pid])
        assert all(jax.tree_util.tree_leaves(eq)), pid
    algo2.train()  # runners rebuilt from the restored env_creator
    algo2.cleanup()


def test_env_runner_drops_autoreset_rows():
    """gymnasium>=1.0 next-step autoreset rows (obs = previous episode's
    terminal frame, action ignored) must not appear in sample batches."""
    import gymnasium as gym

    from ray_tpu.rllib import RLModuleSpec, SingleAgentEnvRunner

    creator = lambda: gym.make("CartPole-v1")  # noqa: E731
    probe = creator()
    spec = RLModuleSpec.from_gym_env(probe, hidden=(8,))
    probe.close()
    runner = SingleAgentEnvRunner(creator, spec, num_envs=2, rollout_fragment_length=300, seed=0)
    import jax

    runner.set_weights(spec.build().get_weights(spec.build().init(jax.random.PRNGKey(0))))
    batch = runner.sample(300)
    # random CartPole episodes last ~20 steps: plenty of resets happened,
    # so dropped rows mean fewer than the raw 600 transitions
    assert batch.count < 600
    # every episode fragment's rewards are all-1 (CartPole): a reset row
    # would have carried reward 0
    assert (batch["rewards"] == 1.0).all()
    runner.stop()


@pytest.mark.slow
def test_sac_pendulum_improves(ray_cluster):
    from ray_tpu.rllib import SACConfig

    cfg = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=1)
        .training(
            lr=1e-3,
            train_batch_size=128,
            num_steps_sampled_before_learning_starts=500,
            sample_batch_size=200,
            updates_per_iteration=200,  # ~1 update per env step (SAC standard)
            model={"hidden": (64, 64)},
        )
        .debugging(seed=0)
    )
    algo = cfg.build()
    best_window = -1e9
    for i in range(25):
        out = algo.train()
        rets = algo.sampler.completed_returns
        if len(rets) >= 5:
            best_window = max(best_window, float(np.mean(rets[-5:])))
        if best_window > -700:
            break
    algo.cleanup()
    # random play sits near -1200..-1600; a learning SAC reaches ≈-150
    # by ~5k steps — -700 is a loose, seed-robust bar
    assert best_window > -700, f"SAC no progress: best 5-episode mean={best_window}"
    assert np.isfinite(out["critic_loss"])


@pytest.mark.slow
def test_bc_clones_cartpole_expert(ray_cluster):
    """Offline BC on scripted-expert CartPole data reaches expert-like
    returns without ever stepping the env during training."""
    import gymnasium as gym

    from ray_tpu.rllib import BCConfig
    from ray_tpu.rllib.utils.sample_batch import SampleBatch

    # scripted expert: push toward the pole's lean (holds ~200+ steps)
    env = gym.make("CartPole-v1")
    obs_rows, act_rows = [], []
    obs, _ = env.reset(seed=0)
    for _ in range(3000):
        a = int(obs[2] + 0.5 * obs[3] > 0)
        obs_rows.append(obs.copy())
        act_rows.append(a)
        obs, r, term, trunc, _ = env.step(a)
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    data = SampleBatch({"obs": np.asarray(obs_rows, np.float32),
                        "actions": np.asarray(act_rows, np.int64)})

    cfg = (
        BCConfig()
        .environment("CartPole-v1")
        .offline_data(input_=data)
        .training(lr=1e-3, train_batch_size=2048, minibatch_size=256, num_epochs=2)
    )
    algo = cfg.build()
    for _ in range(15):
        out = algo.train()
    ret = algo.evaluate()["episode_return_mean"]
    algo.cleanup()
    assert ret > 120, f"BC clone scored only {ret}"
    assert out["bc_logp"] > -0.5  # near-deterministic imitation


class _DoubleCartPole:
    """Two independent CartPole agents in one multi-agent env; episode
    ends when either pole falls (tests per-agent batching + routing)."""

    possible_agents = ["cart_0", "cart_1"]

    def __init__(self):
        import gymnasium as gym

        self._envs = {a: gym.make("CartPole-v1") for a in self.possible_agents}
        self.observation_spaces = {a: e.observation_space for a, e in self._envs.items()}
        self.action_spaces = {a: e.action_space for a, e in self._envs.items()}

    def observation_space_for(self, agent):
        return self.observation_spaces[agent]

    def action_space_for(self, agent):
        return self.action_spaces[agent]

    def reset(self, *, seed=None, options=None):
        obs = {}
        for i, (a, e) in enumerate(self._envs.items()):
            o, _ = e.reset(seed=None if seed is None else seed + i)
            obs[a] = o
        return obs, {}

    def step(self, action_dict):
        obs, rew, term, trunc = {}, {}, {}, {}
        any_done = False
        for a, e in self._envs.items():
            o, r, t, tr, _ = e.step(action_dict[a])
            obs[a], rew[a], term[a], trunc[a] = o, float(r), bool(t), bool(tr)
            any_done = any_done or t or tr
        term["__all__"] = any_done
        trunc["__all__"] = False
        return obs, rew, term, trunc, {}

    def close(self):
        for e in self._envs.values():
            e.close()


@pytest.mark.slow
def test_multi_agent_ppo(ray_cluster):
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment(env_creator=lambda: _DoubleCartPole())
        .env_runners(num_env_runners=0, rollout_fragment_length=256)
        .multi_agent(
            policies={"p0": None, "p1": None},
            policy_mapping_fn=lambda agent_id: "p" + agent_id.split("_")[1],
        )
        .training(lr=3e-4, train_batch_size=512, minibatch_size=128,
                  num_epochs=4, entropy_coeff=0.01)
        .debugging(seed=2)
    )
    algo = cfg.build()
    first = None
    best = 0.0
    saw_policies = set()
    for i in range(15):
        out = algo.train()
        saw_policies |= {k for k in out if k in ("p0", "p1")}
        r = out.get("episode_return_mean")
        if r:
            first = first if first is not None else r
            best = max(best, r)
    algo.cleanup()
    assert saw_policies == {"p0", "p1"}, f"policies trained: {saw_policies}"
    assert first is not None and best > first + 10, f"MA-PPO no progress: first={first} best={best}"


@pytest.mark.slow
def test_impala_learner_thread_decouples_sampling(ray_cluster):
    """A slow SGD step must not stall rollouts: the bounded queue absorbs
    fragments while the learner thread grinds (VERDICT r3 weak #7)."""
    import time as _time

    from ray_tpu.rllib import IMPALAConfig

    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
        .training(rollout_fragment_length=32)
    )
    cfg.learner_queue_size = 64
    algo = cfg.build()
    real_update = algo.learner_group.update_from_batch

    def slow_update(batch, **kw):
        _time.sleep(0.4)
        return real_update(batch, **kw)

    algo.learner_group.update_from_batch = slow_update
    sampled = 0
    for _ in range(8):
        out = algo.train()
        sampled += out["num_env_steps_sampled"]
    lt = algo._learner_thread
    assert lt is not None and lt.is_alive()
    # let the throttled learner finish at least one update (first call
    # also pays jit compile), then check sampling ran ahead of it
    deadline = _time.monotonic() + 60
    while lt.steps_trained == 0 and _time.monotonic() < deadline:
        lt.check_error()
        _time.sleep(0.2)
    trained = lt.steps_trained
    assert sampled > trained > 0, f"sampled={sampled} trained={trained}"
    algo.cleanup()
    deadline = _time.monotonic() + 10
    while lt.is_alive() and _time.monotonic() < deadline:
        _time.sleep(0.1)
    assert not lt.is_alive(), "learner thread did not stop on cleanup"


@pytest.mark.slow
def test_appo_learns(ray_cluster):
    from ray_tpu.rllib import APPOConfig

    # Learning is asserted in sync mode (deterministic pacing); the async
    # learner-thread machinery APPO inherits unchanged from IMPALA is
    # covered by test_impala_async_pipeline + the decoupling test.
    cfg = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4)
        .training(lr=5e-4, entropy_coeff=0.01, rollout_fragment_length=64)
        .debugging(seed=4)
    )
    # CI-size tuning: at this tiny scale a lagging clip anchor costs more
    # than it stabilizes
    cfg.target_network_update_freq = 1
    algo = cfg.build()
    best = 0.0
    for i in range(45):
        out = algo.train()
        r = out.get("episode_return_mean")
        if r:
            best = max(best, r)
        if best > 45:
            break
    algo.cleanup()
    # random play sits near ~24; ~2x that demonstrates the clipped
    # V-trace surrogate is learning
    assert best > 45, f"APPO made no progress: best={best}"


@pytest.mark.slow
def test_impala_async_pipeline(ray_cluster):
    from ray_tpu.rllib import IMPALAConfig

    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2, num_cpus_per_env_runner=1)
        .training(lr=5e-4, entropy_coeff=0.01, rollout_fragment_length=64)
        .debugging(seed=3)
    )
    algo = cfg.build()
    first_return = None
    best = 0.0
    # iterations no longer block on SGD (learner thread), so the budget
    # is in iterations-of-sampling, not updates — give it headroom
    for i in range(150):
        out = algo.train()
        r = out.get("episode_return_mean")
        if r:
            first_return = first_return if first_return is not None else r
            best = max(best, r)
        if best > 60:
            break
    algo.cleanup()
    # async V-trace should at least double the initial return on CartPole
    assert best > 60, f"IMPALA made no progress: first={first_return} best={best}"


def test_dqn_learns_cartpole(ray_cluster):
    """Reward-gated DQN learning test (reference: rllib/BUILD:153
    learning_tests_dqn_cartpole gates on reward, not mechanism): greedy
    eval return must clear the bar within the step budget.  Mechanism
    checks (epsilon anneal, target sync) ride along."""
    from ray_tpu.rllib import DQNConfig

    cfg = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_envs_per_env_runner=2)
        .training(
            lr=1e-3,
            num_steps_sampled_before_learning_starts=500,
            epsilon_decay_timesteps=4000,
            target_network_update_freq=200,
            updates_per_iteration=16,
            sample_batch_size=64,
            train_batch_size=64,
        )
        .evaluation(evaluation_duration=5)
        .debugging(seed=0)
    )
    algo = cfg.build()
    import jax
    import numpy as np

    target_before = jax.tree_util.tree_map(np.asarray, algo.learner.target_params)
    eps0 = None
    out = {}
    best = -np.inf
    for i in range(120):
        out = algo.train()
        eps0 = eps0 if eps0 is not None else out["epsilon"]
        if i >= 20 and i % 10 == 0:
            best = max(best, algo.evaluate()["episode_return_mean"])
            if best > 130:
                break
    assert best > 130, f"DQN failed to learn CartPole: best greedy eval={best}"
    assert out["epsilon"] < eps0  # annealing
    moved = jax.tree_util.tree_map(
        lambda a, b: not np.allclose(a, np.asarray(b)),
        target_before, algo.learner.target_params,
    )
    assert any(jax.tree_util.tree_leaves(moved)), "target network never synced"
    algo.cleanup()


@pytest.mark.slow  # ~39 s learning test: tier-2
def test_dreamerv3_learns_cartpole_from_imagination(ray_cluster):
    """DreamerV3 (reward-gated): the world model's imagination training
    must lift greedy eval clearly above both random (~20) and
    constant-action (~9.5) CartPole baselines (reference:
    rllib/algorithms/dreamerv3 learning tests).  The world-model loss
    must also fall — policy gains in this algorithm are downstream of
    the RSSM actually modeling the env."""
    from ray_tpu.rllib import DreamerV3Config

    cfg = (
        DreamerV3Config()
        .environment("CartPole-v1")
        .training(
            num_steps_sampled_before_learning_starts=400,
            sample_batch_size=200,
            updates_per_iteration=10,
            batch_seqs=8,
            seq_len=16,
            horizon=12,
            deter_size=64,
            stoch_groups=4,
            stoch_classes=8,
            hidden=(64,),
        )
        .evaluation(evaluation_duration=5)
        .debugging(seed=0)
    )
    algo = cfg.build()
    first_wm, last_wm, best = None, None, -np.inf
    for i in range(70):
        out = algo.train()
        if "world_model_loss" in out:
            first_wm = first_wm if first_wm is not None else out["world_model_loss"]
            last_wm = out["world_model_loss"]
        if i >= 35 and i % 6 == 5:
            best = max(best, algo.evaluate()["episode_return_mean"])
            if best > 35:
                break
    algo.cleanup()
    assert best > 35, f"DreamerV3 imagination never beat baselines: best eval={best}"
    assert last_wm < first_wm * 0.75, (first_wm, last_wm)
