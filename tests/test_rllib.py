"""RLlib: PPO/DQN/IMPALA learning + components.

Reference test model: rllib learning_tests (tuned_examples asserting
reward thresholds, rllib/BUILD:153-164) scaled down to CI size, plus
unit tests for sample batches / GAE / replay buffers.
"""

import numpy as np
import pytest

import ray_tpu


def test_sample_batch_ops():
    from ray_tpu.rllib import SampleBatch

    b = SampleBatch({"obs": np.arange(10).reshape(5, 2), "rew": np.ones(5)})
    assert b.count == 5
    sliced = b.slice(1, 3)
    assert sliced.count == 2
    cat = SampleBatch.concat_samples([b, b])
    assert cat.count == 10
    mbs = list(cat.minibatches(4, np.random.default_rng(0)))
    assert len(mbs) == 2 and all(m.count == 4 for m in mbs)


def test_gae_matches_manual():
    from ray_tpu.rllib.utils.postprocessing import compute_gae
    from ray_tpu.rllib.utils.sample_batch import SampleBatch

    batch = SampleBatch(
        {
            "rewards": np.array([1.0, 1.0, 1.0], np.float32),
            "vf_preds": np.array([0.5, 0.4, 0.3], np.float32),
            "terminateds": np.array([False, False, True]),
            "truncateds": np.array([False, False, False]),
        }
    )
    out = compute_gae(batch, last_value=0.0, gamma=0.9, lambda_=1.0)
    # terminal step: delta = 1 - 0.3 = 0.7
    # t1: 1 + 0.9*0.3 - 0.4 + 0.9*0.7 = 1.50
    # t0: 1 + 0.9*0.4 - 0.5 + 0.9*1.50 = 2.21
    np.testing.assert_allclose(out["advantages"], [2.21, 1.5, 0.7], rtol=1e-5)


def test_replay_buffer_wraps():
    from ray_tpu.rllib import ReplayBuffer, SampleBatch

    buf = ReplayBuffer(capacity=8, seed=0)
    for i in range(3):
        buf.add(SampleBatch({"x": np.arange(4) + 4 * i}))
    assert len(buf) == 8
    s = buf.sample(16)
    assert s.count == 16
    assert s["x"].min() >= 4  # first batch was overwritten


def test_prioritized_buffer_prefers_high_priority():
    from ray_tpu.rllib import PrioritizedReplayBuffer, SampleBatch

    buf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, seed=0)
    buf.add(SampleBatch({"x": np.arange(64)}))
    # element 7 gets huge priority
    prios = np.full(64, 0.001)
    prios[7] = 100.0
    buf.update_priorities(np.arange(64), prios)
    s = buf.sample(256)
    frac_7 = (s["x"] == 7).mean()
    assert frac_7 > 0.5


def test_rl_module_shapes():
    import jax

    from ray_tpu.rllib import RLModuleSpec

    spec = RLModuleSpec(observation_dim=4, action_dim=2, discrete=True, hidden=(8,))
    mod = spec.build()
    params = mod.init(jax.random.PRNGKey(0))
    obs = np.zeros((3, 4), np.float32)
    actions, logp, value = mod.forward_exploration(params, obs, jax.random.PRNGKey(1))
    assert actions.shape == (3,) and logp.shape == (3,) and value.shape == (3,)
    a2, v2 = mod.forward_inference(params, obs)
    assert a2.shape == (3,)
    lp, ent, v = mod.forward_train(params, obs, np.zeros(3, np.int32))
    assert float(ent.mean()) > 0


@pytest.mark.slow
def test_ppo_cartpole_learns(ray_cluster):
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2, num_cpus_per_env_runner=1)
        .training(
            lr=3e-4,
            train_batch_size=1024,
            minibatch_size=128,
            num_epochs=6,
            entropy_coeff=0.01,
        )
        .debugging(seed=1)
    )
    algo = cfg.build()
    best = 0.0
    for i in range(30):
        out = algo.train()
        if out.get("episode_return_mean"):
            best = max(best, out["episode_return_mean"])
        if best > 120:
            break
    algo.cleanup()
    assert best > 120, f"PPO failed to learn CartPole: best={best}"


@pytest.mark.slow
def test_ppo_checkpoint_restore(ray_cluster, tmp_path):
    from ray_tpu.rllib import PPO, PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
        .training(train_batch_size=256, minibatch_size=64, num_epochs=2)
    )
    algo = cfg.build()
    algo.train()
    w_before = algo.get_policy_weights()
    ckpt = str(tmp_path / "ppo_ckpt")
    import os

    os.makedirs(ckpt, exist_ok=True)
    algo.save_checkpoint(ckpt)
    algo.cleanup()

    algo2 = PPO.from_checkpoint(ckpt)
    w_after = algo2.get_policy_weights()
    import jax

    leaves_eq = jax.tree_util.tree_map(lambda a, b: np.allclose(a, b), w_before, w_after)
    assert all(jax.tree_util.tree_leaves(leaves_eq))
    algo2.cleanup()


@pytest.mark.slow
def test_impala_async_pipeline(ray_cluster):
    from ray_tpu.rllib import IMPALAConfig

    cfg = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2, num_cpus_per_env_runner=1)
        .training(lr=5e-4, entropy_coeff=0.01, rollout_fragment_length=64)
        .debugging(seed=3)
    )
    algo = cfg.build()
    first_return = None
    best = 0.0
    for i in range(40):
        out = algo.train()
        r = out.get("episode_return_mean")
        if r:
            first_return = first_return if first_return is not None else r
            best = max(best, r)
        if best > 60:
            break
    algo.cleanup()
    # async V-trace should at least double the initial return on CartPole
    assert best > 60, f"IMPALA made no progress: first={first_return} best={best}"
