"""Direct worker-to-worker task submission + actor-task ordering tests.

Reference test model: python/ray/tests/test_basic.py (chained deps),
core_worker/transport tests for sequential_actor_submit_queue ordering.
"""

import threading
import time

import pytest

import ray_tpu


def test_chained_temporary_ref(ray_cluster):
    """Regression: `g.remote(f.remote(x))` drops the inner ref immediately;
    the in-flight direct result must still be promoted for the consumer
    (round-3 bug: ReferenceCounter.remove_owned freed the memory-store
    pending/promote state of escaped refs)."""

    @ray_tpu.remote
    def plus_one(x):
        return x + 1

    @ray_tpu.remote
    def times_two(x):
        return x * 2

    for _ in range(3):
        assert ray_tpu.get(times_two.remote(plus_one.remote(5)), timeout=60) == 12


def test_direct_inline_error_propagates(ray_cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("direct boom")

    with pytest.raises(ValueError, match="direct boom"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_direct_result_used_after_delay(ray_cluster):
    """A memory-store result passed as an arg later (after arrival) is
    inlined into the consumer's spec."""

    @ray_tpu.remote
    def make():
        return {"k": 41}

    @ray_tpu.remote
    def use(d):
        return d["k"] + 1

    ref = make.remote()
    ray_tpu.get(ref, timeout=60)  # ensure it arrived inline
    assert ray_tpu.get(use.remote(ref), timeout=60) == 42


def test_leases_returned_when_idle(ray_cluster):
    """Leased workers go back to the raylet's idle pool after the idle
    timeout, freeing their resources."""
    from ray_tpu._private.worker import get_global_worker

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(20)], timeout=60)
    w = get_global_worker()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        stats = w.raylet_client.call("node_stats")
        if stats["resources_available"].get("CPU") == stats["resources_total"].get("CPU"):
            break
        time.sleep(0.25)
    else:
        pytest.fail(f"lease resources never returned: {stats['resources_available']}")


def test_actor_order_two_submitting_threads(ray_cluster):
    """Per-caller actor-task ordering: calls from one caller process
    execute in sequence-number order even when two threads submit
    concurrently (reference: sequential_actor_submit_queue.h)."""

    @ray_tpu.remote
    class Recorder:
        def __init__(self):
            self.seen = []

        def add(self, tag, i):
            self.seen.append((tag, i))
            return len(self.seen)

        def dump(self):
            return self.seen

    rec = Recorder.remote()
    ray_tpu.get(rec.add.remote("warm", 0), timeout=60)

    errors = []

    def submit(tag):
        try:
            refs = [rec.add.remote(tag, i) for i in range(40)]
            ray_tpu.get(refs, timeout=120)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=submit, args=(t,)) for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    seen = ray_tpu.get(rec.dump.remote(), timeout=60)
    per_tag = {"a": [], "b": []}
    for tag, i in seen:
        if tag in per_tag:
            per_tag[tag].append(i)
    # Each thread's calls must have executed in its own submission order.
    assert per_tag["a"] == sorted(per_tag["a"]), per_tag["a"]
    assert per_tag["b"] == sorted(per_tag["b"]), per_tag["b"]
    assert len(per_tag["a"]) == len(per_tag["b"]) == 40


def test_admit_buffers_out_of_order_sequences():
    """Receiver-side unit test: admission starts at sequence 1 per
    (caller, incarnation); early arrivals are held until the gap fills,
    duplicates and stale-incarnation specs are dropped."""
    from ray_tpu._private.common import TaskSpec
    from ray_tpu._private.ids import ActorID, JobID, TaskID, WorkerID
    from ray_tpu._private.worker import Worker

    w = Worker.__new__(Worker)  # no connection needed for admission logic
    import queue as queue_mod

    w._admit_lock = threading.Lock()
    w._actor_expected = {}
    w._actor_buffer = {}
    w._actor_caller_inc = {}
    w._exec_queue = queue_mod.Queue()

    job = JobID.from_random()
    actor = ActorID.of(job)
    caller = WorkerID.from_random()

    def spec(seq, inc=0):
        return TaskSpec(
            task_id=TaskID.of(actor),
            job_id=job,
            name=f"m{seq}",
            function_key=b"",
            args=[],
            num_returns=1,
            resources=None,
            is_actor_task=True,
            actor_id=actor,
            sequence_number=seq,
            actor_incarnation=inc,
            owner_worker_id=caller,
        )

    def drain():
        out = []
        while not w._exec_queue.empty():
            s, _ = w._exec_queue.get_nowait()
            out.append(s.sequence_number)
        return out

    # Arrival order 2, 4, 1, 3 — nothing admits until 1 shows up; then all
    # flow contiguously.  A duplicate redelivery of 2 is dropped.
    w._admit_actor_task(spec(2), None)
    w._admit_actor_task(spec(4), None)
    assert drain() == []
    w._admit_actor_task(spec(1), None)
    w._admit_actor_task(spec(3), None)
    w._admit_actor_task(spec(2), None)  # duplicate redelivery: dropped
    assert drain() == [1, 2, 3, 4]

    # New incarnation resets admission to 1; stale incarnation 0 drops.
    w._admit_actor_task(spec(1, inc=1), None)
    w._admit_actor_task(spec(5, inc=0), None)  # stale: dropped
    assert drain() == [1]


def test_actor_restart_resets_sequencing(ray_cluster):
    """After an actor restart the new worker has fresh receiver state; the
    caller must renumber so calls keep executing (incarnation reset)."""

    @ray_tpu.remote(max_restarts=1)
    class Flaky:
        def __init__(self):
            self.calls = 0

        def ping(self, i):
            self.calls += 1
            return i

        def die(self):
            import os

            os._exit(1)

    a = Flaky.remote()
    assert ray_tpu.get([a.ping.remote(i) for i in range(5)], timeout=60) == list(range(5))
    a.die.remote()
    _finish_flaky_restart(a)


def test_actor_retry_preserves_order_across_crash(ray_cluster, tmp_path):
    """Induced redelivery: the actor's worker dies mid-stream with calls
    in flight; with max_task_retries=-1 every call completes and each
    incarnation executes its calls in submission order (reference:
    sequential_actor_submit_queue.h + actor_task_submitter retry path).
    Completed-but-unacknowledged calls MAY re-execute on the new
    incarnation — retriable actor tasks are at-least-once, as in the
    reference — but never out of order within an incarnation.  Execution
    is observed through a file because the crash wipes instance state."""
    log = str(tmp_path / "calls.log")
    marker = str(tmp_path / "died")

    @ray_tpu.remote(max_restarts=1, max_task_retries=-1)
    class Crashy:
        def log(self, path, marker, i):
            import os as _os

            if i == 7 and not _os.path.exists(marker):
                open(marker, "w").write("x")
                _os._exit(1)  # dies BEFORE logging: the call must be retried
            with open(path, "a") as f:
                f.write(f"{i}\n")
            return i

    a = Crashy.remote()
    refs = [a.log.remote(log, marker, i) for i in range(15)]
    assert ray_tpu.get(refs, timeout=120) == list(range(15))
    lines = [int(x) for x in open(log).read().split()]
    # The log is two strictly-increasing runs (one per incarnation): the
    # pre-crash run, then the post-restart run that finishes the stream.
    runs = [[lines[0]]] if lines else []
    for x in lines[1:]:
        (runs[-1].append(x) if x > runs[-1][-1] else runs.append([x]))
    assert len(runs) <= 2, f"interleaved execution: {lines}"
    assert runs[-1][-1] == 14
    assert set(lines) == set(range(15)), lines
    # No duplicates within one incarnation.
    for run in runs:
        assert len(run) == len(set(run))


def _finish_flaky_restart(a):
    # Wait for the restart, then keep calling — must not hang or misorder.
    deadline = time.monotonic() + 60
    while True:
        try:
            assert ray_tpu.get(a.ping.remote(100), timeout=10) == 100
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    assert ray_tpu.get([a.ping.remote(i) for i in range(3)], timeout=60) == [0, 1, 2]
