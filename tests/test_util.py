"""util extras: ActorPool, Queue, metrics, state API, timeline.

Reference test model: python/ray/tests/test_actor_pool.py, test_queue.py,
test_metrics_agent.py, python/ray/tests/test_state_api.py.
"""

import os
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def pool_actors(ray_cluster):
    @ray_tpu.remote
    class Doubler:
        def double(self, v):
            return 2 * v

        def slow_double(self, v):
            time.sleep(0.2 if v == 0 else 0.01)
            return 2 * v

    actors = [Doubler.remote() for _ in range(2)]
    yield actors
    for a in actors:
        ray_tpu.kill(a)


def test_actor_pool_ordered(pool_actors):
    from ray_tpu.util import ActorPool

    pool = ActorPool(pool_actors)
    out = list(pool.map(lambda a, v: a.double.remote(v), list(range(8))))
    assert out == [2 * v for v in range(8)]


def test_actor_pool_unordered(pool_actors):
    from ray_tpu.util import ActorPool

    pool = ActorPool(pool_actors)
    out = list(pool.map_unordered(lambda a, v: a.slow_double.remote(v), list(range(6))))
    assert sorted(out) == [2 * v for v in range(6)]


def test_actor_pool_submit_get(pool_actors):
    from ray_tpu.util import ActorPool

    pool = ActorPool(pool_actors)
    pool.submit(lambda a, v: a.double.remote(v), 10)
    pool.submit(lambda a, v: a.double.remote(v), 20)
    assert pool.get_next() == 20
    assert pool.get_next() == 40
    assert not pool.has_next()


def test_queue_basic(ray_cluster):
    from ray_tpu.util.queue import Empty, Queue

    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == 2
    with pytest.raises(Empty):
        q.get(block=False)
    q.shutdown()


def test_queue_producers_consumers(ray_cluster):
    from ray_tpu.util.queue import Queue

    q = Queue()

    @ray_tpu.remote
    def produce(q, lo, hi):
        for i in range(lo, hi):
            q.put(i)
        return hi - lo

    n = ray_tpu.get([produce.remote(q, 0, 5), produce.remote(q, 5, 10)])
    assert sum(n) == 10
    got = sorted(q.get() for _ in range(10))
    assert got == list(range(10))
    q.shutdown()


def test_state_api_actors_and_nodes(ray_cluster):
    from ray_tpu.util import state

    @ray_tpu.remote
    class Sleeper:
        def ping(self):
            return "pong"

    a = Sleeper.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    actors = state.list_actors([("state", "=", "ALIVE")])
    assert any(x["class_name"].endswith("Sleeper") for x in actors)
    nodes = state.list_nodes()
    assert len(nodes) >= 1 and nodes[0]["state"] == "ALIVE"
    ray_tpu.kill(a)


def test_task_events_and_timeline(ray_cluster, tmp_path):
    from ray_tpu.util import state

    @ray_tpu.remote
    def traced_task(x):
        return x + 1

    ray_tpu.get([traced_task.remote(i) for i in range(5)])
    # worker flushes events at most 1/s; poll the GCS table
    deadline = time.monotonic() + 15
    events = []
    while time.monotonic() < deadline:
        events = [e for e in state.list_tasks() if "traced_task" in e["name"]]
        if len(events) >= 5:
            break
        time.sleep(0.5)
    assert len(events) >= 5
    assert all(e["state"] == "FINISHED" for e in events)
    summary = state.summarize_tasks()
    assert any("traced_task" in name for name in summary["summary"])

    out = state.timeline(str(tmp_path / "trace.json"))
    import json

    with open(out) as f:
        trace = json.load(f)
    assert any("traced_task" in ev["name"] for ev in trace)


def test_metrics_roundtrip(ray_cluster):
    from ray_tpu.util import metrics as m
    from ray_tpu.util import state

    c = m.Counter("test_requests_total", description="reqs", tag_keys=("route",))
    c.inc(1.0, tags={"route": "a"})
    c.inc(2.0, tags={"route": "a"})
    g = m.Gauge("test_inflight")
    g.set(7.0)
    h = m.Histogram("test_latency_s", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    m.flush()

    deadline = time.monotonic() + 10
    recs = []
    while time.monotonic() < deadline:
        recs = state.metrics()
        if {r["name"] for r in recs} >= {"test_requests_total", "test_inflight", "test_latency_s"}:
            break
        time.sleep(0.5)
    by_name = {r["name"]: r for r in recs}
    assert by_name["test_requests_total"]["value"] == 3.0
    assert by_name["test_inflight"]["value"] == 7.0
    assert by_name["test_latency_s"]["count"] == 3
    assert by_name["test_latency_s"]["counts"] == [1, 1, 1]

    text = m.prometheus_text(recs)
    assert "test_requests_total" in text and 'le="+Inf"' in text


def test_trace_context_propagates_across_tasks(ray_cluster):
    """W3C trace context rides TaskSpec.trace_parent: every hop of a
    distributed call tree shares one trace id (reference:
    util/tracing/tracing_helper.py)."""
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def leaf():
        return tracing.get_trace_id(), tracing.get_span_id()

    @ray_tpu.remote
    def mid():
        here = tracing.get_trace_id()
        sub_trace, _sub_span = ray_tpu.get(leaf.remote())
        return here, sub_trace

    with tracing.start_span("root") as root:
        mid_trace, leaf_trace = ray_tpu.get(mid.remote(), timeout=60)
    assert mid_trace == root.trace_id, "trace id lost at first hop"
    assert leaf_trace == root.trace_id, "trace id lost at nested hop"
    # untraced submissions carry no context
    @ray_tpu.remote
    def bare():
        return tracing.get_trace_id()
    assert ray_tpu.get(bare.remote(), timeout=60) is None
    spans = tracing.drain_spans()
    assert any(s["name"] == "root" for s in spans)


def test_tracing_traceparent_format():
    from ray_tpu.util import tracing

    hdr = tracing.format_traceparent("a" * 32, "b" * 16)
    assert tracing.parse_traceparent(hdr) == ("a" * 32, "b" * 16)
    assert tracing.parse_traceparent("garbage") is None
    assert tracing.parse_traceparent(None) is None


def test_runtime_env_plugins(ray_cluster, tmp_path):
    from ray_tpu import exceptions
    from ray_tpu._private import runtime_env as renv

    # validation: plugin keys accepted, bad values rejected
    renv.validate({"conda": "myenv"})
    renv.validate({"uv": ["requests"]})
    renv.validate({"image_uri": "gcr.io/x/y:1"})
    with pytest.raises(renv.RuntimeEnvError):
        renv.validate({"uv": "not-a-list"})
    with pytest.raises(renv.RuntimeEnvError):
        renv.validate({"bogus_key": 1})

    # custom plugin: registered, staged in priority order
    staged = []

    class MarkerPlugin(renv.RuntimeEnvPlugin):
        name = "marker"
        priority = 1

        def stage(self, value, gcs_client, session_dir):
            staged.append(value)
            os.environ["MARKER_PLUGIN"] = str(value)

    renv.register_plugin(MarkerPlugin())
    try:
        norm, uploads = renv.prepare({"marker": "hello"})
        assert norm == {"marker": "hello"} and uploads == []
        renv.stage_and_apply({"marker": "hello"}, None, str(tmp_path))
        assert staged == ["hello"]
        assert os.environ.pop("MARKER_PLUGIN") == "hello"
    finally:
        renv._plugins.pop("marker", None)
        renv.SUPPORTED_KEYS.discard("marker")

    # gated plugin fails LOUDLY end-to-end (no container runtime here)
    @ray_tpu.remote(runtime_env={"image_uri": "gcr.io/x/y:1"}, max_retries=0)
    def containered():
        return 1

    with pytest.raises(exceptions.RuntimeEnvSetupError):
        ray_tpu.get(containered.remote(), timeout=120)
