"""Elastic training plane (ISSUE 4): resize-on-preemption for JaxTrainer
with generation-tagged collective re-rendezvous.

Layers drilled here:

1. Tier-1 elastic shrink: a drain notice covering a rank shrinks the
   group to the largest healthy size >= min_workers — survivors keep
   their actors, training resumes from the drain checkpoint, nothing is
   charged to FailureConfig.max_failures, and
   train.get_context().get_world_size() is dynamic across the resize.
2. Chaos matrix (``-m chaos``):
   - the acceptance drill: ``num_workers=4, min_workers=2``, a
     ``preempt`` chaos action killing one rank's raylet mid-step yields
     checkpoint -> shrink to 3 -> completion with final-loss parity vs
     an uninterrupted run, zero failure-budget charges; a subsequent
     mock capacity return grows the group back to 4, with resize events
     visible in the metrics registry and resize spans recorded;
   - shrink refused below min_workers: falls back to the whole-group
     restart path, charged normally;
3. Elastic surfaces: ScalingConfig validation, resize metrics/span
   plumbing.
"""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def elastic_cluster():
    """Head + N worker nodes, with optional per-node chaos env (the
    preemption rule must hit exactly one raylet)."""
    created = []
    saved_env = {}

    def set_env(env):
        for k, v in env.items():
            saved_env.setdefault(k, os.environ.get(k))
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def make(head_args=None, nodes=()):
        c = Cluster(initialize_head=True, head_node_args=head_args or {"num_cpus": 1})
        handles = []
        for kw in nodes:
            kw = dict(kw)
            node_env = kw.pop("node_env", {})
            set_env(node_env)
            handles.append(c.add_node(**kw))
            set_env({k: None for k in node_env})
        c.wait_for_nodes()
        ray_tpu.init(address=c.address)
        created.append(c)
        return c, handles

    yield make
    ray_tpu.shutdown()
    for c in created:
        c.shutdown()
    for k, old in saved_env.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old
    from ray_tpu._private.chaos import CHAOS

    CHAOS.reset()


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


def _elastic_loop(config):
    """Deterministic elastic-aware loop: the 'loss' depends only on the
    step counter, so a run that shrank and grew MUST land on the same
    final loss as an uninterrupted one (the parity check).  Checkpoints
    every step so resizes resume where they left off; per-rank progress
    files expose (node, step, world_size, generation) to the driver."""
    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    ctx = train.get_context()
    resume = train.get_checkpoint()
    start = resume.to_pytree()["step"] if resume is not None else 0
    node_id = ray_tpu.get_runtime_context().get_node_id()
    for step in range(start + 1, config["total_steps"] + 1):
        time.sleep(config.get("step_s", 0.15))
        loss = 1.0 / step
        ckpt = None
        if ctx.get_world_rank() == 0 or ctx.drain_requested():
            ckpt = Checkpoint.from_pytree({"step": step})
        if config.get("progress_dir"):
            path = os.path.join(
                config["progress_dir"], f"rank_{ctx.get_world_rank()}"
            )
            with open(path, "w") as f:
                f.write(
                    f"{node_id} {step} {ctx.get_world_size()} {ctx.get_generation()}"
                )
        train.report(
            {
                "step": step,
                "loss": loss,
                "world_size": ctx.get_world_size(),
                "generation": ctx.get_generation(),
            },
            checkpoint=ckpt,
        )


def _progress(progress_dir):
    """rank -> (node_id, step, world_size, generation) from the files."""
    out = {}
    try:
        for name in os.listdir(progress_dir):
            if not name.startswith("rank_"):
                continue
            with open(os.path.join(progress_dir, name)) as f:
                parts = f.read().split()
            if len(parts) == 4:
                out[int(name[5:])] = (
                    parts[0], int(parts[1]), int(parts[2]), int(parts[3])
                )
    except OSError:
        pass
    return out


def _resize_event_count(direction=None):
    from ray_tpu.util import metrics as metrics_mod

    total = 0.0
    for (name, tags), rec in metrics_mod._registry.items():
        if name != "train_resize_events_total":
            continue
        if direction is not None and ("direction", direction) not in tuple(tags):
            continue
        total += rec.get("value", 0.0)
    return total


def test_scaling_config_elastic_validation():
    from ray_tpu.air.config import ScalingConfig

    assert not ScalingConfig(num_workers=2).elastic
    assert not ScalingConfig(num_workers=2, min_workers=2).elastic
    assert ScalingConfig(num_workers=4, min_workers=2).elastic
    with pytest.raises(ValueError):
        ScalingConfig(num_workers=2, min_workers=3)
    with pytest.raises(ValueError):
        ScalingConfig(num_workers=2, min_workers=0)


def test_elastic_shrink_on_drain(elastic_cluster, tmp_path):
    """Tier-1 elastic smoke: a drain notice covering one of two ranks
    shrinks the group to 1 (>= min_workers), training completes from the
    drain checkpoint with max_failures=0 untouched, and the user loop
    observes the dynamic world size + bumped generation."""
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxConfig, JaxTrainer

    c, handles = elastic_cluster(
        head_args={"num_cpus": 1},
        nodes=[{"num_cpus": 2}, {"num_cpus": 2}],
    )
    worker = ray_tpu._private.worker.get_global_worker()
    progress_dir = str(tmp_path / "progress")
    os.makedirs(progress_dir, exist_ok=True)
    total_steps = 20

    stop = threading.Event()
    drained = []

    def drainer():
        # Once rank 1 passes step 5, drain its node (a preemption notice).
        while not stop.is_set():
            prog = _progress(progress_dir)
            if 1 in prog and prog[1][1] >= 5:
                node_id = prog[1][0]
                worker.gcs_client.call(
                    "drain_node",
                    {
                        "node_id": bytes.fromhex(node_id),
                        "reason": "PREEMPTION",
                        "deadline_s": 60,
                    },
                )
                drained.append(node_id)
                return
            time.sleep(0.1)

    t = threading.Thread(target=drainer, daemon=True)
    t.start()
    try:
        trainer = JaxTrainer(
            _elastic_loop,
            train_loop_config={
                "total_steps": total_steps,
                "progress_dir": progress_dir,
            },
            jax_config=JaxConfig(distributed=False),
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1, resources_per_worker={"CPU": 2}
            ),
            run_config=RunConfig(
                name="elastic_shrink",
                storage_path=str(tmp_path),
                # ZERO budget: a charged restart would raise.
                failure_config=FailureConfig(max_failures=0),
            ),
        )
        result = trainer.fit()
    finally:
        stop.set()
        t.join(timeout=5)

    assert drained, "the drill never drained a node"
    assert result.metrics["step"] == total_steps
    assert result.metrics["loss"] == 1.0 / total_steps
    # The run finished SHRUNKEN: one rank, generation bumped past 0.
    assert result.metrics["world_size"] == 1
    assert result.metrics["generation"] >= 1
    assert _resize_event_count("shrink") >= 1


# ==========================================================================
# Chaos matrix
# ==========================================================================


@pytest.mark.slow  # ~36 s preempt/shrink/grow acceptance: runs under `-m chaos`
@pytest.mark.chaos
def test_elastic_acceptance_preempt_shrink_grow(elastic_cluster, tmp_path):
    """The acceptance drill: num_workers=4, min_workers=2; a seeded
    ``preempt`` chaos action kills one rank's raylet mid-step ->
    checkpoint -> shrink to 3 -> training continues; a mock capacity
    return (new node) grows the group back to 4 at an epoch boundary;
    the final loss has parity with an uninterrupted run; zero charges
    against max_failures; resize events land in the metrics registry and
    resize spans are recorded."""
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxConfig, JaxTrainer

    shrink_before = _resize_event_count("shrink")
    grow_before = _resize_event_count("grow")

    c, handles = elastic_cluster(
        # 0-CPU head: all four ranks must land on worker nodes, so the
        # preempted node is guaranteed to host one.
        head_args={"num_cpus": 0},
        nodes=[
            {
                "num_cpus": 1,
                # ~15 s of ticks so training is well underway even on a
                # slow box, then an 8 s notice before the raylet
                # self-kills: the drain window in which checkpoint +
                # shrink must land.
                "node_env": {
                    "RAY_TPU_testing_chaos_spec": "@raylet.tick:preempt:at=75:ms=8000",
                    "RAY_TPU_testing_chaos_seed": "11",
                },
            },
            {"num_cpus": 1},
            {"num_cpus": 1},
            {"num_cpus": 1},
        ],
    )
    progress_dir = str(tmp_path / "progress")
    os.makedirs(progress_dir, exist_ok=True)
    total_steps = 80

    stop = threading.Event()
    grew = []

    def capacity_returner():
        # Mock capacity return: once any rank reports world_size 3 (the
        # shrink landed), add a replacement node.  No wait_for_nodes —
        # the executor's readiness ping gates the grow, and the cluster
        # may already be tearing down by the time the node registers.
        while not stop.is_set():
            prog = _progress(progress_dir)
            if any(p[2] == 3 for p in prog.values()):
                try:
                    grew.append(c.add_node(num_cpus=1))
                except Exception:
                    pass
                return
            time.sleep(0.2)

    t = threading.Thread(target=capacity_returner, daemon=True)
    t.start()
    try:
        trainer = JaxTrainer(
            _elastic_loop,
            train_loop_config={
                "total_steps": total_steps,
                "progress_dir": progress_dir,
                "step_s": 0.25,
            },
            jax_config=JaxConfig(distributed=False),
            scaling_config=ScalingConfig(
                num_workers=4, min_workers=2, resources_per_worker={"CPU": 1}
            ),
            run_config=RunConfig(
                name="elastic_acceptance",
                storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=0),
            ),
        )
        result = trainer.fit()
    finally:
        stop.set()
        t.join(timeout=5)

    # Parity: same final step and loss as an uninterrupted run.
    assert result.metrics["step"] == total_steps
    assert result.metrics["loss"] == 1.0 / total_steps
    # Shrink to 3 happened (observed by the loop itself), then the mock
    # capacity return grew the group back to 4.
    assert grew, "capacity return never triggered (no shrink to 3 observed)"
    assert result.metrics["world_size"] == 4, result.metrics
    assert result.metrics["generation"] >= 2  # >= one shrink + one grow
    assert _resize_event_count("shrink") >= shrink_before + 1
    assert _resize_event_count("grow") >= grow_before + 1
    # Resize spans recorded (state.timeline() merges these from the span
    # log; assert at the source to stay robust on slow CI flushes).
    from ray_tpu.util import tracing

    names = [s.get("name") for s in tracing._finished_spans]
    assert "train.resize" in names


def _die_hard_loop(config):
    """Every rank dies hard (os._exit) at the configured step on the
    first attempt — below min_workers, so the elastic path must REFUSE to
    shrink and fall back to the charged whole-group restart.  The die
    decision is captured at LOOP ENTRY (before any rank can write the
    marker), so every first-attempt rank dies regardless of step skew."""
    from ray_tpu import train

    ctx = train.get_context()
    marker = config["marker"]
    die = not os.path.exists(marker)
    for step in range(1, config["total_steps"] + 1):
        time.sleep(0.1)
        if step == 3 and die:
            if ctx.get_world_rank() == 0:
                with open(marker, "w") as f:
                    f.write("died")
            os._exit(1)
        train.report({"step": step, "world_size": ctx.get_world_size()})


@pytest.mark.chaos
def test_elastic_shrink_refused_below_min_workers(elastic_cluster, tmp_path):
    """Satellite: when the casualty count would take the group below
    min_workers, shrink is refused and the run falls back to the PR 3
    whole-group restart path — charged normally against max_failures."""
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxConfig, JaxTrainer
    from ray_tpu.train.base_trainer import TrainingFailedError

    elastic_cluster(head_args={"num_cpus": 4})
    marker = str(tmp_path / "all_died")

    def make_trainer(max_failures):
        return JaxTrainer(
            _die_hard_loop,
            train_loop_config={"total_steps": 6, "marker": marker},
            jax_config=JaxConfig(distributed=False),
            scaling_config=ScalingConfig(
                num_workers=2, min_workers=1, resources_per_worker={"CPU": 1}
            ),
            run_config=RunConfig(
                name=f"elastic_refused_{max_failures}",
                storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=max_failures),
            ),
        )

    # Budget of 1: the whole-group death charges ONE failure, the restart
    # completes at full size.
    result = make_trainer(1).fit()
    assert result.metrics["step"] == 6
    assert result.metrics["world_size"] == 2  # full-size restart, no shrink

    # Budget of 0: the same death is charged and the run fails — proof
    # the refused shrink did NOT silently eat the failure.
    os.remove(marker)
    with pytest.raises(TrainingFailedError):
        make_trainer(0).fit()
