"""Core API tests (model: reference python/ray/tests/test_basic.py)."""

import numpy as np
import pytest

import ray_tpu


def test_put_get_small(ray_cluster):
    ref = ray_tpu.put({"a": 1, "b": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_large_numpy(ray_cluster):
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)
    # Zero-copy: the result must be backed by the shared-memory mapping.
    assert not out.flags["OWNDATA"]


def test_simple_task(ray_cluster):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2


def test_task_with_ref_arg(ray_cluster):
    @ray_tpu.remote
    def f(x):
        return x * 2

    r1 = f.remote(10)
    r2 = f.remote(r1)
    assert ray_tpu.get(r2) == 40


def test_task_large_arg_roundtrip(ray_cluster):
    arr = np.ones((512, 512), dtype=np.float32)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    assert ray_tpu.get(total.remote(arr)) == float(arr.sum())


def test_multiple_returns(ray_cluster):
    @ray_tpu.remote(num_returns=2)
    def two():
        return 1, 2

    a, b = two.remote()
    assert ray_tpu.get(a) == 1
    assert ray_tpu.get(b) == 2


def test_task_error_propagation(ray_cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_wait(ray_cluster):
    import time

    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=4)
    assert ready == [f]
    assert not_ready == [s]


def test_nested_tasks(ray_cluster):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1)) == 12


def test_options_override(ray_cluster):
    @ray_tpu.remote
    def f():
        return ray_tpu.get_runtime_context().get_assigned_resources()

    res = ray_tpu.get(f.options(num_cpus=2).remote())
    assert res.get("CPU") == 2


def test_cluster_resources(ray_cluster):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4.0
    assert len(ray_tpu.nodes()) == 1


class TestActors:
    def test_actor_basic(self, ray_cluster):
        @ray_tpu.remote
        class Counter:
            def __init__(self, start=0):
                self.n = start

            def incr(self, by=1):
                self.n += by
                return self.n

            def value(self):
                return self.n

        c = Counter.remote(10)
        assert ray_tpu.get(c.incr.remote()) == 11
        assert ray_tpu.get(c.incr.remote(5)) == 16
        assert ray_tpu.get(c.value.remote()) == 16

    def test_actor_ordering(self, ray_cluster):
        @ray_tpu.remote
        class Seq:
            def __init__(self):
                self.log = []

            def add(self, x):
                self.log.append(x)
                return len(self.log)

            def get_log(self):
                return self.log

        s = Seq.remote()
        for i in range(20):
            s.add.remote(i)
        assert ray_tpu.get(s.get_log.remote()) == list(range(20))

    def test_named_actor(self, ray_cluster):
        @ray_tpu.remote
        class Store:
            def __init__(self):
                self.d = {}

            def set(self, k, v):
                self.d[k] = v

            def get(self, k):
                return self.d.get(k)

        Store.options(name="kvstore").remote()
        h = ray_tpu.get_actor("kvstore")
        ray_tpu.get(h.set.remote("x", 42))
        assert ray_tpu.get(h.get.remote("x")) == 42
        assert "kvstore" in ray_tpu.util.list_named_actors()
        rows = ray_tpu.util.list_named_actors(all_namespaces=True)
        assert any(r["name"] == "kvstore" for r in rows)
        ray_tpu.kill(h)

    def test_actor_error(self, ray_cluster):
        @ray_tpu.remote
        class Bad:
            def fail(self):
                raise RuntimeError("actor error")

        b = Bad.remote()
        with pytest.raises(RuntimeError, match="actor error"):
            ray_tpu.get(b.fail.remote())

    def test_actor_kill(self, ray_cluster):
        @ray_tpu.remote
        class A:
            def ping(self):
                return "pong"

        a = A.remote()
        assert ray_tpu.get(a.ping.remote()) == "pong"
        ray_tpu.kill(a)
        import time

        time.sleep(1.0)
        with pytest.raises(ray_tpu.exceptions.RayActorError):
            ray_tpu.get(a.ping.remote(), timeout=10)

    def test_actor_handle_pass(self, ray_cluster):
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        @ray_tpu.remote
        def bump(counter):
            return ray_tpu.get(counter.incr.remote())

        c = Counter.remote()
        assert ray_tpu.get(bump.remote(c)) == 1
        assert ray_tpu.get(c.incr.remote()) == 2


def test_worker_logs_stream_to_driver(ray_start_regular, capfd):
    """Worker prints reach the driver's stderr with worker prefixes
    (reference: log_monitor.py -> pubsub -> driver printing)."""
    import time

    ray_tpu = ray_start_regular

    @ray_tpu.remote
    def shout():
        print("HELLO-LOG-STREAM-42")
        return 1

    assert ray_tpu.get(shout.remote(), timeout=60) == 1
    deadline = time.monotonic() + 10
    seen = ""
    while time.monotonic() < deadline:
        out, err = capfd.readouterr()
        seen += err + out
        if "HELLO-LOG-STREAM-42" in seen:
            break
        time.sleep(0.3)
    assert "HELLO-LOG-STREAM-42" in seen
    assert "pid=" in seen
