"""GPT-2 model + sharded train step on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


def _batch(cfg, B=4, T=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, size=(B, T + 1), dtype=np.int32)
    return jnp.asarray(tokens[:, :-1]), jnp.asarray(tokens[:, 1:])


def test_gpt2_forward_shapes():
    from ray_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg)
    tokens, _ = _batch(cfg, B=2, T=32)
    logits = gpt2.GPT2(cfg).apply({"params": params}, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits, dtype=np.float32)).all()


def test_gpt2_sharded_train_step_dp_tp_sp():
    """Full dp×tp×sp train step: params tp/fsdp-sharded, batch dp-sharded,
    sequence sp-sharded through ring attention; loss decreases."""
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = create_mesh({"dp": 2, "tp": 2, "sp": 2})
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32, mesh=mesh, sp_axis="sp")
    opt = gpt2.make_adamw(lr=1e-2)
    params, opt_state, specs = gpt2.make_sharded_train_state(cfg, mesh, opt)
    step = gpt2.make_sharded_train_step(cfg, mesh, opt)
    tokens, targets = _batch(cfg, B=4, T=64)
    losses = []
    for i in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_gpt2_tp_matches_single_device():
    """The sharded forward must compute the same function as unsharded."""
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh
    from ray_tpu.parallel.sharding import gpt_sharding_rules, infer_param_spec, shard_tree

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg)
    tokens, _ = _batch(cfg, B=2, T=32)
    ref = gpt2.GPT2(cfg).apply({"params": params}, tokens)

    mesh = create_mesh({"dp": 2, "tp": 2})
    specs = infer_param_spec(params, gpt_sharding_rules(), mesh)
    sharded = shard_tree(params, mesh, specs)
    out = jax.jit(lambda p, t: gpt2.GPT2(cfg).apply({"params": p}, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_param_sharding_rules_hit_tp_axes():
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh
    from ray_tpu.parallel.sharding import gpt_sharding_rules, infer_param_spec

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = create_mesh({"dp": 2, "tp": 4})
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    abstract = jax.eval_shape(lambda: gpt2.init_params(cfg))
    specs = infer_param_spec(abstract, gpt_sharding_rules(), mesh)
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]}
    qkv = [s for p, s in flat.items() if "qkv/kernel" in p]
    assert qkv and all("tp" in str(s) for s in qkv), flat
    down = [s for p, s in flat.items() if "mlp_down/kernel" in p]
    assert down and all(str(s).startswith("PartitionSpec('tp'") for s in down)


# ---------------------------------------------------------------------------
# Llama family


def test_llama_forward_and_loss():
    from ray_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 33), dtype=np.int32))
    logits = llama.Llama(cfg).apply({"params": params}, toks[:, :-1])
    assert logits.shape == (2, 32, cfg.vocab_size)
    loss = float(llama.loss_fn(params, toks[:, :-1], toks[:, 1:], cfg))
    assert np.isfinite(loss)
    # Untrained loss should be near ln(vocab) for a random model.
    assert abs(loss - np.log(cfg.vocab_size)) < 1.5


def test_llama_gqa_kv_heads_smaller():
    from ray_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg)
    blk = params["h_0"]["attn"]
    d_head = cfg.d_model // cfg.n_head
    assert blk["q_proj"]["kernel"].shape[1] == cfg.n_head * d_head
    assert blk["k_proj"]["kernel"].shape[1] == cfg.n_kv_head * d_head
    assert cfg.n_kv_head < cfg.n_head


def test_llama_sharded_train_step():
    from ray_tpu.models import llama
    from ray_tpu.parallel import create_mesh

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = create_mesh({"dp": 2, "tp": 2}, devs[:4])
    cfg = llama.LlamaConfig.tiny(mesh=mesh)
    opt = __import__("optax").sgd(1e-2)
    params, opt_state, specs = llama.make_sharded_train_state(cfg, mesh, opt)
    step = llama.make_sharded_train_step(cfg, mesh, opt)
    toks = np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 65), dtype=np.int32)
    t, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, t, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # learns on the repeated batch
    # tp layout hit the projections
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]}
    assert any("q_proj/kernel" in p and "tp" in str(s) for p, s in flat.items())


def test_llama_rope_rotation_properties():
    from ray_tpu.models.llama import rope

    x = jnp.asarray(np.random.default_rng(2).standard_normal((1, 8, 2, 16)), dtype=jnp.float32)
    r = rope(x, 10000.0)
    # Norm-preserving per position...
    assert np.allclose(np.linalg.norm(np.asarray(r), axis=-1),
                       np.linalg.norm(np.asarray(x), axis=-1), atol=1e-4)
    # ...and position 0 is the identity rotation.
    assert np.allclose(np.asarray(r[:, 0]), np.asarray(x[:, 0]), atol=1e-6)


# ---------------------------------------------------------------------------
# MoE / expert parallelism


def test_moe_routes_and_learns():
    from ray_tpu.models.moe import MoEConfig, MoEMLP

    cfg = MoEConfig(d_model=32, d_ff=64, num_experts=4, top_k=2, dtype=jnp.float32)
    mod = MoEMLP(cfg)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 16, 32)), dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(0), x)["params"]
    out, aux = mod.apply({"params": params}, x)
    assert out.shape == x.shape
    assert np.isfinite(float(aux)) and float(aux) > 0

    def loss(p):
        y, aux = mod.apply({"params": p}, x)
        return ((y - x) ** 2).mean() + aux

    grads = jax.grad(loss)(params)
    norms = [float(jnp.linalg.norm(g)) for g in jax.tree_util.tree_leaves(grads)]
    assert all(np.isfinite(n) for n in norms) and sum(norms) > 0


def test_moe_expert_parallel_matches_single_device():
    """ep-sharded execution must compute exactly what one device does."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models.moe import MoEConfig, MoEMLP, moe_sharding_rules
    from ray_tpu.parallel import create_mesh
    from ray_tpu.parallel.sharding import infer_param_spec, tree_shardings

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = create_mesh({"ep": 4}, devs[:4])
    cfg = MoEConfig(d_model=32, d_ff=64, num_experts=8, top_k=2, dtype=jnp.float32)
    mod = MoEMLP(cfg)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((2, 16, 32)), dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(1), x)["params"]
    ref_out, ref_aux = mod.apply({"params": params}, x)

    specs = infer_param_spec(params, moe_sharding_rules(), mesh)
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]}
    assert str(flat["experts_gate"]).startswith("PartitionSpec('ep'"), flat
    sharded_params = jax.device_put(params, tree_shardings(mesh, specs))
    x_sharded = jax.device_put(x, NamedSharding(mesh, P()))
    out, aux = jax.jit(lambda p, v: mod.apply({"params": p}, v))(sharded_params, x_sharded)
    assert np.allclose(np.asarray(out), np.asarray(ref_out), atol=1e-4)
    assert abs(float(aux) - float(ref_aux)) < 1e-5


def test_vit_overfits_synthetic_batch():
    """ViT (models/vit.py): forward shapes + a few steps overfit a tiny
    labeled batch (the standard can-it-learn smoke for a new model
    family; reference trains ViTs through the Train library)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import vit

    cfg = vit.ViTConfig.tiny(image_size=16, patch_size=4, num_classes=4,
                             dtype=jnp.float32)
    params = vit.init_params(cfg)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(16, 16, 16, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 4, 16))

    logits = vit.ViT(cfg).apply({"params": params}, images)
    assert logits.shape == (16, 4)

    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(vit.make_train_step(cfg, opt))
    first = None
    for _ in range(40):
        params, opt_state, loss = step(params, opt_state, images, labels)
        first = first if first is not None else float(loss)
    last = float(loss)
    assert last < first * 0.5, (first, last)
