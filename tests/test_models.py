"""GPT-2 model + sharded train step on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


def _batch(cfg, B=4, T=64, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, size=(B, T + 1), dtype=np.int32)
    return jnp.asarray(tokens[:, :-1]), jnp.asarray(tokens[:, 1:])


def test_gpt2_forward_shapes():
    from ray_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg)
    tokens, _ = _batch(cfg, B=2, T=32)
    logits = gpt2.GPT2(cfg).apply({"params": params}, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits, dtype=np.float32)).all()


def test_gpt2_sharded_train_step_dp_tp_sp():
    """Full dp×tp×sp train step: params tp/fsdp-sharded, batch dp-sharded,
    sequence sp-sharded through ring attention; loss decreases."""
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = create_mesh({"dp": 2, "tp": 2, "sp": 2})
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32, mesh=mesh, sp_axis="sp")
    opt = gpt2.make_adamw(lr=1e-2)
    params, opt_state, specs = gpt2.make_sharded_train_state(cfg, mesh, opt)
    step = gpt2.make_sharded_train_step(cfg, mesh, opt)
    tokens, targets = _batch(cfg, B=4, T=64)
    losses = []
    for i in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_gpt2_tp_matches_single_device():
    """The sharded forward must compute the same function as unsharded."""
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh
    from ray_tpu.parallel.sharding import gpt_sharding_rules, infer_param_spec, shard_tree

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init_params(cfg)
    tokens, _ = _batch(cfg, B=2, T=32)
    ref = gpt2.GPT2(cfg).apply({"params": params}, tokens)

    mesh = create_mesh({"dp": 2, "tp": 2})
    specs = infer_param_spec(params, gpt_sharding_rules(), mesh)
    sharded = shard_tree(params, mesh, specs)
    out = jax.jit(lambda p, t: gpt2.GPT2(cfg).apply({"params": p}, t))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_param_sharding_rules_hit_tp_axes():
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh
    from ray_tpu.parallel.sharding import gpt_sharding_rules, infer_param_spec

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = create_mesh({"dp": 2, "tp": 4})
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    abstract = jax.eval_shape(lambda: gpt2.init_params(cfg))
    specs = infer_param_spec(abstract, gpt_sharding_rules(), mesh)
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]}
    qkv = [s for p, s in flat.items() if "qkv/kernel" in p]
    assert qkv and all("tp" in str(s) for s in qkv), flat
    down = [s for p, s in flat.items() if "mlp_down/kernel" in p]
    assert down and all(str(s).startswith("PartitionSpec('tp'") for s in down)
