"""GSPMD pjit path (train/sharding/gspmd.py + checkpoint.py): GPT-2
sharded over a batch x model mesh trains with LOSS PARITY vs the
data-parallel baseline, and per-shard checkpoints re-shard onto a
different mesh (the elastic resize semantics).

All tests run single-process on the suite's 8 virtual CPU devices; the
multi-worker variant of the same plan is the trainer integration below
(capability-probe-xfailed on the CPU backend like its data-parallel
siblings)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import ray_tpu.train.sharding as sharding  # noqa: E402
from ray_tpu.models import gpt2  # noqa: E402


def _tiny_cfg():
    # f32 end-to-end so parity checks are exact-ish, not bf16-fuzzy.
    return gpt2.GPT2Config(
        vocab_size=256, n_layer=2, n_head=2, d_model=64, max_seq_len=64,
        dtype=jnp.float32, remat=False,
    )


def _init_fn(cfg):
    def init(rng):
        tokens = jnp.zeros((2, 16), dtype=jnp.int32)
        return gpt2.GPT2(cfg).init(rng, tokens)["params"]

    return init


def _data(steps=3, batch=8, seq=17, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (steps, batch, seq)).astype(np.int32)


def _run(plan, cfg, data):
    opt = gpt2.make_adamw(1e-3)
    params, opt_state = plan.shard_init(_init_fn(cfg), opt)
    step = plan.jit_train_step(gpt2.make_train_step(cfg, opt), params, opt_state)
    losses = []
    for toks in data:
        params, opt_state, loss = step(
            params, opt_state, toks[:, :-1], toks[:, 1:]
        )
        losses.append(float(loss))
    return params, opt_state, losses


def test_gspmd_mesh_shards_params_and_state():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    plan = sharding.build_plan(
        sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 2})
    )
    assert dict(plan.mesh.shape) == {"batch": 4, "model": 2}
    cfg = _tiny_cfg()
    opt = gpt2.make_adamw(1e-3)
    params, opt_state = plan.shard_init(_init_fn(cfg), opt)
    qkv = params["h_0"]["attn"]["qkv"]["kernel"]
    # the model axis really splits the leaf: each shard holds half
    assert qkv.sharding.spec == jax.sharding.PartitionSpec(None, "model")
    shard_cols = {s.data.shape[1] for s in qkv.addressable_shards}
    assert shard_cols == {qkv.shape[1] // 2}
    # optimizer moments follow the SAME layout; scalars replicate
    flat = jax.tree_util.tree_leaves(opt_state)
    assert all(
        getattr(l.sharding, "mesh", None) is plan.mesh
        or l.sharding.is_fully_replicated
        for l in flat
    )


def test_gspmd_loss_parity_vs_data_parallel():
    """The acceptance bar: batch x model sharded GPT-2 trains to the
    same losses as the pure data-parallel layout (same seed/data)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = _tiny_cfg()
    data = _data()
    plan_tp = sharding.build_plan(
        sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 2})
    )
    plan_dp = sharding.build_plan(
        sharding.ShardingConfig(
            mesh=("batch",), mesh_shape={"batch": 8},
            partition_rules=[(r".*", ())],
        )
    )
    _, _, losses_tp = _run(plan_tp, cfg, data)
    _, _, losses_dp = _run(plan_dp, cfg, data)
    assert losses_tp == pytest.approx(losses_dp, abs=1e-4)


def test_sharded_checkpoint_reshards_on_mesh_resize(tmp_path):
    """Per-shard save on a model=2 mesh, restore onto a model=4 mesh
    (shrink/grow-whole-hosts resize): values identical, new layout."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = _tiny_cfg()
    data = _data(steps=2)
    plan_a = sharding.build_plan(
        sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 2})
    )
    params_a, opt_a, _ = _run(plan_a, cfg, data)
    plan_a.save_checkpoint({"params": params_a, "opt": opt_a}, str(tmp_path))

    plan_b = sharding.build_plan(
        sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 4})
    )
    opt = gpt2.make_adamw(1e-3)
    like_p, like_o = plan_b.shard_init(_init_fn(cfg), opt)
    restored = plan_b.load_checkpoint(
        str(tmp_path), {"params": like_p, "opt": like_o}
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(restored["params"]),
        jax.tree_util.tree_leaves(params_a),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    qkv = restored["params"]["h_0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.mesh.shape["model"] == 4
    # training continues from the restored state on the NEW mesh
    step = plan_b.jit_train_step(
        gpt2.make_train_step(cfg, opt), restored["params"], restored["opt"]
    )
    toks = _data(steps=1)[0]
    _, _, loss = step(
        restored["params"], restored["opt"], toks[:, :-1], toks[:, 1:]
    )
    assert np.isfinite(float(loss))


def test_checkpoint_leaf_mismatch_is_typed(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    plan = sharding.build_plan(
        sharding.ShardingConfig(mesh_shape={"batch": -1, "model": 2})
    )
    tree = {"a": jnp.zeros((4, 4))}
    plan.save_checkpoint(tree, str(tmp_path))
    with pytest.raises(ValueError, match="leaves"):
        sharding.load_sharded(str(tmp_path), {"a": tree["a"], "b": tree["a"]})


def _sharded_trainer_loop(config):
    """Multi-worker GSPMD: the trainer carried the ShardingConfig; every
    rank binds it to the global device view via plan_from_context."""
    import jax
    import jax.numpy as jnp

    from ray_tpu import train
    from ray_tpu.models import gpt2
    from ray_tpu.train import sharding

    ctx = train.get_context()
    assert ctx.get_sharding_config() is not None
    plan = sharding.plan_from_context()
    assert plan.mesh.shape["model"] == 2
    assert len(jax.devices()) == 8 * config["num_workers"]
    cfg = gpt2.GPT2Config(
        vocab_size=256, n_layer=2, n_head=2, d_model=64, max_seq_len=64,
        dtype=jnp.float32, remat=False,
    )
    opt = gpt2.make_adamw(1e-3)

    def init(rng):
        return gpt2.GPT2(cfg).init(
            rng, jnp.zeros((2, 16), dtype=jnp.int32)
        )["params"]

    params, opt_state = plan.shard_init(init, opt)
    step = plan.jit_train_step(
        gpt2.make_train_step(cfg, opt), params, opt_state
    )
    import numpy as np

    toks = np.random.default_rng(0).integers(0, 256, (8, 17)).astype(np.int32)
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("sharded_trainer_loop")
    last = None
    for _ in range(2):
        params, opt_state, loss = step(
            params, opt_state, toks[:, :-1], toks[:, 1:]
        )
        last = float(jax.device_get(loss))
    train.report({"loss": last})


def test_jax_trainer_carries_sharding_config(ray_cluster, tmp_path):
    """JaxTrainer(sharding_config=...) reaches every rank's context and
    the 2-worker group forms one 16-device batch x model mesh."""
    from ray_tpu.train import RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxTrainer

    trainer = JaxTrainer(
        _sharded_trainer_loop,
        train_loop_config={"num_workers": 2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="gspmd_cfg", storage_path=str(tmp_path)),
        sharding_config=sharding.ShardingConfig(
            mesh_shape={"batch": -1, "model": 2}
        ),
    )
    result = trainer.fit()
    assert np.isfinite(result.metrics["loss"])
