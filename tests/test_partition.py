"""Partition-tolerant membership: link-level chaos, incarnation fencing,
and the gray-failure suspicion/quarantine ladder.

Three layers of drills (mirroring test_chaos.py):

1. The ``net:<src>-><dst>`` rule family in isolation — parser round
   trips, directional matching, seeded flaky replay, and the
   ``start=``/``for=`` wall-clock arming windows (a partition that
   heals, a link that flaps).
2. The GCS membership state machine, unit-tested by direct
   construction (no sockets): the incarnation fence matrix, the
   suspicion-score blend (gray signals cap below DEAD), and the
   QUARANTINED readmission path (hysteresis + flap budget).
3. Live-cluster drills: a zombie incarnation's writes are rejected
   over the wire with a typed, counted error, and serve routing
   demotes replicas on a QUARANTINED node then re-promotes them after
   readmission.

The asymmetric-partition and gray-failure end-to-end drills (real
raylets behind cut/slow links) live in scripts/partition_smoke.py —
they need whole-process net identities that an in-process test can't
fake.
"""

import os
import pickle
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.exceptions import NodeFencedError


# ----------------------------------------------------------------------
# fixtures
# ----------------------------------------------------------------------


@pytest.fixture()
def chaos_env():
    """In-process chaos plane: set spec env vars, reset the parsed
    rule table, and restore both afterwards."""
    from ray_tpu._private.chaos import CHAOS

    saved = {}

    def set_env(env: dict):
        for k, v in env.items():
            saved.setdefault(k, os.environ.get(k))
            os.environ[k] = v
        CHAOS.reset()
        return CHAOS

    yield set_env
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old
    CHAOS.reset()


@pytest.fixture()
def gcs():
    """A GcsServer constructed but never started: pure membership
    state machine, no sockets, no background loops."""
    import asyncio

    from ray_tpu._private.gcs_server import GcsServer

    loop = asyncio.new_event_loop()
    srv = GcsServer("127.0.0.1:0", {"session_dir": ""}, loop=loop)
    yield srv, loop
    loop.close()


def _add_node(srv, state="ALIVE", inc=5):
    from ray_tpu._private.common import NodeInfo, ResourceSet
    from ray_tpu._private.ids import NodeID

    nid = NodeID.from_random()
    info = NodeInfo(
        node_id=nid,
        raylet_address="",
        object_store_dir="",
        resources_total=ResourceSet.of({}),
        state=state,
        incarnation=inc,
    )
    srv.nodes[nid] = info
    srv.node_incarnations[nid] = inc
    srv.last_heartbeat[nid] = time.monotonic()
    return nid, info


# ----------------------------------------------------------------------
# 1. net: rule family
# ----------------------------------------------------------------------


def test_net_rule_parse_defaults():
    from ray_tpu._private.chaos import _parse_rule

    r = _parse_rule(0, "net:raylet*->gcs:cut", 7)
    assert r.pattern == "net:raylet*->gcs"
    assert r.action == "cut"
    assert r.n == -1  # link rules are sustained by default
    assert r.p == 1.0
    assert r.start_s == 0.0 and r.for_s is None

    r = _parse_rule(1, "net:*->node2:flaky", 7)
    assert r.p == 0.5  # flaky halves the link unless told otherwise
    assert r.n == -1

    r = _parse_rule(2, "net:node1->node2:slow:ms=500", 7)
    assert r.action == "slow"
    assert r.delay_s == pytest.approx(0.5)

    r = _parse_rule(3, "net:a->b:cut:start=5:for=3:p=0.25:n=10", 7)
    assert (r.start_s, r.for_s, r.p, r.n) == (5.0, 3.0, 0.25, 10)


def test_net_rule_rejects_non_link_pattern():
    from ray_tpu._private.chaos import _parse_rule

    # A net action without a directional net:<src>-><dst> pattern is a
    # spec bug, not a silently-never-matching rule.
    with pytest.raises(ValueError):
        _parse_rule(0, "submit_task:cut", 7)
    with pytest.raises(ValueError):
        _parse_rule(0, "net:gcs:cut", 7)  # no "->"


def test_net_stats_round_trip(chaos_env):
    chaos = chaos_env(
        {
            "RAY_TPU_testing_chaos_spec": "net:a->b:cut:start=1:for=2",
            "RAY_TPU_testing_chaos_seed": "11",
        }
    )
    assert chaos.active
    stats = chaos.stats()
    assert stats["seed"] == 11
    [rule] = stats["rules"]
    assert rule["pattern"] == "net:a->b"
    assert rule["action"] == "cut"
    assert rule["start_s"] == 1.0 and rule["for_s"] == 2.0


def test_decide_net_directionality(chaos_env, monkeypatch):
    from ray_tpu._private import telemetry

    fired = []
    monkeypatch.setattr(
        telemetry, "count_chaos_net", lambda p, a: fired.append((p, a))
    )
    chaos = chaos_env(
        {"RAY_TPU_testing_chaos_spec": "net:raylet*->gcs:cut"}
    )
    # src->dst matches: blackholed, and counted as a net injection.
    assert chaos.decide_net("raylet-abc123", "gcs").drop
    # The reverse direction keeps flowing — asymmetric by construction.
    assert chaos.decide_net("gcs", "raylet-abc123").clean
    # Unrelated links untouched.
    assert chaos.decide_net("driver", "gcs").clean
    assert fired == [("net:raylet*->gcs", "cut")]


def test_decide_net_flaky_seeded_replay(chaos_env):
    env = {
        "RAY_TPU_testing_chaos_spec": "net:a->b:flaky:p=0.5",
        "RAY_TPU_testing_chaos_seed": "1234",
    }
    chaos = chaos_env(env)
    first = [chaos.decide_net("a", "b").drop for _ in range(64)]
    chaos.reset()
    second = [chaos.decide_net("a", "b").drop for _ in range(64)]
    assert first == second  # same seed + spec -> identical schedule
    assert True in first and False in first  # genuinely flaky


def test_net_window_cut_heals(chaos_env):
    """``for=`` bounds a partition in wall-clock time: the cut holds,
    then the link heals without any spec change (spawned processes
    can't receive one)."""
    chaos = chaos_env(
        {"RAY_TPU_testing_chaos_spec": "net:a->b:cut:for=0.3"}
    )
    assert chaos.decide_net("a", "b").drop  # armed immediately
    deadline = time.monotonic() + 5
    while chaos.decide_net("a", "b").drop:
        assert time.monotonic() < deadline, "cut window never healed"
        time.sleep(0.05)
    assert chaos.decide_net("a", "b").clean


def test_net_window_delayed_start_and_flap(chaos_env):
    """``start=`` delays arming; two staggered windows on one pattern
    model a flapping link.  Disarmed matches consume no counters."""
    chaos = chaos_env(
        {
            "RAY_TPU_testing_chaos_spec": (
                "net:a->b:cut:start=0.2:for=0.2,"
                "net:a->b:cut:start=0.6:for=0.2"
            )
        }
    )
    assert chaos.decide_net("a", "b").clean  # both windows still closed
    # Disarmed matches must not advance any rule's match ordinal.
    assert all(r["matches"] == 0 for r in chaos.stats()["rules"])

    def _wait(pred, what):
        deadline = time.monotonic() + 5
        while not pred():
            assert time.monotonic() < deadline, what
            time.sleep(0.02)

    _wait(lambda: chaos.decide_net("a", "b").drop, "first flap never cut")
    _wait(lambda: chaos.decide_net("a", "b").clean, "first flap never healed")
    _wait(lambda: chaos.decide_net("a", "b").drop, "second flap never cut")
    _wait(lambda: chaos.decide_net("a", "b").clean, "second flap never healed")


# ----------------------------------------------------------------------
# 2. membership state machine (unit, no sockets)
# ----------------------------------------------------------------------


def test_fence_matrix(gcs, monkeypatch):
    from ray_tpu._private import telemetry
    from ray_tpu._private.ids import NodeID

    srv, _ = gcs
    counted = []
    monkeypatch.setattr(
        telemetry, "count_fence_rejection", lambda m: counted.append(m)
    )

    nid, info = _add_node(srv, state="ALIVE", inc=5)

    # Unstamped payloads (workers, legacy callers) always pass.
    srv._check_fence("m", None, None)
    srv._check_fence("m", nid, None)
    # A node the GCS has never stamped passes (registration races).
    srv._check_fence("m", NodeID.from_random(), 1)
    # The current incarnation of a live node passes.
    srv._check_fence("m", nid, 5)
    assert counted == []

    # Stale incarnation: typed rejection carrying the fenced identity.
    with pytest.raises(NodeFencedError) as ei:
        srv._check_fence("resource_report", nid, 4)
    assert ei.value.node_id == nid.binary()
    assert ei.value.incarnation == 4
    # Raw-bytes node ids (as they arrive in payloads) fence identically.
    with pytest.raises(NodeFencedError):
        srv._check_fence("resource_report", nid.binary(), 4)

    # Equal incarnation but declared DEAD at it: the zombie on the far
    # side of a healed partition.  Its writes must not resurrect it.
    info.state = "DEAD"
    with pytest.raises(NodeFencedError):
        srv._check_fence("object_location_add", nid, 5)
    info.state = "ALIVE"
    srv._check_fence("object_location_add", nid, 5)  # alive again: passes

    # Incarnation known but the NodeInfo itself is gone: fenced too.
    del srv.nodes[nid]
    with pytest.raises(NodeFencedError):
        srv._check_fence("free_objects", nid, 5)

    assert counted == [
        "resource_report",
        "resource_report",
        "object_location_add",
        "free_objects",
    ]


def test_fence_runs_before_heartbeat_touch(gcs, monkeypatch):
    """A zombie's resource_report must not refresh its successor's
    liveness: the fence fires before the heartbeat is touched."""
    from ray_tpu._private import telemetry

    srv, loop = gcs
    monkeypatch.setattr(telemetry, "count_fence_rejection", lambda m: None)
    nid, _ = _add_node(srv, inc=7)
    srv.last_heartbeat[nid] = 123.0  # sentinel
    with pytest.raises(NodeFencedError):
        loop.run_until_complete(
            srv.rpc_resource_report(
                {"node_id": nid.binary(), "incarnation": 6, "available": {}},
                None,
            )
        )
    assert srv.last_heartbeat[nid] == 123.0
    # The current incarnation's report lands normally.
    loop.run_until_complete(
        srv.rpc_resource_report(
            {"node_id": nid.binary(), "incarnation": 7, "available": {}},
            None,
        )
    )
    assert srv.last_heartbeat[nid] != 123.0


def test_registration_stamps_monotonic_incarnation(gcs):
    """Re-registration always lands strictly above every prior stamp,
    and above wall-time — a rebooted GCS that lost the map can never
    re-issue an incarnation a zombie still holds."""
    srv, _ = gcs
    nid, info = _add_node(srv, inc=3)
    inc = max(srv.node_incarnations.get(nid, 0) + 1, int(time.time()))
    assert inc > 3 and inc >= int(time.time())
    # ... and if a prior stamp is already above wall time (clock skew),
    # +1 monotonicity wins.
    srv.node_incarnations[nid] = int(time.time()) + 10_000
    inc2 = max(srv.node_incarnations[nid] + 1, int(time.time()))
    assert inc2 == srv.node_incarnations[nid] + 1


def test_node_fenced_error_pickles_identity():
    err = NodeFencedError("fenced", node_id=b"\x01" * 16, incarnation=42)
    back = pickle.loads(pickle.dumps(err))
    assert isinstance(back, NodeFencedError)
    assert back.node_id == b"\x01" * 16
    assert back.incarnation == 42


def test_suspicion_score_blend(gcs, monkeypatch):
    """Hard silence is the only signal allowed to reach 1.0; gray
    signals (slow-but-alive) cap at 0.9 so they can never drive a
    false DEAD."""
    srv, _ = gcs
    now = time.monotonic()

    nid, _ = _add_node(srv)
    srv.last_heartbeat[nid] = now
    assert srv._suspicion_score(nid, now, threshold=10.0) == 0.0

    # Full silence past the threshold: 1.0.
    srv.last_heartbeat[nid] = now - 20.0
    assert srv._suspicion_score(nid, now, threshold=10.0) == 1.0

    # Pathological gray signals (huge RTT, endless RPC errors) with a
    # fresh heartbeat: capped strictly below the death score.
    srv.last_heartbeat[nid] = now
    srv.node_health[nid] = {"gcs_rtt_ms": 1e9, "gcs_errors": 1e9}
    assert srv._suspicion_score(nid, now, threshold=10.0) == 0.9

    # Channel-health degradation (blocked-seconds rate) is gray too.
    nid2, _ = _add_node(srv)
    srv.last_heartbeat[nid2] = now
    srv._chan_stats[nid2] = {b"w": (100.0, 0.0)}
    srv._chan_prev[nid2] = (0.0, 0.0, now - 1.0)
    assert srv._suspicion_score(nid2, now, threshold=10.0) == 0.9


def test_finish_quarantine_gating(gcs):
    """Only a QUARANTINE-reason drain parks in QUARANTINED; every other
    drain reason keeps its termination semantics."""
    srv, _ = gcs

    _, info = _add_node(srv, state="DRAINING")
    info.drain_reason = "QUARANTINE"
    srv._finish_quarantine(info)
    assert info.state == "QUARANTINED"
    assert info.quarantined_since > 0

    _, info2 = _add_node(srv, state="DRAINING")
    info2.drain_reason = "PREEMPTION"
    srv._finish_quarantine(info2)
    assert info2.state == "DRAINING"

    _, info3 = _add_node(srv, state="ALIVE")
    info3.drain_reason = "QUARANTINE"  # stale reason, node not draining
    srv._finish_quarantine(info3)
    assert info3.state == "ALIVE"


def test_unquarantine_hysteresis_and_flap_budget(gcs, monkeypatch):
    from ray_tpu._private import telemetry
    from ray_tpu._private.config import CONFIG

    srv, _ = gcs
    transitions = []
    monkeypatch.setattr(
        telemetry, "count_quarantine", lambda r, d: transitions.append((r, d))
    )
    hyst = float(CONFIG.unquarantine_hysteresis_s)
    budget = int(CONFIG.node_flap_budget)

    nid, info = _add_node(srv, state="QUARANTINED")
    info.drain_reason = "QUARANTINE"
    info.drain_complete = True
    now = 1000.0

    # Still suspicious: no recovery clock at all.
    srv._maybe_unquarantine(info, score=0.9, now=now)
    assert info.state == "QUARANTINED" and nid not in srv._recover_since

    # Healthy, but the hysteresis window hasn't elapsed.
    srv._maybe_unquarantine(info, score=0.0, now=now)
    assert info.state == "QUARANTINED" and srv._recover_since[nid] == now
    srv._maybe_unquarantine(info, score=0.0, now=now + hyst / 2)
    assert info.state == "QUARANTINED"

    # A suspicion blip mid-window resets the clock.
    srv._maybe_unquarantine(info, score=0.9, now=now + hyst * 0.75)
    assert nid not in srv._recover_since
    srv._maybe_unquarantine(info, score=0.0, now=now + hyst)
    assert info.state == "QUARANTINED"  # clock restarted at now+hyst

    # Sustained health past the window: readmitted, drain state reset,
    # one flap spent.
    srv._maybe_unquarantine(info, score=0.0, now=now + 2 * hyst + 0.1)
    assert info.state == "ALIVE"
    assert info.flap_count == 1
    assert info.drain_reason is None and not info.drain_complete
    assert ("gray_failure", "exit") in transitions

    # Budget exhausted: the node stays parked no matter how healthy.
    info.state = "QUARANTINED"
    info.flap_count = budget
    srv._maybe_unquarantine(info, score=0.0, now=now + 100)
    srv._maybe_unquarantine(info, score=0.0, now=now + 100 + 2 * hyst)
    assert info.state == "QUARANTINED"
    assert info.flap_count == budget


def test_free_batch_shed_is_counted(monkeypatch):
    """The owner-side free batch is bounded across a GCS outage; records
    the bound sheds are visible as telemetry_dropped_total, not a
    silent free leak."""
    from ray_tpu._private import telemetry
    from ray_tpu._private.worker import ReferenceCounter

    class _DeadGcs:
        closed = False

        def push(self, method, payload):
            raise ConnectionError("gcs down")

    class _FakeWorker:
        gcs_client = _DeadGcs()

    drops = []
    monkeypatch.setattr(
        telemetry,
        "count_telemetry_dropped",
        lambda reason, n=1: drops.append((reason, n)),
    )
    rc = ReferenceCounter(_FakeWorker())
    try:
        rc._to_free = [b"%032d" % i for i in range(100_050)]
        rc.flush()
        assert len(rc._to_free) == 100_000
        assert drops == [("gcs_outage_bound", 50)]
        # Under the bound nothing sheds.
        rc.flush()
        assert drops == [("gcs_outage_bound", 50)]
    finally:
        rc.stop()


# ----------------------------------------------------------------------
# 3. live-cluster drills
# ----------------------------------------------------------------------


@pytest.fixture()
def two_node_cluster(request):
    """Head + one worker node, env staged BEFORE spawn (config is
    frozen into children at process creation)."""
    saved = {}
    created = []

    def make(env: dict, head_args=None, nodes=()):
        for k, v in env.items():
            saved.setdefault(k, os.environ.get(k))
            os.environ[k] = v
        c = Cluster(
            initialize_head=True, head_node_args=head_args or {"num_cpus": 2}
        )
        handles = [c.add_node(**kw) for kw in nodes]
        c.wait_for_nodes()
        ray_tpu.init(address=c.address)
        created.append(c)
        return c, handles

    yield make
    try:
        from ray_tpu import serve

        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()
    for c in created:
        c.shutdown()
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old


def _wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.chaos
def test_stale_incarnation_fenced_over_the_wire(two_node_cluster):
    """Zombie-fencing regression: a raylet-originated write stamped with
    a stale incarnation is rejected with a TYPED NodeFencedError across
    the RPC wire, the rejection is counted, and the real node's
    liveness is untouched."""
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import metrics as metrics_mod
    from ray_tpu.util import state

    two_node_cluster({}, nodes=[{"num_cpus": 1, "resources": {"side": 1}}])
    w = get_global_worker()
    info = w.gcs_client.call("get_cluster_info")
    target = next(
        n for n in info["nodes"].values() if not n.get("is_head")
    )
    node_id, inc = target["node_id"], target["incarnation"]
    assert inc > 0

    with pytest.raises(NodeFencedError) as ei:
        w.gcs_client.call(
            "resource_report",
            {"node_id": node_id, "incarnation": inc - 1, "available": {}},
        )
    assert ei.value.node_id == node_id
    assert ei.value.incarnation == inc - 1

    # The current incarnation still passes (the fence is exact).
    assert w.gcs_client.call(
        "resource_report",
        {
            "node_id": node_id,
            "incarnation": inc,
            "available": target["available"],
        },
    )

    # The real node never flinched.
    nodes = {n["node_id"]: n for n in state.list_nodes()}
    assert nodes[bytes(node_id).hex()]["state"] == "ALIVE"

    # The rejection reached the fence counter (GCS-side telemetry
    # flushes into the metrics table on its own cadence).
    metrics_mod.flush()

    def _fence_counted():
        return any(
            r["name"] == "node_fence_rejections_total"
            and (r.get("tags") or {}).get("method") == "resource_report"
            and r.get("value", 0) >= 1
            for r in state.metrics()
        )

    _wait_for(_fence_counted, 15, "node_fence_rejections_total sample")


@pytest.mark.chaos
def test_serve_demotes_and_repromotes_quarantined_node(two_node_cluster):
    """The router stops picking replicas on a QUARANTINED node and
    resumes after the gray-failure ladder readmits it."""
    from ray_tpu import serve
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import state

    two_node_cluster(
        # Readmission needs sustained health for the hysteresis window;
        # keep it short so the re-promotion leg fits the test budget,
        # but long enough to observe demotion while parked.
        {"RAY_TPU_unquarantine_hysteresis_s": "6"},
        head_args={"num_cpus": 4, "resources": {"pin": 1}},
        nodes=[{"num_cpus": 1, "resources": {"pin": 1, "side": 1}}],
    )

    @serve.deployment(
        num_replicas=2,
        ray_actor_options={"num_cpus": 0, "resources": {"pin": 1}},
    )
    def where(_):
        from ray_tpu.runtime_context import get_runtime_context

        return get_runtime_context().get_node_id()

    handle = serve.run(where.bind())

    nodes = state.list_nodes()
    side = next(n for n in nodes if not n["is_head"])["node_id"]

    # Both nodes serve before the quarantine (pin:1 per node forces one
    # replica onto each).
    def _hits(n=24):
        return {handle.remote(None).result(timeout=30) for _ in range(n)}

    _wait_for(lambda: side in _hits(), 60, "replica on the side node to serve")

    # Quarantine the side node through the drain plane (the same path
    # the gray-failure ladder takes).
    w = get_global_worker()
    w.gcs_client.call(
        "drain_node",
        {
            "node_id": bytes.fromhex(side),
            "reason": "QUARANTINE",
            "deadline_s": 5.0,
        },
    )
    _wait_for(
        lambda: any(
            n["node_id"] == side and n["state"] == "QUARANTINED"
            for n in state.list_nodes()
        ),
        30,
        "side node to park in QUARANTINED",
    )

    # Demotion: once the pushed snapshot lands, traffic avoids the
    # quarantined node's replica entirely.
    _wait_for(lambda: side not in _hits(12), 20, "router to demote the replica")
    assert side not in _hits()

    # The node is actually healthy, so the ladder readmits it after the
    # hysteresis window — and the router re-promotes the replica.
    _wait_for(
        lambda: any(
            n["node_id"] == side and n["state"] == "ALIVE"
            for n in state.list_nodes()
        ),
        60,
        "side node to be readmitted",
    )
    _wait_for(lambda: side in _hits(), 60, "router to re-promote the replica")
