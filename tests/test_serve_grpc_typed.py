"""Typed gRPC serving: a protoc-generated-style service registered on
the proxy via grpc_servicer_functions, with typed request/response
messages enforced by the service's own (de)serializers (reference:
serve/_private/proxy.py:538 gRPCProxy + grpc_options.
grpc_servicer_functions; VERDICT r4 weak #5).

Hermetic: the "generated" module is hand-written with the exact
surface protoc emits (message FromString/SerializeToString + an
add_XServicer_to_server that builds typed method handlers), so no
protoc run or .proto file is needed."""

import os
import sys
import textwrap
import time

import pytest

# The fake generated module must be importable in the PROXY ACTOR's
# worker process: write it before the cluster starts and extend
# PYTHONPATH (child_env propagates it to spawned workers).
_MODULE = textwrap.dedent(
    '''
    """Hand-written stand-in for protoc output (module surface only)."""
    import grpc
    import json


    class PredictRequest:
        def __init__(self, x=0.0):
            self.x = float(x)

        def SerializeToString(self):
            return json.dumps({"x": self.x}).encode()

        @classmethod
        def FromString(cls, data):
            return cls(**json.loads(data))


    class PredictResponse:
        def __init__(self, y=0.0):
            self.y = float(y)

        def SerializeToString(self):
            return json.dumps({"y": self.y}).encode()

        @classmethod
        def FromString(cls, data):
            return cls(**json.loads(data))


    def add_PredictorServicer_to_server(servicer, server):
        rpc_method_handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                servicer.Predict,
                request_deserializer=PredictRequest.FromString,
                response_serializer=PredictResponse.SerializeToString,
            ),
        }
        handler = grpc.method_handlers_generic_handler(
            "demo.Predictor", rpc_method_handlers
        )
        server.add_generic_rpc_handlers((handler,))
    '''
)


@pytest.fixture(scope="module")
def typed_cluster(tmp_path_factory):
    import ray_tpu

    d = tmp_path_factory.mktemp("typed_grpc_mod")
    (d / "demo_pb2_grpc.py").write_text(_MODULE)
    sys.path.insert(0, str(d))
    old_pp = os.environ.get("PYTHONPATH", "")
    os.environ["PYTHONPATH"] = str(d) + (os.pathsep + old_pp if old_pp else "")
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    from ray_tpu import serve

    serve.shutdown()
    ray_tpu.shutdown()
    os.environ["PYTHONPATH"] = old_pp
    sys.path.remove(str(d))


def test_typed_grpc_service_routes_messages(typed_cluster):
    import grpc

    import demo_pb2_grpc
    from ray_tpu import serve

    @serve.deployment(name="Doubler")
    class Doubler:
        def Predict(self, req):
            # typed contract: receives PredictRequest, returns PredictResponse
            assert isinstance(req, demo_pb2_grpc.PredictRequest), type(req)
            return demo_pb2_grpc.PredictResponse(y=req.x * 2)

    serve.run(
        Doubler.bind(),
        grpc_port=19544,
        grpc_servicer_functions=["demo_pb2_grpc.add_PredictorServicer_to_server"],
    )

    channel = grpc.insecure_channel("127.0.0.1:19544")
    predict = channel.unary_unary(
        "/demo.Predictor/Predict",
        request_serializer=demo_pb2_grpc.PredictRequest.SerializeToString,
        response_deserializer=demo_pb2_grpc.PredictResponse.FromString,
    )
    resp = predict(
        demo_pb2_grpc.PredictRequest(x=21.0),
        metadata=(("deployment", "Doubler"),),
        timeout=30,
    )
    assert resp.y == 42.0

    # missing deployment metadata is a typed INVALID_ARGUMENT, not a hang
    with pytest.raises(grpc.RpcError) as err:
        predict(demo_pb2_grpc.PredictRequest(x=1.0), timeout=10)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    channel.close()
