"""Every typed exception must survive a pickle round trip intact.

Exceptions are the one payload that crosses EVERY boundary — RPC replies,
channel frames, object-store blobs — and default ``BaseException``
pickling replays ``cls(*args)`` where args is the *formatted message*.
For any exception whose ``__init__`` signature is not ``(message)``,
that replay corrupts fields (task_id becomes the message string) or
re-wraps the message on every hop ("X failed:\nX failed:\n...").  These
tests pin type, message, and structured fields across one AND two round
trips (the second catches drift the first can mask).
"""

import pickle

import pytest

from ray_tpu import exceptions


def _roundtrip(e, times=2):
    for _ in range(times):
        e = pickle.loads(pickle.dumps(e))
    return e


def _cloudpickle():
    import cloudpickle

    return cloudpickle


_PICKLERS = [
    (pickle.dumps, pickle.loads),
    (_cloudpickle().dumps, _cloudpickle().loads),
]


# Every public exception class with representative constructor args.
CASES = [
    exceptions.RayError("boom"),
    exceptions.RayTaskError("f", "Traceback: ValueError: boom\n", ValueError("boom")),
    exceptions.RayActorError("actor gone", actor_id=b"\x01" * 8),
    exceptions.ActorDiedError("died hard", actor_id=b"\x02" * 8),
    exceptions.ActorUnavailableError("away", actor_id=b"\x03" * 8),
    exceptions.WorkerCrashedError("sigkill"),
    exceptions.ObjectLostError(b"\x04" * 8, "copy evicted"),
    exceptions.ObjectReconstructionFailedError(b"\x05" * 8, "lineage exhausted"),
    exceptions.OwnerDiedError(b"\x06" * 8, "owner fell over"),
    exceptions.GetTimeoutError("deadline"),
    exceptions.TaskCancelledError(b"\x07" * 8),
    exceptions.RuntimeEnvSetupError("pip exploded"),
    exceptions.NodeDiedError("node gone"),
    exceptions.NodeFencedError("stale write", node_id=b"\x08" * 8, incarnation=41),
    exceptions.RaySystemError("internal"),
    exceptions.OutOfMemoryError("oom"),
    exceptions.PlacementGroupSchedulingError("infeasible"),
    exceptions.QuotaExceededError("over quota and parked-full"),
]


@pytest.mark.parametrize("exc", CASES, ids=lambda e: type(e).__name__)
def test_roundtrip_preserves_type_and_message(exc):
    got = _roundtrip(exc)
    assert type(got) is type(exc)
    assert str(got) == str(exc)
    assert isinstance(got, exceptions.RayError)


def test_ray_task_error_fields_survive():
    cause = ValueError("boom")
    e = exceptions.RayTaskError("trainer.step", "Traceback (most recent call last):\n...", cause)
    got = _roundtrip(e)
    assert got.function_name == "trainer.step"
    assert got.traceback_str == e.traceback_str
    assert type(got.cause) is ValueError and str(got.cause) == "boom"
    # The message must not grow a second "failed:" frame per hop.
    assert str(got).count("failed:") == 1


def test_as_instanceof_cause_is_catchable_after_roundtrip():
    # The derived class is dynamic (unreachable by module attribute), so
    # __reduce__ ships the fields and re-derives on load — plain pickle
    # must work: the RPC layer and user code both use it on caught errors.
    e = exceptions.RayTaskError.from_exception(KeyError("missing"), "lookup")
    derived = e.as_instanceof_cause()
    for dumps, loads in _PICKLERS:
        got = loads(dumps(derived))
        assert isinstance(got, exceptions.RayTaskError)
        assert isinstance(got, KeyError)
        assert got.function_name == "lookup"
        assert type(got.cause) is KeyError
        assert str(got) == str(derived)
        # A second hop must neither fail nor re-frame the message.
        again = loads(dumps(got))
        assert isinstance(again, KeyError) and str(again) == str(derived)


def test_actor_error_keeps_actor_id():
    for cls in (
        exceptions.RayActorError,
        exceptions.ActorDiedError,
        exceptions.ActorUnavailableError,
    ):
        got = _roundtrip(cls("gone", actor_id=b"\xaa" * 8))
        assert type(got) is cls
        assert got.actor_id == b"\xaa" * 8
        assert str(got) == "gone"


def test_object_lost_keeps_object_id():
    for cls in (
        exceptions.ObjectLostError,
        exceptions.ObjectReconstructionFailedError,
        exceptions.OwnerDiedError,
    ):
        got = _roundtrip(cls(b"\xbb" * 8, "gone"))
        assert type(got) is cls
        assert got.object_id == b"\xbb" * 8
        assert str(got) == "gone"
    # Default-message path must not nest "Object Object ... was lost".
    got = _roundtrip(exceptions.ObjectLostError(b"\xcc" * 8))
    assert got.object_id == b"\xcc" * 8
    assert str(got).count("was lost") == 1


def test_task_cancelled_keeps_task_id():
    got = _roundtrip(exceptions.TaskCancelledError(b"\xdd" * 8))
    assert got.task_id == b"\xdd" * 8
    assert str(got).count("was cancelled") == 1


def test_node_fenced_keeps_incarnation():
    got = _roundtrip(exceptions.NodeFencedError("stale", node_id=b"\xee" * 8, incarnation=7))
    assert got.node_id == b"\xee" * 8
    assert got.incarnation == 7


def test_get_timeout_still_a_timeout():
    got = _roundtrip(exceptions.GetTimeoutError("t"))
    assert isinstance(got, TimeoutError)
