"""Compiled DAGs spanning hosts: cross-raylet edges ride persistent
socket channels chosen at compile time by placement (reference:
accelerated DAGs over the Pathways-style single-controller dataplane).

Two raylets on one machine count as two hosts for transport selection
(node identity, not hostname) — exactly the topology Cluster builds."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    c.add_node(num_cpus=2, resources={"edge": 4})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote
class Stage:
    def __init__(self, inc):
        self.inc = inc
        self.count = 0

    def step(self, x):
        self.count += 1
        return x + self.inc


def _kinds(compiled):
    return {d["kind"] for d in compiled._descs.values()}


def test_cross_host_pipeline_exact_results(cluster):
    """driver -> A(head) -> B(worker node) -> driver: the A->B edge and
    both driver edges to B are sockets; results are exact and ordered."""
    a = Stage.bind(1)
    b = Stage.options(resources={"edge": 0.1}).bind(10)
    with InputNode() as inp:
        dag = b.step.bind(a.step.bind(inp))
    compiled = dag.experimental_compile(max_inflight=8)
    assert compiled._channels_on
    assert "socket" in _kinds(compiled)  # really crossed a raylet
    refs = [compiled.execute(i) for i in range(6)]
    assert [ray_tpu.get(r) for r in refs] == [i + 11 for i in range(6)]
    # steady-state exactness under sustained load (ring + socket mixed)
    for i in range(25):
        assert ray_tpu.get(compiled.execute(i)) == i + 11
    stats = compiled.stats()
    assert {c["kind"] for c in stats["output_channels"]} <= {"ring", "socket"}
    compiled.teardown()


def test_cross_host_fanout_multi_output(cluster):
    """Fan-out to actors on BOTH nodes from one input; fan-in order
    preserved by MultiOutputNode."""
    local = Stage.bind(100)
    remote = Stage.options(resources={"edge": 0.1}).bind(1000)
    with InputNode() as inp:
        dag = MultiOutputNode([local.step.bind(inp), remote.step.bind(inp)])
    compiled = dag.experimental_compile()
    assert "socket" in _kinds(compiled)
    assert ray_tpu.get(compiled.execute(5)) == [105, 1005]
    assert ray_tpu.get(compiled.execute(7)) == [107, 1007]
    compiled.teardown()


def test_cross_host_error_propagates_and_dag_survives(cluster):
    @ray_tpu.remote(resources={"edge": 0.1})
    class Fragile:
        def f(self, x):
            if x < 0:
                raise ValueError("negative!")
            return x * 2

    with InputNode() as inp:
        dag = Fragile.bind().f.bind(inp)
    compiled = dag.experimental_compile()
    assert "socket" in _kinds(compiled)
    assert ray_tpu.get(compiled.execute(4)) == 8
    with pytest.raises(ValueError):
        ray_tpu.get(compiled.execute(-1))
    assert ray_tpu.get(compiled.execute(5)) == 10  # edge still live
    compiled.teardown()


def test_cross_host_roundtrip_latency_sane(cluster):
    """A socket edge round-trip must stay far under the task path's
    multi-ms floor (loose bound: CI boxes swing 2-5x)."""

    @ray_tpu.remote(resources={"edge": 0.1})
    class Echo:
        def echo(self, x):
            return x

    with InputNode() as inp:
        dag = Echo.bind().echo.bind(inp)
    compiled = dag.experimental_compile()
    assert "socket" in _kinds(compiled)
    ray_tpu.get(compiled.execute(0))  # warm
    lat = []
    for i in range(50):
        t0 = time.perf_counter()
        assert ray_tpu.get(compiled.execute(i)) == i
        lat.append(time.perf_counter() - t0)
    lat.sort()
    p50 = lat[len(lat) // 2]
    assert p50 < 0.05, f"socket round-trip p50 {p50 * 1e3:.2f} ms"
    compiled.teardown()
