"""Autoscaler v2: instance state machine + declarative constraints
(reference: python/ray/autoscaler/v2/)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider, NodeProvider
from ray_tpu.autoscaler.v2 import AutoscalerV2, Instance, InstanceManager
from ray_tpu.autoscaler.v2.sdk import request_cluster_resources


class _MockProvider(NodeProvider):
    """In-memory provider for state-machine unit tests."""

    def __init__(self, fail_first: int = 0):
        self.nodes = {}
        self.counter = 0
        self.fail_first = fail_first

    def non_terminated_nodes(self, tag_filters):
        return list(self.nodes)

    def create_node(self, node_config, tags, count):
        if self.fail_first > 0:
            self.fail_first -= 1
            raise RuntimeError("cloud says no")
        out = []
        for _ in range(count):
            self.counter += 1
            nid = f"cloud-{self.counter}"
            self.nodes[nid] = dict(tags)
            out.append(nid)
        return out

    def terminate_node(self, node_id):
        self.nodes.pop(node_id, None)

    def is_running(self, node_id):
        return node_id in self.nodes

    def raylet_address(self, node_id):
        return f"unix:/fake/{node_id}"


def test_instance_lifecycle_happy_path():
    p = _MockProvider()
    im = InstanceManager(p, {"w": {"resources": {"CPU": 2}}})
    (iid,) = im.queue_launch("w")
    im.reconcile({})
    inst = im.instances[iid]
    assert inst.status == "ALLOCATED"
    cloud = inst.cloud_instance_id
    # Ray comes up on the node -> RAY_RUNNING
    im.reconcile({cloud: {"state": "ALIVE"}})
    assert inst.status == "RAY_RUNNING"
    # Ray node dies -> RAY_STOPPED -> TERMINATING -> TERMINATED + provider terminate
    im.reconcile({cloud: {"state": "DEAD"}})
    assert inst.status == "TERMINATED"
    assert cloud not in p.nodes
    # Audit trail recorded every hop.
    assert [s for s, _ in inst.history] == [
        "QUEUED", "REQUESTED", "ALLOCATED", "RAY_RUNNING",
        "RAY_STOPPED", "TERMINATING", "TERMINATED",
    ]


def test_instance_launch_retries_then_fails():
    p = _MockProvider(fail_first=5)
    im = InstanceManager(p, {"w": {"resources": {"CPU": 2}}}, max_launch_retries=3)
    (iid,) = im.queue_launch("w")
    for _ in range(5):
        im.reconcile({})
    assert im.instances[iid].status == "ALLOCATION_FAILED"
    assert im.instances[iid].launch_attempts == 3


def test_illegal_transition_rejected():
    inst = Instance("i-1", "w")
    with pytest.raises(ValueError):
        inst.transition("RAY_RUNNING")  # QUEUED cannot jump to RAY_RUNNING


def test_v2_scales_up_for_tasks(ray_cluster):
    worker = ray_tpu._private.worker.get_global_worker()
    provider = FakeMultiNodeProvider(
        {
            "gcs_address": worker.gcs_client.address,
            "session_dir": worker.session_info.get("session_dir"),
        }
    )
    scaler = AutoscalerV2(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=2,
        idle_timeout_s=9999,
        gcs_client=worker.gcs_client,
    )
    try:

        @ray_tpu.remote(num_cpus=2)
        class Chunk:
            def ping(self):
                return "ok"

        actors = [Chunk.remote() for _ in range(3)]
        refs = [a.ping.remote() for a in actors]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            scaler.update()
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=1)
            if len(ready) == len(refs):
                break
        assert ray_tpu.get(refs, timeout=30) == ["ok"] * 3
        # The instance state machine is eventually consistent: the tasks
        # can finish inside the same tick that launched the node, before
        # a later update() observes the registration and flips
        # ALLOCATED -> RAY_RUNNING.  Keep reconciling until it converges.
        while (
            time.monotonic() < deadline
            and scaler.status()["counts"].get("RAY_RUNNING", 0) < 1
        ):
            scaler.update()
            time.sleep(0.2)
        counts = scaler.status()["counts"]
        assert counts.get("RAY_RUNNING", 0) >= 1, counts
        for a in actors:
            ray_tpu.kill(a)
    finally:
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)


def test_v2_declarative_constraint_launches_without_demand(ray_cluster):
    worker = ray_tpu._private.worker.get_global_worker()
    provider = FakeMultiNodeProvider(
        {
            "gcs_address": worker.gcs_client.address,
            "session_dir": worker.session_info.get("session_dir"),
        }
    )
    scaler = AutoscalerV2(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=2,
        idle_timeout_s=9999,
        gcs_client=worker.gcs_client,
    )
    try:
        # No pending tasks — only the declarative ask: 3 x 2-CPU bundles
        # exceed the 4-CPU head, so a worker must come up.
        request_cluster_resources([{"CPU": 2}] * 3, gcs_client=worker.gcs_client)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            scaler.update()
            if scaler.status()["counts"].get("RAY_RUNNING", 0) >= 1:
                break
            time.sleep(1)
        assert scaler.status()["counts"].get("RAY_RUNNING", 0) >= 1
    finally:
        request_cluster_resources([], gcs_client=worker.gcs_client)
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)


def test_command_runner_updater_phases_and_failure():
    """NodeUpdater runs initialization -> setup -> start_ray in order
    with the env prefix; the first failing command raises (reference:
    _private/updater.py phase ordering)."""
    from ray_tpu.autoscaler.command_runner import (
        CommandRunnerError,
        LocalCommandRunner,
        NodeUpdater,
        SSHCommandRunner,
    )

    calls = []

    class _Proc:
        returncode = 0
        stdout = ""
        stderr = ""

    def recorder(argv, **kwargs):
        calls.append(argv)
        return _Proc()

    updater = NodeUpdater(
        LocalCommandRunner(process_runner=recorder),
        initialization_commands=["apt-get install -y foo"],
        setup_commands=["pip install bar"],
        start_ray_commands=["ray-tpu start --address=$RAY_TPU_GCS_ADDRESS"],
        env={"RAY_TPU_GCS_ADDRESS": "unix:/tmp/gcs.sock"},
    )
    updater.update()
    cmds = [argv[-1] for argv in calls]
    assert "apt-get install -y foo" in cmds[0]
    assert "pip install bar" in cmds[1]
    assert cmds[2].startswith("export RAY_TPU_GCS_ADDRESS=unix:/tmp/gcs.sock;")

    # ssh runner builds a BatchMode argv against the right target
    ssh_calls = []

    def ssh_recorder(argv, **kwargs):
        ssh_calls.append(argv)
        return _Proc()

    SSHCommandRunner("10.0.0.5", user="u", ssh_key="/k", process_runner=ssh_recorder).run("echo hi")
    argv = ssh_calls[0]
    assert argv[0] == "ssh" and "u@10.0.0.5" in argv and "-i" in argv

    # failure propagates with the command in the error
    class _Fail(_Proc):
        returncode = 7
        stderr = "boom"

    failing = NodeUpdater(
        LocalCommandRunner(process_runner=lambda argv, **k: _Fail()),
        setup_commands=["will-fail"],
    )
    with pytest.raises(CommandRunnerError, match="will-fail"):
        failing.update()


def test_tpu_provider_runs_bootstrap_commands_per_host():
    """A READY multi-host slice gets the command phases run on EVERY
    host before turning up-to-date; a failing host marks the slice
    update-failed (VERDICT r4 missing #5)."""
    from ray_tpu.autoscaler import MockTpuClient, TPUNodeProvider

    ran = []

    class _Runner:
        def __init__(self, ip):
            self.ip = ip

        def run(self, cmd, *, timeout=600.0):
            ran.append((self.ip, cmd))
            return ""

    client = MockTpuClient()
    provider = TPUNodeProvider(
        {
            "tpu_client": client,
            "setup_commands": ["pip install ray-tpu"],
            "start_ray_commands": ["ray-tpu start"],
            "command_runner_factory": _Runner,
        },
        cluster_name="bt",
    )
    (nid,) = provider.create_node({"accelerator_type": "v5litepod-16"}, {}, 1)
    provider.non_terminated_nodes({})  # reconcile: READY -> async bootstrap
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if provider.node_tags(nid)["node-status"] == "up-to-date":
            break
        time.sleep(0.05)
    assert provider.node_tags(nid)["node-status"] == "up-to-date"
    ips = {ip for ip, _ in ran}
    assert len(ips) == 4  # v5litepod-16 = 4 hosts
    per_host = [c for ip, c in ran if ip == sorted(ips)[0]]
    assert any("pip install ray-tpu" in c for c in per_host)
    assert any("ray-tpu start" in c for c in per_host)
    # env carries slice identity + worker index
    assert any("RAY_TPU_SLICE_NAME=" + nid in c for _, c in ran)
    assert any("RAY_TPU_SLICE_WORKER_INDEX=3" in c for _, c in ran)

    # failing bootstrap -> update-failed
    class _Boom:
        def __init__(self, ip):
            pass

        def run(self, cmd, *, timeout=600.0):
            from ray_tpu.autoscaler.command_runner import CommandRunnerError

            raise CommandRunnerError(cmd, 1, "nope")

    provider2 = TPUNodeProvider(
        {"tpu_client": MockTpuClient(), "setup_commands": ["x"],
         "command_runner_factory": _Boom},
        cluster_name="bf",
    )
    (nid2,) = provider2.create_node({"accelerator_type": "v5litepod-4"}, {}, 1)
    provider2.non_terminated_nodes({})
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if provider2.node_tags(nid2)["node-status"] == "update-failed":
            break
        time.sleep(0.05)
    assert provider2.node_tags(nid2)["node-status"] == "update-failed"


def test_v2_drives_tpu_slice_provider(ray_cluster):
    """VERDICT r4 missing #8: v2's instance state machine drives the
    TPU-slice provider end-to-end — slice-head demand queues a launch,
    the slice allocates (mock API + local raylet backing), Ray registers
    it (RAY_RUNNING), and the task lands on the slice."""
    from ray_tpu.autoscaler import MockTpuClient, TPUNodeProvider
    from ray_tpu.autoscaler.v2.autoscaler import AutoscalerV2

    worker = ray_tpu._private.worker.get_global_worker()
    client = MockTpuClient()
    provider = TPUNodeProvider(
        {
            "tpu_client": client,
            "launch_local_raylets": True,
            "gcs_address": worker.gcs_client.address,
            "session_dir": worker.session_info.get("session_dir"),
        },
        cluster_name="v2e2e",
    )
    scaler = AutoscalerV2(
        provider,
        node_types={
            "tpu_v5e_16": {
                "resources": {"CPU": 4, "TPU": 16, "TPU-v5litepod-16-head": 1},
                "node_config": {"accelerator_type": "v5litepod-16"},
            }
        },
        max_workers=2,
        idle_timeout_s=9999,
        gcs_client=worker.gcs_client,
    )
    try:

        @ray_tpu.remote(resources={"TPU-v5litepod-16-head": 1, "TPU": 4})
        def on_slice():
            return "v2-on-slice"

        ref = on_slice.remote()
        deadline = time.monotonic() + 120
        done = False
        while time.monotonic() < deadline and not done:
            scaler.update()
            ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=1)
            done = bool(ready)
        assert ray_tpu.get(ref, timeout=30) == "v2-on-slice"
        # Same eventual-consistency as test_v2_scales_up_for_tasks: the
        # task can land inside the launching tick; reconcile until the
        # instance is observed RAY_RUNNING.
        while (
            time.monotonic() < deadline
            and scaler.status()["counts"].get("RAY_RUNNING", 0) < 1
        ):
            scaler.update()
            time.sleep(0.2)
        counts = scaler.status()["counts"]
        assert counts.get("RAY_RUNNING", 0) >= 1, counts
        assert len(client.list()) >= 1
    finally:
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)
