"""Autoscaler v2: instance state machine + declarative constraints
(reference: python/ray/autoscaler/v2/)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider, NodeProvider
from ray_tpu.autoscaler.v2 import AutoscalerV2, Instance, InstanceManager
from ray_tpu.autoscaler.v2.sdk import request_cluster_resources


class _MockProvider(NodeProvider):
    """In-memory provider for state-machine unit tests."""

    def __init__(self, fail_first: int = 0):
        self.nodes = {}
        self.counter = 0
        self.fail_first = fail_first

    def non_terminated_nodes(self, tag_filters):
        return list(self.nodes)

    def create_node(self, node_config, tags, count):
        if self.fail_first > 0:
            self.fail_first -= 1
            raise RuntimeError("cloud says no")
        out = []
        for _ in range(count):
            self.counter += 1
            nid = f"cloud-{self.counter}"
            self.nodes[nid] = dict(tags)
            out.append(nid)
        return out

    def terminate_node(self, node_id):
        self.nodes.pop(node_id, None)

    def is_running(self, node_id):
        return node_id in self.nodes

    def raylet_address(self, node_id):
        return f"unix:/fake/{node_id}"


def test_instance_lifecycle_happy_path():
    p = _MockProvider()
    im = InstanceManager(p, {"w": {"resources": {"CPU": 2}}})
    (iid,) = im.queue_launch("w")
    im.reconcile({})
    inst = im.instances[iid]
    assert inst.status == "ALLOCATED"
    cloud = inst.cloud_instance_id
    # Ray comes up on the node -> RAY_RUNNING
    im.reconcile({cloud: {"state": "ALIVE"}})
    assert inst.status == "RAY_RUNNING"
    # Ray node dies -> RAY_STOPPED -> TERMINATING -> TERMINATED + provider terminate
    im.reconcile({cloud: {"state": "DEAD"}})
    assert inst.status == "TERMINATED"
    assert cloud not in p.nodes
    # Audit trail recorded every hop.
    assert [s for s, _ in inst.history] == [
        "QUEUED", "REQUESTED", "ALLOCATED", "RAY_RUNNING",
        "RAY_STOPPED", "TERMINATING", "TERMINATED",
    ]


def test_instance_launch_retries_then_fails():
    p = _MockProvider(fail_first=5)
    im = InstanceManager(p, {"w": {"resources": {"CPU": 2}}}, max_launch_retries=3)
    (iid,) = im.queue_launch("w")
    for _ in range(5):
        im.reconcile({})
    assert im.instances[iid].status == "ALLOCATION_FAILED"
    assert im.instances[iid].launch_attempts == 3


def test_illegal_transition_rejected():
    inst = Instance("i-1", "w")
    with pytest.raises(ValueError):
        inst.transition("RAY_RUNNING")  # QUEUED cannot jump to RAY_RUNNING


def test_v2_scales_up_for_tasks(ray_cluster):
    worker = ray_tpu._private.worker.get_global_worker()
    provider = FakeMultiNodeProvider(
        {
            "gcs_address": worker.gcs_client.address,
            "session_dir": worker.session_info.get("session_dir"),
        }
    )
    scaler = AutoscalerV2(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=2,
        idle_timeout_s=9999,
        gcs_client=worker.gcs_client,
    )
    try:

        @ray_tpu.remote(num_cpus=2)
        class Chunk:
            def ping(self):
                return "ok"

        actors = [Chunk.remote() for _ in range(3)]
        refs = [a.ping.remote() for a in actors]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            scaler.update()
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=1)
            if len(ready) == len(refs):
                break
        assert ray_tpu.get(refs, timeout=30) == ["ok"] * 3
        counts = scaler.status()["counts"]
        assert counts.get("RAY_RUNNING", 0) >= 1
        for a in actors:
            ray_tpu.kill(a)
    finally:
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)


def test_v2_declarative_constraint_launches_without_demand(ray_cluster):
    worker = ray_tpu._private.worker.get_global_worker()
    provider = FakeMultiNodeProvider(
        {
            "gcs_address": worker.gcs_client.address,
            "session_dir": worker.session_info.get("session_dir"),
        }
    )
    scaler = AutoscalerV2(
        provider,
        node_types={"cpu_worker": {"resources": {"CPU": 2}}},
        max_workers=2,
        idle_timeout_s=9999,
        gcs_client=worker.gcs_client,
    )
    try:
        # No pending tasks — only the declarative ask: 3 x 2-CPU bundles
        # exceed the 4-CPU head, so a worker must come up.
        request_cluster_resources([{"CPU": 2}] * 3, gcs_client=worker.gcs_client)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            scaler.update()
            if scaler.status()["counts"].get("RAY_RUNNING", 0) >= 1:
                break
            time.sleep(1)
        assert scaler.status()["counts"].get("RAY_RUNNING", 0) >= 1
    finally:
        request_cluster_resources([], gcs_client=worker.gcs_client)
        for nid in provider.non_terminated_nodes({}):
            provider.terminate_node(nid)
