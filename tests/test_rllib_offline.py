"""Offline-RL pipeline tests: OfflineData derivations, recorded-rollout
round-trips, MARWIL/CQL learning thresholds, and the separate
evaluation path.

Reference test model: rllib/offline/tests/ (reader/writer round-trips)
plus the BUILD learning_tests gating CQL/MARWIL on reward
(rllib/BUILD:153-164), scaled to CI size."""

import numpy as np
import pytest


def _expert_action(obs) -> int:
    """Scripted CartPole expert: push toward the pole's lean (~200+ return)."""
    return int(obs[2] + 0.5 * obs[3] > 0)


def _cartpole_mixture_rows(n_steps=3000, expert_frac=0.5, seed=0):
    """Mixed expert/random CartPole transitions with episode structure
    (the advantage signal MARWIL needs: expert episodes are long, random
    episodes short)."""
    import gymnasium as gym

    rng = np.random.default_rng(seed)
    env = gym.make("CartPole-v1")
    rows = []
    eps = 0
    use_expert = True
    obs, _ = env.reset(seed=seed)
    steps = 0
    while steps < n_steps:
        a = _expert_action(obs) if use_expert else int(rng.integers(0, 2))
        next_obs, r, term, trunc, _ = env.step(a)
        rows.append(
            {
                "obs": obs.astype(np.float32).tolist(),
                "actions": a,
                "rewards": float(r),
                "terminateds": bool(term),
                "truncateds": bool(trunc),
                "eps_id": eps,
            }
        )
        steps += 1
        if term or trunc:
            eps += 1
            use_expert = rng.random() < expert_frac
            obs, _ = env.reset(seed=seed + eps)
        else:
            obs = next_obs
    env.close()
    return rows


def test_offline_data_next_obs_and_returns():
    """NEXT_OBS shifts inside episodes only; VALUE_TARGETS are the
    per-episode discounted returns-to-go."""
    from ray_tpu.rllib.offline import OfflineData

    rows = [
        # episode 0: two steps
        {"obs": [0.0], "actions": 0, "rewards": 1.0, "terminateds": False, "eps_id": 0},
        {"obs": [1.0], "actions": 1, "rewards": 2.0, "terminateds": True, "eps_id": 0},
        # episode 1: one step
        {"obs": [5.0], "actions": 0, "rewards": 3.0, "terminateds": True, "eps_id": 1},
    ]
    ds = OfflineData(rows).ensure_next_obs().ensure_value_targets(gamma=0.5)
    np.testing.assert_allclose(ds["next_obs"][:, 0], [1.0, 1.0, 5.0])
    # returns-to-go: [1 + 0.5*2, 2, 3]
    np.testing.assert_allclose(ds["value_targets"], [2.0, 2.0, 3.0])


def test_record_rollouts_jsonl_roundtrip(tmp_path):
    """record_rollouts persists JSONL that OfflineData reads back whole."""
    import gymnasium as gym

    from ray_tpu.rllib.offline import OfflineData, record_rollouts

    out = str(tmp_path / "cartpole_random")
    batch = record_rollouts(
        lambda: gym.make("CartPole-v1"),
        lambda obs: int(obs[2] > 0),
        num_steps=120,
        output_path=out,
        seed=3,
    )
    assert batch.count == 120
    ds = OfflineData(out)
    assert ds.count == 120
    np.testing.assert_allclose(
        np.asarray(ds["obs"], np.float32), np.asarray(batch["obs"], np.float32), rtol=1e-6
    )
    assert ds["actions"].dtype.kind in "iu"
    # sampling without replacement below count
    s = ds.sample(32)
    assert s.count == 32 and len(np.unique(s["rewards"], axis=0)) >= 1


def test_marwil_learns_cartpole_from_mixed_data(ray_cluster):
    """MARWIL (beta=1) on 50/50 expert/random data reaches expert-like
    eval returns — the advantage weighting must upweight expert episodes
    (reference: BUILD learning_tests_marwil_cartpole)."""
    from ray_tpu.rllib import MARWILConfig

    rows = _cartpole_mixture_rows(n_steps=4000, expert_frac=0.5, seed=1)
    cfg = (
        MARWILConfig()
        .environment("CartPole-v1")
        .offline_data(input_=rows)
        .training(lr=1e-3, train_batch_size=2048, minibatch_size=256,
                  num_epochs=2, beta=1.0)
        .evaluation(evaluation_interval=10, evaluation_duration=5)
        .debugging(seed=7)
    )
    algo = cfg.build()
    best = -np.inf
    for i in range(30):
        out = algo.train()
        if "evaluation" in out:
            best = max(best, out["evaluation"]["episode_return_mean"])
            if best > 120:
                break
    algo.cleanup()
    assert best > 120, f"MARWIL failed to exceed mixed-data baseline: best={best}"


@pytest.mark.slow  # ~30 s learning gate, like the other *_learns_* drills
def test_cql_learns_one_step_continuous_task(ray_cluster):
    """CQL on a one-step continuous-control dataset recovers near-optimal
    actions from noisy behavior data (reference: BUILD
    learning_tests_cql_pendulum, scaled to a CI-sized task).

    Env: obs ~ U(-1,1)^2, reward = -||a - 0.5*obs||^2, episode ends.
    Behavior data: a = 0.5*obs + N(0, 0.3) — CQL must stay close to the
    data manifold while improving on it."""
    import gymnasium as gym

    from ray_tpu.rllib import CQLConfig

    class OneStepReach(gym.Env):
        observation_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
        action_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)

        def __init__(self):
            self._rng = np.random.default_rng(0)
            self._obs = None

        def reset(self, *, seed=None, options=None):
            if seed is not None:
                self._rng = np.random.default_rng(seed)
            self._obs = self._rng.uniform(-1, 1, 2).astype(np.float32)
            return self._obs, {}

        def step(self, action):
            r = -float(np.sum((np.asarray(action) - 0.5 * self._obs) ** 2))
            return self._obs, r, True, False, {}

    # behavior dataset
    rng = np.random.default_rng(5)
    obs = rng.uniform(-1, 1, (2000, 2)).astype(np.float32)
    acts = np.clip(0.5 * obs + rng.normal(0, 0.3, obs.shape), -1, 1).astype(np.float32)
    rews = -np.sum((acts - 0.5 * obs) ** 2, axis=1).astype(np.float32)
    rows = [
        {"obs": o.tolist(), "actions": a.tolist(), "rewards": float(r),
         "terminateds": True, "truncateds": False, "eps_id": i}
        for i, (o, a, r) in enumerate(zip(obs, acts, rews))
    ]

    cfg = (
        CQLConfig()
        .environment(env_creator=OneStepReach)
        .offline_data(input_=rows)
        .training(lr=3e-4, train_batch_size=256, bc_iters=64,
                  min_q_weight=1.0, updates_per_iteration=64,
                  model={"hidden": (64, 64)})
        .evaluation(evaluation_duration=20)
        .debugging(seed=11)
    )
    algo = cfg.build()
    for _ in range(10):
        out = algo.train()
    ev = algo.evaluate()
    algo.cleanup()
    # random actions score ~ -E||a-t||^2 ≈ -1.2; behavior data mean ≈ -0.18;
    # a learned policy must beat the behavior mean
    assert ev["episode_return_mean"] > -0.15, (
        f"CQL eval {ev['episode_return_mean']} worse than behavior data "
        f"(mean {rews.mean():.3f})"
    )
    assert np.isfinite(out["cql_gap"])


def test_cql_checkpoint_resumes_bc_phase(ray_cluster, tmp_path):
    """bc_iters progress survives save/restore (the BC→SAC switch is
    learner state, not a fresh counter)."""
    from ray_tpu.rllib import CQLConfig

    rng = np.random.default_rng(0)
    obs = rng.uniform(-1, 1, (64, 1)).astype(np.float32)
    rows = [
        {"obs": o.tolist(), "actions": [float(o[0])], "rewards": 0.0,
         "terminateds": True, "truncateds": False, "eps_id": i}
        for i, o in enumerate(obs)
    ]
    cfg = (
        CQLConfig()  # no env: action bounds come from the data envelope
        .offline_data(input_=rows)
        .training(train_batch_size=32, bc_iters=1000, updates_per_iteration=4,
                  model={"hidden": (16,)})
    )
    algo = cfg.build()
    algo.train()
    assert algo.learner._num_updates == 4
    ckpt = str(tmp_path)
    algo.save_checkpoint(ckpt)
    algo2 = cfg.build()
    algo2.load_checkpoint(ckpt)
    assert algo2.learner._num_updates == 4


def test_ppo_evaluation_runners(ray_cluster):
    """evaluate() uses SEPARATE eval runners with explore=False and the
    evaluation_interval wiring lands results under 'evaluation'
    (reference: algorithm.py evaluate())."""
    from ray_tpu.rllib import PPOConfig

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, rollout_fragment_length=64)
        .training(train_batch_size=128, minibatch_size=64, num_epochs=1)
        .evaluation(evaluation_interval=2, evaluation_num_env_runners=1,
                    evaluation_duration=3)
    )
    algo = cfg.build()
    out1 = algo.train()
    assert "evaluation" not in out1  # iteration 1: off-interval
    out2 = algo.train()
    ev = out2["evaluation"]
    assert ev["num_episodes"] == 3
    assert np.isfinite(ev["episode_return_mean"])
    assert ev["episode_return_min"] <= ev["episode_return_mean"] <= ev["episode_return_max"]
    # the eval group exists and is distinct from the training group
    assert algo._eval_runner_group is not algo.env_runner_group
    algo.cleanup()


def test_off_policy_estimators_recover_known_value():
    """IS and WIS on a synthetic bandit where the answer is computable:
    behavior = uniform over 2 actions, reward = action, target prefers
    action 1 with known probability (reference: rllib/offline/estimators
    tests with known-value MDPs)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.offline import ImportanceSampling, WeightedImportanceSampling
    from ray_tpu.rllib.utils.sample_batch import SampleBatch

    class _Prefers1:
        """Minimal target-policy surface: logits (0, 2) everywhere."""

        def forward_train(self, params, obs, actions):
            logits = jnp.stack(
                [jnp.zeros(obs.shape[0]), jnp.full((obs.shape[0],), 2.0)], axis=-1
            )
            logp_all = jax.nn.log_softmax(logits)
            lp = jnp.take_along_axis(
                logp_all, actions[..., None].astype(jnp.int32), axis=-1
            )[..., 0]
            return lp, None, None

    rng = np.random.default_rng(0)
    n = 4000
    actions = rng.integers(0, 2, n)
    batch = SampleBatch({
        "obs": np.zeros((n, 1), np.float32),
        "actions": actions.astype(np.int64),
        "rewards": actions.astype(np.float32),   # reward == action
        "action_logp": np.full(n, np.log(0.5), np.float32),
        "eps_id": np.arange(n),                  # 1-step episodes
    })
    p1 = float(jax.nn.softmax(jnp.array([0.0, 2.0]))[1])  # ≈ 0.8808
    is_est = ImportanceSampling(_Prefers1(), params=None).estimate(batch)
    wis_est = WeightedImportanceSampling(_Prefers1(), params=None).estimate(batch)
    assert is_est["v_behavior"] == pytest.approx(0.5, abs=0.03)
    assert is_est["v_target"] == pytest.approx(p1, abs=0.05)
    assert wis_est["v_target"] == pytest.approx(p1, abs=0.05)
    assert is_est["v_gain"] > 1.5 and wis_est["v_gain"] > 1.5
    assert is_est["num_episodes"] == n

    # interface parity: a real RLModule slots in unchanged
    from ray_tpu.rllib.core.rl_module import RLModuleSpec

    spec = RLModuleSpec(observation_dim=1, action_dim=2, hidden=(8,))
    module = spec.build()
    params = module.init(jax.random.PRNGKey(0))
    out = ImportanceSampling(module, params).estimate(batch)
    assert np.isfinite(out["v_target"]) and out["num_episodes"] == n

    # missing behavior logp / eps_id / empty batch are loud errors, not
    # silent garbage
    bad = SampleBatch({k: v for k, v in batch.items() if k != "action_logp"})
    with pytest.raises(ValueError, match="action_logp"):
        ImportanceSampling(_Prefers1(), params=None).estimate(bad)
    no_eps = SampleBatch({k: v for k, v in batch.items() if k != "eps_id"})
    with pytest.raises(ValueError, match="eps_id"):
        ImportanceSampling(_Prefers1(), params=None).estimate(no_eps)
    with pytest.raises(ValueError, match="empty"):
        ImportanceSampling(_Prefers1(), params=None).estimate(
            SampleBatch({k: v[:0] for k, v in batch.items()})
        )


def test_wis_is_per_decision():
    """Per-decision WIS: a step where target == behavior keeps weight ~1
    even when LATER steps diverge — an episode-mean weighting would drag
    the diverged ratios into the t=0 reward (reference WIS is
    per-decision)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.offline import WeightedImportanceSampling
    from ray_tpu.rllib.utils.sample_batch import SampleBatch

    class _ObsSwitched:
        """Target logp: uniform when obs==0, strongly prefers action 1
        when obs==1."""

        def forward_train(self, params, obs, actions):
            strength = 4.0 * obs[:, 0]
            logits = jnp.stack([jnp.zeros_like(strength), strength], axis=-1)
            logp_all = jax.nn.log_softmax(logits)
            lp = jnp.take_along_axis(
                logp_all, actions[..., None].astype(jnp.int32), axis=-1
            )[..., 0]
            return lp, None, None

    rng = np.random.default_rng(1)
    n_eps = 500
    # 2-step episodes: t=0 obs=0 (target==behavior), reward 1;
    #                  t=1 obs=1 (target diverges),  reward 0
    obs = np.tile(np.array([[0.0], [1.0]], np.float32), (n_eps, 1))
    actions = rng.integers(0, 2, 2 * n_eps).astype(np.int64)
    rewards = np.tile(np.array([1.0, 0.0], np.float32), n_eps)
    batch = SampleBatch({
        "obs": obs,
        "actions": actions,
        "rewards": rewards,
        "action_logp": np.full(2 * n_eps, np.log(0.5), np.float32),
        "eps_id": np.repeat(np.arange(n_eps), 2),
    })
    est = WeightedImportanceSampling(_ObsSwitched(), params=None, gamma=1.0)
    out = est.estimate(batch)
    # all value sits at t=0 where ratios are exactly 1 -> v_target == v_behavior
    assert out["v_behavior"] == pytest.approx(1.0)
    assert out["v_target"] == pytest.approx(1.0, abs=0.05), out
