"""Runtime environments: working_dir / py_modules / env_vars / pip.

Reference behavior being matched: python/ray/_private/runtime_env/
{working_dir.py,pip.py,uri_cache.py} + runtime-env agent error surfacing
(RuntimeEnvSetupError on staging failure).
"""

import os
import zipfile

import pytest

import ray_tpu
from ray_tpu._private import runtime_env as renv
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    c.add_node(num_cpus=2, resources={"remote_node": 1})
    c.wait_for_nodes()
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@pytest.fixture()
def pkg_dir(tmp_path):
    d = tmp_path / "pkg"
    d.mkdir()
    (d / "re_mod_for_test.py").write_text("VALUE = 777\n")
    (d / "data.txt").write_text("data-content")
    sub = d / "sub"
    sub.mkdir()
    (sub / "extra.txt").write_text("extra")
    return str(d)


def test_job_level_runtime_env():
    """runtime_env passed to init() applies to every task of the job.
    Runs FIRST: it owns its own single-node cluster, and must finish
    before the module-scoped multi-node cluster fixture connects."""
    ray_tpu.init(num_cpus=2, runtime_env={"env_vars": {"RE_JOB_VAR": "job"}})
    try:

        @ray_tpu.remote
        def t():
            return os.environ.get("RE_JOB_VAR")

        assert ray_tpu.get(t.remote(), timeout=60) == "job"
    finally:
        ray_tpu.shutdown()


def test_env_vars_applied_and_isolated(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"RE_TEST_VAR": "v1"}})
    def with_env():
        return os.environ.get("RE_TEST_VAR"), os.getpid()

    @ray_tpu.remote
    def without_env():
        return os.environ.get("RE_TEST_VAR"), os.getpid()

    val, pid1 = ray_tpu.get(with_env.remote(), timeout=60)
    other, pid2 = ray_tpu.get(without_env.remote(), timeout=60)
    assert val == "v1"
    assert other is None
    # Different envs must not share worker processes.
    assert pid1 != pid2


def test_same_env_reuses_worker(cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"RE_REUSE": "x"}})
    def t():
        return os.getpid()

    pids = {ray_tpu.get(t.remote(), timeout=60) for _ in range(5)}
    # Sequential tasks with one identical env reuse the same staged worker.
    assert len(pids) == 1


def test_working_dir_ships_cross_node(cluster, pkg_dir):
    """The working_dir is zipped on the driver, stored in the GCS KV, and
    staged on a node the driver never touched."""

    @ray_tpu.remote(resources={"remote_node": 0.1}, runtime_env={"working_dir": pkg_dir})
    def use_wd():
        import re_mod_for_test

        return (
            re_mod_for_test.VALUE,
            open("data.txt").read(),
            open(os.path.join("sub", "extra.txt")).read(),
            os.path.basename(os.getcwd()),
        )

    value, data, extra, cwd = ray_tpu.get(use_wd.remote(), timeout=60)
    assert value == 777
    assert data == "data-content"
    assert extra == "extra"
    assert len(cwd) == 40  # staged under the content sha1


def test_working_dir_on_actor(cluster, pkg_dir):
    @ray_tpu.remote(runtime_env={"working_dir": pkg_dir})
    class A:
        def read(self):
            import re_mod_for_test

            return re_mod_for_test.VALUE

    a = A.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == 777
    ray_tpu.kill(a)


def test_py_modules(cluster, tmp_path):
    # Reference semantics: each py_modules entry is the package directory
    # itself and becomes importable by its own name on the worker.
    pkg = tmp_path / "mods" / "re_pkg_for_test"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("NAME = 're_pkg'\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(pkg)]})
    def use_mod():
        import re_pkg_for_test

        # py_modules must NOT chdir (only working_dir does).
        return re_pkg_for_test.NAME, os.getcwd()

    name, cwd = ray_tpu.get(use_mod.remote(), timeout=60)
    assert name == "re_pkg"
    assert "runtime_resources" not in cwd


def test_staging_failure_raises_runtime_env_setup_error(cluster):
    """A package URI missing from the GCS KV fails staging on the worker;
    the error must surface as RuntimeEnvSetupError, not a hang or a
    worker spawn loop."""
    bogus = {"working_dir": renv.URI_PREFIX + "0" * 40 + ".zip"}

    @ray_tpu.remote(runtime_env=bogus, max_retries=0)
    def t():
        return 1

    with pytest.raises(ray_tpu.exceptions.RuntimeEnvSetupError):
        ray_tpu.get(t.remote(), timeout=60)


def test_pip_local_wheel(cluster, tmp_path):
    """pip specs install into a --target dir on the worker's sys.path.
    Offline-safe: installs a hand-built wheel by absolute path."""
    name, version = "re_wheel_pkg", "0.1.0"
    whl = tmp_path / f"{name}-{version}-py3-none-any.whl"
    dist = f"{name}-{version}.dist-info"
    meta = f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n"
    wheel_meta = "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\nTag: py3-none-any\n"
    with zipfile.ZipFile(whl, "w") as zf:
        zf.writestr(f"{name}.py", "MAGIC = 12321\n")
        zf.writestr(f"{dist}/METADATA", meta)
        zf.writestr(f"{dist}/WHEEL", wheel_meta)
        zf.writestr(f"{dist}/RECORD", "")

    @ray_tpu.remote(runtime_env={"pip": [str(whl)]})
    def use_wheel():
        import re_wheel_pkg

        return re_wheel_pkg.MAGIC

    assert ray_tpu.get(use_wheel.remote(), timeout=120) == 12321


def test_nested_task_inherits_parent_env(cluster):
    """A subtask submitted from inside a task inherits the parent worker's
    runtime env (reference parent-inheritance semantics)."""

    @ray_tpu.remote(runtime_env={"env_vars": {"RE_NEST": "inherited"}})
    def parent():
        @ray_tpu.remote
        def child():
            return os.environ.get("RE_NEST")

        return ray_tpu.get(child.remote(), timeout=30)

    assert ray_tpu.get(parent.remote(), timeout=60) == "inherited"


def test_prepare_hash_stability(tmp_path):
    d = tmp_path / "p"
    d.mkdir()
    (d / "a.py").write_text("x = 1\n")
    n1, u1 = renv.prepare({"working_dir": str(d)})
    n2, u2 = renv.prepare({"working_dir": str(d)})
    assert n1 == n2 and u1[0][0] == u2[0][0]
    # Content change changes the URI.
    (d / "a.py").write_text("x = 2\n")
    n3, _ = renv.prepare({"working_dir": str(d)})
    assert n3["working_dir"] != n1["working_dir"]
    # env merging: task overrides job, env_vars union.
    job = {"env_vars": {"A": "1", "B": "1"}, "working_dir": "gcs://_runtime_envs/x.zip"}
    task = {"env_vars": {"B": "2"}}
    merged = renv.merge(job, task)
    assert merged["env_vars"] == {"A": "1", "B": "2"}
    assert merged["working_dir"] == job["working_dir"]
    assert renv.env_hash(None) == "" and renv.env_hash(merged) != ""
