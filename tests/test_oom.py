"""Memory monitor / OOM worker killing (reference:
src/ray/common/memory_monitor.h:52, worker_killing_policy_group_by_owner.cc).

The clusters here set an explicit worker-memory budget
(memory_limit_bytes) so the tests are deterministic regardless of what
else runs on the host; production defaults to the MemAvailable policy.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

LIMIT = 700 * 1024 * 1024  # headroom for ~4 idle workers (~60 MiB each)


@pytest.fixture()
def oom_cluster():
    saved = os.environ.get("RAY_TPU_memory_limit_bytes")
    os.environ["RAY_TPU_memory_limit_bytes"] = str(LIMIT)
    os.environ["RAY_TPU_memory_monitor_refresh_ms"] = "200"
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    if saved is None:
        os.environ.pop("RAY_TPU_memory_limit_bytes", None)
    else:
        os.environ["RAY_TPU_memory_limit_bytes"] = saved
    os.environ.pop("RAY_TPU_memory_monitor_refresh_ms", None)


def test_oom_task_killed_and_error_names_culprit(oom_cluster):
    @ray_tpu.remote(max_retries=0)
    def hog():
        ballast = bytearray(1024 * 1024 * 1024)  # 1 GiB, way over budget
        for i in range(0, len(ballast), 4096):
            ballast[i] = 1  # touch every page so RSS actually grows
        time.sleep(30)
        return len(ballast)

    with pytest.raises(ray_tpu.exceptions.OutOfMemoryError) as ei:
        ray_tpu.get(hog.remote(), timeout=90)
    assert "hog" in str(ei.value)
    assert "MiB" in str(ei.value)


def test_oom_retries_then_fails(oom_cluster):
    """An OOM-killed task is retriable like a crashed worker; when every
    attempt OOMs, the final error is still OutOfMemoryError."""

    @ray_tpu.remote(max_retries=1)
    def hog2():
        ballast = bytearray(1024 * 1024 * 1024)
        for i in range(0, len(ballast), 4096):
            ballast[i] = 1
        time.sleep(30)
        return 1

    with pytest.raises(ray_tpu.exceptions.OutOfMemoryError):
        ray_tpu.get(hog2.remote(), timeout=150)


def test_oom_survivors_unaffected(oom_cluster):
    """Killing the hog must leave well-behaved tasks running.

    Load-hardened: the victim policy kills the NEWEST working worker
    first (retriable new work before long-running old work), and with
    num_cpus=2 the polite worker is always newer than the hog — so
    while the hog holds its ballast, every 200 ms monitor tick lands on
    whichever polite worker is up, and one kill charges every inflight
    spec on that lease.  On a busy box the polite tasks overlap the
    whole kill window and any finite retry budget exhausts.  Survivor
    semantics here are *eventual completion*, not zero kills: give the
    polite tasks an unlimited retry budget, settle the hog's OOM death
    first (which releases the memory pressure), then condition-poll the
    survivors under a generous deadline."""

    @ray_tpu.remote(max_retries=0)
    def hog3():
        ballast = bytearray(1024 * 1024 * 1024)
        for i in range(0, len(ballast), 4096):
            ballast[i] = 1
        time.sleep(30)
        return 1

    @ray_tpu.remote(max_retries=-1)
    def polite(x):
        time.sleep(0.2)
        return x * 2

    bad = hog3.remote()
    good = [polite.remote(i) for i in range(8)]
    with pytest.raises(ray_tpu.exceptions.OutOfMemoryError):
        ray_tpu.get(bad, timeout=120)
    assert ray_tpu.get(good, timeout=150) == [i * 2 for i in range(8)]
