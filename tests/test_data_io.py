"""Data IO parity: new sinks (numpy/tfrecords/avro/webdataset/images)
and sources (avro/mongo/bigquery/iceberg), all hermetic — external
services are injected stubs, binary formats use the in-repo codecs
(reference test model: python/ray/data/tests/test_{tfrecords,avro,
mongo,bigquery}*.py with mocked clients)."""

import json
import os

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# codec units


def test_avro_ocf_roundtrip_full_types(tmp_path):
    from ray_tpu.data._internal import avro

    schema = {
        "type": "record",
        "name": "r",
        "fields": [
            {"name": "i", "type": "long"},
            {"name": "f", "type": "double"},
            {"name": "s", "type": "string"},
            {"name": "b", "type": "bytes"},
            {"name": "maybe", "type": ["null", "long"]},
            {"name": "tags", "type": {"type": "array", "items": "string"}},
            {"name": "props", "type": {"type": "map", "values": "long"}},
            {"name": "color", "type": {"type": "enum", "name": "c", "symbols": ["R", "G"]}},
            {"name": "nested", "type": {"type": "record", "name": "n", "fields": [
                {"name": "x", "type": "int"}]}},
        ],
    }
    rows = [
        {"i": 1, "f": 2.5, "s": "hey", "b": b"\x00\x01", "maybe": None,
         "tags": ["a", "b"], "props": {"k": 9}, "color": "G", "nested": {"x": 7}},
        {"i": -42, "f": -0.5, "s": "", "b": b"", "maybe": 12,
         "tags": [], "props": {}, "color": "R", "nested": {"x": -1}},
    ]
    path = str(tmp_path / "t.avro")
    avro.write_ocf(path, schema, rows)
    rschema, riter = avro.read_ocf(path)
    assert rschema["name"] == "r"
    assert list(riter) == rows
    # null codec too
    avro.write_ocf(path, schema, rows, codec="null")
    _, riter = avro.read_ocf(path)
    assert list(riter) == rows


def test_tfrecord_example_roundtrip_and_crc(tmp_path):
    from ray_tpu.data._internal import tfrecord

    row = {"label": 3, "score": 0.5, "name": b"abc", "vec": [1.0, 2.0, 3.0],
           "ids": [10, 20, -5]}
    blob = tfrecord.encode_example(row)
    back = tfrecord.decode_example(blob)
    assert back["label"] == 3
    assert back["score"] == pytest.approx(0.5)
    assert back["name"] == b"abc"
    assert back["vec"] == pytest.approx([1.0, 2.0, 3.0])
    assert back["ids"] == [10, 20, -5]

    path = str(tmp_path / "x.tfrecords")
    with open(path, "wb") as f:
        tfrecord.write_record(f, blob)
    assert next(iter(tfrecord.read_records(path, verify_crc=True))) == blob
    # corrupt one payload byte: CRC verification must catch it
    data = bytearray(open(path, "rb").read())
    data[14] ^= 0xFF
    open(path, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="crc"):
        list(tfrecord.read_records(path, verify_crc=True))


# ---------------------------------------------------------------------------
# dataset-level sink/source round trips


def test_write_read_tfrecords(ray_cluster, tmp_path):
    import ray_tpu.data as rd

    out = str(tmp_path / "tfr")
    rd.from_items(
        [{"x": i, "w": float(i) / 2, "tag": f"t{i}".encode()} for i in range(20)]
    ).write_tfrecords(out)
    back = rd.read_tfrecords(out).take_all()
    assert sorted(r["x"] for r in back) == list(range(20))
    assert {r["tag"] for r in back} == {f"t{i}".encode() for i in range(20)}


def test_write_read_avro(ray_cluster, tmp_path):
    import ray_tpu.data as rd

    out = str(tmp_path / "avro")
    rd.from_items(
        [{"id": i, "name": f"row{i}", "score": i * 1.5} for i in range(25)]
    ).write_avro(out)
    assert any(f.endswith(".avro") for f in os.listdir(out))
    back = rd.read_avro(out).take_all()
    assert sorted(r["id"] for r in back) == list(range(25))
    assert {r["name"] for r in back} == {f"row{i}" for i in range(25)}


def test_write_read_numpy(ray_cluster, tmp_path):
    import ray_tpu.data as rd

    out = str(tmp_path / "npy")
    rd.from_numpy(np.arange(12.0).reshape(12, 1)).write_numpy(out)
    back = rd.read_numpy(out).take_all()
    vals = sorted(float(np.asarray(r["data"]).ravel()[0]) for r in back)
    assert vals == [float(i) for i in range(12)]


def test_write_read_webdataset(ray_cluster, tmp_path):
    import ray_tpu.data as rd

    out = str(tmp_path / "wds")
    rows = [
        {"__key__": f"{i:04d}", "jpg": bytes([i] * 4), "json": {"label": i}}
        for i in range(6)
    ]
    rd.from_items(rows).write_webdataset(out)
    assert any(f.endswith(".tar") for f in os.listdir(out))
    back = rd.read_webdataset(out).take_all()
    assert sorted(r["__key__"] for r in back) == [f"{i:04d}" for i in range(6)]
    by_key = {r["__key__"]: r for r in back}
    assert by_key["0003"]["jpg"] == bytes([3] * 4)
    assert by_key["0003"]["json"]["label"] == 3


def test_write_read_images(ray_cluster, tmp_path):
    import ray_tpu.data as rd

    out = str(tmp_path / "imgs")
    imgs = np.stack([np.full((4, 4, 3), i * 10, np.uint8) for i in range(5)])
    rd.from_numpy(imgs).map(lambda r: {"image": r["data"]}).write_images(out)
    assert len(os.listdir(out)) == 5
    back = rd.read_images(out).take_all()
    means = sorted(int(np.asarray(r["image"]).mean()) for r in back)
    assert means == [0, 10, 20, 30, 40]


# ---------------------------------------------------------------------------
# service-backed sources with injected stub clients


class _StubMongoCursor:
    def __init__(self, docs):
        self._docs = docs

    def sort(self, key, direction):
        self._docs = sorted(self._docs, key=lambda d: d[key])
        return self

    def skip(self, n):
        self._docs = self._docs[n:]
        return self

    def limit(self, n):
        self._docs = self._docs[:n]
        return self

    def __iter__(self):
        return iter(self._docs)


class _StubMongoCollection:
    DOCS = [{"_id": i, "val": i * 2, "name": f"d{i}"} for i in range(30)]

    def count_documents(self, filt):
        return len(self.DOCS)

    def find(self, filt):
        return _StubMongoCursor(list(self.DOCS))


class _StubMongoClient:
    def __getitem__(self, name):
        return {"coll": _StubMongoCollection()}  # db -> collections


def test_read_mongo_with_stub_client(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.read_mongo("db", "coll", client_factory=_StubMongoClient, parallelism=4)
    rows = ds.take_all()
    assert sorted(r["val"] for r in rows) == [i * 2 for i in range(30)]
    assert all("_id" not in r for r in rows)


class _StubBQJob:
    def __init__(self, rows):
        self._rows = rows

    def result(self):
        return self._rows


class _StubBQClient:
    TABLE = [{"n": i, "sq": i * i} for i in range(17)]

    def query(self, sql):
        base = "SELECT * FROM tbl"
        if sql.startswith("SELECT COUNT(*)"):
            return _StubBQJob([{"n": len(self.TABLE)}])
        if "LIMIT" in sql:
            import re

            m = re.search(r"LIMIT (\d+) OFFSET (\d+)", sql)
            limit, off = int(m.group(1)), int(m.group(2))
            return _StubBQJob(self.TABLE[off : off + limit])
        return _StubBQJob(list(self.TABLE))


def test_read_bigquery_with_stub_client(ray_cluster):
    import ray_tpu.data as rd

    ds = rd.read_bigquery(
        project_id="p", query="SELECT * FROM tbl",
        client_factory=_StubBQClient, parallelism=3,
    )
    rows = ds.take_all()
    assert sorted(r["n"] for r in rows) == list(range(17))
    assert all(r["sq"] == r["n"] ** 2 for r in rows)


# ---------------------------------------------------------------------------
# iceberg scan over a hand-built table


def test_read_iceberg_scan(ray_cluster, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    import ray_tpu.data as rd
    from ray_tpu.data._internal import avro

    root = tmp_path / "tbl"
    (root / "data").mkdir(parents=True)
    (root / "metadata").mkdir()

    # two parquet data files + one that a DELETED manifest entry drops
    for name, lo in (("a.parquet", 0), ("b.parquet", 10), ("gone.parquet", 100)):
        pq.write_table(
            pa.table({"v": list(range(lo, lo + 10))}), str(root / "data" / name)
        )

    manifest_entry_schema = {
        "type": "record", "name": "manifest_entry", "fields": [
            {"name": "status", "type": "int"},
            {"name": "data_file", "type": {"type": "record", "name": "data_file",
             "fields": [
                 {"name": "content", "type": "int"},
                 {"name": "file_path", "type": "string"},
                 {"name": "record_count", "type": "long"},
             ]}},
        ],
    }
    manifest_path = str(root / "metadata" / "m1.avro")
    avro.write_ocf(manifest_path, manifest_entry_schema, [
        {"status": 1, "data_file": {"content": 0,
         "file_path": f"file://{root}/data/a.parquet", "record_count": 10}},
        {"status": 1, "data_file": {"content": 0,
         "file_path": f"file://{root}/data/b.parquet", "record_count": 10}},
        {"status": 2, "data_file": {"content": 0,  # deleted entry: skipped
         "file_path": f"file://{root}/data/gone.parquet", "record_count": 10}},
    ])

    mlist_schema = {
        "type": "record", "name": "manifest_file", "fields": [
            {"name": "manifest_path", "type": "string"},
            {"name": "manifest_length", "type": "long"},
        ],
    }
    mlist_path = str(root / "metadata" / "snap-1.avro")
    avro.write_ocf(mlist_path, mlist_schema, [
        {"manifest_path": f"file://{manifest_path}",
         "manifest_length": os.path.getsize(manifest_path)},
    ])

    meta_path = str(root / "metadata" / "v2.metadata.json")
    with open(meta_path, "w") as f:
        json.dump({
            "format-version": 2,
            "current-snapshot-id": 1,
            "snapshots": [{"snapshot-id": 1, "manifest-list": f"file://{mlist_path}"}],
        }, f)

    rows = rd.read_iceberg(meta_path).take_all()
    assert sorted(r["v"] for r in rows) == list(range(20))


def test_from_torch_map_style_dataset(ray_cluster):
    import torch
    from torch.utils.data import TensorDataset

    import ray_tpu.data as rd

    xs = torch.arange(20, dtype=torch.float32).reshape(20, 1)
    ys = torch.arange(20)
    ds = rd.from_torch(TensorDataset(xs, ys), parallelism=3)
    rows = ds.take_all()
    assert len(rows) == 20
    # (x, y) samples land as item_0/item_1 columns, tensors as numpy
    got = sorted(int(np.asarray(r["item_1"])) for r in rows)
    assert got == list(range(20))
    assert np.asarray(rows[0]["item_0"]).shape == (1,)

    with pytest.raises(TypeError, match="map-style"):
        rd.from_torch(iter([1, 2, 3]))


def test_read_delta_log_replay(ray_cluster, tmp_path):
    """Delta Lake scan replays the _delta_log: checkpoint snapshot +
    later JSON commits, with remove actions dropping files."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    import ray_tpu.data as rd

    root = tmp_path / "delta"
    (root / "_delta_log").mkdir(parents=True)
    for name, lo in (("a.parquet", 0), ("b.parquet", 10), ("old.parquet", 100)):
        pq.write_table(pa.table({"v": list(range(lo, lo + 10))}), str(root / name))

    # checkpoint at version 1 snapshots {a, old}
    pq.write_table(
        pa.table({"add": [{"path": "a.parquet"}, {"path": "old.parquet"}]}),
        str(root / "_delta_log" / "00000000000000000001.checkpoint.parquet"),
    )
    # superseded commit BEFORE the checkpoint must be ignored
    (root / "_delta_log" / "00000000000000000000.json").write_text(
        json.dumps({"add": {"path": "ghost.parquet"}}) + "\n"
    )
    # commit 2: add b, remove old
    (root / "_delta_log" / "00000000000000000002.json").write_text(
        json.dumps({"add": {"path": "b.parquet"}}) + "\n"
        + json.dumps({"remove": {"path": "old.parquet"}}) + "\n"
    )

    rows = rd.read_delta(str(root)).take_all()
    assert sorted(r["v"] for r in rows) == list(range(20))  # a + b, not old

    with pytest.raises(FileNotFoundError, match="_delta_log"):
        rd.read_delta(str(tmp_path)).take_all()
