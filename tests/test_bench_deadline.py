"""bench.py deadline ladder: the flagship bench must emit a parseable
JSON record under BOTH a healthy backend and a wedged TPU tunnel.

Round-4 postmortem: BENCH_r04.json was `{rc: 124, tail: "", parsed: null}`
because the stage budgets summed past the driver's own timeout and the
one JSON line printed only at the very end.  These tests pin the redesign:
a bounded chip probe, a global deadline, and incremental emission —
simulated-wedge included (BENCH_FAKE_WEDGE hangs backend init exactly the
way the real tunnel does).

Reference discipline: release/microbenchmark/run_microbenchmark.py
(capture everything or say why).
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(env_overrides, timeout):
    env = dict(os.environ)
    # the child must see the REAL platform selection logic, not the
    # conftest CPU pin (the wedge prelude triggers only off-cpu)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = ""
    env.update(env_overrides)
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=timeout,
    )
    records = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    return proc, records


@pytest.mark.slow  # ~21 s wedged-subprocess deadline drill: tier-2
def test_wedged_tunnel_still_emits_record():
    """A hanging backend init (the real wedge signature) must still yield
    parseable JSON lines well inside the global deadline."""
    t0 = time.time()
    proc, records = _run_bench(
        {
            "BENCH_FAKE_WEDGE": "1",
            "BENCH_DEADLINE_S": "240",
            "BENCH_PROBE_BUDGET_S": "5",
            "BENCH_SKIP_PPO": "1",
        },
        timeout=280,
    )
    elapsed = time.time() - t0
    assert records, f"no JSON records in output:\n{proc.stdout}\n{proc.stderr}"
    final = records[-1]
    assert final["metric"] == "gpt2_small_train_tokens_per_sec_per_chip"
    assert final["on_tpu"] is False
    assert final["value"] > 0, final
    # every emitted line must be independently complete
    for rec in records:
        assert "value" in rec and "unit" in rec and "on_tpu" in rec
    assert elapsed < 260, f"bench overran its deadline: {elapsed:.0f}s"


@pytest.mark.slow  # ~30 s full bench-harness record; gate logic unit-tested above
def test_healthy_cpu_backend_full_record():
    """With a healthy (CPU) backend the record carries the framework
    number, the raw comparison, and the probe timing."""
    proc, records = _run_bench(
        {
            "JAX_PLATFORMS": "cpu",
            "BENCH_DEADLINE_S": "240",
            "BENCH_SKIP_PPO": "1",
        },
        timeout=280,
    )
    assert records, f"no JSON records in output:\n{proc.stdout}\n{proc.stderr}"
    final = records[-1]
    assert final["value"] > 0
    assert final["on_tpu"] is False  # cpu backend
    assert "chip_probe_secs" in final
    assert "raw_tokens_per_sec_per_chip" in final
    # incremental emission: an interim record precedes the final one
    assert len(records) >= 2
