"""DAG API + compiled graphs + durable workflows.

Reference test model: python/ray/dag/tests/, python/ray/workflow/tests/
(test_basic_workflows.py resume-after-failure pattern).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


def test_function_dag(ray_cluster):
    @ray_tpu.remote
    def plus_one(x):
        return x + 1

    @ray_tpu.remote
    def times_two(x):
        return x * 2

    with InputNode() as inp:
        dag = times_two.bind(plus_one.bind(inp))
    ref = dag.execute(5)
    assert ray_tpu.get(ref) == 12


def test_dag_multi_output_and_input_attr(ray_cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def neg(a):
        return -a

    with InputNode() as inp:
        s = add.bind(inp["a"], inp["b"])
        dag = MultiOutputNode([s, neg.bind(s)])
    refs = dag.execute({"a": 3, "b": 4})
    assert ray_tpu.get(refs) == [7, -7]


def test_actor_dag(ray_cluster):
    @ray_tpu.remote
    class Accumulator:
        def __init__(self, start):
            self.total = start

        def add(self, x):
            self.total += x
            return self.total

    with InputNode() as inp:
        acc = Accumulator.bind(100)
        dag = acc.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 105


def test_compiled_dag_reuses_actors(ray_cluster):
    import os

    @ray_tpu.remote
    class Stage:
        def __init__(self):
            self.pid = os.getpid()
            self.calls = 0

        def work(self, x):
            self.calls += 1
            return (x + 1, self.pid, self.calls)

    with InputNode() as inp:
        stage = Stage.bind()
        dag = stage.work.bind(inp)
    compiled = dag.experimental_compile()
    out1 = ray_tpu.get(compiled.execute(1))
    out2 = ray_tpu.get(compiled.execute(10))
    assert out1[0] == 2 and out2[0] == 11
    assert out1[1] == out2[1]  # same actor process
    assert out2[2] == 2  # state persisted across executions
    compiled.teardown()


def test_compiled_dag_throughput(ray_cluster):
    """Compiled execution must beat per-call DAG walking + actor restarts
    (reference claim: compiled graphs bypass scheduler overhead)."""

    @ray_tpu.remote
    class Echo:
        def echo(self, x):
            return x

    with InputNode() as inp:
        dag = Echo.bind().echo.bind(inp)
    compiled = dag.experimental_compile()
    ray_tpu.get(compiled.execute(0))  # warm
    t0 = time.time()
    n = 50
    for i in range(n):
        ray_tpu.get(compiled.execute(i))
    dt = time.time() - t0
    compiled.teardown()
    assert dt / n < 0.1, f"compiled DAG round-trip too slow: {dt / n * 1000:.1f} ms"


def test_workflow_run_and_output(ray_cluster, tmp_path):
    from ray_tpu import workflow

    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(double.bind(inp))
    out = workflow.run(dag, workflow_id="wf1", input_val=10)
    assert out == 21
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 21
    assert ("wf1", "SUCCESSFUL") in workflow.list_all()


def test_workflow_resume_skips_completed_steps(ray_cluster, tmp_path):
    from ray_tpu import workflow

    workflow.init(str(tmp_path))
    marker = str(tmp_path / "side_effects")

    @ray_tpu.remote
    def step_a(x):
        with open(marker, "a") as f:
            f.write("a")
        return x + 1

    flag_file = str(tmp_path / "crash_once")

    @ray_tpu.remote
    def flaky(x, flag=flag_file):
        if not os.path.exists(flag):
            open(flag, "w").close()
            raise RuntimeError("simulated crash")
        return x * 100

    with InputNode() as inp:
        dag = flaky.bind(step_a.bind(inp))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf_resume", input_val=1)
    assert workflow.get_status("wf_resume") == "FAILED"
    # resume: step_a is checkpointed, only flaky re-runs
    out = workflow.resume("wf_resume")
    assert out == 200
    with open(marker) as f:
        assert f.read() == "a"  # step_a ran exactly once
    assert workflow.get_status("wf_resume") == "SUCCESSFUL"


def test_workflow_actor_steps_checkpoint_and_restore_state(ray_cluster, tmp_path):
    """Actor steps checkpoint outputs AND actor state (get_state/set_state):
    a resume replays completed actor-step outputs from storage and
    restores the actor's counter before the first live step — no
    re-execution of completed steps (VERDICT r4 ask #10; reference:
    workflow_executor.py checkpoints every step)."""
    from ray_tpu import workflow

    workflow.init(str(tmp_path))
    calls_marker = str(tmp_path / "accum_calls")

    @ray_tpu.remote
    class Accumulator:
        def __init__(self):
            self.total = 0

        def add(self, x):
            with open(calls_marker, "a") as f:
                f.write("x")
            self.total += x
            return self.total

        def get_state(self):
            return {"total": self.total}

        def set_state(self, state):
            self.total = state["total"]

    flag_file = str(tmp_path / "crash_once_actor")

    @ray_tpu.remote
    def flaky_gate(x, flag=flag_file):
        if not os.path.exists(flag):
            open(flag, "w").close()
            raise RuntimeError("simulated crash")
        return x

    acc = Accumulator.bind()
    with InputNode() as inp:
        first = acc.add.bind(inp)          # 0 + 7 = 7, checkpointed
        gated = flaky_gate.bind(first)     # crashes on first run
        dag = acc.add.bind(gated)          # resumed: needs total==7 restored

    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf_actor", input_val=7)
    assert workflow.get_status("wf_actor") == "FAILED"

    out = workflow.resume("wf_actor")
    # 7 (replayed from checkpoint) + 7 on a RESTORED total of 7 → 14
    assert out == 14
    with open(calls_marker) as f:
        # first add ran once (original attempt); second add ran once
        # (after resume); the first add was NOT re-executed on resume
        assert f.read() == "xx"
    assert workflow.get_status("wf_actor") == "SUCCESSFUL"


def test_compiled_dag_execute_many_exact(ray_cluster):
    """execute_many batches K executions into one channel write per
    edge; results come back per-ref, exact, in order — including through
    a multi-actor pipeline and a multi-output fan-out."""

    @ray_tpu.remote
    class Stage:
        def inc(self, x):
            return x + 1

        def double(self, x):
            return x * 2

    with InputNode() as inp:
        a = Stage.bind()
        b = Stage.bind()
        mid = a.inc.bind(inp)
        dag = MultiOutputNode([b.double.bind(mid), mid])
    compiled = dag.experimental_compile(max_inflight=64)
    try:
        assert compiled._channels_on
        refs = compiled.execute_many(list(range(16)))
        assert len(refs) == 16
        for i, ref in enumerate(refs):
            assert ray_tpu.get(ref) == [(i + 1) * 2, i + 1]
        # interleaves with single executes on the same channels
        r1 = compiled.execute(100)
        many = compiled.execute_many([200, 300])
        assert ray_tpu.get(r1) == [202, 101]
        assert ray_tpu.get(many[0]) == [402, 201]
        assert ray_tpu.get(many[1]) == [602, 301]
    finally:
        compiled.teardown()


def test_compiled_dag_execute_many_per_entry_errors(ray_cluster):
    """One failing entry in a batch errors ONLY its own ref; the other
    entries of the same batched frame still resolve."""

    @ray_tpu.remote
    class Divider:
        def div(self, x):
            return 10 // x

    with InputNode() as inp:
        dag = Divider.bind().div.bind(inp)
    compiled = dag.experimental_compile(max_inflight=16)
    try:
        refs = compiled.execute_many([5, 0, 2])
        assert ray_tpu.get(refs[0]) == 2
        with pytest.raises(ZeroDivisionError):
            ray_tpu.get(refs[1])
        assert ray_tpu.get(refs[2]) == 5
        # the DAG stays usable after the per-entry error
        assert ray_tpu.get(compiled.execute(10)) == 1
    finally:
        compiled.teardown()


def test_execute_many_inflight_bound_and_fallbacks(ray_cluster):
    """The driver-side in-flight cap counts K batched executions; and
    graphs with input-independent source nodes take the sequential
    fallback (their single frames would desync batched edges)."""

    @ray_tpu.remote
    class Echo:
        def echo(self, x):
            return x

    with InputNode() as inp:
        dag = Echo.bind().echo.bind(inp)
    compiled = dag.experimental_compile(max_inflight=4)
    try:
        with pytest.raises(RuntimeError, match="max_inflight"):
            compiled.execute_many(list(range(8)))
        refs = compiled.execute_many([1, 2])
        assert [ray_tpu.get(r) for r in refs] == [1, 2]
    finally:
        compiled.teardown()

    @ray_tpu.remote
    def seed():
        return 7

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag2 = add.bind(seed.bind(), inp)
    compiled2 = dag2.experimental_compile(max_inflight=16)
    try:
        assert compiled2._has_const_sources
        refs = compiled2.execute_many([1, 2, 3])  # sequential fallback
        assert [ray_tpu.get(r) for r in refs] == [8, 9, 10]
    finally:
        compiled2.teardown()
