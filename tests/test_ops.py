"""Numerics tests: ring attention and pallas flash attention vs the XLA
reference implementation, on a virtual 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.ops.attention import reference_causal_attention  # noqa: E402


def _rand_qkv(B=2, T=128, H=4, D=16, dtype=jnp.float32, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(k1, (B, T, H, D), dtype)
    k = jax.random.normal(k2, (B, T, H, D), dtype)
    v = jax.random.normal(k3, (B, T, H, D), dtype)
    return q, k, v


def test_reference_attention_is_causal():
    q, k, v = _rand_qkv()
    out1 = reference_causal_attention(q, k, v)
    # Perturb the future: outputs at earlier positions must not change.
    k2 = k.at[:, 64:].set(0.0)
    v2 = v.at[:, 64:].set(0.0)
    out2 = reference_causal_attention(q, k2, v2)
    np.testing.assert_allclose(out1[:, :64], out2[:, :64], rtol=1e-5, atol=1e-5)


def test_ring_attention_matches_reference():
    from ray_tpu.ops.ring_attention import ring_causal_attention
    from ray_tpu.parallel import create_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    mesh = create_mesh({"sp": 4})
    q, k, v = _rand_qkv(B=2, T=128, H=4, D=16)
    ref = reference_causal_attention(q, k, v)
    out = jax.jit(lambda q, k, v: ring_causal_attention(q, k, v, mesh=mesh, axis="sp"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ring_attention_composes_with_dp():
    from ray_tpu.ops.ring_attention import ring_causal_attention
    from ray_tpu.parallel import create_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = create_mesh({"dp": 2, "sp": 4})
    q, k, v = _rand_qkv(B=4, T=64, H=2, D=8)
    ref = reference_causal_attention(q, k, v)
    out = jax.jit(lambda q, k, v: ring_causal_attention(q, k, v, mesh=mesh, axis="sp"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pallas_flash_attention_interpret_matches_reference():
    from ray_tpu.ops.pallas_attention import flash_attention

    q, k, v = _rand_qkv(B=1, T=256, H=2, D=32)
    ref = reference_causal_attention(q, k, v)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pallas_flash_attention_grads_match_reference():
    from ray_tpu.ops.pallas_attention import flash_attention

    q, k, v = _rand_qkv(B=1, T=256, H=2, D=32)

    def loss_ref(q, k, v):
        return (reference_causal_attention(q, k, v) ** 2).sum()

    def loss_fa(q, k, v):
        return (flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                interpret=True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fa, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3)
