"""Cluster flight recorder: cross-process span aggregation, the merged
Perfetto timeline, Prometheus exposition round-trip, dashboard
observability endpoints, and the instrumentation overhead guard
(reference: python/ray/tests/test_metrics_agent.py, `ray timeline`)."""

import json
import time
from urllib import request as urlrequest

import pytest

import ray_tpu
from ray_tpu.util import state, tracing
from ray_tpu.util import metrics as metrics_mod


@pytest.fixture(scope="module")
def obs():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


def _poll(fn, timeout=20.0, interval=0.4):
    deadline = time.monotonic() + timeout
    while True:
        out = fn()
        if out:
            return out
        if time.monotonic() >= deadline:
            return out
        time.sleep(interval)


# ----------------------------------------------------------------------
# span propagation + cluster timeline (the acceptance criterion)
# ----------------------------------------------------------------------
def test_cluster_timeline_cross_process(obs, tmp_path):
    """A remote() call tree produces ONE trace whose spans come from >=2
    distinct PIDs with parent/child links that survive the process hop:
    root (driver) -> task::mid (worker A) -> task::leaf (worker B)."""

    @ray_tpu.remote
    def leaf():
        return "leaf-done"

    @ray_tpu.remote
    def mid():
        return ray_tpu.get(leaf.remote())

    with tracing.start_span("obs-root") as root:
        assert ray_tpu.get(mid.remote(), timeout=60) == "leaf-done"

    def fetch():
        sp = state.spans()
        names = {s["name"] for s in sp}
        if "obs-root" in names and any("mid" in n for n in names) and any(
            "leaf" in n for n in names
        ):
            return sp
        return None

    sp = _poll(fetch)
    assert sp, "spans did not reach the GCS span table"
    ours = [s for s in sp if s["trace_id"] == root.trace_id]
    by_id = {s["span_id"]: s for s in ours}
    root_span = next(s for s in ours if s["name"] == "obs-root")
    mid_span = next(s for s in ours if s["name"].endswith("mid"))
    leaf_span = next(s for s in ours if s["name"].endswith("leaf"))
    # parent/child nesting across process boundaries
    assert root_span["parent_span_id"] is None
    assert mid_span["parent_span_id"] == root_span["span_id"]
    assert leaf_span["parent_span_id"] == mid_span["span_id"]
    assert leaf_span["parent_span_id"] in by_id and mid_span["parent_span_id"] in by_id
    # spans span processes: driver + at least one distinct worker pid
    pids = {root_span["pid"], mid_span["pid"], leaf_span["pid"]}
    assert len(pids) >= 2, f"expected >=2 distinct PIDs, got {pids}"

    # the timeline export carries the same spans as Chrome-trace events
    out = state.timeline(str(tmp_path / "trace.json"))
    with open(out) as f:
        trace = json.load(f)
    span_events = [e for e in trace if e.get("cat") == "span"]
    ev_pids = {e["pid"] for e in span_events
               if e["args"].get("trace_id") == root.trace_id}
    assert len(ev_pids) >= 2
    for e in span_events:
        assert {"trace_id", "span_id"} <= set(e["args"])
    # grouped view agrees
    tr = next(t for t in state.traces() if t["trace_id"] == root.trace_id)
    assert tr["span_count"] >= 3 and len(tr["pids"]) >= 2


# ----------------------------------------------------------------------
# Prometheus exposition round-trip
# ----------------------------------------------------------------------
def _parse_exposition(text: str):
    """Minimal Prometheus text-format parser: returns (samples, types)
    where samples is {(name, frozenset(labels)): value}."""
    samples, types = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ", 3)
            types.setdefault(name, []).append(mtype)
            continue
        if line.startswith("#"):
            continue
        metric, _, value = line.rpartition(" ")
        labels = {}
        if "{" in metric:
            name, _, rest = metric.partition("{")
            body = rest.rstrip("}")
            i, cur_key, cur, in_q, esc = 0, None, "", False, False
            # label values may contain escaped quotes/commas — walk chars
            while i < len(body):
                ch = body[i]
                if in_q:
                    if esc:
                        cur += {"n": "\n", '"': '"', "\\": "\\"}.get(ch, ch)
                        esc = False
                    elif ch == "\\":
                        esc = True
                    elif ch == '"':
                        in_q = False
                        labels[cur_key] = cur
                        cur = ""
                    else:
                        cur += ch
                elif ch == '"':
                    in_q = True
                elif ch == "=":
                    cur_key, cur = cur, ""
                elif ch == ",":
                    cur = ""
                else:
                    cur += ch
                i += 1
        else:
            name = metric
        samples[(name, frozenset(labels.items()))] = float(value)
    return samples, types


def test_prometheus_roundtrip_and_label_escaping():
    records = [
        {
            "name": "odd_counter",
            "type": "counter",
            "description": "labels with\nnewlines and \\slashes",
            "value": 3.0,
            "tags": {"path": 'a"b\\c\nd', "plain": "ok"},
        },
        {
            "name": "lat_hist",
            "type": "histogram",
            "description": "latency",
            "buckets": [0.1, 1.0],
            "counts": [2, 1, 1],
            "sum": 3.3,
            "count": 4,
            "tags": {"m": "x"},
        },
        {
            "name": "lat_hist",
            "type": "histogram",
            "description": "latency",
            "buckets": [0.1, 1.0],
            "counts": [1, 0, 0],
            "sum": 0.05,
            "count": 1,
            "tags": {"m": "y"},
        },
    ]
    text = metrics_mod.prometheus_text(records)
    samples, types = _parse_exposition(text)
    # exactly one # TYPE line per metric name (grouping, not duplication)
    assert all(len(v) == 1 for v in types.values()), types
    assert types["odd_counter"] == ["counter"] and types["lat_hist"] == ["histogram"]
    # the escaped label value round-trips byte-for-byte
    key = ("odd_counter", frozenset({("path", 'a"b\\c\nd'), ("plain", "ok")}.__iter__()))
    assert samples[key] == 3.0
    # histogram exposition: cumulative buckets + _sum/_count per series
    assert samples[("lat_hist_bucket", frozenset({("m", "x"), ("le", "+Inf")}))] == 4
    assert samples[("lat_hist_bucket", frozenset({("m", "x"), ("le", "0.1")}))] == 2
    assert samples[("lat_hist_count", frozenset({("m", "x")}))] == 4
    assert samples[("lat_hist_count", frozenset({("m", "y")}))] == 1
    # a single trailing newline, no blank # HELP spam
    assert text.endswith("\n") and "# HELP odd_counter" in text


def test_live_metrics_exposition_parses(obs):
    """The cluster's real /metrics view (core instrumentation included)
    parses cleanly and exposes rpc_latency_seconds histograms per
    method."""

    @ray_tpu.remote
    def touch(x):
        return x

    ray_tpu.get([touch.remote(i) for i in range(5)])
    metrics_mod.flush()

    def fetch():
        recs = state.metrics()
        if any(r["name"] == "rpc_latency_seconds" for r in recs):
            return recs
        return None

    recs = _poll(fetch, timeout=15)
    assert recs, "rpc_latency_seconds never reached the GCS"
    text = metrics_mod.prometheus_text(recs)
    samples, types = _parse_exposition(text)
    assert all(len(v) == 1 for v in types.values())
    assert types["rpc_latency_seconds"] == ["histogram"]
    methods = {
        dict(k[1]).get("method")
        for k in samples
        if k[0] == "rpc_latency_seconds_count"
    }
    assert len(methods) >= 2, f"expected per-method series, got {methods}"


# ----------------------------------------------------------------------
# dashboard endpoints
# ----------------------------------------------------------------------
def test_dashboard_observability_endpoints(obs):
    url = obs.dashboard_url
    assert url

    @ray_tpu.remote
    def ping():
        return 1

    with tracing.start_span("dash-root"):
        ray_tpu.get([ping.remote() for _ in range(3)])
    tracing.flush()

    def fetch():
        with urlrequest.urlopen(url + "/api/traces", timeout=10) as r:
            traces = json.loads(r.read())
        if any(t["span_count"] >= 2 for t in traces):
            return traces
        return None

    traces = _poll(fetch, timeout=15)
    assert traces, "/api/traces never showed a multi-span trace"

    req = urlrequest.urlopen(url + "/api/timeline", timeout=10)
    assert "attachment" in req.headers.get("Content-Disposition", "")
    tl = json.loads(req.read())
    assert any(e.get("cat") == "span" for e in tl)
    assert any(e.get("ph") == "M" for e in tl)  # perfetto process names

    with urlrequest.urlopen(url + "/api/chaos", timeout=10) as r:
        chaos = json.loads(r.read())
    # no chaos configured: endpoint reports inactive but well-formed views
    assert chaos["active"] is False
    assert chaos["gcs"] is not None and chaos["gcs"]["rules"] == []
    assert isinstance(chaos["nodes"], dict) and len(chaos["nodes"]) >= 1
    for view in chaos["nodes"].values():
        assert "rules" in view and "spec" in view


# ----------------------------------------------------------------------
# chaos stats accounting (process-local, no cluster needed)
# ----------------------------------------------------------------------
def test_chaos_stats_counts_injections():
    from ray_tpu._private.chaos import CHAOS
    from ray_tpu._private.config import CONFIG

    CONFIG._overrides["testing_chaos_spec"] = "obs_fake_*:drop_req:n=2"
    CONFIG._overrides["testing_chaos_seed"] = 7
    CHAOS.reset()
    try:
        assert CHAOS.decide("obs_fake_call", "req").drop
        assert CHAOS.decide("obs_fake_call", "req").drop
        assert not CHAOS.decide("obs_fake_call", "req").drop  # n=2 exhausted
        st = CHAOS.stats()
        assert st["active"] and st["seed"] == 7
        (rule,) = st["rules"]
        assert rule["pattern"] == "obs_fake_*" and rule["action"] == "drop_req"
        assert rule["matches"] == 3 and rule["fired"] == 2
        assert st["schedule_len"] == 3
    finally:
        CONFIG._overrides.pop("testing_chaos_spec", None)
        CONFIG._overrides.pop("testing_chaos_seed", None)
        CHAOS.reset()


# ----------------------------------------------------------------------
# idempotent GCS read retry
# ----------------------------------------------------------------------
def test_call_idempotent_retries_timeouts():
    from ray_tpu._private import rpc

    class FlakyClient:
        def __init__(self, fail_n):
            self.fail_n = fail_n
            self.calls = 0

        def call(self, method, payload=None, timeout=None):
            self.calls += 1
            if self.calls <= self.fail_n:
                raise rpc.CallTimeout(f"{method} timed out")
            return ("ok", method, payload)

    c = FlakyClient(fail_n=2)
    assert rpc.call_idempotent(c, "kv_get", ("ns", b"k"))[0] == "ok"
    assert c.calls == 3

    # budget exhaustion surfaces the original CallTimeout
    c2 = FlakyClient(fail_n=99)
    with pytest.raises(rpc.CallTimeout):
        rpc.call_idempotent(c2, "kv_get", None)
    assert c2.calls >= 3


# ----------------------------------------------------------------------
# overhead guard
# ----------------------------------------------------------------------
def test_instrumentation_overhead_budget(obs):
    """The flight recorder must cost <5% of bench_micro task throughput.
    A task involves ~10 instrumented events (client+server RPC observes,
    task phases, span record); measure the real per-event cost and the
    real per-task wall time and assert the ratio."""
    from ray_tpu._private import telemetry

    @ray_tpu.remote
    def nop():
        return b"ok"

    # warm the path (lease grants, function table)
    ray_tpu.get([nop.remote() for _ in range(20)])
    n_tasks = 200
    t0 = time.perf_counter()
    ray_tpu.get([nop.remote() for _ in range(n_tasks)])
    per_task_s = (time.perf_counter() - t0) / n_tasks

    n_ops = 5000
    t0 = time.perf_counter()
    for _ in range(n_ops):
        telemetry.observe_rpc("overhead_probe", "client", 0.001)
        telemetry.observe_task_phase("exec", 0.001)
    per_event_s = (time.perf_counter() - t0) / (2 * n_ops)

    # Direct-path critical-path events per task: submit + e2e (driver),
    # exec + span-context check (worker); exec_direct/task_finished are
    # uninstrumented pushes and server-side observes land in other
    # processes, off the driver-throughput critical path.  6 = that
    # census (~4) with headroom.
    events_per_task = 6
    overhead = events_per_task * per_event_s / per_task_s
    assert overhead < 0.05, (
        f"instrumentation overhead {overhead:.1%} >= 5% "
        f"(per-event {per_event_s * 1e6:.2f}us, per-task {per_task_s * 1e3:.2f}ms)"
    )


def test_dataplane_trailer_overhead_budget():
    """Trace propagation must be free when off: an untraced frame is
    byte-identical to a pre-trailer encode (ZERO trailer bytes on the
    wire — the strongest possible zero-serialization-cost proof, and
    deterministic where a timing ratio flakes on a loaded 1-core box),
    a traced frame pays exactly TRACE_LEN extra, and both decode
    transparently.  The TIMING half of the guard is the bench gate:
    bench_micro.py channel_rtt_us_untraced vs the checked-in
    BENCH_micro_head.json capture, compared like-for-like by
    bench_gate.py."""
    from ray_tpu._private import wire
    from ray_tpu.util import tracing

    payload = {"prompt": list(range(16)), "max_tokens": 8}
    plain = wire.encode(payload, tag=3)
    assert plain[0] & wire.TRACE_FLAG == 0
    # no ambient context -> channels pass trace=None -> identical bytes
    assert wire.encode(payload, tag=3, trace=None) == plain

    trace = ("ab" * 16, "cd" * 8, 0, time.time())
    traced = wire.encode(payload, tag=3, trace=trace)
    assert traced[0] & wire.TRACE_FLAG
    assert len(traced) == len(plain) + wire.TRACE_LEN

    # both decode transparently; decode_traced surfaces the context
    assert wire.decode(memoryview(plain))[1] == payload
    assert wire.decode(memoryview(traced))[1] == payload
    tag, val, tctx = wire.decode_traced(memoryview(traced))
    assert (tag, val) == (3, payload) and tctx[0] == "ab" * 16
    tag, val, tctx = wire.decode_traced(memoryview(plain))
    assert (tag, val, tctx) == (3, payload, None)
    assert tracing.current_context() is None


def test_telemetry_kill_switch():
    """telemetry_enabled=False turns every instrumentation site into a
    boolean check and records nothing new."""
    from ray_tpu._private import telemetry
    from ray_tpu._private.config import CONFIG

    CONFIG._overrides["telemetry_enabled"] = False
    telemetry.refresh()
    try:
        assert telemetry.enabled() is False
        before = dict(metrics_mod._registry)
        telemetry.observe_rpc("kill_switch_probe", "client", 1.0)
        telemetry.count_retry("kill_switch_probe")
        assert not any(
            k[0] in ("rpc_latency_seconds", "retry_backoff_total")
            and any("kill_switch_probe" in str(t) for t in k[1])
            for k in metrics_mod._registry
            if k not in before
        )
    finally:
        CONFIG._overrides.pop("telemetry_enabled", None)
        telemetry.refresh()
        assert telemetry.enabled() is True


def test_span_flush_batch_cap():
    """Each flush() ships at most span_flush_max_batch spans (ROADMAP
    PR-2 follow-up: bounded report frames under sustained load); the
    remainder goes out on subsequent flushes."""
    from ray_tpu._private.config import CONFIG

    tracing.drain_spans()  # clean slate
    shipped_batches = []

    orig_report = metrics_mod.report

    def capture(method, payload):
        if method == "span_report":
            shipped_batches.append(len(payload["spans"]))
            return True
        return orig_report(method, payload)

    CONFIG._overrides["span_flush_max_batch"] = 10
    metrics_mod.report, orig = capture, metrics_mod.report
    try:
        for i in range(25):
            with tracing.start_span(f"cap-span-{i}"):
                pass
        for _ in range(5):
            tracing.flush()
        assert shipped_batches, "flush never shipped"
        assert max(shipped_batches) <= 10, shipped_batches
        assert sum(shipped_batches) >= 25  # everything eventually ships
    finally:
        metrics_mod.report = orig
        CONFIG._overrides.pop("span_flush_max_batch", None)
        tracing.drain_spans()


def test_span_head_sampling_deterministic():
    """span_sample_rate head-samples whole traces at record time,
    deterministically in the trace id: rate 0 records nothing, rate 1
    records everything, and the keep/drop verdict for one trace id is
    stable (so multi-process trees stay whole)."""
    from ray_tpu._private.config import CONFIG
    from ray_tpu.util.tracing import _sampled

    tracing.drain_spans()
    CONFIG._overrides["span_sample_rate"] = 0.0
    try:
        with tracing.start_span("never-kept"):
            pass
        assert tracing.drain_spans() == []
        CONFIG._overrides["span_sample_rate"] = 1.0
        with tracing.start_span("always-kept"):
            pass
        assert [s["name"] for s in tracing.drain_spans()] == ["always-kept"]
        # Determinism of the per-trace verdict at a partial rate.
        CONFIG._overrides["span_sample_rate"] = 0.5
        # Sampling keys off the FIRST 8 hex chars of the trace id.
        ids = [f"{i:08x}" + "0" * 24 for i in range(0, 2**32, 2**28)]
        v1 = [_sampled(t) for t in ids]
        v2 = [_sampled(t) for t in ids]
        assert v1 == v2
        assert any(v1) and not all(v1)  # rate actually partitions
    finally:
        CONFIG._overrides.pop("span_sample_rate", None)
        tracing.drain_spans()
