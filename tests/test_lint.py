"""graftlint tier-1 gate + per-checker fixture tests.

Two layers:

- **fixture tests** — for every checker, a known-bad snippet that must
  be flagged and a known-good twin that must pass.  These pin the
  checker semantics so a refactor of the analyzer can't silently stop
  catching the bug class it was built for.
- **the gate** — ``ray_tpu/`` itself must lint clean against the
  checked-in ``.graftlint.toml`` baseline, under the <30 s budget, with
  no stale baseline entries.  This is the tier-1 assertion that holds
  the invariants for every future PR.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.devtools.lint import baseline as baseline_mod
from ray_tpu.devtools.lint import core
from ray_tpu.devtools.lint.cli import main as cli_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_source(tmp_path, source, select, filename="mod.py", docs=None):
    """Write ``source`` into a scratch tree and run the selected checker.
    Returns the violations for that checker only (bad-suppression rides
    along when asked for explicitly)."""
    f = tmp_path / filename
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    if docs is not None:
        d = tmp_path / "docs" / "observability.md"
        d.parent.mkdir(exist_ok=True)
        d.write_text(textwrap.dedent(docs))
    result = core.run_lint([str(f)], root=str(tmp_path), select=list(select))
    assert not result.parse_errors, result.parse_errors
    return result.violations


# ---------------------------------------------------------------- retry-gate

BAD_SLEEP_LOOP = """
    import time

    def wait_for_it(check):
        while not check():
            time.sleep(0.5)
"""

GOOD_POLICY_LOOP = """
    import time
    from ray_tpu._private import retry

    def wait_for_it(check):
        bo = retry.POLL.start(deadline_s=30)
        while not check():
            delay = bo.next_delay()
            if delay is None:
                raise TimeoutError
            time.sleep(delay)
"""

BAD_HANDROLLED_RPC = """
    def fetch(client):
        while True:
            try:
                return client.call("get_thing")
            except ConnectionError:
                continue
"""

GOOD_IDEMPOTENT_RPC = """
    from ray_tpu._private import rpc, retry

    def fetch(client):
        return rpc.call_idempotent(client, "get_thing", policy=retry.GCS_READ)
"""


def test_retry_gate_flags_fixed_sleep_loop(tmp_path):
    v = lint_source(tmp_path, BAD_SLEEP_LOOP, ["retry-gate"])
    assert [x.tag for x in v] == ["sleep=0.5"]
    assert v[0].symbol == "wait_for_it"


def test_retry_gate_passes_policy_loop(tmp_path):
    assert lint_source(tmp_path, GOOD_POLICY_LOOP, ["retry-gate"]) == []


def test_retry_gate_flags_handrolled_rpc_retry(tmp_path):
    v = lint_source(tmp_path, BAD_HANDROLLED_RPC, ["retry-gate"])
    assert [x.tag for x in v] == ["handrolled-rpc-retry"]


def test_retry_gate_passes_idempotent_call(tmp_path):
    assert lint_source(tmp_path, GOOD_IDEMPOTENT_RPC, ["retry-gate"]) == []


def test_retry_gate_ignores_yield_sleep(tmp_path):
    src = """
        import time

        def spin():
            while True:
                time.sleep(0)
    """
    assert lint_source(tmp_path, src, ["retry-gate"]) == []


# ---------------------------------------------------------------- lock-order

BAD_LOCK_CYCLE = """
    import threading

    a = threading.Lock()
    b = threading.Lock()

    def one():
        with a:
            with b:
                pass

    def two():
        with b:
            with a:
                pass
"""

GOOD_LOCK_ORDER = """
    import threading

    a = threading.Lock()
    b = threading.Lock()

    def one():
        with a:
            with b:
                pass

    def two():
        with a:
            with b:
                pass
"""

BAD_BLOCKING_UNDER_LOCK = """
    import threading
    import time

    class Pool:
        def __init__(self):
            self._mu = threading.Lock()

        def drain(self, client):
            with self._mu:
                client.call("flush")
"""

GOOD_BLOCKING_OUTSIDE_LOCK = """
    import threading
    import time

    class Pool:
        def __init__(self):
            self._mu = threading.Lock()

        def drain(self, client):
            with self._mu:
                todo = True
            if todo:
                client.call("flush")
"""


def test_lock_order_flags_cycle(tmp_path):
    v = lint_source(tmp_path, BAD_LOCK_CYCLE, ["lock-order"])
    cycles = [x for x in v if x.tag.startswith("cycle:")]
    assert len(cycles) == 1
    assert "potential deadlock" in cycles[0].message


def test_lock_order_passes_consistent_order(tmp_path):
    v = lint_source(tmp_path, GOOD_LOCK_ORDER, ["lock-order"])
    assert [x for x in v if x.tag.startswith("cycle:")] == []


def test_lock_order_flags_rpc_under_lock(tmp_path):
    v = lint_source(tmp_path, BAD_BLOCKING_UNDER_LOCK, ["lock-order"])
    assert len(v) == 1 and v[0].tag.startswith("blocking:rpc call@")
    assert v[0].symbol == "Pool.drain"


def test_lock_order_passes_rpc_outside_lock(tmp_path):
    assert lint_source(tmp_path, GOOD_BLOCKING_OUTSIDE_LOCK, ["lock-order"]) == []


def test_lock_order_closure_does_not_inherit_held_set(tmp_path):
    # A function *defined* under a lock does not *run* under it.
    src = """
        import threading
        import time

        mu = threading.Lock()

        def make_worker():
            with mu:
                def worker():
                    time.sleep(1.0)
                return worker
    """
    assert lint_source(tmp_path, src, ["lock-order"]) == []


# ----------------------------------------------------------- thread-lifecycle

BAD_ORPHAN_THREAD = """
    import threading

    class Loop:
        def start(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            pass
"""

GOOD_JOINED_THREAD = BAD_ORPHAN_THREAD + """
        def stop(self):
            self._t.join()
"""

GOOD_DAEMON_THREAD = """
    import threading

    def fire_and_forget(fn):
        threading.Thread(target=fn, daemon=True).start()
"""


def test_thread_lifecycle_flags_orphan(tmp_path):
    v = lint_source(tmp_path, BAD_ORPHAN_THREAD, ["thread-lifecycle"])
    assert len(v) == 1 and v[0].tag == "handle=self._t"


def test_thread_lifecycle_passes_joined(tmp_path):
    assert lint_source(tmp_path, GOOD_JOINED_THREAD, ["thread-lifecycle"]) == []


def test_thread_lifecycle_passes_daemon(tmp_path):
    assert lint_source(tmp_path, GOOD_DAEMON_THREAD, ["thread-lifecycle"]) == []


# --------------------------------------------------------- blocking-in-handler

BAD_SLEEP_IN_HANDLER = """
    import time

    class Server:
        async def rpc_get_thing(self, req):
            self._settle()
            return {}

        def _settle(self):
            time.sleep(0.2)
"""

GOOD_ASYNC_SLEEP = """
    import asyncio

    class Server:
        async def rpc_get_thing(self, req):
            await asyncio.sleep(0.2)
            return {}
"""

BAD_SLEEP_IN_PUSH_CALLBACK = """
    import time

    class Watcher:
        def connect(self, make_client):
            self._client = make_client(on_push=self._on_push)

        def _on_push(self, msg):
            time.sleep(1.0)
"""


def test_blocking_handler_flags_sleep_via_helper(tmp_path):
    v = lint_source(tmp_path, BAD_SLEEP_IN_HANDLER, ["blocking-in-handler"])
    assert len(v) == 1
    assert v[0].symbol == "Server._settle"
    assert "rpc_get_thing" in v[0].tag


def test_blocking_handler_passes_async_sleep(tmp_path):
    assert lint_source(tmp_path, GOOD_ASYNC_SLEEP, ["blocking-in-handler"]) == []


def test_blocking_handler_flags_pubsub_callback(tmp_path):
    v = lint_source(tmp_path, BAD_SLEEP_IN_PUSH_CALLBACK, ["blocking-in-handler"])
    assert len(v) == 1 and v[0].symbol == "Watcher._on_push"


def test_blocking_handler_cross_module_helper_module(tmp_path):
    """The PR 5 follow-up: a blocking call reached THROUGH a helper
    module (`from pkg import helper; helper.settle()`) must be caught —
    module-local analysis used to stop at the import boundary."""
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "__init__.py").write_text("")
    (tmp_path / "pkg" / "helper.py").write_text(textwrap.dedent("""
        import time

        def settle():
            time.sleep(0.2)
    """))
    (tmp_path / "pkg" / "server.py").write_text(textwrap.dedent("""
        from pkg import helper

        class Server:
            async def rpc_get_thing(self, req):
                helper.settle()
                return {}
    """))
    result = core.run_lint([str(tmp_path)], root=str(tmp_path),
                           select=["blocking-in-handler"])
    v = [x for x in result.violations if x.check == "blocking-in-handler"]
    assert len(v) == 1
    assert v[0].path == "pkg/helper.py" and v[0].symbol == "settle"
    assert "rpc_get_thing" in v[0].tag


def test_blocking_handler_cross_module_symbol_import(tmp_path):
    """`from pkg.helper import settle` direct-symbol imports resolve
    too, including constructor calls (`Class()` -> `Class.__init__`)."""
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "__init__.py").write_text("")
    (tmp_path / "pkg" / "helper.py").write_text(textwrap.dedent("""
        import time

        class SyncClient:
            def __init__(self):
                time.sleep(1.0)
    """))
    (tmp_path / "pkg" / "server.py").write_text(textwrap.dedent("""
        from pkg.helper import SyncClient

        class Server:
            async def rpc_connect(self, req):
                return SyncClient()
    """))
    result = core.run_lint([str(tmp_path)], root=str(tmp_path),
                           select=["blocking-in-handler"])
    v = [x for x in result.violations if x.check == "blocking-in-handler"]
    assert len(v) == 1
    assert v[0].symbol == "SyncClient.__init__"
    assert "rpc_connect" in v[0].tag


def test_blocking_handler_cross_module_clean_helper_passes(tmp_path):
    """A helper module with no blocking calls adds no findings."""
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "__init__.py").write_text("")
    (tmp_path / "pkg" / "helper.py").write_text(textwrap.dedent("""
        def settle():
            return 1 + 1
    """))
    (tmp_path / "pkg" / "server.py").write_text(textwrap.dedent("""
        from pkg import helper

        class Server:
            async def rpc_get_thing(self, req):
                return helper.settle()
    """))
    result = core.run_lint([str(tmp_path)], root=str(tmp_path),
                           select=["blocking-in-handler"])
    assert [x for x in result.violations if x.check == "blocking-in-handler"] == []


def test_blocking_handler_exempts_thread_target_closure(tmp_path):
    # The checker's own advice: defer blocking work to a worker thread.
    # The closure's sleep runs on that thread, not the dispatch loop.
    src = """
        import threading
        import time

        class Server:
            async def rpc_slow_op(self, req):
                def bg():
                    time.sleep(5.0)
                threading.Thread(target=bg, daemon=True).start()
                return {}
    """
    assert lint_source(tmp_path, src, ["blocking-in-handler"]) == []


# -------------------------------------------------------------- metrics-drift

CATALOG_ONLY_DOCUMENTED = """
    # Observability

    ## Metric catalog

    | name | type | tags | meaning |
    |---|---|---|---|
    | `documented_total` | counter | — | is in code and catalog |
"""

BAD_UNDOCUMENTED_METRIC = """
    from ray_tpu.util.metrics import Counter

    documented = Counter("documented_total", description="fine")
    rogue = Counter("rogue_total", description="not in the catalog")
"""

CATALOG_WITH_ORPHAN = CATALOG_ONLY_DOCUMENTED + """\
    | `ghost_total` | counter | — | no code creates this |
"""

GOOD_IN_SYNC_METRIC = """
    from ray_tpu.util.metrics import Counter

    documented = Counter("documented_total", description="fine")
"""

BAD_CARDINALITY_TAG = GOOD_IN_SYNC_METRIC + """
    def record(node_id):
        documented.inc(1, tags={"node": f"{node_id}"})
"""


def test_metrics_drift_flags_undocumented_instrument(tmp_path):
    v = lint_source(
        tmp_path, BAD_UNDOCUMENTED_METRIC, ["metrics-drift"],
        docs=CATALOG_ONLY_DOCUMENTED,
    )
    assert [x.tag for x in v] == ["undocumented:rogue_total"]


def test_metrics_drift_flags_orphaned_catalog_row(tmp_path):
    v = lint_source(
        tmp_path, GOOD_IN_SYNC_METRIC, ["metrics-drift"],
        docs=CATALOG_WITH_ORPHAN,
    )
    assert [x.tag for x in v] == ["orphaned:ghost_total"]
    assert v[0].path == "docs/observability.md"


def test_metrics_drift_passes_in_sync(tmp_path):
    v = lint_source(
        tmp_path, GOOD_IN_SYNC_METRIC, ["metrics-drift"],
        docs=CATALOG_ONLY_DOCUMENTED,
    )
    assert v == []


def test_metrics_drift_flags_unbounded_cardinality(tmp_path):
    v = lint_source(
        tmp_path, BAD_CARDINALITY_TAG, ["metrics-drift"],
        docs=CATALOG_ONLY_DOCUMENTED,
    )
    assert [x.tag for x in v] == ["cardinality:node"]


# ------------------------------------------------------------- generation-key

BAD_HANDROLLED_GEN_KEY = """
    def stash(kv, group, gen, rank, payload):
        kv.put(f"{group}/gen{gen}/{rank}", payload)
"""

BAD_HANDROLLED_CKPT_DIR = """
    def resume_dir(base, gen, step, rank):
        return f"{base}/checkpoint_g{gen:03d}_{step:06d}_rank{rank}"
"""

GOOD_DESCRIBED_IN_DOCSTRING = '''
    def helper():
        """Keys look like <group>/gen<G>/<rank>; see cpu_group._key."""
        return None
'''


def test_generation_key_flags_handrolled_rendezvous_key(tmp_path):
    v = lint_source(tmp_path, BAD_HANDROLLED_GEN_KEY, ["generation-key"])
    assert len(v) == 1 and v[0].tag.startswith("rendezvous key:")


def test_generation_key_flags_handrolled_checkpoint_dir(tmp_path):
    v = lint_source(tmp_path, BAD_HANDROLLED_CKPT_DIR, ["generation-key"])
    assert len(v) == 1 and v[0].tag.startswith("checkpoint dir:")


def test_generation_key_exempts_docstrings(tmp_path):
    assert lint_source(tmp_path, GOOD_DESCRIBED_IN_DOCSTRING, ["generation-key"]) == []


def test_generation_key_exempts_canonical_module(tmp_path):
    # The same string inside the canonical helper module is the one
    # place allowed to build the format.
    v = lint_source(
        tmp_path, BAD_HANDROLLED_GEN_KEY, ["generation-key"],
        filename="ray_tpu/util/collective/cpu_group.py",
    )
    assert v == []


# ---------------------------------------------------------------- trace-orphan

BAD_AMBIENT_RECORD_SPAN = """
    from ray_tpu.util import tracing

    def on_frame(t0, t1):
        tracing.record_span("serve.replica.call", t0, t1, {"method": "f"})
"""

GOOD_EXPLICIT_FRAME_CONTEXT = """
    from ray_tpu.util import tracing

    def on_frame(t0, t1, tctx):
        tracing.record_span(
            "serve.replica.call", t0, t1, {"method": "f"},
            context=(tctx[0], tracing.new_span_id(), tctx[1]),
        )
"""

GOOD_EXPLICIT_AMBIENT_CONTEXT = """
    from ray_tpu.util import tracing

    def on_frame(t0, t1):
        tracing.record_span(
            "serve.replica.call", t0, t1, None,
            context=tracing.current_context(),
        )
"""

GOOD_EVENT_AND_START_SPAN = """
    from ray_tpu.util import tracing

    def on_compile(t0, t1):
        tracing.record_event_span("jax.compile", t0, t1, {"fn": "step"})
        with tracing.start_span("serve.router", {"method": "f"}):
            pass
"""


def test_trace_orphan_flags_ambient_record_span(tmp_path):
    v = lint_source(tmp_path, BAD_AMBIENT_RECORD_SPAN, ["trace-orphan"])
    assert len(v) == 1 and v[0].check == "trace-orphan"
    assert "context=" in v[0].message


def test_trace_orphan_passes_explicit_frame_context(tmp_path):
    assert lint_source(tmp_path, GOOD_EXPLICIT_FRAME_CONTEXT, ["trace-orphan"]) == []


def test_trace_orphan_passes_explicit_ambient_context(tmp_path):
    # context=tracing.current_context() is the same read, stated.
    assert lint_source(tmp_path, GOOD_EXPLICIT_AMBIENT_CONTEXT, ["trace-orphan"]) == []


def test_trace_orphan_allows_event_and_start_span(tmp_path):
    assert lint_source(tmp_path, GOOD_EVENT_AND_START_SPAN, ["trace-orphan"]) == []


def test_trace_orphan_exempts_tracing_module(tmp_path):
    v = lint_source(
        tmp_path, BAD_AMBIENT_RECORD_SPAN, ["trace-orphan"],
        filename="ray_tpu/util/tracing/__init__.py",
    )
    assert v == []


# ------------------------------------------------- suppressions and baseline

def test_inline_disable_with_reason_suppresses(tmp_path):
    src = """
        import time

        def cadence_loop():
            while True:
                # graftlint: disable=retry-gate -- fixed-cadence ticker, not a retry
                time.sleep(0.5)
    """
    v = lint_source(tmp_path, src, ["retry-gate"])
    assert len(v) == 1 and v[0].suppressed_by == "inline"


def test_inline_disable_without_reason_is_a_violation(tmp_path):
    # The reasonless marker is assembled via replace() so THIS file's raw
    # source doesn't itself scan as a reasonless disable (bad-suppression
    # deliberately can't be suppressed or baselined — tests/ is linted).
    src = """
        import time

        def cadence_loop():
            while True:
                time.sleep(0.5)  # graftlint: REASONLESS_DISABLE
    """.replace("REASONLESS_DISABLE", "disable=retry-gate")
    v = lint_source(tmp_path, src, ["retry-gate", "bad-suppression"])
    checks = sorted(x.check for x in v if x.suppressed_by is None)
    # The reasonless disable both fails to suppress and is itself flagged.
    assert checks == ["bad-suppression", "retry-gate"]


def test_baseline_round_trip(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(BAD_SLEEP_LOOP))
    found = core.run_lint([str(f)], root=str(tmp_path), select=["retry-gate"])
    assert len(found.unsuppressed) == 1

    # write -> load -> apply: the same violation is now suppressed.
    bl_path = tmp_path / ".graftlint.toml"
    n = baseline_mod.write(str(bl_path), found.unsuppressed,
                           reason="fixture: accepted for the round-trip test")
    assert n == 1
    bl = baseline_mod.load(str(bl_path))
    again = core.run_lint([str(f)], root=str(tmp_path), baseline=bl,
                          select=["retry-gate"])
    assert again.unsuppressed == [] and len(again.suppressed) == 1
    assert again.unused_baseline == []

    # A baseline entry matching nothing is reported as stale.
    bl2 = baseline_mod.load(str(bl_path))
    bl2.entries[0].path = "nonexistent.py"
    stale = core.run_lint([str(f)], root=str(tmp_path), baseline=bl2,
                          select=["retry-gate"])
    assert len(stale.unsuppressed) == 1 and len(stale.unused_baseline) == 1


def test_inline_disable_star_suppresses_everything(tmp_path):
    src = """
        import time

        def cadence_loop():
            while True:
                time.sleep(0.5)  # graftlint: disable=* -- fixture: blanket opt-out
    """
    v = lint_source(tmp_path, src, ["retry-gate"])
    assert len(v) == 1 and v[0].suppressed_by == "inline"


def test_repo_root_fallback_is_a_directory(tmp_path):
    # No pyproject/.git/.graftlint.toml marker anywhere above tmp_path:
    # the starting directory (not the file) must become the root, so
    # violation relpaths stay real filenames and suppressions can match.
    f = tmp_path / "markerless.py"
    f.write_text(textwrap.dedent(BAD_SLEEP_LOOP))
    root = core.repo_root_for(str(f))
    if root == str(tmp_path):  # only meaningful when truly markerless
        result = core.run_lint([str(f)], select=["retry-gate"])
        assert [v.path for v in result.unsuppressed] == ["markerless.py"]


def test_baseline_rejects_malformed_toml(tmp_path):
    bl_path = tmp_path / ".graftlint.toml"
    bl_path.write_text('version = 1\n\n[[suppress]]\ncheck = [unclosed\n')
    with pytest.raises(baseline_mod.BaselineError):
        baseline_mod.load(str(bl_path))


def test_baseline_rejects_reasonless_entry(tmp_path):
    bl_path = tmp_path / ".graftlint.toml"
    bl_path.write_text(
        'version = 1\n\n[[suppress]]\ncheck = "retry-gate"\npath = "x.py"\n'
    )
    with pytest.raises(baseline_mod.BaselineError, match="reason"):
        baseline_mod.load(str(bl_path))


# -------------------------------------------------------------- the real gate

# ------------------------------------------------------------ import-cycle


def _lint_tree(tmp_path, files, select):
    for rel, src in files.items():
        f = tmp_path / rel
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent(src))
    result = core.run_lint([str(tmp_path)], root=str(tmp_path), select=list(select))
    assert not result.parse_errors, result.parse_errors
    return result.violations


def test_import_cycle_module_level_flagged(tmp_path):
    v = _lint_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/a.py": "import pkg.b\n",
            "pkg/b.py": "from pkg import a\n",
        },
        ["import-cycle"],
    )
    assert len(v) == 1, [x.format() for x in v]
    assert "pkg.a" in v[0].message and "pkg.b" in v[0].message
    # Identity tag is the sorted member list: stable across line drift.
    assert v[0].tag == "cycle:pkg.a>pkg.b"


def test_import_cycle_function_local_is_clean(tmp_path):
    """The house convention: breaking a cycle with a function-local
    import must satisfy the checker (imports inside functions don't run
    at import time)."""
    v = _lint_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/a.py": "import pkg.b\n",
            "pkg/b.py": "def f():\n    from pkg import a\n    return a\n",
        },
        ["import-cycle"],
    )
    assert v == [], [x.format() for x in v]


def test_import_cycle_type_checking_guard_is_clean(tmp_path):
    v = _lint_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/a.py": "import pkg.b\n",
            "pkg/b.py": (
                "from typing import TYPE_CHECKING\n"
                "if TYPE_CHECKING:\n    import pkg.a\n"
            ),
        },
        ["import-cycle"],
    )
    assert v == [], [x.format() for x in v]


def test_import_cycle_three_module_loop_single_violation(tmp_path):
    v = _lint_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/a.py": "import pkg.b\n",
            "pkg/b.py": "import pkg.c\n",
            "pkg/c.py": "import pkg.a\n",
        },
        ["import-cycle"],
    )
    assert len(v) == 1
    assert v[0].tag == "cycle:pkg.a>pkg.b>pkg.c"


def test_metrics_drift_wildcard_family_row_covers_instruments(tmp_path):
    """A catalog family row (test_*) covers literal instruments matching
    it — no per-instrument row needed."""
    docs = """
        # obs

        ## Metric catalog

        | name | type | tags | meaning |
        |---|---|---|---|
        | `test_*` | any | any | test-only family |
    """
    v = lint_source(
        tmp_path,
        """
        from ray_tpu.util import metrics as m

        c = m.Counter("test_requests_total", "test counter")
        """,
        ["metrics-drift"],
        docs=docs,
    )
    assert v == [], [x.format() for x in v]


def test_graftlint_gate_repo_is_clean():
    """THE tier-1 gate: ray_tpu/ AND tests/ lint clean against the
    checked-in baseline, inside the budget, with no stale entries."""
    bl = baseline_mod.load_default(REPO_ROOT)
    assert bl is not None, ".graftlint.toml missing from the repo root"
    for e in bl.entries:
        assert e.reason.strip(), f"baseline entry without a reason: {e}"
        assert not e.reason.lower().startswith("todo"), (
            f"placeholder reason in checked-in baseline: {e}"
        )
    result = core.run_lint(
        [os.path.join(REPO_ROOT, "ray_tpu"), os.path.join(REPO_ROOT, "tests")],
        root=REPO_ROOT,
        baseline=bl,
    )
    assert result.parse_errors == []
    assert result.unsuppressed == [], "\n".join(
        v.format() for v in result.unsuppressed
    )
    assert result.unused_baseline == [], (
        f"stale baseline entries: {result.unused_baseline}"
    )
    assert result.files_checked > 100  # the walk really covered the tree
    assert result.elapsed_s < 30.0


def test_graftlint_cli_entrypoint():
    """`python -m ray_tpu.devtools.lint ray_tpu/` exits 0 (the exact
    command verify.sh runs)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lint", "ray_tpu", "tests",
         "--strict"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_graftlint_cli_select_and_exit_code(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text(textwrap.dedent(BAD_SLEEP_LOOP))
    rc = cli_main([str(f), "--root", str(tmp_path), "--select", "retry-gate"])
    assert rc == 1
    rc = cli_main([str(f), "--root", str(tmp_path), "--select", "thread-lifecycle"])
    assert rc == 0
    assert cli_main(["--list-checks"]) == 0
    assert cli_main([str(f), "--select", "not-a-check"]) == 2


# ------------------------------------------------------------ rpc-contract

SERVER_WITH_PING = """
    class Server:
        async def rpc_ping(self, payload, conn):
            return "pong"
"""

BAD_RPC_TYPO = {
    "server.py": SERVER_WITH_PING,
    "client.py": """
        def use(client):
            client.call("ping", None)
            return client.call("pingg", None)
    """,
}

GOOD_RPC_WIRED = {
    "server.py": SERVER_WITH_PING,
    "client.py": """
        def use(client):
            return client.call("ping", None)
    """,
}

BAD_RPC_DEAD_ENDPOINT = {
    "server.py": """
        class Server:
            async def rpc_ping(self, payload, conn):
                return "pong"

            async def rpc_orphan(self, payload, conn):
                return 1
    """,
    "client.py": """
        def use(client):
            return client.call("ping", None)
    """,
}

BAD_PAYLOAD_DRIFT = {
    "server.py": """
        class Server:
            async def rpc_report(self, payload, conn):
                a = payload["node_id"]
                b = payload["available"]
                return a, b
    """,
    "client.py": """
        def use(client):
            return client.call("report", {"node_id": b"n"})
    """,
}

GOOD_PAYLOAD_COMPLETE = {
    "server.py": """
        class Server:
            async def rpc_report(self, payload, conn):
                a = payload["node_id"]
                b = payload["available"]
                c = payload.get("total")
                return a, b, c
    """,
    "client.py": """
        def use(client):
            return client.call("report", {"node_id": b"n", "available": {}})
    """,
}

BAD_RETRY_UNSAFE = {
    "server.py": """
        class Server:
            async def rpc_bump(self, payload, conn):
                self.n += payload["delta"]
                return self.n
    """,
    "client.py": """
        from ray_tpu._private.rpc import call_idempotent

        def use(client):
            return call_idempotent(client, "bump", {"delta": 1})
    """,
}

GOOD_RETRY_READONLY = {
    "server.py": '''
        class Server:
            async def rpc_peek(self, payload, conn):
                """rpc-contract: read-only -- lookup only."""
                return self.n
    ''',
    "client.py": """
        from ray_tpu._private.rpc import call_idempotent

        def use(client):
            return call_idempotent(client, "peek", None)
    """,
}

GOOD_RETRY_TOKEN = {
    "server.py": """
        class Server:
            async def rpc_bump(self, payload, conn):
                tok = payload["token"]
                if tok in self.seen:
                    return self.n
                self.seen.add(tok)
                self.n += payload["delta"]
                return self.n
    """,
    "client.py": """
        from ray_tpu._private.rpc import call_idempotent

        def use(client):
            return call_idempotent(client, "bump", {"delta": 1, "token": "t1"})
    """,
}

BAD_FENCE_MISSING = {
    "server.py": """
        class Gcs:
            def _check_fence(self, method, node_id, incarnation):
                raise NotImplementedError

            async def rpc_heartbeat(self, payload, conn):
                node_id = payload["node_id"]
                self.last_seen[node_id] = 1
                return True
    """,
    "client.py": """
        def use(client):
            return client.call("heartbeat", {"node_id": b"n", "incarnation": 1})
    """,
}

GOOD_FENCE_FIRST = {
    "server.py": """
        class Gcs:
            def _check_fence(self, method, node_id, incarnation):
                raise NotImplementedError

            async def rpc_heartbeat(self, payload, conn):
                node_id = payload["node_id"]
                self._check_fence("heartbeat", node_id, payload.get("incarnation"))
                self.last_seen[node_id] = 1
                return True
    """,
    "client.py": """
        def use(client):
            return client.call("heartbeat", {"node_id": b"n", "incarnation": 1})
    """,
}


def test_rpc_contract_flags_typo_endpoint(tmp_path):
    v = _lint_tree(tmp_path, BAD_RPC_TYPO, ["rpc-contract"])
    assert [x.tag for x in v] == ["no-handler:method=pingg"], [x.format() for x in v]


def test_rpc_contract_passes_wired_endpoint(tmp_path):
    assert _lint_tree(tmp_path, GOOD_RPC_WIRED, ["rpc-contract"]) == []


def test_rpc_contract_flags_dead_endpoint(tmp_path):
    v = _lint_tree(tmp_path, BAD_RPC_DEAD_ENDPOINT, ["rpc-contract"])
    assert [x.tag for x in v] == ["dead-endpoint:method=orphan"], [x.format() for x in v]
    assert v[0].symbol == "Server.rpc_orphan"


def test_rpc_contract_flags_payload_key_drift(tmp_path):
    v = _lint_tree(tmp_path, BAD_PAYLOAD_DRIFT, ["rpc-contract"])
    assert [x.tag for x in v] == ["payload-drift:method=report:missing=available"]
    assert v[0].path == "client.py"  # flagged at the call site


def test_rpc_contract_passes_complete_payload(tmp_path):
    # .get()-guarded keys are optional: only bare subscripts are required.
    assert _lint_tree(tmp_path, GOOD_PAYLOAD_COMPLETE, ["rpc-contract"]) == []


def test_rpc_contract_flags_retry_unsafe_idempotent_call(tmp_path):
    v = _lint_tree(tmp_path, BAD_RETRY_UNSAFE, ["rpc-contract"])
    assert [x.tag for x in v] == ["retry-unsafe:method=bump"], [x.format() for x in v]


def test_rpc_contract_passes_declared_read_only(tmp_path):
    assert _lint_tree(tmp_path, GOOD_RETRY_READONLY, ["rpc-contract"]) == []


def test_rpc_contract_passes_token_consuming_handler(tmp_path):
    assert _lint_tree(tmp_path, GOOD_RETRY_TOKEN, ["rpc-contract"]) == []


def test_rpc_contract_flags_fence_missing(tmp_path):
    v = _lint_tree(tmp_path, BAD_FENCE_MISSING, ["rpc-contract"])
    assert [x.tag for x in v] == ["fence-missing:method=heartbeat"], [x.format() for x in v]
    assert v[0].symbol == "Gcs.rpc_heartbeat"


def test_rpc_contract_passes_fence_before_write(tmp_path):
    assert _lint_tree(tmp_path, GOOD_FENCE_FIRST, ["rpc-contract"]) == []


# -------------------------------------------------------- shared-state-race

BAD_CROSS_THREAD_UNLOCKED = """
    import threading

    class Pump:
        def __init__(self):
            self.items = []
            self._lock = threading.Lock()
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            while True:
                self.items.append(1)

        def drain(self):
            return list(self.items)
"""

GOOD_LOCKED_TWIN = """
    import threading

    class Pump:
        def __init__(self):
            self.items = []
            self._lock = threading.Lock()
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            while True:
                with self._lock:
                    self.items.append(1)

        def drain(self):
            with self._lock:
                return list(self.items)
"""

GOOD_SINGLE_WRITER_FLAG = """
    import threading

    class Task:
        def __init__(self):
            self._done = False
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            self._done = True

        def poll(self):
            return self._done
"""

GOOD_QUEUE_HANDOFF = """
    import queue
    import threading

    class Pump:
        def __init__(self):
            self.q: "queue.Queue" = queue.Queue()
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            while True:
                self.q.put(1)

        def drain(self):
            return self.q.get()
"""

GOOD_MANUAL_ACQUIRE = """
    import threading

    class Pump:
        def __init__(self):
            self.items = []
            self._lock = threading.Lock()
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            with self._lock:
                self.items.append(1)

        def drain(self):
            if not self._lock.acquire(blocking=False):
                return []
            try:
                return list(self.items)
            finally:
                self._lock.release()
"""


def test_shared_state_race_flags_cross_thread_unlocked_write(tmp_path):
    v = lint_source(tmp_path, BAD_CROSS_THREAD_UNLOCKED, ["shared-state-race"])
    assert [x.tag for x in v] == ["attr=Pump.items"], [x.format() for x in v]
    assert v[0].symbol == "Pump"


def test_shared_state_race_passes_locked_twin(tmp_path):
    assert lint_source(tmp_path, GOOD_LOCKED_TWIN, ["shared-state-race"]) == []


def test_shared_state_race_passes_single_writer_flag(tmp_path):
    assert lint_source(tmp_path, GOOD_SINGLE_WRITER_FLAG, ["shared-state-race"]) == []


def test_shared_state_race_passes_queue_handoff(tmp_path):
    assert lint_source(tmp_path, GOOD_QUEUE_HANDOFF, ["shared-state-race"]) == []


def test_shared_state_race_passes_try_finally_release(tmp_path):
    assert lint_source(tmp_path, GOOD_MANUAL_ACQUIRE, ["shared-state-race"]) == []


def test_shared_state_race_skips_tests_tree(tmp_path):
    f = tmp_path / "tests" / "test_thing.py"
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(BAD_CROSS_THREAD_UNLOCKED))
    result = core.run_lint([str(f)], root=str(tmp_path),
                           select=["shared-state-race"])
    assert result.violations == []


# --------------------------------------------------- json output + ast cache


def test_graftlint_json_output(tmp_path, capsys):
    f = tmp_path / "bad.py"
    f.write_text(textwrap.dedent(BAD_SLEEP_LOOP))
    import json as json_mod

    rc = cli_main([str(f), "--root", str(tmp_path), "--json"])
    assert rc == 1
    report = json_mod.loads(capsys.readouterr().out)
    assert report["unsuppressed"] == 1
    assert report["by_check"]["retry-gate"] == 1
    assert report["by_check"]["rpc-contract"] == 0
    assert set(report["checks_run"]) >= {"rpc-contract", "shared-state-race"}
    assert report["cache"]["hits"] + report["cache"]["misses"] == 1
    v = report["violations"][0]
    assert v["check"] == "retry-gate" and v["path"] == "bad.py"


def test_ast_cache_hits_on_second_run(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(GOOD_POLICY_LOOP))
    first = core.run_lint([str(f)], root=str(tmp_path), select=["retry-gate"])
    assert (first.cache_hits, first.cache_misses) == (0, 1)
    second = core.run_lint([str(f)], root=str(tmp_path), select=["retry-gate"])
    assert (second.cache_hits, second.cache_misses) == (1, 0)
    # Same verdict either way.
    assert second.violations == first.violations

    # An edit changes the content hash: clean miss, fresh tree, and the
    # new violation is seen (a stale cache would hide it).
    f.write_text(textwrap.dedent(BAD_SLEEP_LOOP))
    third = core.run_lint([str(f)], root=str(tmp_path), select=["retry-gate"])
    assert third.cache_misses == 1
    assert len(third.unsuppressed) == 1


def test_ast_cache_survives_corrupt_entry(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(GOOD_POLICY_LOOP))
    core.run_lint([str(f)], root=str(tmp_path), select=["retry-gate"])
    cache_dir = tmp_path / ".graftlint_cache"
    entries = list(cache_dir.iterdir())
    assert entries, "cache dir is empty after a miss"
    for e in entries:
        e.write_bytes(b"not a pickle")
    result = core.run_lint([str(f)], root=str(tmp_path), select=["retry-gate"])
    assert result.parse_errors == []
    assert result.cache_misses == 1  # fell back to a fresh parse


def test_ast_cache_disabled_flag(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(GOOD_POLICY_LOOP))
    result = core.run_lint([str(f)], root=str(tmp_path),
                           select=["retry-gate"], use_cache=False)
    assert (result.cache_hits, result.cache_misses) == (0, 0)
    assert not (tmp_path / ".graftlint_cache").exists()
