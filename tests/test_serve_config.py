"""Serve declarative config plane: schema round-trips, serve build,
config-driven deploy of a multi-deployment app, replica-count flips via
re-deploy (reference: python/ray/serve/schema.py + serve/scripts.py;
test model: serve/tests/test_config_files + test_cli).
"""

import sys
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.schema import (
    ApplicationSchema,
    DeploymentSchema,
    ServeDeploySchema,
    build_app_schema,
)


@pytest.fixture(scope="module")
def serve_cluster(ray_cluster):
    yield ray_cluster
    serve.shutdown()


APP_MODULE = textwrap.dedent(
    """
    from ray_tpu import serve

    @serve.deployment(name="Preprocess")
    class Preprocess:
        def __call__(self, x):
            return x * 2

    @serve.deployment(name="Ingress")
    class Ingress:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            doubled = self.pre.remote(x).result()
            return doubled + 1

    app = Ingress.bind(Preprocess.bind())
    """
)


@pytest.fixture(scope="module")
def app_module(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve_cfg_app")
    (d / "sc_demo_app.py").write_text(APP_MODULE)
    sys.path.insert(0, str(d))
    yield "sc_demo_app"
    sys.path.remove(str(d))


def test_schema_yaml_roundtrip(tmp_path):
    schema = ServeDeploySchema(
        applications=[
            ApplicationSchema(
                import_path="m:app",
                name="a1",
                route_prefix="/a1",
                deployments=[DeploymentSchema(name="D", num_replicas=3)],
            )
        ],
        http_options={"port": 8045},
    )
    path = str(tmp_path / "config.yaml")
    schema.to_yaml(path)
    loaded = ServeDeploySchema.from_file(path)
    assert loaded.applications[0].import_path == "m:app"
    assert loaded.applications[0].deployments[0].num_replicas == 3
    assert loaded.http_options["port"] == 8045
    # overrides() drops unset fields
    assert loaded.applications[0].deployments[0].overrides() == {"num_replicas": 3}


def test_serve_build_emits_all_deployments(app_module):
    schema = build_app_schema(f"{app_module}:app")
    names = {d.name for d in schema.deployments}
    assert names == {"Preprocess", "Ingress"}
    # effective defaults spelled out, ready for editing
    pre = next(d for d in schema.deployments if d.name == "Preprocess")
    assert pre.num_replicas == 1


def test_deploy_config_two_deployment_app_and_flip_replicas(
    serve_cluster, app_module, tmp_path
):
    """The VERDICT r4 'done' criterion: deploy a 2-deployment app from a
    YAML, then flip replica counts via re-deploy."""
    config = ServeDeploySchema(
        applications=[
            ApplicationSchema(
                import_path=f"{app_module}:app",
                route_prefix="/demo",
                deployments=[DeploymentSchema(name="Preprocess", num_replicas=2)],
            )
        ]
    )
    path = str(tmp_path / "deploy.yaml")
    config.to_yaml(path)

    statuses = serve.deploy_config(ServeDeploySchema.from_file(path))
    assert set(statuses["default"]) == {"Preprocess", "Ingress"}

    st = serve.status()
    assert st["Preprocess"]["target"] == 2
    assert st["Ingress"]["target"] == 1

    # the composed graph actually serves: Ingress calls Preprocess
    handle = serve.get_deployment_handle("Ingress")
    assert handle.remote(21).result(timeout=10) == 43

    # flip replica counts via config re-deploy (rolling through the
    # same controller path; long-poll pushes the membership change)
    config.applications[0].deployments[0] = DeploymentSchema(
        name="Preprocess", num_replicas=1
    )
    serve.deploy_config(config)
    deadline = time.time() + 30
    while time.time() < deadline:
        st = serve.status()
        if st["Preprocess"]["target"] == 1 and st["Preprocess"]["num_running"] == 1:
            break
        time.sleep(0.2)
    st = serve.status()
    assert st["Preprocess"]["target"] == 1, st
    # still serving after the scale-down
    assert handle.remote(5).result(timeout=10) == 11


def test_cli_serve_build_writes_yaml(app_module, tmp_path):
    from ray_tpu.scripts.cli import main

    out = str(tmp_path / "built.yaml")
    rc = main(["serve", "build", f"{app_module}:app", "-o", out])
    assert rc == 0
    schema = ServeDeploySchema.from_file(out)
    assert {d.name for d in schema.applications[0].deployments} == {
        "Preprocess",
        "Ingress",
    }
