"""Overload-resilient serving (PR 18 tentpole): SLO-class admission,
tenant-fair KV scheduling, decode-lane preemption-by-recompute, brownout
degradation, per-tenant token-rate quotas at the proxy, and multiplexed
model variants.

Layers under test:

- pure math: TokenBucket / TenantBuckets / DegradationController (no
  engine, no cluster);
- engine: DRF fair queue under a tenant flood, preempt-by-recompute
  token-exactness vs an uninterrupted greedy run, cancel+preempt storm
  leak accounting, brownout shed semantics (interactive never shed);
- replica: multiplexed model_id -> variant engine with LRU swap;
- cluster/HTTP: identity threading (header + handle kwarg), quota 429
  with Retry-After attributed to the over-quota tenant only;
- chaos (slow): tenant storm with a replica kill mid-storm, and a
  seeded SIGKILL exactly between KV free and requeue mid-preemption.
"""

import asyncio
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.exceptions import RequestShedError
from ray_tpu.serve.llm import LLMConfig, LLMEngine
from ray_tpu.serve.llm.engine import FINISHED
from ray_tpu.serve.llm.overload import (
    DegradationController,
    TenantBuckets,
    TokenBucket,
    normalize_slo,
)

PROXY_PORT = 18129


@pytest.fixture(scope="module")
def serve_cluster(ray_cluster):
    yield ray_cluster
    try:
        serve.shutdown()
    except Exception:  # noqa: BLE001 — a chaos drill may have torn down
        pass


def _tiny(**kw) -> LLMConfig:
    base = dict(model="tiny", max_batch_size=4, num_blocks=64, block_size=8,
                default_max_tokens=8)
    base.update(kw)
    return LLMConfig(**base)


async def _drain(req):
    toks = []
    while True:
        ev = await req.out.get()
        if ev is FINISHED:
            return toks
        toks.append(ev["token"])


def _wait_route(prefix: str, port: int = PROXY_PORT, timeout: float = 30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/-/routes", timeout=5
            ) as r:
                if prefix in json.loads(r.read()):
                    return
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.3)
    raise AssertionError(f"route {prefix} never became live")


def _post(path: str, payload: dict, headers: dict = None,
          port: int = PROXY_PORT, timeout: float = 60.0):
    """(status, body_bytes, response_headers); HTTP errors return their
    status instead of raising."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# ----------------------------------------------------------------------
# pure math: token buckets
# ----------------------------------------------------------------------
def test_token_bucket_charge_refund_refill():
    b = TokenBucket(rate=10, burst=20)
    assert b.charge(20, now=0.0)          # full burst goes through
    assert not b.charge(1, now=0.0)       # empty: refused, NOT deducted
    assert b.level(now=0.0) == 0.0
    b.refund(5)
    assert b.charge(5, now=0.0)           # the refund is spendable
    assert b.charge(10, now=1.0)          # 1s at rate 10 refilled 10
    assert not b.charge(1, now=1.0)
    # refill caps at burst, refund caps at burst
    assert b.level(now=100.0) == 20.0
    b.refund(10**6)
    assert b.level(now=100.0) == 20.0


def test_token_bucket_retry_after():
    b = TokenBucket(rate=10, burst=20)
    assert b.charge(20, now=0.0)
    # 10-token deficit at 10 tok/s -> 1s (and never below the 1s floor)
    assert b.retry_after(10, now=0.0) == pytest.approx(1.0)
    assert b.retry_after(2, now=0.0) == 1.0
    # a request larger than burst is bounded by the burst deficit
    assert b.retry_after(10**9, now=0.0) == pytest.approx(2.0)
    frozen = TokenBucket(rate=0, burst=5)
    assert frozen.charge(5, now=0.0)
    assert frozen.retry_after(1, now=0.0) == 60.0


def test_tenant_buckets_unregistered_unlimited():
    tb = TenantBuckets({"metered": {"rate": 5, "burst": 10}})
    assert set(tb.registered()) == {"metered"}
    # no quota entry -> always admitted, no retry hint
    for _ in range(100):
        assert tb.charge("anon", 10**6, now=0.0) == (True, 0.0)
    ok, retry = tb.charge("metered", 10, now=0.0)
    assert ok and retry == 0.0
    ok, retry = tb.charge("metered", 1, now=0.0)
    assert not ok and retry >= 1.0
    tb.refund("metered", 4)
    assert tb.charge("metered", 4, now=0.0) == (True, 0.0)
    # refunding an unregistered tenant is a no-op, not an error
    tb.refund("anon", 50)


def test_normalize_slo():
    assert normalize_slo("interactive") == "interactive"
    assert normalize_slo(" Batch ") == "batch"
    for junk in (None, "", "gold-tier", "INTERACTIVE!!", "0"):
        assert normalize_slo(junk) == "standard"


# ----------------------------------------------------------------------
# pure math: brownout ladder
# ----------------------------------------------------------------------
def test_degradation_ladder_hysteresis_and_monotonicity():
    d = DegradationController(ttft_slo_s=1.0, queue_high=10,
                              down_ticks=3, up_ticks=5)
    assert d.enabled
    levels = [d.level]
    # sustained violation: one step per down_ticks, never a jump
    for _ in range(12):
        levels.append(d.tick(5.0, 0))
    assert levels[:10] == [0, 0, 0, 1, 1, 1, 2, 2, 2, 3]
    assert d.level == 3  # clamped at LEVEL_MAX
    assert all(abs(b - a) <= 1 for a, b in zip(levels, levels[1:]))
    # the hysteresis band (between recover_margin*bound and bound)
    # HOLDS the level and resets both streaks — no flapping
    for _ in range(20):
        assert d.tick(0.9, 0) == 3
    # two healthy ticks then a violation: the healthy streak resets
    d.tick(0.1, 0), d.tick(0.1, 0)
    d.tick(5.0, 0)
    for _ in range(4):
        assert d.tick(0.1, 0) == 3
    # sustained healthy: recovers one step per up_ticks back to 0
    up = [d.tick(0.1, 0) for _ in range(16)]
    assert up[0] == 2 and up[-1] == 0
    assert all(abs(b - a) <= 1 for a, b in zip(up, up[1:]))
    # queue depth alone violates too
    d2 = DegradationController(ttft_slo_s=1.0, queue_high=10, down_ticks=1)
    d2.tick(None, 50)
    assert d2.level == 1


def test_degradation_shed_ordering_never_interactive():
    d = DegradationController(ttft_slo_s=1.0, queue_high=10, down_ticks=1)
    for expect_batch, expect_std in [(False, False), (False, False),
                                     (True, False), (True, True)]:
        assert d.should_shed("batch") is expect_batch
        assert d.should_shed("standard") is expect_std
        assert d.should_shed("interactive") is False
        d.tick(9.0, 0)
    # at the deepest level interactive STILL flows
    assert d.level == 3 and not d.should_shed("interactive")
    # level >= 1 clamps only batch generation budgets
    assert d.max_tokens_cap("batch", 500) == d.batch_max_tokens
    assert d.max_tokens_cap("standard", 500) == 500
    assert d.max_tokens_cap("interactive", 500) == 500
    # disabled controller is inert regardless of signals
    off = DegradationController(ttft_slo_s=0.0, queue_high=1, down_ticks=1)
    assert not off.enabled
    for _ in range(10):
        assert off.tick(10**6, 10**6) == 0
    assert not off.should_shed("batch")


# ----------------------------------------------------------------------
# engine: tenant-fair queue, preemption, storm accounting, brownout
# ----------------------------------------------------------------------
def test_engine_fair_queue_victim_overtakes_hog_backlog():
    """With DRF fairness a newly-arrived tenant's request is admitted
    ahead of another tenant's queued backlog (zero dominant share beats
    any positive share) — FIFO would make it wait behind all of it."""

    async def main():
        eng = LLMEngine(_tiny(max_batch_size=2, preempt_wait_s=30.0,
                              tenant_weights={"hog": 1.0, "victim": 1.0}))
        hogs = [
            await eng.add_request([1 + i, 2, 3], max_tokens=30,
                                  tenant="hog", slo="batch")
            for i in range(6)
        ]
        while not all(h.generated >= 1 for h in hogs[:2]):
            await asyncio.sleep(0.01)
        vic = await eng.add_request([9, 9], max_tokens=4,
                                    tenant="victim", slo="interactive")
        st_mid = eng.stats()
        await asyncio.gather(*[_drain(r) for r in hogs + [vic]])
        report = eng.bm.leak_report()
        await eng.stop()
        return hogs, vic, st_mid, report

    hogs, vic, st_mid, report = asyncio.run(main())
    # per-tenant usage was visible while contended
    assert "hog" in st_mid["tenants"], st_mid
    # the victim overtook the ENTIRE queued hog backlog (two lanes can
    # free at one step boundary, so a hog may join the SAME step — but
    # never an earlier one; FIFO would have made the victim wait for 4)
    queued_hogs = hogs[2:]
    assert all(vic.join_step <= h.join_step for h in queued_hogs), (
        vic.join_step, [h.join_step for h in queued_hogs]
    )
    assert len(vic.tokens) == 4
    assert report["blocks_in_use"] == 0


def test_engine_preempt_by_recompute_token_exact():
    """An interactive arrival with no free lane preempts a batch lane;
    the victim's KV is freed and its generated-so-far folds into the
    prompt, so its final token sequence is IDENTICAL to an uninterrupted
    greedy run — preemption must be invisible in the output."""
    prompts, hog_tokens = [[3, 1, 4], [2, 7, 1]], 40

    async def interrupted():
        eng = LLMEngine(_tiny(max_batch_size=2, preempt_wait_s=0.005,
                              temperature=0.0,
                              tenant_weights={"a": 1.0, "b": 1.0}))
        hogs = [
            await eng.add_request(p, max_tokens=hog_tokens,
                                  tenant="a", slo="batch")
            for p in prompts
        ]
        while not all(h.generated >= 3 for h in hogs):
            await asyncio.sleep(0.01)
        vic = await eng.add_request([5, 5], max_tokens=4,
                                    tenant="b", slo="interactive")
        await asyncio.gather(*[_drain(r) for r in hogs + [vic]])
        st = eng.stats()
        report = eng.bm.leak_report()
        await eng.stop()
        return hogs, vic, st, report

    async def uninterrupted(prompt):
        eng = LLMEngine(_tiny(max_batch_size=2, temperature=0.0))
        req = await eng.add_request(prompt, max_tokens=hog_tokens)
        toks = await _drain(req)
        await eng.stop()
        return toks

    hogs, vic, st, report = asyncio.run(interrupted())
    assert st["preemptions_total"] >= 1, "drill is vacuous: nothing preempted"
    assert any(h.preemptions >= 1 for h in hogs), (
        "a batch lane should have been the victim"
    )
    # victims are only ever strictly-lower-priority lanes
    assert vic.preemptions == 0
    assert any(e["type"] == "preemption" and e["victim_slo"] == "batch"
               for e in st["events"]), st["events"]
    # token-exactness: EVERY hog (preempted or not) parity-checks against
    # its own uninterrupted greedy run — preemption is invisible
    for hog, prompt in zip(hogs, prompts):
        assert hog.tokens == asyncio.run(uninterrupted(prompt)), (
            f"hog with {hog.preemptions} preemption(s) diverged"
        )
    # KV accounting balanced through free -> fold -> re-prefill
    assert report["blocks_in_use"] == 0
    assert report["total_allocs"] == report["total_frees"]


def test_engine_preempt_parity_exact_for_known_victim():
    """Single-lane variant pins WHICH request is preempted, so the
    parity assertion is exact: same prompt, same seed, one run preempted
    (possibly repeatedly), one not — byte-identical token streams."""
    prompt, n = [6, 2, 8], 30

    async def run(preempt: bool):
        eng = LLMEngine(_tiny(max_batch_size=1, preempt_wait_s=0.005,
                              temperature=0.0,
                              tenant_weights={"a": 1.0, "b": 1.0}))
        hog = await eng.add_request(prompt, max_tokens=n,
                                    tenant="a", slo="batch")
        vics = []
        if preempt:
            while hog.generated < 4:
                await asyncio.sleep(0.01)
            vics.append(await eng.add_request([5], max_tokens=3,
                                              tenant="b", slo="interactive"))
            while not vics[0].finish_reason:
                await asyncio.sleep(0.01)
            # a second wave AFTER the hog is back in the lane forces a
            # second preemption through the fold-resume path
            while hog.slot < 0 and not hog.finish_reason:
                await asyncio.sleep(0.005)
            vics.append(await eng.add_request([7], max_tokens=3,
                                              tenant="b", slo="interactive"))
        await asyncio.gather(*[_drain(r) for r in [hog] + vics])
        st = eng.stats()
        report = eng.bm.leak_report()
        await eng.stop()
        return hog, st, report

    hog_p, st_p, rep_p = asyncio.run(run(preempt=True))
    hog_o, _, _ = asyncio.run(run(preempt=False))
    assert hog_p.preemptions >= 2, "drill is vacuous: fewer than 2 preemptions"
    assert st_p["preemptions_total"] >= 2
    assert hog_p.tokens == hog_o.tokens, (
        "preempt-by-recompute diverged from the uninterrupted run"
    )
    assert len(hog_p.tokens) == n and hog_p.finish_reason == "length"
    assert rep_p["blocks_in_use"] == 0
    assert rep_p["total_allocs"] == rep_p["total_frees"]


def test_engine_cancel_preempt_storm_zero_leak():
    """A storm of mixed-class multi-tenant requests with cancels landing
    on waiting, running, and preempted requests must balance the KV pool
    to zero — `_finish` is the only exit and every path reaches it."""

    async def main():
        eng = LLMEngine(_tiny(max_batch_size=2, preempt_wait_s=0.02,
                              num_blocks=96,
                              tenant_weights={"a": 1.0, "b": 1.0}))
        reqs = []
        for i in range(24):
            r = await eng.add_request(
                [1 + (i % 7), 2, 3],
                max_tokens=6 + (i % 9),
                tenant="a" if i % 2 == 0 else "b",
                slo=("interactive", "standard", "batch")[i % 3],
            )
            reqs.append(r)
            if i % 3 == 0:
                await asyncio.sleep(0.005)
            if i % 4 == 3:  # cancel a recent one in whatever state it is
                eng.cancel(reqs[i - 1].request_id)
        await asyncio.sleep(0.05)
        for r in reqs[::5]:  # second wave, some mid-decode / post-preempt
            eng.cancel(r.request_id)
        await asyncio.gather(*[_drain(r) for r in reqs])
        deadline = time.monotonic() + 10
        while eng.bm.blocks_in_use and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        report = eng.bm.leak_report()
        await eng.stop()
        return report

    report = asyncio.run(main())
    assert report["blocks_in_use"] == 0, report
    assert report["live_sequences"] == 0
    assert report["total_allocs"] == report["total_frees"]


def test_engine_brownout_sheds_batch_admits_interactive():
    async def main():
        eng = LLMEngine(_tiny(slo_ttft_s=0.5, max_queue=64))
        # drive the ladder directly (the engine ticks it at its metrics
        # cadence; the ladder math itself is unit-tested above)
        for _ in range(3):
            eng._degrade.tick(10.0, 10**6)
        assert eng._degrade.level == 1
        # level 1: batch budgets clamp, nothing shed yet
        br = await eng.add_request([1, 2], max_tokens=500, slo="batch")
        assert br.max_tokens == eng._degrade.batch_max_tokens
        for _ in range(6):
            eng._degrade.tick(10.0, 10**6)
        assert eng._degrade.level == 3
        with pytest.raises(RequestShedError):
            await eng.add_request([3], max_tokens=4, slo="batch")
        with pytest.raises(RequestShedError):
            await eng.add_request([3], max_tokens=4, slo="standard")
        # interactive is NEVER shed by brownout
        ir = await eng.add_request([4, 5], max_tokens=4, slo="interactive")
        await asyncio.gather(_drain(br), _drain(ir))
        st = eng.stats()
        await eng.stop()
        return ir, st

    ir, st = asyncio.run(main())
    assert len(ir.tokens) == 4
    assert st["degradation_level"] == 3
    assert st["shed_total"] == 2


# ----------------------------------------------------------------------
# replica: multiplexed model variants with LRU swap
# ----------------------------------------------------------------------
def test_multiplex_variant_lru_swap_and_eviction_count():
    from ray_tpu.serve.llm.deployment import LLMServer

    async def main():
        srv = LLMServer(_tiny(name="mx").to_dict())
        e_a = await srv._engine_for({"model_id": "a", "prompt": [1]})
        e_b = await srv._engine_for({"model_id": "b", "prompt": [1]})
        assert e_a is not e_b is not srv.engine
        # cache hit: same id -> same engine, no reload
        assert await srv._engine_for({"model_id": "a", "prompt": [1]}) is e_a
        assert srv._mx_evictions == 0
        # third variant exceeds MAX_MODELS_PER_REPLICA=2 -> LRU (b) out
        e_c = await srv._engine_for({"model_id": "c", "prompt": [1]})
        ids = {v.model_id for v in srv._loaded_variants()}
        assert ids == {"a", "c"} and srv._mx_evictions == 1
        # the evicted id reloads as a FRESH engine (and evicts again)
        e_b2 = await srv._engine_for({"model_id": "b", "prompt": [1]})
        assert e_b2 is not e_b and srv._mx_evictions == 2
        # empty model_id means the base engine
        assert await srv._engine_for({"prompt": [1]}) is srv.engine
        # a variant engine actually serves, with its own derived weights
        req = await e_c.add_request([1, 2, 3], max_tokens=4)
        toks = await _drain(req)
        assert len(toks) == 4
        stats = srv.stats()
        assert set(stats["multiplex"]["loaded_model_ids"]) == {"c", "b"}
        assert stats["multiplex"]["evictions"] == 2
        await srv.__serve_shutdown__()

    asyncio.run(main())


# ----------------------------------------------------------------------
# cluster: identity threading + proxy quota admission (tier-1)
# ----------------------------------------------------------------------
def test_identity_threads_header_and_handle_to_replica(serve_cluster):
    """tenant + SLO class reach the replica's request context through
    BOTH front doors: the proxy's x-serve-* headers and the handle's
    options(tenant=, slo_class=) — across the compiled-channel frames."""

    @serve.deployment(name="whoami", route_prefix="/whoami")
    class WhoAmI:
        def __call__(self, payload):
            return {"tenant": serve.get_request_tenant(),
                    "slo": serve.get_request_slo()}

    handle = serve.run(WhoAmI.bind(), name="whoami_app", http_port=PROXY_PORT)
    # handle kwarg path
    out = handle.options(tenant="acme", slo_class="interactive").remote(
        {}).result(timeout=60)
    assert out == {"tenant": "acme", "slo": "interactive"}
    # no identity -> defaults (and the derived handle didn't stick)
    out = handle.remote({}).result(timeout=60)
    assert out == {"tenant": "default", "slo": "standard"}
    # unknown SLO strings clamp instead of minting labels
    out = handle.options(tenant="acme", slo_class="platinum").remote(
        {}).result(timeout=60)
    assert out["slo"] == "standard"
    # HTTP header path through the proxy
    _wait_route("/whoami")
    status, body, _ = _post("/whoami", {"x": 1},
                            headers={"x-serve-tenant": "acme",
                                     "x-serve-slo": "interactive"})
    assert status == 200 and json.loads(body) == {
        "tenant": "acme", "slo": "interactive"}
    # payload fields win over headers
    status, body, _ = _post("/whoami", {"tenant": "beta", "slo": "batch"},
                            headers={"x-serve-tenant": "acme"})
    assert status == 200 and json.loads(body) == {
        "tenant": "beta", "slo": "batch"}
    serve.delete("whoami")


def test_proxy_tenant_quota_429_attributed_to_hostile_only(serve_cluster):
    """Over-quota tenants get 429 + Retry-After at the proxy; in-quota
    tenants are untouched, and the shed counters attribute every quota
    shed to the hostile tenant only."""
    from ray_tpu.serve import llm

    cfg = _tiny(
        name="llm_quota",
        tenant_quotas={
            # hostile: one small burst, then effectively frozen
            "hostile": {"rate": 0.001, "burst": 30},
            "victim": {"rate": 1e6, "burst": 1e6},
        },
    )
    app = llm.build_app(cfg, route_prefix="/quota")
    serve.run(app, name="llm_quota_app", http_port=PROXY_PORT)
    _wait_route("/quota")

    def call(tenant):
        return _post("/quota", {"prompt": "hi", "max_tokens": 8},
                     headers={"x-serve-tenant": tenant})

    # hostile: the burst admits ~3 requests (est = 2 prompt bytes + 8),
    # then the bucket refuses — completion refunds only the unused part
    codes = [call("hostile")[0] for _ in range(8)]
    assert 200 in codes, codes
    rejected = [c for c in codes if c == 429]
    assert rejected, f"hostile was never throttled: {codes}"
    status, _, headers = call("hostile")
    assert status == 429
    assert int(headers.get("Retry-After", "0")) >= 1
    # the victim flows freely the whole time
    for _ in range(5):
        status, body, _ = call("victim")
        assert status == 200, (status, body)
        assert json.loads(body)["num_tokens"] == 8
    # shed attribution: only the hostile tenant appears
    with urllib.request.urlopen(
        f"http://127.0.0.1:{PROXY_PORT}/-/stats", timeout=10
    ) as r:
        stats = json.loads(r.read())
    per_tenant = stats.get("shed_tenant", {}).get("llm_quota", {})
    assert per_tenant.get("hostile", 0) >= len(rejected), stats
    assert "victim" not in per_tenant, stats
    serve.delete("llm_quota")


# ----------------------------------------------------------------------
# chaos drills (slow): tenant storm + replica kill, SIGKILL mid-preempt
# ----------------------------------------------------------------------
@pytest.mark.slow  # multi-replica storm with a kill: runs under `-m chaos`
@pytest.mark.chaos
def test_chaos_tenant_storm_with_replica_kill(serve_cluster):
    """A hostile tenant floods at many times its quota while a victim
    tenant streams interactively; one replica is killed mid-storm.  The
    victim's established streams all complete (retries absorb the kill),
    its TTFT stays bounded, every quota shed lands on the hostile tenant,
    and KV accounting on the survivors balances to zero."""
    from ray_tpu.serve import llm
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    cfg = _tiny(
        name="llm_storm",
        max_batch_size=4,
        num_blocks=128,
        preempt_wait_s=0.1,
        temperature=0.0,
        tenant_weights={"hostile": 1.0, "victim": 1.0},
        tenant_quotas={
            "hostile": {"rate": 20, "burst": 40},
            "victim": {"rate": 1e6, "burst": 1e6},
        },
    )
    app = llm.build_app(cfg, num_replicas=2)
    serve.run(app, name="llm_storm_app", http_port=PROXY_PORT)
    _wait_route("/llm_storm")
    controller = ray_tpu.get_actor(CONTROLLER_NAME, "serve")

    stop = threading.Event()
    hostile = {"sent": 0, "ok": 0, "throttled": 0, "other": 0}

    def hostile_flood():
        while not stop.is_set():
            hostile["sent"] += 1
            try:
                status, _, _ = _post(
                    "/llm_storm", {"prompt": "h" * 16, "max_tokens": 16},
                    headers={"x-serve-tenant": "hostile",
                             "x-serve-slo": "batch"},
                    timeout=30,
                )
                if status == 200:
                    hostile["ok"] += 1
                elif status == 429:
                    hostile["throttled"] += 1
                else:
                    hostile["other"] += 1
            except Exception:  # noqa: BLE001 — the kill may drop one
                hostile["other"] += 1

    def victim_stream_once():
        """One interactive victim stream; returns its TTFT (s)."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{PROXY_PORT}/llm_storm",
            data=json.dumps({"prompt": "v", "max_tokens": 8}).encode(),
            headers={"Content-Type": "application/json",
                     "x-serve-stream": "1",
                     "x-serve-tenant": "victim",
                     "x-serve-slo": "interactive"},
        )
        t0 = time.time()
        with urllib.request.urlopen(req, timeout=60) as resp:
            first = resp.readline()  # established: first token event
            ttft = time.time() - t0
            assert first
            body = resp.read().decode()
        events = [json.loads(l) for l in body.splitlines() if l]
        assert events and events[-1].get("done"), events
        return ttft

    floods = [threading.Thread(target=hostile_flood, daemon=True)
              for _ in range(3)]
    for t in floods:
        t.start()

    ttfts, raw_failures, completed = [], 0, 0
    killed = False
    try:
        for i in range(10):
            for attempt in range(4):
                try:
                    ttfts.append(victim_stream_once())
                    completed += 1
                    break
                except Exception:  # noqa: BLE001 — kill races a stream
                    raw_failures += 1
                    time.sleep(0.5)
            else:
                raise AssertionError(
                    f"victim stream {i} failed every retry "
                    f"(raw_failures={raw_failures})"
                )
            if completed == 3 and not killed:
                reps = ray_tpu.get(controller.get_replicas.remote("llm_storm"))
                victim_rep = reps[0]
                ray_tpu.kill(
                    ray_tpu.get_actor(victim_rep["actor_name"], "serve")
                )
                killed = True
    finally:
        stop.set()
        for t in floods:
            t.join(timeout=30)

    assert killed, "the drill never killed a replica"
    assert completed == 10, "a victim stream was permanently lost"
    # TTFT bound: generous for the 1-core CI box, but it proves the
    # hostile flood and the kill never starved the interactive class
    ttfts.sort()
    p99 = ttfts[max(0, int(len(ttfts) * 0.99) - 1)]
    assert p99 < 30.0, f"victim TTFT blew out under storm: {ttfts}"
    assert hostile["throttled"] >= 5, hostile
    assert hostile["sent"] >= 3 * hostile["ok"], (
        f"flood too weak to prove throttling: {hostile}"
    )
    # shed attribution: quota sheds are the hostile tenant's alone
    with urllib.request.urlopen(
        f"http://127.0.0.1:{PROXY_PORT}/-/stats", timeout=10
    ) as r:
        stats = json.loads(r.read())
    per_tenant = stats.get("shed_tenant", {}).get("llm_storm", {})
    assert per_tenant.get("hostile", 0) >= 5, stats
    assert "victim" not in per_tenant, stats
    # the dead replica is replaced and KV balances to zero everywhere
    deadline = time.time() + 60
    reps = []
    while time.time() < deadline:
        reps = ray_tpu.get(controller.get_replicas.remote("llm_storm"))
        if len(reps) == 2:
            break
        time.sleep(0.5)
    assert len(reps) == 2, f"replica never replaced: {reps}"
    deadline = time.time() + 30
    leaks = None
    while time.time() < deadline:
        leaks = {}
        for rep in reps:
            try:
                st = ray_tpu.get(
                    ray_tpu.get_actor(rep["actor_name"], "serve").stats.remote()
                )
                leaks[rep["replica_id"]] = st.get("kv_blocks_in_use", -1)
            except Exception:  # noqa: BLE001 — replica still starting
                leaks[rep["replica_id"]] = -1
        if all(v == 0 for v in leaks.values()):
            break
        time.sleep(0.5)
    assert all(v == 0 for v in leaks.values()), f"KV leak after storm: {leaks}"
    serve.delete("llm_storm")


@pytest.mark.slow  # own cluster: the chaos spec must precede process spawn
@pytest.mark.chaos
def test_chaos_sigkill_mid_preemption_zero_leak():
    """A seeded SIGKILL lands exactly in the preemption window — after
    the victim's KV pages are freed, before the requeue.  The replica
    dies mid-preemption; the controller must replace it, the replacement
    must serve with ZERO leaked KV blocks, and the plane must not wedge."""
    saved = {
        k: os.environ.get(k)
        for k in ("RAY_TPU_testing_chaos_spec", "RAY_TPU_testing_chaos_seed")
    }
    for fn in (serve.shutdown, ray_tpu.shutdown):
        try:
            fn()
        except Exception:  # noqa: BLE001
            pass
    os.environ["RAY_TPU_testing_chaos_spec"] = "@serve.preempt.evict:kill:at=1"
    os.environ["RAY_TPU_testing_chaos_seed"] = "7"
    from ray_tpu._private.chaos import CHAOS

    CHAOS.reset()
    try:
        ray_tpu.init(num_cpus=4)
        from ray_tpu.serve import llm
        from ray_tpu.serve._private.controller import CONTROLLER_NAME

        cfg = _tiny(name="llm_psig", max_batch_size=1, preempt_wait_s=0.05,
                    temperature=0.0,
                    tenant_weights={"a": 1.0, "b": 1.0})
        handle = serve.run(llm.build_app(cfg), name="llm_psig_app")
        controller = ray_tpu.get_actor(CONTROLLER_NAME, "serve")
        reps0 = ray_tpu.get(controller.get_replicas.remote("llm_psig"))
        assert len(reps0) == 1
        rid0 = reps0[0]["replica_id"]

        # occupy the single lane with a long batch-class stream
        gen = handle.options(stream=True, tenant="a", slo_class="batch")\
            .generate.remote({"prompt": [1, 2, 3], "max_tokens": 400})
        it = iter(gen)
        next(it)  # established

        # an interactive arrival forces the preemption whose evict-side
        # chaos point kills the replica (os._exit between free + requeue)
        def poke():
            try:
                handle.options(tenant="b", slo_class="interactive").remote(
                    {"prompt": [5], "max_tokens": 3}
                ).result(timeout=20)
            except Exception:  # noqa: BLE001 — died with the replica
                pass

        threading.Thread(target=poke, daemon=True).start()

        # the kill fired iff the replica id changes
        deadline = time.time() + 90
        reps = []
        while time.time() < deadline:
            reps = ray_tpu.get(controller.get_replicas.remote("llm_psig"))
            if len(reps) == 1 and reps[0]["replica_id"] != rid0:
                break
            time.sleep(0.5)
        assert reps and reps[0]["replica_id"] != rid0, (
            "chaos kill at serve.preempt.evict never fired (no preemption?)"
        )
        # the orphaned stream dies with its replica, never wedges
        try:
            for _ in it:
                pass
        except Exception:  # noqa: BLE001 — expected: replica death
            pass

        # the replacement serves immediately and its KV pool is clean
        out = None
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                out = handle.options(tenant="b", slo_class="interactive")\
                    .remote({"prompt": [9], "max_tokens": 4}).result(timeout=30)
                break
            except Exception:  # noqa: BLE001 — raced the dead membership
                time.sleep(0.3)
        assert out is not None and out["num_tokens"] == 4, (
            "replacement replica never served"
        )
        deadline = time.time() + 30
        st = None
        while time.time() < deadline:
            st = handle.stats.remote().result(timeout=30)
            if st["kv_blocks_in_use"] == 0 and st["waiting"] == 0:
                break
            time.sleep(0.3)
        assert st["kv_blocks_in_use"] == 0, st["kv_leak_report"]
        rep = st["kv_leak_report"]
        assert rep["total_allocs"] == rep["total_frees"], rep
        serve.delete("llm_psig")
    finally:
        for fn in (serve.shutdown, ray_tpu.shutdown):
            try:
                fn()
            except Exception:  # noqa: BLE001
                pass
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        CHAOS.reset()
