"""Durable checkpoint plane drills (ISSUE 16 acceptance): the
snapshot-commit protocol, verified restore with last-good fallback, the
bounded async writer, retention GC, and the seeded ``ckpt:<phase>``
SIGKILL matrix.

The core invariant every test here enforces from a different angle:
**a checkpoint either verifies completely or is never adopted.**  A
writer killed at ANY phase (mid-shard, pre-commit, mid-manifest), a
bit-flipped shard, a torn manifest — all of them restart training from
the last COMMITTED checkpoint, never from plausible garbage.

Chaos drills ride the same seeded ``ckpt:<phase>:<action>`` rule family
as the dataplane's ``chan:`` rules (see chaos.py): per-rule ordinal
streams make every schedule replayable from (spec, seed) alone.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from ray_tpu.train import checkpoint_plane as cp
from ray_tpu.train.checkpoint_plane import (
    AsyncCheckpointWriter,
    CheckpointCorruptionError,
    CheckpointWriteError,
    MANIFEST_NAME,
)


# ---------------------------------------------------------------------------
# helpers


def _make_src(tmp_path, payloads=None):
    src = tmp_path / "src"
    src.mkdir(exist_ok=True)
    for name, data in (payloads or {"weights.bin": b"w" * 4096, "opt.bin": b"o" * 512}).items():
        (src / name).write_bytes(data)
    return str(src)


def _commit_chain(tmp_path, root_name="exp", n=3, start=1):
    """n committed checkpoints checkpoint_00000{start..} under root."""
    root = tmp_path / root_name
    root.mkdir(exist_ok=True)
    dests = []
    for i in range(start, start + n):
        src = _make_src(tmp_path, {"state.bin": f"step-{i}".encode() * 100})
        dest = str(root / f"checkpoint_{i:06d}")
        cp.persist_dir(src, dest, meta={"idx": i}, mode="sync")
        dests.append(dest)
    return str(root), dests


def _flip_byte(path):
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))


def _counter_value(name):
    from ray_tpu.util import metrics as metrics_mod

    rec = metrics_mod._registry.get((name, ()))
    return rec["value"] if rec else 0.0


@pytest.fixture()
def ckpt_chaos():
    """Seeded ckpt:* chaos spec for in-process write-path drills;
    restores the environment and the plane afterwards."""
    saved = {}

    def set_spec(spec, seed="11"):
        for k, v in {
            "RAY_TPU_testing_chaos_spec": spec,
            "RAY_TPU_testing_chaos_seed": seed,
        }.items():
            saved.setdefault(k, os.environ.get(k))
            os.environ[k] = v
        from ray_tpu._private.chaos import CHAOS

        CHAOS.reset()

    yield set_spec
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old
    from ray_tpu._private.chaos import CHAOS

    CHAOS.reset()


# ---------------------------------------------------------------------------
# commit protocol


def test_snapshot_commit_verifies_and_leaves_no_residue(tmp_path):
    """persist_dir publishes every file atomically + a CRC manifest;
    the result verifies, round-trips its metadata, and leaves zero .tmp
    residue."""
    src = _make_src(tmp_path)
    dest = str(tmp_path / "exp" / "checkpoint_000001")
    out = cp.persist_dir(src, dest, meta={"experiment": "e", "idx": 1}, mode="sync")
    assert out == dest
    manifest = cp.verify_checkpoint(dest)
    assert manifest["meta"]["experiment"] == "e"
    assert set(manifest["shards"]) == {"weights.bin", "opt.bin"}
    assert manifest["shards"]["weights.bin"]["bytes"] == 4096
    assert not [f for f in os.listdir(dest) if f.endswith(".tmp")]
    assert cp.is_committed(dest)
    # byte-identical copy
    with open(os.path.join(dest, "weights.bin"), "rb") as f:
        assert f.read() == b"w" * 4096


def test_write_file_atomic_returns_intended_crc(tmp_path):
    import zlib

    data = b"payload" * 99
    crc = cp.write_file_atomic(str(tmp_path), "shard.bin", data)
    assert crc == zlib.crc32(data) & 0xFFFFFFFF
    assert (tmp_path / "shard.bin").read_bytes() == data


def test_uncommitted_dir_is_never_verified(tmp_path):
    d = tmp_path / "checkpoint_000005"
    d.mkdir()
    (d / "weights.bin").write_bytes(b"plausible")
    with pytest.raises(CheckpointCorruptionError, match="uncommitted"):
        cp.verify_checkpoint(str(d))
    assert not cp.is_committed(str(d))


def test_torn_manifest_is_corruption_not_a_checkpoint(tmp_path):
    root, dests = _commit_chain(tmp_path, n=1)
    mp = os.path.join(dests[0], MANIFEST_NAME)
    data = open(mp, "rb").read()
    with open(mp, "wb") as f:
        f.write(data[: len(data) // 2])  # storage tear
    with pytest.raises(CheckpointCorruptionError, match="manifest"):
        cp.load_manifest(dests[0])
    assert not cp.is_committed(dests[0])


# ---------------------------------------------------------------------------
# verified restore + fallback chain


def test_restore_fallback_walks_to_last_good_and_counts(tmp_path):
    """The ISSUE acceptance chain: K committed-but-bit-flipped, K-1
    bit-flipped too, K-2 good → restore skips two (counted in
    checkpoint_restore_fallbacks_total) and adopts K-2."""
    root, dests = _commit_chain(tmp_path, n=3)  # 1, 2, 3
    _flip_byte(os.path.join(dests[2], "state.bin"))  # K
    _flip_byte(os.path.join(dests[1], "state.bin"))  # K-1
    before = _counter_value("checkpoint_restore_fallbacks_total")
    got = cp.resolve_restore(root=root)
    assert got == dests[0]  # K-2 adopted
    assert _counter_value("checkpoint_restore_fallbacks_total") == before + 2
    # and the survivors actually verify
    cp.verify_checkpoint(got)


def test_restore_prefers_preferred_then_falls_back(tmp_path):
    root, dests = _commit_chain(tmp_path, n=2)
    # preferred (the resume request) is corrupt → chain under root wins
    _flip_byte(os.path.join(dests[1], "state.bin"))
    got = cp.resolve_restore(preferred=dests[1], root=root)
    assert got == dests[0]


def test_restore_never_adopts_uncommitted_over_committed(tmp_path):
    root, dests = _commit_chain(tmp_path, n=1)
    debris = os.path.join(root, "checkpoint_000009")
    os.makedirs(debris)
    with open(os.path.join(debris, "state.bin"), "wb") as f:
        f.write(b"newer but never committed")
    got = cp.resolve_restore(root=root)
    assert got == dests[0]


def test_restore_all_corrupt_raises_never_adopts(tmp_path):
    root, dests = _commit_chain(tmp_path, n=2)
    for d in dests:
        _flip_byte(os.path.join(d, "state.bin"))
    with pytest.raises(CheckpointCorruptionError, match="no checkpoint"):
        cp.resolve_restore(root=root)


def test_restore_legacy_chain_without_manifests(tmp_path):
    """Pre-plane checkpoints (no manifest anywhere, no commit ever
    attempted) load newest-as-is for compatibility."""
    root = tmp_path / "legacy"
    root.mkdir()
    for i in (1, 2):
        d = root / f"checkpoint_{i:06d}"
        d.mkdir()
        (d / "state.bin").write_bytes(b"old-world")
    assert cp.resolve_restore(root=str(root)) == str(root / "checkpoint_000002")


def test_restore_orders_by_generation_then_index(tmp_path):
    root = tmp_path / "exp"
    root.mkdir()
    # Build the name the way the session does (the canonical format the
    # plane's _CKPT_NAME regex parses): generation-prefixed + rank-suffixed.
    # graftlint: disable=generation-key -- this test drills the parser of that very format
    gen_name = f"checkpoint_g{1:03d}_{2:06d}_rank{0}"
    names = ["checkpoint_000009", gen_name]
    for n in names:
        src = _make_src(tmp_path, {"s.bin": n.encode()})
        cp.persist_dir(src, str(root / n), mode="sync")
    # generation 1 outranks a higher plain index of generation 0
    assert cp.resolve_restore(root=str(root), rank=0) == str(root / gen_name)
    cands = cp.candidate_checkpoints(str(root), rank=1)
    assert cands == [str(root / "checkpoint_000009")]  # rank filter


# ---------------------------------------------------------------------------
# retention GC


def test_gc_keeps_newest_k_and_pinned(tmp_path):
    root, dests = _commit_chain(tmp_path, n=5)
    before = _counter_value("checkpoint_gc_reclaimed_total")
    n = cp.gc_checkpoints(root, keep=2, pinned=[dests[0]], grace_s=9999)
    left = sorted(os.listdir(root))
    assert n == 2
    assert left == ["checkpoint_000001", "checkpoint_000004", "checkpoint_000005"]
    assert _counter_value("checkpoint_gc_reclaimed_total") == before + 2


def test_gc_debris_respects_grace_window(tmp_path):
    root, dests = _commit_chain(tmp_path, n=1)
    young = os.path.join(root, "checkpoint_000007")
    old = os.path.join(root, "checkpoint_000008")
    for d in (young, old):
        os.makedirs(d)
        with open(os.path.join(d, "x"), "wb") as f:
            f.write(b"partial")
    os.utime(old, (time.time() - 3600, time.time() - 3600))
    n = cp.gc_checkpoints(root, keep=3, grace_s=600)
    # the old debris is reclaimed; the in-flight-looking young one and
    # the committed checkpoint survive
    assert n == 1
    assert sorted(os.listdir(root)) == ["checkpoint_000001", "checkpoint_000007"]


# ---------------------------------------------------------------------------
# async writer: backpressure + deferred typed error


def test_async_writer_backpressures_never_drops():
    w = AsyncCheckpointWriter(name="t-ckpt-writer")
    try:
        order = []
        gate = threading.Event()

        def slow():
            gate.wait(5.0)
            order.append("first")

        w.submit(slow)
        assert w.busy
        t0 = time.monotonic()
        threading.Timer(0.25, gate.set).start()
        w.submit(lambda: order.append("second"))  # parks until slow() lands
        waited = time.monotonic() - t0
        assert waited >= 0.2  # genuinely blocked, not dropped
        assert order[0] == "first"
        assert w.wait(timeout=5.0)
        assert order == ["first", "second"]
    finally:
        w.close()


def test_async_writer_surfaces_failure_on_next_submit():
    w = AsyncCheckpointWriter(name="t-ckpt-writer-err")
    try:
        w.submit(lambda: (_ for _ in ()).throw(OSError("disk full")))
        # the NEXT submit parks until the failing write lands, then
        # raises its held failure instead of queueing on top of it
        with pytest.raises(CheckpointWriteError, match="disk full"):
            w.submit(lambda: None)
        # the error is consumed once; the writer is usable again
        done = threading.Event()
        w.submit(done.set)
        assert done.wait(5.0)
        w.wait(timeout=5.0)
    finally:
        w.close()


def test_async_writer_wait_raises_held_error():
    w = AsyncCheckpointWriter(name="t-ckpt-writer-wait")
    try:
        w.submit(lambda: (_ for _ in ()).throw(ValueError("boom")))
        # wait() blocks until the failing write lands, then raises it
        with pytest.raises(CheckpointWriteError, match="boom"):
            w.wait(timeout=10.0)
    finally:
        w.close()


def test_async_writer_close_is_clean_and_final():
    w = AsyncCheckpointWriter(name="t-ckpt-writer-close")
    w.submit(lambda: None)
    w.close(timeout=5.0)
    assert not (w._thread and w._thread.is_alive())
    with pytest.raises(CheckpointWriteError, match="closed"):
        w.submit(lambda: None)
    w.close()  # idempotent


# ---------------------------------------------------------------------------
# chaos: torn writes and bit rot (in-process, seeded)


def test_chaos_torn_shard_is_caught_by_verify(tmp_path, ckpt_chaos):
    """A torn shard write (truncated file under the final name — the
    no-commit-protocol failure model) commits a manifest whose CRC can
    never match: restore falls back to the previous checkpoint."""
    root, dests = _commit_chain(tmp_path, n=1)
    ckpt_chaos("ckpt:shard:torn_write:at=1")
    src = _make_src(tmp_path, {"state.bin": b"torn-target" * 200})
    dest = os.path.join(root, "checkpoint_000002")
    cp.persist_dir(src, dest, mode="sync")  # commits, but shard is torn
    with pytest.raises(CheckpointCorruptionError):
        cp.verify_checkpoint(dest)
    assert cp.resolve_restore(root=root) == dests[0]


def test_chaos_bit_flip_never_adopted(tmp_path, ckpt_chaos):
    """Seeded bit rot on a committed shard: verification rejects it and
    the loader walks back — the bit-flipped checkpoint is NEVER adopted
    (the ISSUE's zero-corrupted-restores acceptance)."""
    root, dests = _commit_chain(tmp_path, n=1)
    ckpt_chaos("ckpt:shard:bit_flip:at=1")
    src = _make_src(tmp_path, {"state.bin": b"rot-target" * 300})
    dest = os.path.join(root, "checkpoint_000002")
    cp.persist_dir(src, dest, mode="sync")
    with pytest.raises(CheckpointCorruptionError, match="CRC32"):
        cp.verify_checkpoint(dest)
    assert cp.resolve_restore(root=root) == dests[0]


def test_chaos_torn_manifest_falls_back(tmp_path, ckpt_chaos):
    root, dests = _commit_chain(tmp_path, n=1)
    ckpt_chaos("ckpt:manifest:torn_write:at=1")
    src = _make_src(tmp_path, {"state.bin": b"x" * 100})
    dest = os.path.join(root, "checkpoint_000002")
    cp.persist_dir(src, dest, mode="sync")
    assert not cp.is_committed(dest)  # torn manifest = uncommitted
    assert cp.resolve_restore(root=root) == dests[0]


# ---------------------------------------------------------------------------
# chaos: the SIGKILL phase matrix (subprocess — real os._exit(137))

_CHILD = r"""
import os, sys
from ray_tpu.train import checkpoint_plane as cp
src, dest = sys.argv[1], sys.argv[2]
cp.persist_dir(src, dest, meta={"idx": 2}, mode="sync")
"""


def _run_kill_child(tmp_path, phase, root):
    src = _make_src(tmp_path, {"state.bin": b"victim" * 500})
    dest = os.path.join(root, "checkpoint_000002")
    env = dict(os.environ)
    env["RAY_TPU_testing_chaos_spec"] = f"ckpt:{phase}:kill:at=1"
    env["RAY_TPU_testing_chaos_seed"] = "11"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, src, dest],
        env=env, capture_output=True, timeout=120,
    )
    return proc, dest


@pytest.mark.chaos
@pytest.mark.parametrize("phase", ["shard", "precommit", "manifest"])
def test_chaos_sigkill_at_every_phase_restarts_to_last_committed(
    tmp_path, phase
):
    """THE tentpole drill: a writer SIGKILLed mid-shard, between the
    last shard and the manifest, or mid-manifest-write leaves a
    checkpoint that is never committed and never adopted — restore
    lands on the previous committed checkpoint at every phase."""
    root, dests = _commit_chain(tmp_path, n=1)
    proc, dest = _run_kill_child(tmp_path, phase, root)
    assert proc.returncode == 137, proc.stderr.decode()
    # killed-mid-write directory is uncommitted (or torn) — never valid
    assert not cp.is_committed(dest)
    with pytest.raises(CheckpointCorruptionError):
        cp.verify_checkpoint(dest)
    # the one loader everything uses falls back to last committed
    assert cp.resolve_restore(root=root) == dests[0]
    # ... and retention GC reclaims the debris once past the grace window
    os.utime(dest, (time.time() - 3600, time.time() - 3600))
    assert cp.gc_checkpoints(root, keep=3, grace_s=60) == 1
    assert not os.path.exists(dest)
    # rerun the same write without chaos: the path itself is sound
    src2 = _make_src(tmp_path, {"state.bin": b"clean" * 500})
    cp.persist_dir(src2, dest, mode="sync")
    assert cp.resolve_restore(root=root) == dest


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_kill_restart_loss_parity(tmp_path):
    """Kill-restart loss parity: a training loop SIGKILLed mid-write
    restarts from the last committed checkpoint and reaches EXACTLY the
    state of a never-killed run (state here is a deterministic fold, so
    parity is byte-exact)."""
    script = r"""
import json, os, sys
from ray_tpu.train import checkpoint_plane as cp
root = sys.argv[1]; steps = int(sys.argv[2])
state, start = 0, 0
got = cp.resolve_restore(root=root)
if got:
    with open(os.path.join(got, "state.json")) as f:
        d = json.load(f)
    state, start = d["state"], d["step"] + 1
for step in range(start, steps):
    state = (state * 31 + step) % 1000003
    src = os.path.join(root, "_stage")
    os.makedirs(src, exist_ok=True)
    with open(os.path.join(src, "state.json"), "w") as f:
        json.dump({"state": state, "step": step}, f)
    cp.persist_dir(src, os.path.join(root, f"checkpoint_{step:06d}"), mode="sync")
    cp.gc_checkpoints(root, keep=3, grace_s=9999)
print(state)
"""
    def run(root, chaos_spec=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("RAY_TPU_testing_chaos_spec", None)
        if chaos_spec:
            env["RAY_TPU_testing_chaos_spec"] = chaos_spec
            env["RAY_TPU_testing_chaos_seed"] = "11"
        return subprocess.run(
            [sys.executable, "-c", script, root, "8"],
            env=env, capture_output=True, timeout=180,
        )

    clean_root = str(tmp_path / "clean"); os.makedirs(clean_root)
    chaos_root = str(tmp_path / "chaos"); os.makedirs(chaos_root)
    ref = run(clean_root)
    assert ref.returncode == 0, ref.stderr.decode()
    # kill on the 5th shard write, then on the next run's 2nd precommit
    p1 = run(chaos_root, "ckpt:shard:kill:at=5")
    assert p1.returncode == 137
    p2 = run(chaos_root, "ckpt:precommit:kill:at=2")
    assert p2.returncode == 137
    p3 = run(chaos_root)  # final run to completion
    assert p3.returncode == 0, p3.stderr.decode()
    assert p3.stdout.strip() == ref.stdout.strip()  # exact parity
