"""Train library: JaxTrainer end-to-end on real worker processes.

The minimum end-to-end slice from SURVEY.md §7: a 2-worker
DataParallelTrainer MLP on CPU — but with the real jax.distributed
bootstrap (Gloo collectives between the two actor processes, global
16-device mesh) rather than a mock.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.jax import JaxConfig, JaxTrainer


def _mlp_loop(config):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu.models import mlp

    ctx = train.get_context()
    assert ctx.get_world_size() == config["num_workers"]

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    cfg = mlp.MLPConfig(in_dim=16, hidden=(32,), num_classes=4)
    params = mlp.init_params(cfg, jax.random.PRNGKey(0))

    resume = train.get_checkpoint()
    if resume is not None:
        params = resume.to_pytree()

    opt = optax.sgd(0.1)
    opt_state = opt.init(params)
    step = jax.jit(mlp.make_train_step(cfg, opt))

    rng = np.random.default_rng(42)
    n_local = 8
    data_sharding = NamedSharding(mesh, P("dp"))

    for epoch in range(config["epochs"]):
        x_local = rng.standard_normal((n_local, 16)).astype(np.float32)
        y_local = (x_local.sum(axis=1) > 0).astype(np.int32)
        x = jax.make_array_from_process_local_data(data_sharding, x_local)
        y = jax.make_array_from_process_local_data(data_sharding, y_local)
        params, opt_state, loss = step(params, opt_state, x, y)
        loss_val = float(jax.device_get(loss))
        ckpt = None
        if ctx.get_world_rank() == 0 and epoch == config["epochs"] - 1:
            host_params = jax.device_get(params)
            ckpt = Checkpoint.from_pytree(host_params)
        train.report({"loss": loss_val, "epoch": epoch}, checkpoint=ckpt)


@pytest.mark.parametrize("num_workers", [2])
def test_jax_trainer_distributed_mlp(ray_cluster, tmp_path, num_workers):
    trainer = JaxTrainer(
        _mlp_loop,
        train_loop_config={"epochs": 3, "num_workers": num_workers},
        scaling_config=ScalingConfig(num_workers=num_workers),
        run_config=RunConfig(name="mlp_test", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics is not None
    assert result.metrics["epoch"] == 2
    assert np.isfinite(result.metrics["loss"])
    assert result.checkpoint is not None
    tree = result.checkpoint.to_pytree()
    assert "dense_0" in tree


def test_jax_trainer_resume_from_checkpoint(ray_cluster, tmp_path):
    trainer = JaxTrainer(
        _mlp_loop,
        train_loop_config={"epochs": 2, "num_workers": 2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="mlp_resume_a", storage_path=str(tmp_path)),
    )
    r1 = trainer.fit()
    trainer2 = JaxTrainer(
        _mlp_loop,
        train_loop_config={"epochs": 1, "num_workers": 2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="mlp_resume_b", storage_path=str(tmp_path)),
        resume_from_checkpoint=r1.checkpoint,
    )
    r2 = trainer2.fit()
    assert r2.metrics["loss"] <= r1.metrics["loss"] + 0.5  # continued, not reset


def test_trainer_restore_from_experiment_dir(ray_cluster, tmp_path):
    """Trainer.restore(path) rebuilds the trainer from the saved
    trainer.pkl and resumes from the latest checkpoint (reference:
    train/base_trainer.py:250)."""
    trainer = JaxTrainer(
        _mlp_loop,
        train_loop_config={"epochs": 2, "num_workers": 2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="mlp_restore", storage_path=str(tmp_path)),
    )
    r1 = trainer.fit()
    exp_dir = os.path.join(str(tmp_path), "mlp_restore")
    assert JaxTrainer.can_restore(exp_dir)
    restored = JaxTrainer.restore(exp_dir)
    assert restored.resume_from_checkpoint is not None
    assert restored.train_loop_config["epochs"] == 2
    r2 = restored.fit()
    # Restored run continued from r1's params (loss did not reset).
    assert r2.metrics["loss"] <= r1.metrics["loss"] + 0.5
    # Overrides replace saved fields.
    restored2 = JaxTrainer.restore(exp_dir, train_loop_config={"epochs": 1, "num_workers": 2})
    assert restored2.train_loop_config["epochs"] == 1


def _flaky_loop(config):
    marker = config["marker"]
    if not os.path.exists(marker):
        with open(marker, "w") as f:
            f.write("x")
        raise RuntimeError("injected first-attempt failure")
    train.report({"ok": 1})


def test_failure_config_retries(ray_cluster, tmp_path):
    trainer = JaxTrainer(
        _flaky_loop,
        train_loop_config={"marker": str(tmp_path / "marker")},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="flaky", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.metrics == {"ok": 1}


def _kill_rank1_once_loop(config):
    """First attempt: rank 1 dies HARD (os._exit — no exception, no
    teardown, the signature of an OOM/SIGKILL/preempted-host death)
    mid-training, after the jax.distributed rendezvous is up.  Second
    attempt: everyone trains to completion."""
    import jax

    ctx = train.get_context()
    # The re-rendezvous proof: every attempt sees the FULL world again —
    # process_count comes from the jax.distributed coordinator, so a
    # half-rebuilt group would fail here.
    assert jax.process_count() == config["num_workers"]
    marker = config["marker"]
    if ctx.get_world_rank() == 1 and not os.path.exists(marker):
        with open(marker, "w") as f:
            f.write("killed")
        os._exit(1)
    train.report({"ok": 1, "procs": jax.process_count()})


@pytest.mark.slow  # ~104 s whole-mesh restart drill: runs under `-m chaos`
@pytest.mark.chaos
def test_killed_worker_whole_mesh_restart(ray_cluster, tmp_path):
    """Recovery drill (ISSUE 1): a killed training worker triggers a
    clean WHOLE-mesh restart — XLA's world is static, so the dead rank
    cannot rejoin; the group is torn down, fresh workers are leased, and
    jax.distributed re-rendezvouses with a new coordinator — and the job
    completes."""
    marker = tmp_path / "rank1_killed"
    trainer = JaxTrainer(
        _kill_rank1_once_loop,
        train_loop_config={"marker": str(marker), "num_workers": 2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="mesh_restart", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.metrics == {"ok": 1, "procs": 2}
    assert marker.exists(), "the fault was never injected"


def test_failure_without_retries_raises(ray_cluster, tmp_path):
    def always_fail(config):
        raise ValueError("nope")

    trainer = JaxTrainer(
        always_fail,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="fail", storage_path=str(tmp_path)),
    )
    with pytest.raises(train.TrainingFailedError):
        trainer.fit()


def _gpt2_data_loop(config):
    """The BASELINE configs[3] shape in miniature: every worker is one
    jax.distributed process of a single global mesh; the sharded GPT-2
    step (dp × tp Megatron layout) consumes batches straight from this
    rank's Dataset.streaming_split shard via iter_jax_batches
    (reference: train/data_parallel_trainer.py:428 + dataset.py:1482)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu import train
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import create_mesh

    ctx = train.get_context()
    assert ctx.get_world_size() == config["num_workers"]
    n_global = len(jax.devices())
    assert n_global == 8 * config["num_workers"], n_global  # ONE global mesh

    # Align ranks before the (slow, 1-core CPU) compile: Gloo's clique
    # rendezvous times out if one rank reaches the first collective
    # long before its peer.
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("gpt2_data_loop_start")

    mesh = create_mesh({"dp": n_global // 2, "tp": 2}, jax.devices())
    cfg = gpt2.GPT2Config(
        vocab_size=256, n_layer=1, n_head=2, d_model=64, max_seq_len=64, mesh=mesh
    )
    opt = gpt2.make_adamw(1e-3)
    params, opt_state, _specs = gpt2.make_sharded_train_state(cfg, mesh, opt)
    step = gpt2.make_sharded_train_step(cfg, mesh, opt)

    shard = train.get_dataset_shard("train")
    data_sharding = NamedSharding(mesh, P("dp"))
    steps, last_loss = 0, None
    for batch in shard.iter_jax_batches(
        batch_size=config["per_worker_batch"],
        sharding=data_sharding,
        dtypes={"data": np.int32},
    ):
        toks = batch["data"]  # global [B, T+1] assembled across ranks
        assert toks.shape[0] == config["per_worker_batch"] * config["num_workers"]
        params, opt_state, loss = step(params, opt_state, toks[:, :-1], toks[:, 1:])
        last_loss = float(jax.device_get(loss))
        steps += 1
    train.report({"loss": last_loss, "steps": steps})


def test_jax_trainer_sharded_gpt2_streaming_split(ray_cluster, tmp_path):
    """VERDICT r4 ask #2: trainer + data + mesh in ONE path — 2 worker
    processes form a 16-device global mesh, run the sharded GPT-2 step,
    fed by streaming_split shards."""
    import ray_tpu.data as rdata

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, (32, 33), dtype=np.int64)  # < vocab_size
    ds = rdata.from_numpy(tokens)

    trainer = JaxTrainer(
        _gpt2_data_loop,
        train_loop_config={"num_workers": 2, "per_worker_batch": 4},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="gpt2_stream", storage_path=str(tmp_path)),
        datasets={"train": ds},
    )
    result = trainer.fit()
    assert result.metrics is not None
    # 32 rows / (4 per worker × 2 workers) = 4 global steps
    assert result.metrics["steps"] == 4, result.metrics
    assert np.isfinite(result.metrics["loss"])


def test_typed_restore_sharded_gpt2_with_closure_loop(ray_cluster, tmp_path):
    """VERDICT r4 ask #8: Trainer.restore re-binds unpicklable fields as
    a typed API.  The train loop is a CLOSURE (plain-pickle fails), so
    trainer.pkl records it by name; restore() without the override
    raises naming exactly that parameter, and the typed restore with a
    fresh loop + datasets resumes the sharded-GPT-2 run."""
    import ray_tpu.data as rdata

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 256, (32, 33), dtype=np.int64)
    cfg = {"num_workers": 2, "per_worker_batch": 4}

    def loop(config):  # closure over cfg -> not plain-picklable
        _gpt2_data_loop(cfg)

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="gpt2_typed_restore", storage_path=str(tmp_path)),
        datasets={"train": rdata.from_numpy(tokens)},
    )
    r1 = trainer.fit()
    assert r1.metrics["steps"] == 4

    exp_dir = os.path.join(str(tmp_path), "gpt2_typed_restore")
    assert JaxTrainer.can_restore(exp_dir)
    # restoring without the unpicklable field is a TYPED error naming it
    with pytest.raises(ValueError, match="train_loop_per_worker"):
        JaxTrainer.restore(exp_dir)
    restored = JaxTrainer.restore(
        exp_dir,
        train_loop_per_worker=loop,
        datasets={"train": rdata.from_numpy(tokens)},
    )
    r2 = restored.fit()
    assert r2.metrics["steps"] == 4
    assert np.isfinite(r2.metrics["loss"])
