"""Multi-tenant job plane (ISSUE 8): quotas, DRF fair-share lease
scheduling, and safe priority preemption.

Layers drilled here:

1. Pure model math (``_private/tenants.py``): dominant shares, quota
   checks, and the fair-share pick order (no intra-tenant queue-jumping,
   over-quota tenants skipped, work conservation across tenants).
2. Tier-1 quota plane: registry RPCs, admission parking + resume,
   typed backpressure (``QuotaExceededError``), and the accounting edge
   cases — actor restarts don't double-charge, PG bundles spanning
   nodes charge once, detached actors outlive their driver and keep
   charging their tenant, elastic grow is blocked at a quota boundary
   and resumes when the quota rises.
3. Chaos acceptance (``-m chaos``):
   - 3 competing tenants with unequal quotas under sustained demand:
     steady-state usage respects quotas within 10%, and a mid-drill
     node kill does not let any tenant exceed its quota after recovery;
   - a high-priority submission preempts a low-priority elastic trainer
     via checkpoint-and-shrink: no lost work (final-loss parity), no
     charge to ``FailureConfig.max_failures``.
"""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import tenants as tenants_mod
from ray_tpu._private.common import ResourceSet
from ray_tpu.cluster_utils import Cluster


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        # graftlint: disable=retry-gate -- deadline-bounded assertion poll; 0.2 s is the scan resolution, not a retry delay
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture()
def tenant_cluster():
    """Head + optional worker nodes with tenant-plane env knobs applied
    for every spawned process (config rides child_env)."""
    created = []
    saved_env = {}

    def set_env(env):
        for k, v in env.items():
            saved_env.setdefault(k, os.environ.get(k))
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def make(head_args=None, nodes=(), env=None, **init_kwargs):
        set_env(env or {})
        c = Cluster(initialize_head=True, head_node_args=head_args or {"num_cpus": 4})
        handles = [c.add_node(**dict(kw)) for kw in nodes]
        c.wait_for_nodes()
        ray_tpu.init(address=c.address, **init_kwargs)
        created.append(c)
        return c, handles

    yield make
    ray_tpu.shutdown()
    for c in created:
        c.shutdown()
    for k, old in saved_env.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old


def _gcs():
    return ray_tpu._private.worker.get_global_worker().gcs_client


def _tenant_view(name):
    for t in _gcs().call("list_tenants", None):
        if t["name"] == name:
            return t
    return None


def _usage_cpu(name):
    t = _tenant_view(name)
    return (t or {}).get("usage", {}).get("CPU", 0.0)


# ==========================================================================
# 1. pure model math
# ==========================================================================


def test_dominant_share_and_quota_math():
    totals = {"CPU": 10.0, "TPU": 4.0}
    assert tenants_mod.dominant_share({"CPU": 5.0}, totals) == 0.5
    # Dominant = the max share across resources.
    assert tenants_mod.dominant_share({"CPU": 2.0, "TPU": 2.0}, totals) == 0.5
    # Weight divides the share (weight 2 = entitled to twice as much).
    assert tenants_mod.dominant_share({"CPU": 5.0}, totals, weight=2.0) == 0.25
    # Resources the cluster doesn't have are ignored.
    assert tenants_mod.dominant_share({"accel": 3.0}, totals) == 0.0
    assert not tenants_mod.over_quota({"CPU": 1.0}, {"CPU": 1.0}, {"CPU": 2.0})
    assert tenants_mod.over_quota({"CPU": 1.5}, {"CPU": 1.0}, {"CPU": 2.0})
    # Empty quota = unlimited.
    assert not tenants_mod.over_quota({"CPU": 99.0}, None, {})


class _Fut:
    def done(self):
        return False


def _w(cpu, tenant, priority=0, seq=0):
    return tenants_mod.LeaseWaiter(
        res=ResourceSet.of({"CPU": cpu}), fut=_Fut(), tenant=tenant,
        priority=priority, seq=seq,
    )


def test_pick_next_drf_order_and_priority():
    totals = {"CPU": 8.0}
    avail = ResourceSet.of({"CPU": 4})
    # B has the lower dominant share -> B goes first despite higher seq.
    usage = {"a": {"CPU": 4.0}, "b": {"CPU": 1.0}}
    waiters = [_w(1, "a", seq=1), _w(1, "b", seq=2)]
    assert tenants_mod.pick_next(waiters, avail, usage, totals, {}).tenant == "b"
    # Within one tenant, priority wins, then FIFO.
    waiters = [_w(1, "a", priority=0, seq=1), _w(1, "a", priority=5, seq=9)]
    assert tenants_mod.pick_next(waiters, avail, usage, totals, {}).priority == 5


def test_pick_next_no_intra_tenant_queue_jumping():
    """A tenant's big parked head blocks its OWN later small requests
    (anti-starvation), but not other tenants (work conservation)."""
    totals = {"CPU": 8.0}
    avail = ResourceSet.of({"CPU": 2})
    usage = {"a": {"CPU": 0.0}, "b": {"CPU": 4.0}}
    big_a = _w(4, "a", seq=1)   # does not fit
    small_a = _w(1, "a", seq=2)  # must NOT jump its own queue
    small_b = _w(1, "b", seq=3)  # other tenant: may proceed
    got = tenants_mod.pick_next([big_a, small_a, small_b], avail, usage, totals, {})
    assert got is small_b


def test_pick_next_skips_over_quota_tenant():
    totals = {"CPU": 8.0}
    avail = ResourceSet.of({"CPU": 4})
    specs = {
        "a": tenants_mod.TenantSpec("a", quota=ResourceSet.of({"CPU": 2})),
    }
    usage = {"a": {"CPU": 2.0}, "b": {"CPU": 3.0}}
    waiters = [_w(1, "a", seq=1), _w(1, "b", seq=2)]
    got = tenants_mod.pick_next(waiters, avail, usage, totals, specs)
    assert got.tenant == "b"
    # Quota enforcement off: DRF order alone decides (a has lower share).
    got = tenants_mod.pick_next(
        waiters, avail, usage, totals, specs, enforce_quota=False
    )
    assert got.tenant == "a"


def test_preemption_victim_order():
    totals = {"CPU": 8.0}
    specs = {"over": tenants_mod.TenantSpec("over", quota=ResourceSet.of({"CPU": 1}))}
    usage = {"over": {"CPU": 2.0}, "big": {"CPU": 5.0}, "small": {"CPU": 1.0}}
    jobs = [
        {"tenant": "small", "priority": 0, "start_time": 3.0},
        {"tenant": "big", "priority": 0, "start_time": 2.0},
        {"tenant": "over", "priority": 1, "start_time": 1.0},
    ]
    ordered = tenants_mod.preemption_victim_order(jobs, usage, totals, specs)
    # Over-quota first (despite higher priority), then highest share.
    assert [j["tenant"] for j in ordered] == ["over", "big", "small"]


def test_fair_dispatch_order():
    """The raylet-mediated dispatch queue's ordering rule (carried PR 6
    follow-up): (priority, FIFO) within a tenant, round-robin across
    tenants ascending dominant share."""
    totals = {"CPU": 8.0}
    usage = {"hog": {"CPU": 6.0}, "light": {"CPU": 1.0}}
    # entries: (tenant, priority, seq, item)
    entries = [
        ("hog", 0, 1, "h1"),
        ("hog", 0, 2, "h2"),
        ("hog", 5, 3, "h3-prio"),
        ("light", 0, 4, "l1"),
        ("light", 0, 5, "l2"),
    ]
    out = tenants_mod.fair_dispatch_order(entries, usage, totals, {})
    # light (lower share) leads each round; hog's high-priority task
    # jumps hog's own FIFO but NOT light's turn.
    assert out == ["l1", "h3-prio", "l2", "h1", "h2"]
    # weight raises effective fair share: a weighted hog wins the tie
    specs = {"hog": tenants_mod.TenantSpec("hog", weight=10.0)}
    out = tenants_mod.fair_dispatch_order(entries, usage, totals, specs)
    assert out[0] == "h3-prio"
    # empty usage: pure (priority, FIFO) interleave, deterministic
    assert tenants_mod.fair_dispatch_order([], {}, totals, {}) == []


def test_fair_dispatch_order_single_tenant_is_priority_fifo():
    """Degenerate case (one job/tenant): ordering reduces to the queue's
    existing (priority, FIFO) semantics — no behavior change."""
    entries = [("t", 0, 1, "a"), ("t", 2, 2, "b"), ("t", 0, 3, "c")]
    out = tenants_mod.fair_dispatch_order(entries, {}, {"CPU": 4.0}, {})
    assert out == ["b", "a", "c"]


def test_tenant_label_bounded():
    assert tenants_mod.tenant_label("teamA", {"teamA"}) == "teamA"
    assert tenants_mod.tenant_label("randomX", {"teamA"}) == "other"
    assert tenants_mod.tenant_label(None, ()) == "default"
    assert tenants_mod.resource_label("CPU") == "CPU"
    assert tenants_mod.resource_label("node:10.0.0.1") == "other"


# ==========================================================================
# 2. tier-1 quota plane
# ==========================================================================


@ray_tpu.remote(num_cpus=1)
class _Holder:
    def ping(self):
        return "ok"

    def pid(self):
        return os.getpid()


def test_quota_registry_and_usage(tenant_cluster):
    tenant_cluster(head_args={"num_cpus": 4}, tenant="teamA")
    out = _gcs().call(
        "tenant_set_quota",
        {"tenant": "teamA", "quota": {"CPU": 2}, "weight": 2.0, "priority": 1},
    )
    assert out["quota"] == {"CPU": 2.0} and out["weight"] == 2.0
    a = _Holder.remote()
    assert ray_tpu.get(a.ping.remote()) == "ok"
    _wait(lambda: _usage_cpu("teamA") == 1.0, 10, "usage to reflect the actor")
    view = _tenant_view("teamA")
    assert view["dominant_share"] > 0
    got = _gcs().call("get_tenant", "teamA")
    assert got["quota"] == {"CPU": 2.0}


def test_quota_parks_actor_and_resumes(tenant_cluster):
    tenant_cluster(head_args={"num_cpus": 4}, tenant="teamA")
    _gcs().call("tenant_set_quota", {"tenant": "teamA", "quota": {"CPU": 2}})
    a1, a2 = _Holder.remote(), _Holder.remote()
    assert ray_tpu.get([a1.ping.remote(), a2.ping.remote()]) == ["ok", "ok"]
    a3 = _Holder.remote()  # over quota: parks, does not fail
    _wait(lambda: (_tenant_view("teamA") or {}).get("parked") == 1, 10, "a3 to park")
    # Parked means parked — it never came up.
    with pytest.raises(Exception):
        ray_tpu.get(a3.ping.remote(), timeout=1.5)
    ray_tpu.kill(a1)
    # Freed quota admits the parked actor.
    assert ray_tpu.get(a3.ping.remote(), timeout=30) == "ok"
    _wait(lambda: (_tenant_view("teamA") or {}).get("parked") == 0, 10, "unpark")
    # Settle: the optimistic admission ledger overlaps the raylet report
    # for <1 s after an admission — steady state is back at the quota.
    _wait(lambda: _usage_cpu("teamA") <= 2.0 + 1e-6, 10, "usage settle")


def test_quota_backpressure_typed_error(tenant_cluster):
    from ray_tpu.exceptions import QuotaExceededError

    tenant_cluster(
        head_args={"num_cpus": 4},
        env={"RAY_TPU_tenant_max_parked": "1"},
        tenant="teamB",
    )
    _gcs().call("tenant_set_quota", {"tenant": "teamB", "quota": {"CPU": 1}})
    a1 = _Holder.remote()
    assert ray_tpu.get(a1.ping.remote()) == "ok"
    a2 = _Holder.remote()  # parks (1 allowed)
    _wait(lambda: (_tenant_view("teamB") or {}).get("parked") == 1, 10, "a2 to park")
    # Third admission: parked queue is full -> typed fail-fast.
    with pytest.raises(QuotaExceededError):
        _Holder.remote()
    del a2


def _try(fn):
    try:
        return fn()
    except Exception:
        return None


def test_actor_restart_not_double_charged(tenant_cluster):
    tenant_cluster(head_args={"num_cpus": 4}, tenant="teamC")
    _gcs().call("tenant_set_quota", {"tenant": "teamC", "quota": {"CPU": 3}})
    a = _Holder.options(max_restarts=2).remote()
    pid = ray_tpu.get(a.pid.remote())
    _wait(lambda: _usage_cpu("teamC") == 1.0, 10, "initial charge")
    os.kill(pid, 9)

    def restarted_pid():
        p = _try(lambda: ray_tpu.get(a.pid.remote(), timeout=2))
        return p if p and p != pid else None

    # The restarted incarnation answers from a NEW pid...
    new_pid = _wait(restarted_pid, 60, "actor restart")
    assert new_pid != pid
    # ... and the tenant is charged exactly once, not per incarnation.
    time.sleep(1.0)
    _wait(lambda: _usage_cpu("teamC") == 1.0, 10, "single charge after restart")


def test_pg_bundles_spanning_nodes_charged_once(tenant_cluster):
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    tenant_cluster(
        head_args={"num_cpus": 2},
        nodes=[{"num_cpus": 2}, {"num_cpus": 2}],
        tenant="teamPG",
    )
    _gcs().call("tenant_set_quota", {"tenant": "teamPG", "quota": {"CPU": 4}})
    pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")
    assert pg.wait(timeout_seconds=60)
    # Both bundles (spanning two nodes) charge the tenant: 4 CPUs total.
    _wait(lambda: _usage_cpu("teamPG") == 4.0, 10, "PG reservation charged")
    # A second PG would exceed the quota: it parks PENDING.
    pg2 = placement_group([{"CPU": 1}])
    assert not pg2.wait(timeout_seconds=3)
    remove_placement_group(pg)
    # Freed reservation admits the parked group.
    assert pg2.wait(timeout_seconds=60)
    _wait(lambda: _usage_cpu("teamPG") == 1.0, 10, "usage after remove")
    remove_placement_group(pg2)


def test_detached_actor_outlives_driver_and_charges_tenant(tenant_cluster):
    c, _ = tenant_cluster(
        head_args={"num_cpus": 4}, tenant="ops", namespace="opsns"
    )
    script = textwrap.dedent(
        """
        import ray_tpu, sys
        ray_tpu.init(address=sys.argv[1], tenant="ops", namespace="opsns")

        @ray_tpu.remote(num_cpus=1)
        class Keeper:
            def ping(self):
                return "alive"

        k = Keeper.options(name="keeper", lifetime="detached").remote()
        assert ray_tpu.get(k.ping.remote()) == "alive"
        ray_tpu.shutdown()
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", script, c.address],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # The creating driver is gone; the detached actor still answers from
    # another job's driver, and its tenant stays charged.
    k = _wait(
        lambda: _try(lambda: ray_tpu.get_actor("keeper", namespace="opsns")),
        30, "detached actor lookup",
    )
    assert ray_tpu.get(k.ping.remote(), timeout=10) == "alive"
    _wait(lambda: _usage_cpu("ops") == 1.0, 15, "detached actor still charged")
    ray_tpu.kill(k)
    _wait(lambda: _usage_cpu("ops") == 0.0, 15, "charge released on kill")


def test_elastic_grow_blocked_at_quota_boundary(tenant_cluster):
    """Elastic shrink/grow crossing a quota boundary: a group shrunk
    within quota cannot grow past it — the grow's actors park and the
    batch times out (group unchanged); raising the quota admits them."""
    from ray_tpu.train._internal.worker_group import WorkerGroup

    tenant_cluster(head_args={"num_cpus": 4}, tenant="train")
    _gcs().call("tenant_set_quota", {"tenant": "train", "quota": {"CPU": 2}})
    group = WorkerGroup(2, {"CPU": 1})
    assert len(group.alive_ranks(timeout=60)) == 2
    _wait(lambda: _usage_cpu("train") == 2.0, 10, "group charged")
    # Shrink within quota...
    group.remove_ranks([1])
    _wait(lambda: _usage_cpu("train") == 1.0, 10, "shrink released quota")
    # ...grow back: first +1 fits the quota, the second crosses it.
    assert group.add_workers(1, ready_timeout=30.0) == 1
    assert group.add_workers(1, ready_timeout=4.0) == 0  # parked, timed out
    assert len(group.workers) == 2
    # Raise the quota: the next grow attempt succeeds.
    _gcs().call("tenant_set_quota", {"tenant": "train", "quota": {"CPU": 3}})
    assert group.add_workers(1, ready_timeout=60.0) == 1
    assert len(group.workers) == 3
    group.shutdown()


def test_lost_capacity_published_for_noticeless_node_death(tenant_cluster):
    """Carried PR 4 follow-up: a worker node that dies WITHOUT a drain
    notice (heartbeat-timeout / connection-close DEAD) still lands in
    the autoscaler's lost_capacity replacement feed, tagged NODE_DEATH —
    only planned IDLE_TERMINATION capacity is excluded."""
    c, handles = tenant_cluster(head_args={"num_cpus": 2}, nodes=[{"num_cpus": 2}])
    c.remove_node(handles[0])  # hard kill: no drain, no notice

    def lost():
        lm = _gcs().call("get_load_metrics", None)
        return [
            e for e in lm.get("lost_capacity", ())
            if e.get("reason") == "NODE_DEATH"
        ]
    records = _wait(lambda: lost() or None, 30, "NODE_DEATH lost_capacity record")
    assert records[0]["resources_total"].get("CPU") == 2.0


# ==========================================================================
# 3. chaos acceptance drills
# ==========================================================================


_LOAD_DRIVER = textwrap.dedent(
    """
    import sys, time
    import ray_tpu

    addr, tenant, prio, inflight, secs = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4]),
        float(sys.argv[5]),
    )
    ray_tpu.init(address=addr, tenant=tenant, priority=prio)

    @ray_tpu.remote(num_cpus=1, max_retries=-1)
    def burn(t):
        time.sleep(t)
        return 1

    pending = []
    deadline = time.time() + secs
    while time.time() < deadline:
        while len(pending) < inflight:
            pending.append(burn.remote(0.2))
        _done, pending = ray_tpu.wait(
            pending, num_returns=1, timeout=1.0
        )
    ray_tpu.shutdown()
    """
)


@pytest.mark.chaos
@pytest.mark.slow  # ~45 s sustained-demand drill: runs under `-m chaos`
def test_three_tenant_fairness_quotas_and_node_kill(tenant_cluster, tmp_path):
    """The acceptance drill: tenants A/B/C with unequal quotas (6/3/3)
    saturate a 12-CPU cluster with sustained 1-CPU task demand.  Steady
    state: each tenant's average usage is its quota within 10%, and no
    instantaneous sample ever exceeds a quota.  Mid-drill, a worker node
    is killed (12 -> 8 CPUs): after recovery no tenant exceeds its
    quota."""
    c, handles = tenant_cluster(
        head_args={"num_cpus": 8}, nodes=[{"num_cpus": 4}]
    )
    gcs = _gcs()
    quotas = {"tA": 6.0, "tB": 3.0, "tC": 3.0}
    for name, q in quotas.items():
        gcs.call("tenant_set_quota", {"tenant": name, "quota": {"CPU": q}})

    drill_s = 40.0
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _LOAD_DRIVER, c.address, name, "0", "10",
             str(drill_s)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for name in quotas
    ]
    try:
        # Warm up, then sample steady state.
        samples = {name: [] for name in quotas}
        t0 = time.monotonic()
        time.sleep(8.0)
        while time.monotonic() - t0 < 18.0:
            for name in quotas:
                samples[name].append(_usage_cpu(name))
            # graftlint: disable=retry-gate -- fixed sampling cadence of the drill's usage time series
            time.sleep(0.4)
        for name, q in quotas.items():
            avg = sum(samples[name]) / max(1, len(samples[name]))
            assert abs(avg - q) <= 0.1 * q + 0.3, (
                f"{name}: steady-state usage {avg:.2f} not within 10% of "
                f"quota {q} (samples={samples[name][-8:]})"
            )
            # Hard bound with a one-sample grace: the cross-raylet grant
            # race can overshoot for <1 s before the reconciliation loop
            # revokes the excess lease — a PERSISTENT overshoot fails.
            over = [u for u in samples[name] if u > q + 1e-6]
            assert len(over) <= 2, (
                f"{name}: quota {q} exceeded persistently: {over}"
            )

        # Mid-drill node kill: 12 -> 8 CPUs.
        c.remove_node(handles[0])
        time.sleep(6.0)  # recovery: retries re-lease on the survivor
        post = {name: [] for name in quotas}
        while time.monotonic() - t0 < drill_s - 2:
            for name in quotas:
                u = _usage_cpu(name)
                post[name].append(u)
                assert u <= quotas[name] + 1e-6, (
                    f"{name} exceeded quota after node kill: {u}"
                )
            # graftlint: disable=retry-gate -- fixed sampling cadence of the drill's usage time series
            time.sleep(0.4)
        # The survivor's 8 CPUs are still being used (work conservation).
        assert any(sum(p) > 0 for p in post.values())
    finally:
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()


_URGENT_DRIVER = textwrap.dedent(
    """
    import sys, time
    import ray_tpu

    addr = sys.argv[1]
    ray_tpu.init(address=addr, tenant="urgent", priority=5)

    @ray_tpu.remote(num_cpus=1)
    class Rush:
        def ping(self):
            return "ok"

    # Two 1-CPU actors against a cluster where the low-priority elastic
    # trainer holds all but one CPU: the second actor starves until the
    # preemption plane shrinks the trainer.
    actors = [Rush.remote() for _ in range(2)]
    got = ray_tpu.get([a.ping.remote() for a in actors], timeout=90)
    assert got == ["ok", "ok"], got
    time.sleep(2)
    ray_tpu.shutdown()
    """
)


@pytest.mark.chaos
@pytest.mark.slow  # ~20 s trainer drill: runs under `-m chaos`
def test_priority_preemption_elastic_checkpoint_shrink(tenant_cluster, tmp_path):
    """A high-priority submission preempts a low-priority elastic
    trainer through checkpoint-and-shrink: the urgent job's actors come
    up, the trainer finishes every step (final-loss parity = no lost
    work) at a reduced world size, and nothing is charged to
    max_failures (max_failures=0 would raise on any charge)."""
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.jax import JaxConfig, JaxTrainer
    from ray_tpu.train import Checkpoint  # noqa: F401 (exercised in loop)

    c, _ = tenant_cluster(
        head_args={"num_cpus": 4},
        env={
            "RAY_TPU_preemption_grace_s": "3",
            "RAY_TPU_preemption_check_period_ms": "300",
        },
        tenant="train",
        priority=0,
    )
    progress_dir = str(tmp_path / "progress")
    os.makedirs(progress_dir, exist_ok=True)
    total_steps = 60

    def loop(config):
        from ray_tpu import train
        from ray_tpu.train import Checkpoint

        ctx = train.get_context()
        resume = train.get_checkpoint()
        start = resume.to_pytree()["step"] if resume is not None else 0
        for step in range(start + 1, config["total_steps"] + 1):
            # graftlint: disable=retry-gate -- simulated train-step duration, not a retry delay
            time.sleep(0.15)
            # Deterministic loss: parity proves no step was lost/redone.
            loss = 1.0 / step
            ckpt = Checkpoint.from_pytree({"step": step})
            with open(
                os.path.join(config["progress_dir"], f"rank_{ctx.get_world_rank()}"),
                "w",
            ) as f:
                f.write(f"{step} {ctx.get_world_size()}")
            train.report(
                {"step": step, "loss": loss, "world_size": ctx.get_world_size()},
                checkpoint=ckpt,
            )

    urgent = {}

    def rank0_step():
        raw = _try(
            lambda: open(os.path.join(progress_dir, "rank_0")).read().split()
        )
        return int(raw[0]) if raw else 0

    def launch_urgent():
        # Wait for the trainer to make some progress first.
        _wait(lambda: rank0_step() >= 3, 60, "trainer progress")
        urgent["proc"] = subprocess.run(
            [sys.executable, "-c", _URGENT_DRIVER, c.address],
            capture_output=True, text=True, timeout=180,
        )

    t = threading.Thread(target=launch_urgent, daemon=True)
    t.start()
    trainer = JaxTrainer(
        loop,
        train_loop_config={
            "total_steps": total_steps, "progress_dir": progress_dir,
        },
        jax_config=JaxConfig(distributed=False),
        scaling_config=ScalingConfig(
            num_workers=3, min_workers=1, resources_per_worker={"CPU": 1}
        ),
        run_config=RunConfig(
            name="preempt_shrink",
            storage_path=str(tmp_path),
            # ZERO budget: any charged restart raises TrainingFailedError.
            failure_config=FailureConfig(max_failures=0),
        ),
    )
    result = trainer.fit()
    t.join(timeout=120)

    proc = urgent.get("proc")
    assert proc is not None, "urgent driver never launched"
    assert proc.returncode == 0, proc.stderr[-2000:] or proc.stdout[-2000:]
    # No lost work: the deterministic loss landed exactly on the last step.
    assert result.metrics["step"] == total_steps
    assert result.metrics["loss"] == 1.0 / total_steps
    # The trainer really shrank for the urgent job.
    assert result.metrics["world_size"] < 3
    from ray_tpu.util import metrics as metrics_mod

    shrank = sum(
        rec.get("value", 0.0)
        for (name, tags), rec in metrics_mod._registry.items()
        if name == "train_resize_events_total"
        and ("trigger", "preempt") in tuple(tags)
    )
    assert shrank >= 1, "no preempt-triggered resize recorded"


def test_lease_ledger_prevents_cross_raylet_over_admission(tenant_cluster):
    """PR 6 follow-up regression (charge-at-admission ledger): when a
    tenant's quota exceeds one node's capacity, its demand spills to a
    peer raylet whose usage view is a report period (~1 s) stale — both
    raylets could grant against the same headroom, over-admitting past
    the quota until cooperative revocation mopped up.  Here revocation
    is DISABLED (chaos drops every revoke_lease push) and the holds are
    long, so any over-admission is persistent and visible: the GCS
    lease-admission ledger (charge at admission, reconcile on report)
    alone must keep concurrent usage at/below the quota."""
    tenant_cluster(
        head_args={"num_cpus": 4},
        nodes=[{"num_cpus": 4}],
        env={"RAY_TPU_testing_chaos_spec": "revoke_lease:drop_req:n=-1"},
        tenant="teamQ",
    )
    # quota 6 > head's 4 CPUs: demand past 4 spills to the worker raylet
    _gcs().call("tenant_set_quota", {"tenant": "teamQ", "quota": {"CPU": 6}})

    @ray_tpu.remote(num_cpus=1)
    def hold(t):
        time.sleep(t)
        return 1

    refs = [hold.remote(25.0) for _ in range(10)]
    try:
        overshoot = []  # (t, usage) samples above quota
        peak = 0.0
        t0 = time.monotonic()
        while time.monotonic() - t0 < 8.0:
            u = _usage_cpu("teamQ")
            peak = max(peak, u)
            if u > 6.0 + 1e-6:
                overshoot.append((round(time.monotonic() - t0, 1), u))
            # graftlint: disable=retry-gate -- fixed sampling cadence of the drill's usage time series
            time.sleep(0.2)
        # Pre-ledger behavior: both raylets grant into the same headroom
        # and usage sits at 7-8 for the WHOLE 30 s hold (revocation is
        # disabled, so nothing can mop an excess lease up — persistence
        # IS the over-admission signal).  With charge-at-admission the
        # only tolerated artifact is the grant-burst accounting overlap
        # (ledger entry + report both carrying a fresh lease for a few
        # hundred ms) — never a persistent excess lease.
        assert not [o for o in overshoot if o[0] > 2.5], (
            f"over-admission persisted past the grant burst: {overshoot}"
        )
        # work conservation: the plane still filled the quota across nodes
        assert peak >= 5.0, f"peak usage only {peak}"
    finally:
        for r in refs:
            try:
                ray_tpu.cancel(r, force=True)
            except Exception:
                pass
