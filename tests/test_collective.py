"""Collective group tests (reference:
python/ray/util/collective/tests/) — CPU backend between real actors."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective


@ray_tpu.remote
class Member:
    def __init__(self, rank, world):
        self.rank = rank
        self.world = world

    def join(self, group="default"):
        collective.init_collective_group(self.world, self.rank, backend="cpu", group_name=group)
        return True

    def do_allreduce(self, group="default"):
        arr = np.full(4, float(self.rank + 1), np.float32)
        return collective.allreduce(arr, group_name=group)

    def do_big_allreduce(self, group="default"):
        arr = np.full(500_000, float(self.rank + 1), np.float32)  # ring path
        out = collective.allreduce(arr, group_name=group)
        return float(out[0]), float(out[-1])

    def do_broadcast(self, group="default"):
        arr = np.arange(3, dtype=np.float32) if self.rank == 0 else np.zeros(3, np.float32)
        return collective.broadcast(arr, src_rank=0, group_name=group)

    def do_allgather(self, group="default"):
        return collective.allgather(np.full(2, float(self.rank), np.float32), group_name=group)

    def do_barrier(self, group="default"):
        collective.barrier(group_name=group)
        return True


@pytest.fixture(scope="module")
def members(ray_cluster):
    world = 3
    actors = [Member.remote(r, world) for r in range(world)]
    ray_tpu.get([a.join.remote("g1") for a in actors])
    yield actors


def test_allreduce(members):
    outs = ray_tpu.get([a.do_allreduce.remote("g1") for a in members])
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 6.0, np.float32))  # 1+2+3


def test_ring_allreduce_large(members):
    outs = ray_tpu.get([a.do_big_allreduce.remote("g1") for a in members])
    for first, last in outs:
        assert first == 6.0 and last == 6.0


def test_broadcast(members):
    outs = ray_tpu.get([a.do_broadcast.remote("g1") for a in members])
    for out in outs:
        np.testing.assert_array_equal(out, np.arange(3, dtype=np.float32))


def test_allgather(members):
    outs = ray_tpu.get([a.do_allgather.remote("g1") for a in members])
    for out in outs:
        assert len(out) == 3
        for r, piece in enumerate(out):
            np.testing.assert_array_equal(piece, np.full(2, float(r), np.float32))


def test_barrier(members):
    assert all(ray_tpu.get([a.do_barrier.remote("g1") for a in members]))


def test_declarative_create(ray_cluster):
    actors = [Member.remote(r, 2) for r in range(2)]
    collective.create_collective_group(actors, 2, [0, 1], backend="cpu", group_name="g2")
    outs = ray_tpu.get([a.do_allreduce.remote("g2") for a in actors])
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 3.0, np.float32))


# ==========================================================================
# Generation-tagged rendezvous (ISSUE 4): elastic destroy+recreate under a
# generation bump; typed rendezvous timeout; straggler invalidation.
# ==========================================================================


class _FakeKV:
    """In-process stand-in for the GCS KV (unit tests need no cluster)."""

    def __init__(self):
        self.d = {}

    def __call__(self, method, payload):
        if method == "kv_put":
            ns, key, value, overwrite = payload
            if not overwrite and (ns, bytes(key)) in self.d:
                return False
            self.d[(ns, bytes(key))] = value
            return True
        if method == "kv_get":
            ns, key = payload
            return self.d.get((ns, bytes(key)))
        if method == "kv_put_max":
            ns, key, value = payload
            try:
                cur = int((self.d.get((ns, bytes(key))) or b"").decode() or -1)
            except ValueError:
                cur = -1
            new = max(cur, int(value))
            self.d[(ns, bytes(key))] = str(new).encode()
            return new
        if method == "kv_del":
            ns, key = payload
            return self.d.pop((ns, bytes(key)), None) is not None
        if method == "kv_keys":
            ns, prefix = payload
            return [k for (n, k) in self.d if n == ns and k.startswith(bytes(prefix))]
        raise AssertionError(f"unexpected kv method {method}")


def test_rendezvous_timeout_names_missing_ranks():
    """Satellite bugfix: the rendezvous poll rides the unified retry
    policy under a deadline budget and raises a TYPED error naming every
    rank that never joined (not a bare TimeoutError for the first)."""
    from ray_tpu.util.collective.cpu_group import CPUCollectiveGroup
    from ray_tpu.util.collective import RendezvousTimeoutError

    kv = _FakeKV()
    with pytest.raises(RendezvousTimeoutError) as ei:
        CPUCollectiveGroup(3, 0, "gt_timeout", kv, rendezvous_timeout_s=0.5)
    assert ei.value.missing_ranks == [1, 2]
    assert ei.value.group_name == "gt_timeout"
    assert "1, 2" in str(ei.value) or "[1, 2]" in str(ei.value)


def test_generation_keys_and_stale_join_rejected():
    """Rendezvous keys are generation-scoped and a member joining at a
    superseded generation fails immediately with GroupInvalidatedError."""
    from ray_tpu.util.collective.cpu_group import (
        KV_NS,
        CPUCollectiveGroup,
        GroupInvalidatedError,
    )

    kv = _FakeKV()
    g = CPUCollectiveGroup(1, 0, "gt_gen", kv, generation=2)
    # Address published under the generation-scoped key + marker written.
    assert (KV_NS, b"gt_gen/gen2/0") in kv.d
    assert kv.d[(KV_NS, b"gt_gen/gen")] == b"2"
    assert g.current_generation() == 2
    g.destroy()

    # The marker has advanced: a gen-1 straggler cannot even rendezvous.
    with pytest.raises(GroupInvalidatedError) as ei:
        CPUCollectiveGroup(1, 0, "gt_gen", kv, generation=1)
    assert ei.value.current_generation == 2


def test_manager_destroy_recreate_under_generation_bump(ray_cluster):
    """GroupManager: re-init at a HIGHER generation atomically replaces
    the local group; same/lower generation is refused."""
    from ray_tpu.util.collective import collective as coll

    assert collective.init_collective_group(1, 0, group_name="g_bump", generation=0)
    g0 = coll._manager.get("g_bump")
    with pytest.raises(ValueError, match="strictly higher generation"):
        collective.init_collective_group(1, 0, group_name="g_bump", generation=0)
    assert collective.init_collective_group(1, 0, group_name="g_bump", generation=1)
    g1 = coll._manager.get("g_bump")
    assert g1 is not g0 and g1.generation == 1
    assert g0._closed  # old mesh torn down, not leaked
    assert collective.get_collective_group_generation("g_bump") == 1
    collective.destroy_collective_group("g_bump")


def test_invalidate_reaps_stale_rendezvous_keys(ray_cluster):
    """invalidate_collective_group bumps the marker and deletes the
    superseded generations' rendezvous keys from the GCS KV."""
    worker = ray_tpu._private.worker.get_global_worker()
    assert collective.init_collective_group(1, 0, group_name="g_reap", generation=0)
    new_gen = collective.invalidate_collective_group("g_reap")
    assert new_gen == 1
    assert collective.get_collective_group_generation("g_reap") == 1
    keys = worker.gcs_client.call("kv_keys", ("collective", b"g_reap/"))
    assert all(k == b"g_reap/gen" for k in keys), keys


@ray_tpu.remote
class GenMember:
    def __init__(self, rank, world):
        self.rank = rank
        self.world = world

    def join(self, group, generation=0, world=None, rank=None):
        collective.init_collective_group(
            self.world if world is None else world,
            self.rank if rank is None else rank,
            backend="cpu", group_name=group, generation=generation,
        )
        return True

    def blocking_allreduce(self, group):
        """Runs a collective that will block on its peer; returns how it
        ended instead of raising (typed across the actor boundary)."""
        try:
            collective.allreduce(np.ones(4, np.float32), group_name=group)
            return "completed"
        except collective.GroupInvalidatedError:
            return "invalidated"
        except Exception as e:  # noqa: BLE001
            return f"other:{type(e).__name__}"


def test_old_generation_straggler_gets_invalidated(ray_cluster):
    """The elastic teardown drill: while a straggler is blocked inside a
    collective of generation 0, the group is invalidated and re-formed;
    the straggler gets a clean GroupInvalidatedError — NOT a hang in a
    TCP mesh that will never complete."""
    a, b = GenMember.remote(0, 2), GenMember.remote(1, 2)
    ray_tpu.get([x.join.remote("g_strag", 0) for x in (a, b)])
    # Warm-up: one full allreduce establishes the TCP pair, so the
    # straggler below blocks in recv() on a LIVE socket (the hang mode).
    outs = ray_tpu.get(
        [x.blocking_allreduce.remote("g_strag") for x in (a, b)], timeout=60
    )
    assert outs == ["completed", "completed"]
    # b's star-allreduce sends its chunk to rank 0 and then blocks
    # waiting for the reduced result, which never comes (a does not run
    # the collective).
    pending = b.blocking_allreduce.remote("g_strag")
    time.sleep(0.5)
    # Elastic resize: driver bumps the generation, survivor a re-joins as
    # a world of 1 at generation 1 (its local gen-0 mesh is destroyed —
    # the destroy closes the socket b is blocked on).
    new_gen = collective.invalidate_collective_group("g_strag")
    assert new_gen == 1
    # Survivor re-forms as a world of ONE at the new generation.
    ray_tpu.get(a.join.remote("g_strag", new_gen, 1, 0), timeout=30)
    # The straggler surfaces the typed invalidation (bounded wait: the
    # whole point is that this does NOT hang).
    assert ray_tpu.get(pending, timeout=30) == "invalidated"
