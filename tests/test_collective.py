"""Collective group tests (reference:
python/ray/util/collective/tests/) — CPU backend between real actors."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective


@ray_tpu.remote
class Member:
    def __init__(self, rank, world):
        self.rank = rank
        self.world = world

    def join(self, group="default"):
        collective.init_collective_group(self.world, self.rank, backend="cpu", group_name=group)
        return True

    def do_allreduce(self, group="default"):
        arr = np.full(4, float(self.rank + 1), np.float32)
        return collective.allreduce(arr, group_name=group)

    def do_big_allreduce(self, group="default"):
        arr = np.full(500_000, float(self.rank + 1), np.float32)  # ring path
        out = collective.allreduce(arr, group_name=group)
        return float(out[0]), float(out[-1])

    def do_broadcast(self, group="default"):
        arr = np.arange(3, dtype=np.float32) if self.rank == 0 else np.zeros(3, np.float32)
        return collective.broadcast(arr, src_rank=0, group_name=group)

    def do_allgather(self, group="default"):
        return collective.allgather(np.full(2, float(self.rank), np.float32), group_name=group)

    def do_barrier(self, group="default"):
        collective.barrier(group_name=group)
        return True


@pytest.fixture(scope="module")
def members(ray_cluster):
    world = 3
    actors = [Member.remote(r, world) for r in range(world)]
    ray_tpu.get([a.join.remote("g1") for a in actors])
    yield actors


def test_allreduce(members):
    outs = ray_tpu.get([a.do_allreduce.remote("g1") for a in members])
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 6.0, np.float32))  # 1+2+3


def test_ring_allreduce_large(members):
    outs = ray_tpu.get([a.do_big_allreduce.remote("g1") for a in members])
    for first, last in outs:
        assert first == 6.0 and last == 6.0


def test_broadcast(members):
    outs = ray_tpu.get([a.do_broadcast.remote("g1") for a in members])
    for out in outs:
        np.testing.assert_array_equal(out, np.arange(3, dtype=np.float32))


def test_allgather(members):
    outs = ray_tpu.get([a.do_allgather.remote("g1") for a in members])
    for out in outs:
        assert len(out) == 3
        for r, piece in enumerate(out):
            np.testing.assert_array_equal(piece, np.full(2, float(r), np.float32))


def test_barrier(members):
    assert all(ray_tpu.get([a.do_barrier.remote("g1") for a in members]))


def test_declarative_create(ray_cluster):
    actors = [Member.remote(r, 2) for r in range(2)]
    collective.create_collective_group(actors, 2, [0, 1], backend="cpu", group_name="g2")
    outs = ray_tpu.get([a.do_allreduce.remote("g2") for a in actors])
    for out in outs:
        np.testing.assert_array_equal(out, np.full(4, 3.0, np.float32))
