import os

# Library tests (train/models/parallel) run JAX on a virtual 8-device CPU
# mesh; core tests never import jax.  Must be set before any jax import.
# Unconditional: the environment may pin JAX_PLATFORMS to a real TPU
# backend via sitecustomize (which imports jax before this file runs).
# Env assignments cover spawned worker processes; config.update covers
# this process, where jax is already imported.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="module")
def ray_cluster():
    """Module-scoped local cluster (spawning processes is expensive on the
    1-core CI box; reference pattern: python/ray/tests/conftest.py
    ray_start_regular_shared)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()
