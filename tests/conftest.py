import os

# Library tests (train/models/parallel) run JAX on a virtual 8-device CPU
# mesh; core tests never import jax.  Must be set before any jax import.
# Unconditional: the environment may pin JAX_PLATFORMS to a real TPU
# backend via sitecustomize (which imports jax before this file runs).
# Env assignments cover spawned worker processes; config.update covers
# this process, where jax is already imported.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

import pytest  # noqa: E402

# ----------------------------------------------------------------------
# Environment-limited tier-1 guards (ROADMAP "known environment-limited
# failures", promoted here as capability-probed xfails).  strict=False:
# an environment that CAN run them reports XPASS, never a failure — the
# guards only reclassify, they can't hide a recovery.  Tier-1 output
# thus separates "env-limited" (x) from real regressions (F).
# ----------------------------------------------------------------------


def _jax_capabilities():
    caps = {"shard_map": False, "multiprocess_backend": False}
    try:
        import jax
    except ImportError:
        return caps
    # jax.shard_map moved to the top level in later jax; models/ops/
    # pipeline code uses the top-level spelling.
    caps["shard_map"] = hasattr(jax, "shard_map")
    # Multi-process computations (jax.distributed across actor processes)
    # are not implemented by the CPU PJRT backend this suite pins
    # (JAX_PLATFORMS=cpu): "Multiprocess computations aren't implemented
    # on the CPU backend".  A non-cpu backend would support them.
    caps["multiprocess_backend"] = jax.default_backend() != "cpu"
    return caps


# nodeid substring -> capability key whose absence xfails it
_ENV_LIMITED = {
    "test_models.py::test_gpt2_sharded_train_step_dp_tp_sp": "shard_map",
    "test_ops.py::test_ring_attention_matches_reference": "shard_map",
    "test_ops.py::test_ring_attention_composes_with_dp": "shard_map",
    "test_pipeline.py::test_gpt2_pp_interleaved_matches_unpipelined": "shard_map",
    "test_sharded_train.py::test_jax_trainer_carries_sharding_config": "multiprocess_backend",
    "test_train.py::test_jax_trainer_distributed_mlp": "multiprocess_backend",
    "test_train.py::test_jax_trainer_resume_from_checkpoint": "multiprocess_backend",
    "test_train.py::test_trainer_restore_from_experiment_dir": "multiprocess_backend",
    "test_train.py::test_jax_trainer_sharded_gpt2_streaming_split": "multiprocess_backend",
    "test_train.py::test_typed_restore_sharded_gpt2_with_closure_loop": "multiprocess_backend",
}

_CAP_REASON = {
    "shard_map": "env-limited: this jax has no jax.shard_map",
    "multiprocess_backend": (
        "env-limited: multiprocess computations aren't implemented on "
        "the CPU jax backend this suite pins"
    ),
}


def pytest_collection_modifyitems(config, items):
    caps = _jax_capabilities()
    for item in items:
        for pattern, cap in _ENV_LIMITED.items():
            if pattern in item.nodeid and not caps[cap]:
                item.add_marker(
                    pytest.mark.xfail(strict=False, reason=_CAP_REASON[cap])
                )
                break


@pytest.fixture(scope="module")
def ray_cluster():
    """Module-scoped local cluster (spawning processes is expensive on the
    1-core CI box; reference pattern: python/ray/tests/conftest.py
    ray_start_regular_shared)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()
