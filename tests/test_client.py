"""Ray Client: remote drivers over ray:// (reference:
util/client/ARCHITECTURE.md — server is a normal driver; client holds
stubs and the server does all bookkeeping)."""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu

PORT = 25043


@pytest.fixture(scope="module")
def client_server():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    gcs = ctx.address_info["gcs_address"]
    srv = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu.util.client.server_main",
            "--gcs-address", gcs, "--listen", f"tcp:127.0.0.1:{PORT}",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    # Wait for it to listen.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            from ray_tpu._private import rpc

            rpc.RpcClient(f"tcp:127.0.0.1:{PORT}").close()
            break
        except Exception:
            time.sleep(0.3)
    yield f"ray://127.0.0.1:{PORT}"
    srv.terminate()
    srv.wait(timeout=10)
    ray_tpu.shutdown()


def _run_client(code: str) -> str:
    """Run a driver script in a FRESH interpreter (a true remote client:
    no shared state with the cluster process)."""
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env=dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_client_tasks_actors_objects(client_server):
    out = _run_client(
        f'''
import ray_tpu
ray_tpu.init(address="{client_server}")

@ray_tpu.remote
def f(x):
    return x * 2

assert ray_tpu.get(f.remote(21)) == 42

@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0
    def incr(self, k):
        self.n += k
        return self.n

c = Counter.remote()
assert ray_tpu.get(c.incr.remote(5)) == 5
assert ray_tpu.get(c.incr.remote(7)) == 12

ref = ray_tpu.put({{"k": [1, 2, 3]}})
assert ray_tpu.get(ref) == {{"k": [1, 2, 3]}}

r1, r2 = f.remote(1), f.remote(2)
ready, rest = ray_tpu.wait([r1, r2], num_returns=2, timeout=30)
assert len(ready) == 2 and not rest

# refs as args cross the wire by id
big = ray_tpu.put(list(range(100)))
@ray_tpu.remote
def total(xs):
    return sum(xs)
assert ray_tpu.get(total.remote(big)) == 4950

ray_tpu.shutdown()
print("CLIENT-OK")
'''
    )
    assert "CLIENT-OK" in out


def test_client_errors_propagate(client_server):
    out = _run_client(
        f'''
import ray_tpu
ray_tpu.init(address="{client_server}")

@ray_tpu.remote(max_retries=0)
def boom():
    raise ValueError("kapow")

try:
    ray_tpu.get(boom.remote(), timeout=60)
    raise SystemExit("no raise")
except ValueError:
    print("ERROR-OK")
ray_tpu.shutdown()
'''
    )
    assert "ERROR-OK" in out


def test_client_runtime_env_and_namespace(client_server, tmp_path):
    """runtime_env is packaged on the CLIENT machine (working_dir zip of
    the client's filesystem, shipped via the server into the GCS KV) and
    namespace is the client driver's, not the server's (reference: ray
    client applies the job runtime_env from the remote driver)."""
    wd = tmp_path / "client_wd"
    wd.mkdir()
    (wd / "client_data.txt").write_text("from-the-client-box")
    out = _run_client(
        f'''
import ray_tpu
ray_tpu.init(
    address="{client_server}",
    namespace="client-ns",
    runtime_env={{"working_dir": r"{wd}", "env_vars": {{"CLIENT_RE": "yes"}}}},
)

@ray_tpu.remote
def read():
    import os
    return open("client_data.txt").read(), os.environ.get("CLIENT_RE")

data, ev = ray_tpu.get(read.remote(), timeout=60)
assert data == "from-the-client-box", data
assert ev == "yes", ev

@ray_tpu.remote
class Named:
    def ping(self):
        return "ns-ok"

n = Named.options(name="client_named", lifetime="detached").remote()
assert ray_tpu.get(n.ping.remote()) == "ns-ok"
# Lookup without an explicit namespace must resolve in the client's.
h = ray_tpu.get_actor("client_named")
assert ray_tpu.get(h.ping.remote()) == "ns-ok"
ray_tpu.kill(h)
print("CLIENT-ENV-OK")
'''
    )
    assert "CLIENT-ENV-OK" in out


def test_client_rejects_cluster_shaping_args(client_server):
    out = _run_client(
        f'''
import ray_tpu
try:
    ray_tpu.init(address="{client_server}", num_cpus=4)
    raise SystemExit("no raise")
except ValueError as e:
    assert "num_cpus" in str(e)
    print("REJECT-OK")
'''
    )
    assert "REJECT-OK" in out


def test_client_disconnect_releases_actors(client_server):
    """Non-detached actors created by a client die with its connection
    (reference: server release_all on disconnect)."""
    _run_client(
        f'''
import ray_tpu
ray_tpu.init(address="{client_server}")

@ray_tpu.remote
class Ghost:
    def ping(self):
        return 1

g = Ghost.remote()
assert ray_tpu.get(g.ping.remote()) == 1
# exit WITHOUT killing: the server must clean up on disconnect
'''
    )
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        alive = [
            a
            for a in ray_tpu.util.state.list_actors()
            if a["state"] == "ALIVE" and "Ghost" in a["class_name"]
        ]
        if not alive:
            return
        time.sleep(0.5)
    raise AssertionError(f"client's actors survived disconnect: {alive}")
