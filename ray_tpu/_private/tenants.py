"""Multi-tenant job plane: tenant model, DRF fair-share math, quota checks.

"Millions of users" means many concurrent *jobs*, not one big one.  Every
job carries a ``tenant`` (a billing/isolation domain — defaults to
``"default"``) and a ``priority`` class within that tenant.  A tenant may
register a resource **quota** (CPU/TPU/memory/...) in the GCS; admission
(actors, placement groups) and the raylet lease path enforce it:
over-quota requests *park* with backpressure instead of queueing
unboundedly or failing.

Scheduling across tenants is DRF-style (dominant resource fairness,
Ghodsi et al.): each tenant's **dominant share** is the maximum over
resources of ``usage[r] / cluster_total[r]`` divided by the tenant's
weight; the scheduler always serves the tenant with the lowest dominant
share first, which converges on weighted fair shares without any central
assignment.  Within a tenant, higher ``priority`` wins, then FIFO.

This module is pure model + math shared by the GCS (admission, pending
ordering, preemption victim selection) and every raylet (lease-queue
ordering, quota gating) — no RPC, no asyncio, unit-testable in
isolation.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu._private.common import ResourceSet

DEFAULT_TENANT = "default"

# Resources considered for dominant-share computation and quota checks.
# Custom resources flow through quota enforcement too (a quota may name
# any resource), but only these appear as metric label values — see
# resource_label() — so label cardinality stays bounded.
_LABELLED_RESOURCES = ("CPU", "TPU", "GPU", "memory")


@dataclass
class TenantSpec:
    """One registered tenant: quota + scheduling weight + default
    priority.  Unregistered tenants implicitly get (no quota, weight 1.0,
    priority 0) — they compete on fair share alone."""

    name: str
    quota: ResourceSet = field(default_factory=ResourceSet)
    weight: float = 1.0
    priority: int = 0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "quota": dict(self.quota),
            "weight": self.weight,
            "priority": self.priority,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        return cls(
            name=d["name"],
            quota=ResourceSet.of(d.get("quota")),
            weight=float(d.get("weight", 1.0)) or 1.0,
            priority=int(d.get("priority", 0)),
        )


def normalize_tenant(tenant: Optional[str]) -> str:
    t = (tenant or "").strip()
    return t if t else DEFAULT_TENANT


def tenant_label(tenant: Optional[str], registered: Iterable[str]) -> str:
    """Bounded-cardinality metric label for a tenant: registered tenants
    (and the default) keep their name, anything else folds into
    ``other`` so a stream of ad-hoc tenant strings can't mint unbounded
    time series."""
    t = normalize_tenant(tenant)
    if t == DEFAULT_TENANT or t in set(registered):
        return t
    return "other"


def resource_label(resource: str) -> str:
    """Bounded-cardinality label for a resource name (custom resources
    fold into ``other``)."""
    return resource if resource in _LABELLED_RESOURCES else "other"


def dominant_share(
    usage: Optional[Dict[str, float]],
    totals: Optional[Dict[str, float]],
    weight: float = 1.0,
) -> float:
    """DRF dominant share: max over resources of usage/total, divided by
    the tenant's weight.  Resources absent from ``totals`` are ignored
    (nothing to be fair about for a resource the cluster doesn't have)."""
    if not usage or not totals:
        return 0.0
    share = 0.0
    for r, used in usage.items():
        cap = totals.get(r, 0.0)
        if cap > 0 and used > 0:
            share = max(share, used / cap)
    return share / (weight if weight > 0 else 1.0)


def over_quota(
    usage: Optional[Dict[str, float]],
    extra: Optional[Dict[str, float]],
    quota: Optional[Dict[str, float]],
) -> bool:
    """True iff ``usage + extra`` exceeds ``quota`` in any resource the
    quota names.  An empty/None quota never rejects (unlimited)."""
    if not quota:
        return False
    for r, cap in quota.items():
        have = (usage or {}).get(r, 0.0) + (extra or {}).get(r, 0.0)
        if have > cap + 1e-9:
            return True
    return False


def add_usage(into: Dict[str, Dict[str, float]], tenant: str, res: Dict[str, float]):
    """Accumulate ``res`` into ``into[tenant]`` (plain dicts, callers own
    the container)."""
    acc = into.setdefault(tenant, {})
    for k, v in res.items():
        if v:
            acc[k] = acc.get(k, 0.0) + v


@dataclass
class LeaseWaiter:
    """One parked worker-lease request in a raylet's fair-share queue."""

    res: ResourceSet
    fut: object  # asyncio.Future granted with True
    tenant: str = DEFAULT_TENANT
    priority: int = 0
    seq: int = 0
    enqueued: float = field(default_factory=time.monotonic)


def pick_next(
    waiters: Iterable[LeaseWaiter],
    available: ResourceSet,
    usage: Dict[str, Dict[str, float]],
    totals: Dict[str, float],
    tenants: Dict[str, TenantSpec],
    enforce_quota: bool = True,
) -> Optional[LeaseWaiter]:
    """Fair-share selection for one grant.

    Per tenant, only the *best* waiter is a candidate (highest priority,
    then FIFO) — no intra-tenant queue-jumping, so a stream of small
    requests can never starve a parked large one of the same tenant.
    Across tenants, candidates are served in ascending dominant-share
    order (weighted DRF); a candidate whose tenant is over quota, or
    whose shape doesn't fit ``available``, is skipped — other tenants
    keep the node busy (work conservation)."""
    heads: Dict[str, LeaseWaiter] = {}
    for w in waiters:
        fut = w.fut
        if fut is not None and getattr(fut, "done", None) and fut.done():
            continue
        cur = heads.get(w.tenant)
        if cur is None or (-w.priority, w.seq) < (-cur.priority, cur.seq):
            heads[w.tenant] = w
    if not heads:
        return None

    def order_key(item: Tuple[str, LeaseWaiter]):
        tenant, w = item
        spec = tenants.get(tenant)
        weight = spec.weight if spec else 1.0
        return (
            dominant_share(usage.get(tenant), totals, weight),
            -w.priority,
            w.seq,
        )

    for tenant, w in sorted(heads.items(), key=order_key):
        if not w.res.fits_in(available):
            continue
        if enforce_quota:
            spec = tenants.get(tenant)
            if spec is not None and over_quota(usage.get(tenant), w.res, spec.quota):
                continue
        return w
    return None


def fair_dispatch_order(
    entries: List[Tuple[str, int, int, object]],
    usage: Dict[str, Dict[str, float]],
    totals: Dict[str, float],
    tenants: Dict[str, TenantSpec],
) -> List[object]:
    """Tenant-fair ordering for a raylet's mediated dispatch queue —
    the same rule the lease queue applies per grant, adapted to a whole
    queue pass: within a tenant strictly (priority desc, FIFO), so no
    intra-tenant queue-jumping; across tenants round-robin in ascending
    weighted dominant share, so the low-share tenant's head runs first
    but a burst from one tenant can't monopolize an entire pass (the
    lease path re-evaluates share per grant; the round-robin is that
    re-evaluation's queue-pass approximation).

    ``entries`` are ``(tenant, priority, seq, item)``; returns items.
    """
    by_tenant: Dict[str, List[Tuple[int, int, object]]] = {}
    for tenant, priority, seq, item in entries:
        by_tenant.setdefault(tenant, []).append((-priority, seq, item))
    for lst in by_tenant.values():
        lst.sort(key=lambda t: (t[0], t[1]))

    def tenant_key(tenant: str):
        spec = tenants.get(tenant)
        weight = spec.weight if spec else 1.0
        head = by_tenant[tenant][0]
        return (dominant_share(usage.get(tenant), totals, weight), head[0], head[1])

    order = sorted(by_tenant, key=tenant_key)
    out: List[object] = []
    depth = 0
    while True:
        emitted = False
        for tenant in order:
            lst = by_tenant[tenant]
            if depth < len(lst):
                out.append(lst[depth][2])
                emitted = True
        if not emitted:
            return out
        depth += 1


def preemption_victim_order(
    jobs: List[dict],
    usage: Dict[str, Dict[str, float]],
    totals: Dict[str, float],
    tenants: Dict[str, TenantSpec],
) -> List[dict]:
    """Order candidate victim jobs for priority preemption: over-quota
    tenants first, then highest dominant share, then lowest priority,
    then youngest job (least sunk work).  Each ``job`` dict needs
    ``tenant``, ``priority`` and ``start_time``."""

    def key(job: dict):
        tenant = normalize_tenant(job.get("tenant"))
        spec = tenants.get(tenant)
        over = (
            spec is not None
            and bool(spec.quota)
            and over_quota(usage.get(tenant), None, spec.quota)
        )
        share = dominant_share(
            usage.get(tenant), totals, spec.weight if spec else 1.0
        )
        return (
            0 if over else 1,  # over-quota tenants first
            -share,
            int(job.get("priority", 0)),
            -float(job.get("start_time", 0.0)),  # youngest first
        )

    return sorted(jobs, key=key)
