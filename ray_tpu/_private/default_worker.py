"""Worker process entrypoint (reference:
python/ray/_private/workers/default_worker.py).  Connects back to the
raylet that spawned it (addresses via env) and runs the task loop."""

from __future__ import annotations

import logging
import sys


def main():
    logging.basicConfig(level=logging.INFO, format="[worker %(asctime)s] %(message)s")
    import os
    import sys as _sys

    # Debugging aid: RAY_TPU_WORKER_STACK_DUMP_S=N dumps every thread's
    # stack to the worker log every N seconds (hung-worker triage).
    dump_s = os.environ.get("RAY_TPU_WORKER_STACK_DUMP_S")
    if dump_s:
        import faulthandler

        faulthandler.dump_traceback_later(float(dump_s), repeat=True, exit=False)

    # A sitecustomize may have imported jax and pinned a platform before
    # this runs; the job's JAX_PLATFORMS env must win in workers.
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms and "jax" in _sys.modules:
        try:
            _sys.modules["jax"].config.update("jax_platforms", platforms)
        except Exception:
            pass
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    worker.connect_worker()
    try:
        worker.main_loop()
    except KeyboardInterrupt:
        pass
    sys.exit(0)


if __name__ == "__main__":
    main()
