"""Standalone GCS process (reference: gcs/gcs_server/gcs_server_main.cc).

head_main co-hosts GCS + head raylet for the common single-command
bring-up; this entrypoint runs the GCS alone so it can be restarted
independently of any raylet — the deployment shape the reference uses,
and what the GCS fault-tolerance tests exercise.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from ray_tpu._private.config import CONFIG
from ray_tpu._private.gcs_server import GcsServer


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--config", default="")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO, format="[%(asctime)s %(name)s] %(message)s")
    if args.config:
        CONFIG.load_overrides(args.config)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    gcs = GcsServer(args.address, {"session_dir": args.session_dir}, loop=loop)

    stop_event = asyncio.Event()

    def _sig(*_):
        loop.call_soon_threadsafe(stop_event.set)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    async def run():
        await gcs.start()
        await stop_event.wait()
        try:
            await asyncio.wait_for(gcs.stop(), timeout=2)
        except Exception:
            pass

    loop.run_until_complete(run())


if __name__ == "__main__":
    main()
