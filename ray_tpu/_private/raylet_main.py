"""Worker-node process: one raylet + embedded object store.

(reference: src/ray/raylet/main.cc:123 — raylet embedding plasma.)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import signal

from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import NodeID
from ray_tpu._private.raylet import Raylet


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--raylet-address", required=True)
    parser.add_argument("--store-dir", required=True)
    parser.add_argument("--resources", required=True)
    parser.add_argument("--config", default="")
    parser.add_argument("--owner-pid", type=int, default=0)
    parser.add_argument("--labels", default="{}")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO, format="[%(asctime)s %(name)s] %(message)s")
    if args.config:
        CONFIG.load_overrides(args.config)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)

    raylet = Raylet(
        node_id=NodeID.from_random(),
        address=args.raylet_address,
        gcs_address=args.gcs_address,
        store_dir=args.store_dir,
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        session_dir=args.session_dir,
        loop=loop,
    )

    stop_event = asyncio.Event()

    def _sig(*_):
        loop.call_soon_threadsafe(stop_event.set)

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)


    async def run():
        raylet.on_fatal = stop_event.set
        await raylet.start()
        from ray_tpu._private.node import owner_watchdog

        watchdog_task = (
            asyncio.ensure_future(owner_watchdog(args.owner_pid, stop_event))
            if args.owner_pid
            else None
        )
        await stop_event.wait()
        try:
            await asyncio.wait_for(raylet.stop(), timeout=4)
        except Exception:
            pass

    loop.run_until_complete(run())


if __name__ == "__main__":
    main()
