"""Owner-side in-process store for small task results.

Direct-pushed tasks return small results inline on the task-finished reply
instead of sealing them in the shared-memory store; the owner keeps them
here and `get`/`wait` resolve without any RPC (reference:
src/ray/core_worker/store_provider/memory_store/ — small returns are
piggybacked on the PushTask reply and live in the owner's memory store
until the ref escapes, at which point they are promoted to plasma).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional


class MemoryStore:
    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        # Returns of in-flight direct tasks: a get on these waits for the
        # task-finished reply instead of falling through to the shm store
        # (which may never see the object).
        self._pending: set = set()
        # Pending oids whose value must be copied to the shm store on
        # arrival (their ref escaped while the task was in flight).
        self._promote: set = set()
        # Pending oids whose owner ref died: the arriving blob is promoted
        # (if flagged) but NOT retained — retaining results nobody can get
        # leaks owner memory on fire-and-forget workloads.
        self._drop: set = set()
        self._cond = threading.Condition()

    # -- owner bookkeeping -------------------------------------------------
    def add_pending(self, oids: Iterable[bytes]) -> None:
        with self._cond:
            self._pending.update(oids)

    def put(self, oid: bytes, blob: bytes) -> bool:
        """Returns True if the caller must promote the blob to the shm
        store (a consumer was promised it there while it was in flight).
        Results whose ref already died arrive, get promoted if promised,
        and are not retained."""
        with self._cond:
            was_pending = oid in self._pending
            self._pending.discard(oid)
            needs_promote = oid in self._promote
            self._promote.discard(oid)
            dropped = oid in self._drop or not was_pending
            self._drop.discard(oid)
            if not dropped:
                self._data[oid] = blob
            self._cond.notify_all()
        return needs_promote

    def mark_promote(self, oid: bytes):
        """Ask for promotion of an in-flight result.  If the value already
        arrived, returns its blob (caller promotes immediately)."""
        with self._cond:
            blob = self._data.get(oid)
            if blob is not None:
                return blob
            if oid in self._pending:
                self._promote.add(oid)
            return None

    def resolve_stored(self, oids: Iterable[bytes]) -> None:
        """The task finished but its results went to the shm store (too
        large to inline, or an error stored for non-owners too)."""
        with self._cond:
            for oid in oids:
                self._pending.discard(oid)
            self._cond.notify_all()

    def free(self, oid: bytes) -> None:
        with self._cond:
            self._data.pop(oid, None)
            self._pending.discard(oid)
            self._promote.discard(oid)
            self._drop.discard(oid)

    def free_if_settled(self, oid: bytes) -> None:
        """Drop the blob if the result already arrived; an in-flight one
        keeps its pending/promote state so arrival still runs promotion,
        but the arriving blob itself is not retained (no refs remain)."""
        with self._cond:
            if oid in self._pending:
                self._drop.add(oid)
            else:
                self._data.pop(oid, None)

    # -- read side ---------------------------------------------------------
    def contains(self, oid: bytes) -> bool:
        return oid in self._data

    def is_pending(self, oid: bytes) -> bool:
        return oid in self._pending

    def is_tracked(self, oid: bytes) -> bool:
        return oid in self._data or oid in self._pending

    def get(self, oid: bytes) -> Optional[bytes]:
        return self._data.get(oid)

    def get_wait(self, oid: bytes, deadline: Optional[float]) -> Optional[bytes]:
        """Block while `oid` is pending; return its blob, or None if the
        result was stored externally (caller falls through to the shm
        store) or the deadline passed."""
        with self._cond:
            while True:
                blob = self._data.get(oid)
                if blob is not None:
                    return blob
                if oid not in self._pending:
                    return None
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining if remaining is not None else 1.0)

    def wait_any(self, timeout: float) -> None:
        """Sleep until any put/resolve event (or timeout)."""
        with self._cond:
            self._cond.wait(timeout)
