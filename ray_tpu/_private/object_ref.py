"""ObjectRef — a distributed future (reference: python/ray/_raylet.pyx
ObjectRef).  Client-side reference counting: when the last local reference
to an *owned* object drops, the owner releases it cluster-wide (reference:
src/ray/core_worker/reference_count.h:64 — the full borrowing protocol is
simplified to owner-local counting plus explicit free)."""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, object_id: ObjectID, owned: bool = False):
        self._id = object_id
        self._owned = owned
        if owned:
            from ray_tpu._private.worker import global_worker_maybe

            w = global_worker_maybe()
            if w is not None:
                w.reference_counter.add_owned(object_id)

    @property
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self.hex()})"

    def __reduce__(self):
        # Crossing a process boundary always produces a borrowed ref.  The
        # owner promotes any memory-store-only value to the shm store at
        # this point so the borrower can fetch it (reference: memory store
        # → plasma promotion on escape).
        from ray_tpu._private.worker import global_worker_maybe

        w = global_worker_maybe()
        if w is not None and w.connected:
            try:
                w.on_ref_serialized(self._id)
            except Exception:
                pass
        return (_restore_ref, (self._id.binary(),))

    def __del__(self):
        if self._owned:
            try:
                from ray_tpu._private.worker import global_worker_maybe

                w = global_worker_maybe()
                if w is not None:
                    w.reference_counter.remove_owned(self._id)
            except Exception:
                return  # interpreter shutdown: import machinery torn down

    # Allow `await ref` inside async actors.
    def __await__(self):
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        result = yield from w.get_async(self).__await__()
        return result

    def future(self):
        """concurrent.futures.Future resolving to the object's value."""
        import concurrent.futures
        import threading

        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _fetch():
            try:
                fut.set_result(w.get([self])[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_fetch, daemon=True).start()
        return fut


def _restore_ref(binary: bytes) -> ObjectRef:
    return ObjectRef(ObjectID(binary), owned=False)
