"""Streaming generators: ``num_returns="streaming"``.

Reference: src/ray/core_worker/generator_waiter.h + ObjectRefGenerator in
python/ray/_raylet.pyx; used pervasively by the reference Data executor
so a consumer can start on the first yielded block before the producer
finishes.

Protocol here: a streaming task's yields are sealed incrementally as
return indices 1..N of the task (``TaskSpec.stream_item_id``); return
index 0 is the end-of-stream sentinel — a :class:`StreamEnd` carrying the
item count on success, or the task's error.  On the direct call paths the
executing worker pushes a ``stream_item`` message per yield over the same
connection that later carries ``task_finished`` (socket FIFO ⇒ items are
seen before the end).  On the raylet-mediated path there are no pushes;
the owner's generator falls back to polling the store, where the items
and the sentinel were sealed.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_ref import ObjectRef


class StreamEnd:
    """End-of-stream sentinel stored as a streaming task's return 0."""

    __slots__ = ("count",)

    def __init__(self, count: int):
        self.count = count

    def __reduce__(self):
        return (StreamEnd, (self.count,))

    def __repr__(self):
        return f"StreamEnd(count={self.count})"


class _StreamState:
    """Owner-side arrival log for one streaming task."""

    __slots__ = ("cond", "arrived", "finished", "saw_push")

    def __init__(self):
        self.cond = threading.Condition()
        # item index -> True once its object is fetchable.
        self.arrived: Dict[int, bool] = {}
        # task_finished seen (sentinel resolvable).
        self.finished = False
        # Any stream_item/task_finished PUSH observed?  False means the
        # raylet-mediated path (items sealed in the store by construction,
        # no per-item existence check needed when draining after the end).
        self.saw_push = False

    def on_item(self, index: int):
        with self.cond:
            self.saw_push = True
            self.arrived[index] = True
            self.cond.notify_all()

    def on_finished(self, pushed: bool = True):
        with self.cond:
            if pushed:
                self.saw_push = True
            self.finished = True
            self.cond.notify_all()


class ObjectRefGenerator:
    """Iterator of ObjectRefs for a streaming task's yields, in yield
    order.  ``next()`` blocks until the next item is ready; when the task
    finishes it raises StopIteration (or the task's error, re-raised at
    the position the task failed)."""

    def __init__(self, worker, spec):
        self._worker = worker
        self._spec = spec
        self._task_id = spec.task_id
        self._consumed = 0
        self._count: Optional[int] = None  # known once the sentinel reads
        self._error: Optional[Exception] = None
        self._state = worker._register_stream(spec)
        self._last_poll = time.monotonic()
        self._fallback_deadline: Optional[float] = None
        # The GENERATOR owns the end-of-stream sentinel's lifetime: without
        # this owned ref, a submit path that builds-and-drops the usual
        # return-ref list would eagerly free the sentinel cluster-wide at
        # submit time, and any consumer reaching _resolve_sentinel after
        # the ~200ms free flush finds it gone (the first call on a fresh
        # driver won the race, every later one timed out — the bug shape
        # that surfaced through serve streaming).  Dropped with the
        # generator, so abandoned streams still free their sentinel.
        self._sentinel_ref = ObjectRef(spec.return_ids()[0], owned=True)

    # -- iteration ------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self._next_internal(timeout=None)

    def next(self, timeout: Optional[float] = None) -> ObjectRef:
        return self._next_internal(timeout)

    def _item_ref(self) -> ObjectRef:
        ref = ObjectRef(self._spec.stream_item_id(self._consumed), owned=True)
        self._consumed += 1
        return ref

    def _fallback_item_ref(
        self, block: bool = True, caller_deadline: Optional[float] = None
    ) -> Optional[ObjectRef]:
        """Sentinel says this item exists but its push never arrived.

        On the raylet-mediated path (no pushes ever observed) every item
        is sealed in the store by construction — hand the ref out for
        free.  On the push path a missing item means its inline push was
        lost (direct server loop stopped racing process exit): verify
        before handing out a ref the consumer's get() would hang on, and
        surface ObjectLostError if it truly never sealed.  With
        ``block=False`` (try_next) a single probe is made per call;
        None means "not confirmed yet, ask again"."""
        if not self._state.saw_push:
            return self._item_ref()
        oid = self._spec.stream_item_id(self._consumed)
        if self._fallback_deadline is None:
            self._fallback_deadline = time.monotonic() + 2.0
        from ray_tpu._private import retry

        bo = retry.STREAM_POLL.start()
        while True:
            with self._state.cond:
                arrived = self._consumed in self._state.arrived
                if arrived:
                    del self._state.arrived[self._consumed]
            if arrived:
                # The push landed after all (e.g. shm promotion failed but
                # the owner's memory store holds it) — resolvable locally.
                self._fallback_deadline = None
                return self._item_ref()
            if self._store_has(oid):
                self._fallback_deadline = None
                return self._item_ref()
            if time.monotonic() > self._fallback_deadline:
                from ray_tpu import exceptions

                self._worker._drop_stream(self._task_id)
                raise exceptions.ObjectLostError(
                    f"stream item {self._consumed} of {self._spec.name} was "
                    "announced by the end-of-stream sentinel but never sealed "
                    "(its inline push was lost)"
                )
            if caller_deadline is not None and time.monotonic() > caller_deadline:
                from ray_tpu import exceptions

                raise exceptions.GetTimeoutError(
                    f"no stream item from {self._spec.name} before timeout"
                )
            if not block:
                return None
            time.sleep(bo.next_delay() or 0.1)

    def _resolve_sentinel(self):
        """Read return 0: StreamEnd(count) or raises the task error."""
        sentinel = ObjectRef(self._spec.return_ids()[0], owned=False)
        value = self._worker.get([sentinel], timeout=30)[0]
        if isinstance(value, StreamEnd):
            self._count = value.count
        else:  # pragma: no cover — get() re-raises stored errors
            raise RuntimeError(f"unexpected stream sentinel: {value!r}")

    def _next_internal(self, timeout: Optional[float]) -> ObjectRef:
        deadline = None if timeout is None else time.monotonic() + timeout
        state = self._state
        while True:
            with state.cond:
                if self._consumed in state.arrived:
                    del state.arrived[self._consumed]
                    return self._item_ref()
                finished = state.finished
            if self._count is not None or finished:
                if self._count is None:
                    self._resolve_sentinel()  # raises the task's error
                if self._consumed < self._count:
                    # Sentinel read but this item's push never arrived
                    # (raylet-mediated path, or push raced shutdown).
                    return self._fallback_item_ref(caller_deadline=deadline)
                self._worker._drop_stream(self._task_id)
                raise StopIteration
            # Raylet-mediated fallback: no pushes arrive at all — probe
            # for the next item / the sentinel (rate-limited; on the push
            # path these probes can never win, so they're pure overhead).
            now = time.monotonic()
            if now - self._last_poll > 0.2:
                self._last_poll = now
                if self._store_has(self._spec.stream_item_id(self._consumed)):
                    return self._item_ref()
                if self._store_has(self._spec.return_ids()[0]):
                    state.on_finished(pushed=False)
                    continue
            if deadline is not None and time.monotonic() > deadline:
                from ray_tpu import exceptions

                raise exceptions.GetTimeoutError(
                    f"no stream item from {self._spec.name} within {timeout}s"
                )
            with state.cond:
                state.cond.wait(0.05)

    def try_next(self) -> Optional[ObjectRef]:
        """Non-blocking: the next item's ref if ready, None otherwise;
        raises StopIteration (or the task's error) at end of stream.
        Push-path checks are pure-local; the store fallback (for
        raylet-mediated submissions) is rate-limited to one probe per
        200 ms so pollers don't hammer the raylet with RPCs."""
        state = self._state
        with state.cond:
            if self._consumed in state.arrived:
                del state.arrived[self._consumed]
                return self._item_ref()
            finished = state.finished
        if self._count is not None or finished:
            if self._count is None:
                self._resolve_sentinel()  # raises the task's error
            if self._consumed < self._count:
                return self._fallback_item_ref(block=False)
            self._worker._drop_stream(self._task_id)
            raise StopIteration
        now = time.monotonic()
        if now - self._last_poll > 0.2:
            self._last_poll = now
            if self._store_has(self._spec.stream_item_id(self._consumed)):
                return self._item_ref()
            if self._store_has(self._spec.return_ids()[0]):
                state.on_finished(pushed=False)
        return None

    def _store_has(self, oid: ObjectID) -> bool:
        """Cluster-wide existence probe via the GCS object directory —
        on the raylet-mediated path the items are sealed on the executing
        node, which need not be the owner's (a local store_contains would
        never see them)."""
        try:
            gcs = self._worker.gcs_client
            # A best-effort probe must not park on the GCS reconnect gate:
            # during an outage, consumption continues on pushes + the
            # local store check (found by the gcs-restart-mid-stream
            # drill, which this once stalled for the whole 60 s budget).
            if getattr(gcs, "ready", False) and gcs.call(
                "object_locations_get", oid.binary(), timeout=10
            ):
                return True
            # Small objects can live only in the owner's raylet store
            # (inline put), which reports locations too — but check
            # locally as a cheap belt-and-braces fallback.
            return bool(
                self._worker.raylet_client.call("store_contains", oid.binary(), timeout=10)
            )
        except Exception:
            return False

    # -- conveniences ---------------------------------------------------
    def __del__(self):
        try:
            self._worker._drop_stream(self._task_id)
        except Exception:
            pass

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio

        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(None, self.__next__)
        except StopIteration:
            raise StopAsyncIteration
