"""On-demand sampling profiler + JAX/XLA introspection: the cluster's
bottleneck-attribution plane.

The PR 2 flight recorder answers *where time went between processes*
(spans, RPC/task-phase metrics).  This module answers the next two
questions the perf arc needs (reference: `ray timeline` + per-worker
py-spy/memray hooks; Podracer-style work diagnoses via per-step device
and compile profiles, not RPC spans):

- **What is a hot process doing?**  A stdlib-only wall/CPU sampling
  profiler: a daemon thread walks ``sys._current_frames()`` at a
  configurable Hz and folds stacks into counts.  Any live worker /
  actor host / raylet / the GCS can be attached via the
  ``profile_start`` / ``profile_stop`` / ``profile_dump`` RPC surface
  (handlers delegate to ``handle_profile_*`` here — they never block
  the dispatch loop).  Finished captures also ship to the GCS profile
  table through the existing metrics/span report channel, so a capture
  survives its driver.
- **What is the device doing?**  ``instrument_jit`` wraps a jitted
  callable with compile-time/retrace counters and first-trace
  ``cost_analysis()`` FLOPs/bytes; ``report_device_memory`` publishes
  ``live_buffers``/``memory_stats`` gauges where the backend supports
  them (CPU-safe no-op otherwise).

Exports: ``collapse`` (collapsed-stack / flamegraph lines),
``speedscope`` (speedscope JSON), ``merge_records`` (fold per-process
captures into one cluster profile keyed by actor/tenant label).
``ray_tpu.util.profiling`` is the driver-side orchestration on top.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import CONFIG


class ProfilerError(Exception):
    """Base error of the profiling plane."""


class ProfilerConflictError(ProfilerError):
    """A session is already running in this process.  One sampler per
    process: two concurrent captures would double the overhead and
    interleave their sample sets; the second attach gets this typed
    error (carrying the live session id) instead of silently sharing."""

    def __init__(self, message: str, session_id: str = ""):
        super().__init__(message)
        self.session_id = session_id

    def __reduce__(self):
        # Keep session_id across the RPC pickle boundary (default
        # Exception reduction only replays args[0]).
        return (type(self), (self.args[0], self.session_id))


class ProfilerSessionNotFound(ProfilerError):
    """stop/dump named a session this process doesn't have (already
    reaped, or the caller's target restarted in between)."""


# Fallback idle heuristic for CPU mode on platforms without per-thread
# CPU accounting (/proc): leaf functions that mean "this thread is
# parked, not computing".  The blocking call itself is C code (no
# Python frame), so the heuristic keys on the Python caller
# conventionally wrapping it.
_IDLE_LEAF_NAMES = frozenset(
    {
        "wait",
        "_wait_for_tstate_lock",
        "select",
        "poll",
        "epoll",
        "accept",
        "recv",
        "recv_into",
        "readexactly",
        "_recv_exact",
        "read",
        "readline",
        "get",  # queue.Queue.get parks on a condition
        "join",
        "flush_loop",
        "run_forever",
        "sleep",
    }
)


class _ThreadCpuClock:
    """Per-thread CPU-time deltas from /proc/self/task/<tid>/stat
    (Linux).  A thread whose utime+stime did not advance since the last
    sample was parked (C-level sleep/select/recv included — which the
    Python-frame leaf heuristic cannot see).  ``delta(py_tid)`` is
    None when accounting is unavailable → caller falls back to the
    leaf-name heuristic."""

    def __init__(self):
        self._available = os.path.isdir("/proc/self/task")
        self._native: Dict[int, int] = {}  # python tid -> native tid
        self._last: Dict[int, int] = {}  # native tid -> cpu jiffies

    def _refresh_native_map(self) -> None:
        for t in threading.enumerate():
            nid = getattr(t, "native_id", None)
            if nid is not None:
                self._native[t.ident] = nid

    def _cpu_jiffies(self, native_tid: int) -> Optional[int]:
        try:
            with open(f"/proc/self/task/{native_tid}/stat", "rb") as f:
                data = f.read()
            # utime, stime are fields 14, 15 (1-based), after the
            # parenthesized comm which may itself contain spaces.
            rest = data.rsplit(b")", 1)[1].split()
            return int(rest[11]) + int(rest[12])
        except (OSError, IndexError, ValueError):
            return None

    def delta(self, py_tid: int) -> Optional[int]:
        """CPU jiffies this thread burned since its previous probe;
        None = unknown (no accounting for this thread/platform).  Used
        as the sample WEIGHT: when GIL contention stretches the tick
        interval, a continuously-computing thread still accrues its
        full CPU time while a housekeeping loop's 1-jiffy blip stays a
        blip."""
        if not self._available:
            return None
        nid = self._native.get(py_tid)
        if nid is None:
            self._refresh_native_map()
            nid = self._native.get(py_tid)
            if nid is None:
                return None
        cur = self._cpu_jiffies(nid)
        if cur is None:
            # Stale mapping: the thread behind this Python ident exited
            # and a new thread reused the ident — re-resolve once so
            # churned threads don't permanently fall back to the leaf
            # heuristic (or read a recycled tid's clock).
            self._native.pop(py_tid, None)
            self._refresh_native_map()
            nid = self._native.get(py_tid)
            cur = self._cpu_jiffies(nid) if nid is not None else None
            if cur is None:
                return None
        prev = self._last.get(nid)
        self._last[nid] = cur
        if prev is None:
            return 0  # no baseline yet: treat the first probe as idle
        return max(0, cur - prev)


def _frame_label(code) -> str:
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class SamplingProfiler:
    """One capture: a daemon thread sampling every live thread's stack.

    ``mode="wall"`` keeps every sample; ``mode="cpu"`` drops samples
    whose leaf frame is a known parked-thread idiom (see
    ``_IDLE_LEAF_NAMES``) — an approximation, but a useful one without
    OS-level thread state (stdlib-only by design).
    """

    def __init__(
        self,
        session_id: str,
        hz: float,
        duration_s: float,
        mode: str = "wall",
        label: str = "",
        on_finish=None,
    ):
        self.session_id = session_id
        self.hz = max(1.0, min(float(hz), 1000.0))
        self.duration_s = float(duration_s)
        self.mode = mode if mode in ("wall", "cpu") else "wall"
        self.label = label
        self.started_at = time.time()
        self.ended_at: Optional[float] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._samples: Dict[Tuple[str, ...], int] = {}
        self._ticks = 0
        self._sample_count = 0
        self._idle_dropped = 0
        self._threads_seen: set = set()
        self._errors: List[str] = []
        self._max_depth = int(CONFIG.profile_max_stack_depth)
        self._on_finish = on_finish
        self._cpu_clock = _ThreadCpuClock() if self.mode == "cpu" else None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"profile-sampler-{session_id[:8]}"
        )

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    @property
    def running(self) -> bool:
        return self._thread.is_alive()

    def _run(self) -> None:
        interval = 1.0 / self.hz
        deadline = time.monotonic() + self.duration_s
        own_tid = threading.get_ident()
        try:
            while not self._stop.is_set() and time.monotonic() < deadline:
                t0 = time.perf_counter()
                self._sample_once(own_tid)
                # Absorb the sampling cost into the interval so the
                # effective rate stays ~hz instead of hz + walk time.
                self._stop.wait(max(0.0005, interval - (time.perf_counter() - t0)))
        except Exception as e:  # noqa: BLE001 — a broken sampler must end cleanly
            with self._lock:
                self._errors.append(f"sampler died: {type(e).__name__}: {e}")
        finally:
            with self._lock:
                self.ended_at = time.time()
            if self._on_finish is not None:
                try:
                    self._on_finish(self)
                except Exception:  # noqa: BLE001 — best-effort ship
                    pass

    def _sample_once(self, own_tid: int) -> None:
        # Phase 1 — walk every stack WITHOUT any GIL-releasing call in
        # between: the frame objects in the snapshot stay live only
        # while the sampled threads cannot run.  (The CPU-clock probes
        # below do file I/O, which releases the GIL; probing first once
        # produced truncated single-frame stacks of frames the thread
        # had already popped.)
        frames = sys._current_frames()
        walked: List[Tuple[int, str, Tuple[str, ...]]] = []
        for tid, top in frames.items():
            if tid == own_tid:
                continue
            stack: List[str] = []
            f = top
            depth = 0
            while f is not None and depth < self._max_depth:
                stack.append(_frame_label(f.f_code))
                f = f.f_back
                depth += 1
            stack.reverse()
            walked.append((tid, top.f_code.co_name, tuple(stack)))
        # Phase 2 — filter + fold (CPU-clock probes may release the GIL
        # freely now; the stacks are already copied out as strings).
        with self._lock:
            self._ticks += 1
            for tid, leaf_name, key in walked:
                self._threads_seen.add(tid)
                weight = 1
                if self.mode == "cpu":
                    # Real per-thread CPU accounting where the OS
                    # provides it (samples weighted by jiffies burned);
                    # leaf-name heuristic otherwise.
                    delta = self._cpu_clock.delta(tid)
                    if delta == 0 or (
                        delta is None and leaf_name in _IDLE_LEAF_NAMES
                    ):
                        self._idle_dropped += 1
                        continue
                    if delta is not None:
                        weight = delta
                self._samples[key] = self._samples.get(key, 0) + weight
                self._sample_count += weight

    # -- export ---------------------------------------------------------
    def snapshot(self, partial: Optional[bool] = None) -> Dict[str, Any]:
        """The session's record — safe to call mid-capture (a dump of a
        dying worker returns whatever was sampled so far)."""
        with self._lock:
            samples = {";".join(k): v for k, v in self._samples.items()}
            errors = list(self._errors)
            ticks, count = self._ticks, self._sample_count
            idle, nthreads = self._idle_dropped, len(self._threads_seen)
            ended_at = self.ended_at
        return {
            "session_id": self.session_id,
            "label": self.label,
            "pid": os.getpid(),
            "hz": self.hz,
            "mode": self.mode,
            "duration_s": self.duration_s,
            "started_at": self.started_at,
            "ended_at": ended_at,
            "running": self.running if partial is None else partial,
            "ticks": ticks,
            "sample_count": count,
            "idle_dropped": idle,
            "threads_seen": nthreads,
            "errors": errors,
            "samples": samples,
        }


# ----------------------------------------------------------------------
# per-process session registry (one active capture per process)
# ----------------------------------------------------------------------
_registry_lock = threading.Lock()
_active: Optional[SamplingProfiler] = None
_last_record: Optional[Dict[str, Any]] = None


def _ship_finished(profiler: SamplingProfiler) -> None:
    """Natural end of a capture: cache the record locally (a late dump
    RPC still gets it) and ship it to the GCS profile table through the
    existing report channel (worker GCS client, or the raylet/GCS
    report channel — same path spans ride)."""
    global _last_record
    record = profiler.snapshot(partial=False)
    with _registry_lock:
        _last_record = record
    from ray_tpu._private import telemetry

    telemetry.count_profile_session("completed")
    try:
        from ray_tpu.util import metrics as metrics_mod
        from ray_tpu.util import tracing

        tracing.record_event_span(
            "profile.capture",
            record["started_at"],
            record["ended_at"] or time.time(),
            attributes={
                "label": record["label"],
                "hz": record["hz"],
                "mode": record["mode"],
                "sample_count": record["sample_count"],
            },
        )
        metrics_mod.report(
            "profile_report",
            {
                "profile": record,
                # per-tenant accounting in the GCS profile table (same
                # stamp the span flusher carries)
                "tenant": os.environ.get("RAY_TPU_TENANT") or "default",
            },
        )
    except Exception:  # noqa: BLE001 — shipping is best-effort
        pass


def handle_profile_start(payload: Optional[dict]) -> Dict[str, Any]:
    """RPC surface: attach a sampler to THIS process.  Non-blocking —
    spawns the daemon sampler thread and returns immediately."""
    global _active
    payload = payload or {}
    duration = min(
        max(0.05, float(payload.get("duration_s") or 10.0)),
        float(CONFIG.profile_max_duration_s),
    )
    hz = float(payload.get("hz") or CONFIG.profile_default_hz)
    mode = payload.get("mode") or "wall"
    label = str(payload.get("label") or f"pid:{os.getpid()}")
    session_id = payload.get("session_id") or _new_session_id()
    with _registry_lock:
        # Conflict gate keys on ended_at, not thread liveness: a just-
        # registered session whose thread hasn't started yet (start()
        # below, still under this lock) and a running one both have
        # ended_at None — checking Thread.is_alive() here left a window
        # where a concurrent attach could silently overwrite the
        # registry and double the sampling overhead.
        if _active is not None and _active.ended_at is None:
            from ray_tpu._private import telemetry

            telemetry.count_profile_session("conflict")
            raise ProfilerConflictError(
                f"a profile session ({_active.session_id}) is already running "
                f"in pid {os.getpid()}; stop it or wait for its deadline",
                session_id=_active.session_id,
            )
        prof = SamplingProfiler(
            session_id, hz, duration, mode=mode, label=label, on_finish=_ship_finished
        )
        _active = prof
        try:
            prof.start()
        except Exception:
            # Thread spawn failed (e.g. at the process thread limit): a
            # registered-but-never-started session would hold the
            # conflict gate (ended_at stays None with no thread to set
            # it) and brick profiling for the process — release the
            # slot and surface the error instead.
            _active = None
            raise
    return {
        "session_id": session_id,
        "pid": os.getpid(),
        "hz": prof.hz,
        "mode": prof.mode,
        "duration_s": duration,
        "started_at": prof.started_at,
        "label": label,
    }


def _find(session_id: Optional[str]) -> SamplingProfiler:
    if _active is None or (session_id and _active.session_id != session_id):
        raise ProfilerSessionNotFound(
            f"no profile session {session_id or '<any>'} in pid {os.getpid()}"
        )
    return _active


def handle_profile_stop(payload: Optional[dict]) -> Dict[str, Any]:
    """Stop the capture early; returns the final record."""
    payload = payload or {}
    with _registry_lock:
        prof = _find(payload.get("session_id"))
    prof.stop()
    # The sampler thread exits within one interval; don't join on the
    # dispatch loop — snapshot now (records through the last tick).
    return prof.snapshot(partial=False)


def handle_profile_dump(payload: Optional[dict]) -> Dict[str, Any]:
    """Dump the capture (partial if still running).  ``stop=True``
    (default) also ends it — the one-call dump-and-detach the driver
    orchestration uses."""
    global _last_record
    payload = payload or {}
    sid = payload.get("session_id")
    with _registry_lock:
        if _active is None or (sid and _active.session_id != sid):
            if _last_record is not None and (
                not sid or _last_record["session_id"] == sid
            ):
                return _last_record
            raise ProfilerSessionNotFound(
                f"no profile session {sid or '<any>'} in pid {os.getpid()}"
            )
        prof = _active
    if payload.get("stop", True):
        prof.stop()
    return prof.snapshot()


def active_session_id() -> Optional[str]:
    with _registry_lock:
        if _active is not None and _active.running:
            return _active.session_id
    return None


def _new_session_id() -> str:
    import secrets

    return secrets.token_hex(8)


# ----------------------------------------------------------------------
# export formats (pure functions; shared by util.profiling + dashboard)
# ----------------------------------------------------------------------
def collapse(record: Dict[str, Any], root: Optional[str] = None) -> str:
    """Brendan-Gregg collapsed-stack lines ("f1;f2;f3 count"), the
    input format of flamegraph.pl / speedscope / inferno.  ``root``
    (default: the record's label) prefixes every stack so merged
    cluster profiles stay attributable per process."""
    prefix = record.get("label", "") if root is None else root
    lines = []
    for stack, count in sorted(record.get("samples", {}).items()):
        line = f"{prefix};{stack}" if prefix else stack
        lines.append(f"{line} {count}")
    return "\n".join(lines) + ("\n" if lines else "")


def merge_records(records: List[Dict[str, Any]]) -> Dict[str, int]:
    """Fold per-process capture records into one cluster-wide stack
    map, each stack rooted at its process label (actor/tenant/raylet),
    so one flamegraph shows the whole cluster with per-target subtrees."""
    merged: Dict[str, int] = {}
    for rec in records:
        prefix = rec.get("label", "")
        for stack, count in rec.get("samples", {}).items():
            key = f"{prefix};{stack}" if prefix else stack
            merged[key] = merged.get(key, 0) + count
    return merged


def speedscope(records: List[Dict[str, Any]], name: str = "ray_tpu profile") -> Dict[str, Any]:
    """Speedscope JSON (sampled profiles, one per capture record) —
    https://www.speedscope.app file-format-schema.  Aggregated stacks
    become one weighted sample each; weights are sample counts."""
    frames: List[Dict[str, str]] = []
    frame_idx: Dict[str, int] = {}

    def fidx(label: str) -> int:
        i = frame_idx.get(label)
        if i is None:
            i = frame_idx[label] = len(frames)
            frames.append({"name": label})
        return i

    profiles = []
    for rec in records:
        samples: List[List[int]] = []
        weights: List[float] = []
        for stack, count in sorted(rec.get("samples", {}).items()):
            samples.append([fidx(fr) for fr in stack.split(";")])
            weights.append(float(count))
        profiles.append(
            {
                "type": "sampled",
                "name": rec.get("label") or f"pid {rec.get('pid')}",
                "unit": "none",
                "startValue": 0.0,
                "endValue": float(sum(weights)),
                "samples": samples,
                "weights": weights,
            }
        )
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": profiles,
        "name": name,
        "exporter": "ray_tpu.profiling",
        "activeProfileIndex": 0,
    }


def top_frames(records: List[Dict[str, Any]], n: int = 10) -> List[Tuple[str, int, float]]:
    """(leaf_frame, samples, fraction) of the hottest exclusive frames
    across the given records — the "what is it doing" one-liner."""
    counts: Dict[str, int] = {}
    total = 0
    for rec in records:
        for stack, count in rec.get("samples", {}).items():
            leaf = stack.rsplit(";", 1)[-1]
            counts[leaf] = counts.get(leaf, 0) + count
            total += count
    ranked = sorted(counts.items(), key=lambda kv: -kv[1])[:n]
    return [(fr, c, (c / total if total else 0.0)) for fr, c in ranked]


# ----------------------------------------------------------------------
# JAX/XLA introspection (CPU-safe; no-ops when jax is absent)
# ----------------------------------------------------------------------
_jit_lock = threading.Lock()
_jit_records: Dict[str, Dict[str, Any]] = {}


def _cache_size(jfn) -> Optional[int]:
    """Compiled-executable cache size of a jitted callable, or None when
    the jax version doesn't expose it (then only the first call is
    counted as a compile)."""
    try:
        return int(jfn._cache_size())
    except Exception:  # noqa: BLE001 — private API, version-dependent
        return None


def jit_stats(name: Optional[str] = None) -> Dict[str, Any]:
    """Per-instrumented-function compile/retrace/cost records."""
    with _jit_lock:
        if name is not None:
            return dict(_jit_records.get(name, {}))
        return {k: dict(v) for k, v in _jit_records.items()}


def instrument_jit(name: str, jfn):
    """Wrap an already-jitted callable with compile-time and retrace
    counters plus first-trace cost_analysis.

    Steady-state cost per call: one cache-size probe + two
    perf_counter reads (~0.5 us) — far inside the telemetry budget for
    step-scale functions.  When a call triggers a (re)trace, its wall
    time is recorded as ``jax_compile_seconds`` (trace+compile+first
    run — the stall the operator actually sees) and a
    ``jax.compile`` span lands in the timeline.  Disabled via
    ``jax_introspection=False`` (returns ``jfn`` unwrapped).
    """
    try:
        if not CONFIG.jax_introspection:
            return jfn
    except Exception:  # noqa: BLE001 — config unavailable in exotic contexts
        pass
    state = {"cache_size": _cache_size(jfn) or 0, "compiles": 0}
    with _jit_lock:
        _jit_records.setdefault(
            name,
            {"compiles": 0, "retraces": 0, "compile_seconds": 0.0, "flops": None,
             "bytes_accessed": None},
        )

    def wrapped(*args, **kwargs):
        from ray_tpu._private import telemetry

        # cost_analysis runs BEFORE the first call: donate_argnums
        # functions consume their buffers, so lowering afterwards would
        # trace over deleted arrays.
        if not state.get("cost_done"):
            state["cost_done"] = True
            _capture_cost(name, jfn, args, kwargs)
        t_wall = time.time()
        t0 = time.perf_counter()
        out = jfn(*args, **kwargs)
        dt = time.perf_counter() - t0
        cs = _cache_size(jfn)
        compiled = (cs is not None and cs > state["cache_size"]) or (
            cs is None and state["compiles"] == 0
        )
        if compiled:
            state["cache_size"] = cs if cs is not None else state["cache_size"]
            state["compiles"] += 1
            first = state["compiles"] == 1
            with _jit_lock:
                rec = _jit_records[name]
                rec["compiles"] += 1
                rec["compile_seconds"] += dt
                if not first:
                    rec["retraces"] += 1
            telemetry.observe_jax_compile(name, dt)
            if not first:
                telemetry.count_jax_retrace(name)
            try:
                from ray_tpu.util import tracing

                tracing.record_event_span(
                    "jax.compile",
                    t_wall,
                    t_wall + dt,
                    attributes={"function": name, "retrace": not first},
                )
            except Exception:  # noqa: BLE001
                pass
        return out

    wrapped.__name__ = f"instrumented_{name}"
    wrapped.__wrapped__ = jfn
    return wrapped


def _capture_cost(name: str, jfn, args, kwargs) -> None:
    """First-trace cost_analysis: FLOPs + bytes accessed from the
    lowered computation (one extra trace, never on the steady path).
    Backends that don't implement it just skip."""
    try:
        if not CONFIG.jax_cost_analysis:
            return
        lowered = jfn.lower(*args, **kwargs)
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return
        flops = float(ca.get("flops", 0.0) or 0.0)
        nbytes = float(ca.get("bytes accessed", 0.0) or 0.0)
        with _jit_lock:
            rec = _jit_records[name]
            rec["flops"] = flops
            rec["bytes_accessed"] = nbytes
        from ray_tpu._private import telemetry

        telemetry.set_jax_cost(name, flops, nbytes)
    except Exception:  # noqa: BLE001 — introspection must never break the hot path
        pass


_dev_report_lock = threading.Lock()
_last_dev_report = 0.0


def report_device_memory(min_interval_s: float = 1.0) -> None:
    """Publish per-device memory gauges (``memory_stats``) and the live
    on-device buffer count (``live_arrays``) where the backend supports
    them.  CPU backends typically report nothing — then this is a
    cheap no-op.  Rate-limited so per-step call sites cost one clock
    read on the fast path."""
    global _last_dev_report
    from ray_tpu._private import telemetry

    if not telemetry.enabled():
        return
    now = time.monotonic()
    if now - _last_dev_report < min_interval_s:
        return  # lock-free fast path for per-step call sites
    with _dev_report_lock:
        if now - _last_dev_report < min_interval_s:
            return
        _last_dev_report = now
    try:
        import jax
    except Exception:  # noqa: BLE001 — no jax in this process
        return
    try:
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend init failure
        return
    live_by_dev: Dict[str, int] = {}
    try:
        for arr in jax.live_arrays():
            for d in getattr(arr, "devices", lambda: [])():
                key = f"{d.platform}:{d.id}"
                live_by_dev[key] = live_by_dev.get(key, 0) + 1
    except Exception:  # noqa: BLE001
        pass
    for d in devices:
        dev_label = f"{d.platform}:{d.id}"
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — unsupported backend
            stats = None
        if stats:
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                telemetry.set_device_memory(dev_label, "in_use", float(in_use))
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                telemetry.set_device_memory(dev_label, "peak", float(peak))
            limit = stats.get("bytes_limit")
            if limit is not None:
                telemetry.set_device_memory(dev_label, "limit", float(limit))
        if dev_label in live_by_dev:
            telemetry.set_device_live_buffers(dev_label, live_by_dev[dev_label])
